"""Partition-tolerant cluster: failure detector, auto-heal,
anti-entropy (docs/CLUSTER.md).

The chaos matrix the tentpole promises: a wedged-but-connected peer
is declared down within the detector window (the failure mode the
legacy EOF-only monitor can never see), a transient blip parks casts
without purging anything, suspect peers fast-fail instead of
blocking CONNECTs into ``call_timeout``, and a healed partition
reconverges all five replicated planes byte-exactly against a
never-partitioned oracle cluster — with zero manual rejoin.

Multi-node-in-one-process over real sockets: each node gets its own
SocketTransport (private IO thread). The module-global fault
registry is scoped per transport via ``fault_peers``/``fault_local``
so a partition severs exactly the links the scenario names.
"""

import time

import pytest

from emqx_tpu import faults
from emqx_tpu.cluster import (Cluster, ClusterConfig,
                              PeerUnavailableError)
from emqx_tpu.cluster_net import SocketTransport
from emqx_tpu.modules.retainer import RetainerModule
from emqx_tpu.node import Node
from emqx_tpu.types import Message

#: recent-but-fixed timestamp base: retained LWW and tombstones are
#: timestamp-ordered, so byte-exact oracle comparison needs the SAME
#: timestamps in both clusters — but the retainer sweeps tombstones
#: older than an hour, so they must also be *current*
TS = float(int(time.time()))


def _fast_cfg(**kw) -> ClusterConfig:
    base = dict(heartbeat_interval_s=0.1, heartbeat_timeout_s=0.5,
                suspect_after=1, down_after=3, ok_after=1,
                anti_entropy_interval_s=0.5, call_timeout_s=2.0,
                redial_backoff_s=0.1, redial_backoff_max_s=0.5)
    base.update(kw)
    return ClusterConfig(**base)


class Sub:
    def __init__(self, cid):
        self.client_id = cid
        self.inbox = []

    def deliver(self, t, m):
        self.inbox.append((t, m))


def _mk_net(n, config, cookie, retainer=False, immune=False):
    nodes, trs, cls = [], [], []
    for i in range(n):
        node = Node(name=f"hn{i}", boot_listeners=False)
        if retainer:
            node.modules.load(RetainerModule)
        tr = SocketTransport(f"hn{i}", cookie=cookie, config=config)
        if immune:
            # a second cluster in this process must not feel the
            # chaos armed for the first one
            tr.fault_peers = set()
            tr.fault_local = False
        tr.serve()
        cl = Cluster(node, transport=tr, config=config)
        nodes.append(node)
        trs.append(tr)
        cls.append(cl)
    for i in range(1, n):
        cls[i].join_remote("127.0.0.1", trs[0].port)
    return nodes, trs, cls


def _teardown(trs, cls):
    for cl in cls:
        cl.close()
    for tr in trs:
        tr.close()


def _wait(pred, timeout=20.0, msg="condition not met in time"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(msg)


def _partition(trs, side_a, side_b):
    """Sever every link between the two index sets, both ways."""
    for i in side_a:
        trs[i].fault_peers = {f"hn{j}" for j in side_b}
    for j in side_b:
        trs[j].fault_peers = {f"hn{i}" for i in side_a}
    faults.set_master(True)
    faults.arm("net.partition", times=0)


def _converged(clusters):
    digests = [cl.plane_digests() for cl in clusters]
    return all(d == digests[0] for d in digests[1:])


# -- failure detector ------------------------------------------------------


def test_wedged_peer_declared_down_then_autoheals():
    """A wedged-but-connected peer (TCP up, frames swallowed, no
    replies — peer.wedge) is declared down within the detector
    window; un-wedging triggers the reappearance probe → auto-heal →
    membership and routes re-merge with zero manual rejoin."""
    cfg = _fast_cfg()
    nodes, trs, cls = _mk_net(2, cfg, "wedge-heal")
    try:
        s = Sub("w1")
        nodes[1].broker.subscribe(s, "wedge/+")
        _wait(lambda: nodes[0].router.has_dest("wedge/+", "hn1"),
              5, "route never replicated")
        # wedge ONLY hn1's inbound loop: hn0 keeps answering, so the
        # failure is asymmetric — exactly what EOF detection misses
        trs[0].fault_local = False
        faults.set_master(True)
        t0 = time.time()
        faults.arm("peer.wedge", times=0)
        try:
            # detector window: suspect_after(1) + down_after(3)
            # misses at interval 0.1s / timeout 0.5s ≈ 2s nominal
            _wait(lambda: cls[0].members == ["hn0"], 10,
                  "wedged peer never declared down")
            detect_s = time.time() - t0
            assert detect_s < 8.0, f"detection took {detect_s:.1f}s"
            assert trs[0].peer_state("hn1") == "down"
            # nodedown purged the wedged peer's routes (the legacy
            # contract, now reachable for wedged peers at all)
            _wait(lambda: not nodes[0].router.has_dest(
                "wedge/+", "hn1"), 5, "down peer's routes not purged")
        finally:
            faults.disarm("peer.wedge")
        # reappearance probe → auto-heal: members re-merge and
        # anti-entropy restores the purged routes, no manual rejoin
        _wait(lambda: sorted(cls[0].members) == ["hn0", "hn1"]
              and nodes[0].router.has_dest("wedge/+", "hn1"), 15,
              "auto-heal never reconverged the wedged peer")
        _wait(lambda: _converged(cls), 10,
              "digests did not converge after heal")
    finally:
        faults.clear()
        _teardown(trs, cls)


def test_transient_blip_suspect_parks_nothing_purged():
    """A link blip shorter than the down window only demotes the
    peer to suspect: membership and routes stay, casts park in the
    buffer, and recovery flushes them — nothing is purged on
    suspicion."""
    cfg = _fast_cfg(down_after=1000)  # suspect is a stable state
    nodes, trs, cls = _mk_net(2, cfg, "blip")
    try:
        s = Sub("b1")
        nodes[1].broker.subscribe(s, "blip/pre")
        _wait(lambda: nodes[0].router.has_dest("blip/pre", "hn1"), 5)
        _partition(trs, [0], [1])
        try:
            _wait(lambda: trs[0].peer_state("hn1") == "suspect", 10,
                  "blip never became suspect")
            # suspect ≠ dead: NOTHING is purged
            assert sorted(cls[0].members) == ["hn0", "hn1"]
            assert nodes[0].router.has_dest("blip/pre", "hn1")
            # a route added while suspect parks in the cast buffer
            s0 = Sub("b0")
            nodes[0].broker.subscribe(s0, "blip/during")
            time.sleep(0.3)
            assert not nodes[1].router.has_dest("blip/during", "hn0")
        finally:
            faults.disarm("net.partition")
        _wait(lambda: trs[0].peer_state("hn1") == "ok", 10,
              "suspect never recovered to ok")
        # recovery unparks the buffered cast: the route lands late,
        # not lost
        _wait(lambda: nodes[1].router.has_dest("blip/during", "hn0"),
              10, "parked cast never flushed after recovery")
        assert sorted(cls[0].members) == ["hn0", "hn1"]
    finally:
        faults.clear()
        _teardown(trs, cls)


def test_suspect_fast_fail_and_degraded_locker_quorum():
    """No broker path blocks ``call_timeout`` on a suspect peer:
    transport calls raise PeerUnavailableError without touching the
    wire, and the CM locker's quorum proceeds degraded (majority of
    the responsive membership) instead of stalling a CONNECT."""
    cfg = _fast_cfg(down_after=1000)
    nodes, trs, cls = _mk_net(2, cfg, "fastfail")
    try:
        _partition(trs, [0], [1])
        try:
            _wait(lambda: trs[0].peer_state("hn1") == "suspect", 10)
            t0 = time.time()
            with pytest.raises(PeerUnavailableError):
                trs[0].call("hn1", "ping")
            assert time.time() - t0 < 1.0, "fast-fail touched the wire"
            # locker: 1 of 2 votes is no full majority, but the only
            # non-voter is suspect — degraded grant, fast
            t0 = time.time()
            assert cls[0].locker.acquire("ff-client") is True
            elapsed = time.time() - t0
            assert elapsed < 2.0, \
                f"CONNECT-path lock blocked {elapsed:.1f}s on suspect"
            cls[0].locker.release_local("ff-client", "hn0")
            drained = cls[0].drain_counters()
            assert drained.get("locker.degraded", 0) >= 1
            assert drained.get("rpc.fastfail", 0) >= 1
            assert drained.get("hb.suspects", 0) >= 1
        finally:
            faults.disarm("net.partition")
    finally:
        faults.clear()
        _teardown(trs, cls)


def test_bounded_call_on_wedged_peer():
    """With the detector on, a call into a wedged peer is bounded by
    the per-peer deadline even while the peer still counts as ok —
    and the deadline cancels the coroutine, so the link lock is
    released (a second call doesn't inherit a wedged lock)."""
    cfg = _fast_cfg(heartbeat_interval_s=5.0, suspect_after=1000,
                    down_after=2000, call_timeout_s=1.0)
    nodes, trs, cls = _mk_net(2, cfg, "bounded")
    try:
        trs[0].fault_local = False
        faults.set_master(True)
        faults.arm("peer.wedge", times=0)
        try:
            for _ in range(2):  # second call pins the lock release
                t0 = time.time()
                with pytest.raises(ConnectionError):
                    trs[0].call("hn1", "ping")
                assert time.time() - t0 < 3.0
        finally:
            faults.disarm("peer.wedge")
    finally:
        faults.clear()
        _teardown(trs, cls)


# -- anti-entropy ----------------------------------------------------------


def test_net_drop_loss_repaired_by_anti_entropy():
    """net.drop discards a claimed cast burst as if sent — the
    at-most-once loss that silently diverged route tables forever
    pre-heal. The loss is counted, and one anti-entropy sync repairs
    it."""
    cfg = _fast_cfg(heartbeat_interval_s=1.0,
                    anti_entropy_interval_s=0)  # manual sync below
    nodes, trs, cls = _mk_net(2, cfg, "drop")
    try:
        trs[1].fault_peers = set()  # only hn0's outbound drops
        trs[0].fault_peers = {"hn1"}
        faults.set_master(True)
        faults.arm("net.drop", times=1)
        s = Sub("d0")
        nodes[0].broker.subscribe(s, "drop/lost")
        time.sleep(0.5)
        assert not nodes[1].router.has_dest("drop/lost", "hn0"), \
            "cast was not dropped — arm raced a call drain"
        drained = cls[0].drain_counters()
        assert drained.get("forward.dropped", 0) == 1
        repaired = cls[0].anti_entropy_sync("hn1")
        assert repaired >= 1
        assert nodes[1].router.has_dest("drop/lost", "hn0")
        assert _converged(cls)
        # a second sync on converged tables repairs nothing (one
        # digest round-trip, no entry transfer)
        assert cls[0].anti_entropy_sync("hn1") == 0
    finally:
        faults.clear()
        _teardown(trs, cls)


def test_net_delay_inflates_rtt_without_loss():
    """net.delay stalls frames to a peer, losing nothing: heartbeats
    keep succeeding (the peer stays ``ok`` — a slow link is NOT a
    partition) while the measured RTT inflates by ~delay_ms. This is
    the slow-WAN shape the detector must ride out without flapping,
    and the lag knob the repl.ship stall scenario leans on."""
    cfg = _fast_cfg(heartbeat_interval_s=0.1, heartbeat_timeout_s=2.0,
                    suspect_after=3)
    nodes, trs, cls = _mk_net(2, cfg, "dly")
    try:
        _wait(lambda: trs[0].health_info()
              .get("hn1", {}).get("rtt_ms") is not None,
              msg="no heartbeat RTT before arming")
        trs[1].fault_peers = set()   # only hn0's outbound is slow
        trs[0].fault_peers = {"hn1"}
        faults.set_master(True)
        faults.arm("net.delay", times=0, delay_ms=250.0)
        _wait(lambda: (trs[0].health_info()["hn1"]["rtt_ms"] or 0)
              >= 200.0, msg="delay never showed up in heartbeat RTT")
        assert trs[0].peer_state("hn1") == "ok", \
            "a slow link must not be declared suspect/down"
        faults.disarm("net.delay")
        _wait(lambda: (trs[0].health_info()["hn1"]["rtt_ms"] or 1e9)
              < 200.0, msg="RTT never recovered after disarm")
        assert trs[0].peer_state("hn1") == "ok"
    finally:
        faults.clear()
        _teardown(trs, cls)


def test_cast_buffer_full_drop_is_counted():
    """The cast-buffer-full shed (previously a log line only) counts
    into ``forward.dropped`` so at-most-once loss is observable."""
    tr = SocketTransport("solo", cookie="full")
    try:
        tr.serve()
        tr.register_peer("ghost", "127.0.0.1", 1)  # nothing listens
        tr._CAST_BUF_MAX = 64
        tr.cast("ghost", "forward", "f", "x" * 64)  # fills the buffer
        tr.cast("ghost", "forward", "f", "y")       # shed + counted
        assert tr.drain_counters().get("forward.dropped", 0) == 1
    finally:
        tr.close()


# -- the heal matrix -------------------------------------------------------


def _apply_phase1(nodes, cls, subs):
    """Pre-partition state on all five planes."""
    n0, n1, n2 = nodes
    subs["a"] = Sub("pa")
    n0.broker.subscribe(subs["a"], "heal/a/#")
    subs["b"] = Sub("pb")
    n1.broker.subscribe(subs["b"], "heal/b/+")
    subs["c"] = Sub("pc")
    n2.broker.subscribe(subs["c"], "heal/c")
    subs["s"] = Sub("ps")
    n2.broker.subscribe(subs["s"], "$share/g/heal/s")
    cls[1].client_up("c-base-1")
    cls[2].client_up("c-base-2")
    n2.broker.banned.create("clientid", "bad-guy", by="op",
                            reason="matrix")
    n0.broker.publish(Message(topic="keep/x", payload=b"v1",
                              flags={"retain": True}, timestamp=TS))


def _apply_phase2(nodes, cls, subs):
    """Route/registry/weight/ban/retained churn — run DURING the
    partition on the chaos cluster, partition-free on the oracle."""
    n0, n1, n2 = nodes
    # majority side mutates...
    subs["d"] = Sub("pd")
    n0.broker.subscribe(subs["d"], "heal/d/#")
    n1.broker.unsubscribe(subs["b"], "heal/b/+")  # stale-delete repair
    cls[0].client_up("c-major")
    n0.broker.banned.create("username", "evil", by="op", reason="p2")
    n0.broker.publish(Message(topic="keep/y", payload=b"v2",
                              flags={"retain": True},
                              timestamp=TS + 1))
    n0.broker.publish(Message(topic="keep/x", payload=b"",
                              flags={"retain": True},
                              timestamp=TS + 2))  # delete + tombstone
    # ...and so does the isolated minority side
    subs["e"] = Sub("pe")
    n2.broker.subscribe(subs["e"], "heal/e/+")
    subs["t"] = Sub("pt")
    n2.broker.subscribe(subs["t"], "$share/g2/heal/t")
    cls[2].client_up("c-minor")


def test_partition_heal_converges_all_planes_vs_oracle():
    """The headline chaos scenario: a 3-node cluster partitions
    {hn0,hn1} | {hn2} during churn on BOTH sides, heals, and every
    replicated plane (routes, registry, shared weights, bans,
    retained + tombstones) reconverges byte-exactly to what a
    never-partitioned oracle cluster computes for the same operation
    sequence — with zero manual rejoin."""
    cfg = _fast_cfg()
    nodes, trs, cls = _mk_net(3, cfg, "matrix", retainer=True)
    onodes, otrs, ocls = _mk_net(3, cfg, "oracle", retainer=True,
                                 immune=True)
    subs, osubs = {}, {}
    try:
        _apply_phase1(nodes, cls, subs)
        _apply_phase1(onodes, ocls, osubs)
        _wait(lambda: _converged(cls) and _converged(ocls), 20,
              "pre-partition state never converged")

        _partition(trs, [0, 1], [2])
        try:
            # both sides must actually observe the split
            _wait(lambda: cls[0].members == ["hn0", "hn1"]
                  and cls[2].members == ["hn2"], 15,
                  "partition never detected")
            _apply_phase2(nodes, cls, subs)
            _apply_phase2(onodes, ocls, osubs)
            time.sleep(0.5)  # let the split sides settle mid-churn
            # divergence is real: the isolated side is missing the
            # majority's churn and vice versa
            assert cls[0].plane_digests() != cls[2].plane_digests()
        finally:
            faults.disarm("net.partition")

        # zero manual rejoin: reappearance probes → auto-heal →
        # anti-entropy, background sweep mops up residual drift
        _wait(lambda: all(sorted(c.members) == ["hn0", "hn1", "hn2"]
                          for c in cls), 30,
              "membership never re-merged after heal")
        _wait(lambda: _converged(cls), 30,
              "plane digests never converged after heal")
        _wait(lambda: _converged(ocls), 20,
              "oracle cluster never converged")
        healed = cls[0].plane_digests()
        oracle = ocls[0].plane_digests()
        assert healed == oracle, (
            f"healed cluster != never-partitioned oracle:\n"
            f"  healed: {healed}\n  oracle: {oracle}")
        # spot-check semantics behind the digests: the tombstoned
        # topic is gone everywhere, the minority's routes are back
        for n in nodes:
            ret = n.modules._loaded["retainer"]
            assert "keep/x" not in ret._store
            assert "keep/y" in ret._store
            assert n.router.has_dest("heal/e/+", "hn2")
            assert not n.router.has_dest("heal/b/+", "hn1")
            assert n.broker.banned.look_up("username", "evil")
        # heal left its audit trail
        total = {}
        for c in cls:
            for k, v in c.drain_counters().items():
                total[k] = total.get(k, 0) + v
        assert total.get("heal.rejoins", 0) >= 1
        assert total.get("hb.downs", 0) >= 1
        assert total.get("hb.reappears", 0) >= 1
    finally:
        faults.clear()
        _teardown(trs, cls)
        _teardown(otrs, ocls)


# -- legacy parity ---------------------------------------------------------


def test_detector_off_is_legacy_build():
    """``detector = false`` (and no config at all) reproduce the
    EOF-only failure story: no heartbeat task, no heal worker, no
    suspect state, no fast-fail — and a wedged-but-connected peer is
    never declared down (the gap the detector exists to close)."""
    cfg = ClusterConfig(detector=False)
    nodes, trs, cls = _mk_net(2, cfg, "legacy")
    try:
        assert trs[0]._hb_enabled is False
        assert cls[0]._heal_thread is None
        assert trs[0].peer_state("hn1") == "ok"
        assert trs[0].health_info() == {}
        trs[0].fault_local = False
        faults.set_master(True)
        faults.arm("peer.wedge", times=0)
        try:
            time.sleep(1.5)
            # TCP is up, frames vanish — the legacy link monitor
            # sees nothing and membership never changes
            assert sorted(cls[0].members) == ["hn0", "hn1"]
        finally:
            faults.disarm("peer.wedge")
    finally:
        faults.clear()
        _teardown(trs, cls)


def test_no_config_transport_has_no_detector():
    tr = SocketTransport("lone", cookie="none")
    try:
        tr.serve()
        assert tr._hb_enabled is False
        assert tr.peer_state("whoever") == "ok"
    finally:
        tr.close()


# -- config + observability surfaces ---------------------------------------


def test_cluster_config_section_parses_and_validates():
    from emqx_tpu.config import ConfigError, parse_config

    cfg = parse_config({"cluster": {"detector": True,
                                    "heartbeat_interval_s": 0.5,
                                    "down_after": 7}})
    assert cfg.cluster.heartbeat_interval_s == 0.5
    assert cfg.cluster.down_after == 7
    with pytest.raises(ConfigError):
        parse_config({"cluster": {"heartbeat_intervall_s": 1.0}})
    with pytest.raises(ConfigError):
        parse_config({"cluster": {"suspect_after": 5, "down_after": 2}})
    with pytest.raises(ConfigError):
        parse_config({"cluster": {"detector": "yes"}})


def test_ctl_and_stats_surface_cluster_health():
    cfg = _fast_cfg(anti_entropy_interval_s=0)
    nodes, trs, cls = _mk_net(2, cfg, "obs")
    try:
        nodes[0].cluster = cls[0]
        _wait(lambda: trs[0].health_info().get("hn1", {})
              .get("rtt_ms") is not None, 10,
              "no heartbeat RTT recorded")
        import json

        out = json.loads(nodes[0].ctl.run(["cluster", "status"]))
        assert out["health"]["hn1"]["state"] == "ok"
        assert out["health"]["hn1"]["rtt_ms"] > 0
        assert "anti_entropy" in out
        # the stats tick publishes the gauges + folds the counters
        nodes[0].stats.tick()
        assert nodes[0].stats.getstat("cluster.members.count") == 2
        assert nodes[0].stats.getstat("cluster.member.state") == 0
        assert nodes[0].stats.getstat("cluster.hb.rtt_ms") > 0
        assert nodes[0].stats.getstat("cluster.member.hn1.state") == 0
        assert nodes[0].metrics.val("cluster.hb.suspects") == 0
    finally:
        faults.clear()
        _teardown(trs, cls)


def test_forward_drop_alarm_via_stats_tick():
    """cluster.forward.dropped raises the cluster_forward_dropped
    alarm on the tick that observes new drops and clears it on the
    first quiet tick."""
    cfg = _fast_cfg(anti_entropy_interval_s=0)
    nodes, trs, cls = _mk_net(2, cfg, "alarm")
    try:
        nodes[0].cluster = cls[0]
        trs[0]._count("forward.dropped", 3)
        nodes[0].stats.tick()
        active = {a.name for a in nodes[0].alarms.get_alarms("activated")}
        assert "cluster_forward_dropped" in active
        assert nodes[0].metrics.val("cluster.forward.dropped") == 3
        nodes[0].stats.tick()  # quiet tick clears
        active = {a.name for a in nodes[0].alarms.get_alarms("activated")}
        assert "cluster_forward_dropped" not in active
    finally:
        faults.clear()
        _teardown(trs, cls)
