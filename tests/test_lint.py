"""Self-tests for the static-analysis gate (scripts/analysis/,
docs/ANALYSIS.md).

Every rule is demonstrated twice on inline source fixtures: a
minimal bad example it must FIRE on, and the good twin it must stay
silent on — plus the pragma/waiver engine, and the full-tree gate
itself (which exercises the real legacy-path waivers: the loops=1
single-loop ingress fast paths, the Metrics single-writer mode).
"""

import os
import subprocess
import sys
import textwrap

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import analysis  # noqa: E402
from analysis import Context  # noqa: E402


def lint(src, path="emqx_tpu/example.py", ctx=None, rule=None):
    kept, suppressed = analysis.analyze_source(
        textwrap.dedent(src), path=path, ctx=ctx, rule=rule)
    return kept, suppressed


def rules_of(findings):
    return [f.rule for f in findings]


def has(findings, rule):
    return any(f.rule == rule for f in findings)


# -- core rules (the original linter, carried over) ------------------------

def test_core_rules_fire_on_bad_examples():
    kept, _ = lint("""\
        import os
        def f(x=[]):
            try:
                pass
            except:
                pass
            if x == None:
                assert (x, "oops")
        def f():
            pass
        """)
    for rule in ("F401", "B006", "E722", "E711", "F631", "F811"):
        assert has(kept, rule), (rule, kept)


def test_core_rules_silent_on_good_twin():
    kept, _ = lint("""\
        import os
        def f(x=None):
            try:
                pass
            except ValueError:
                pass
            if x is None:
                assert x, "oops"
            return os.sep
        """)
    assert kept == []


def test_f401_string_annotation_counts_as_use():
    # the old linter flagged imports used only in quoted annotations
    kept, _ = lint("""\
        from typing import TYPE_CHECKING
        if TYPE_CHECKING:
            from emqx_tpu.router import Router
        def f(r: "Router") -> "Router":
            return r
        """)
    assert kept == []


def test_f401_type_checking_block_is_checked():
    # ...and never looked inside TYPE_CHECKING blocks at all: a dead
    # typing import could rot there forever
    kept, _ = lint("""\
        from typing import TYPE_CHECKING
        if TYPE_CHECKING:
            from emqx_tpu.router import Router
        def f(x):
            return x
        """)
    assert has(kept, "F401")


def test_e999_syntax_error():
    kept, _ = lint("def f(:\n")
    assert rules_of(kept) == ["E999"]


# -- CD101: cross-domain call into a loop-only function --------------------

_CD101_BAD = """\
    from emqx_tpu.concurrency import bg_thread, owner_loop

    class C:
        @owner_loop
        def deliver(self):
            pass

        @bg_thread
        def worker(self):
            self.deliver()
    """

def test_cd101_fires_on_cross_domain_call():
    kept, _ = lint(_CD101_BAD)
    assert rules_of(kept) == ["CD101"]


def test_cd101_silent_when_marshaled_or_same_domain():
    kept, _ = lint("""\
        from emqx_tpu.concurrency import bg_thread, owner_loop

        class C:
            @owner_loop
            def deliver(self):
                pass

            @owner_loop
            def tail(self):
                self.deliver()     # loop -> loop: fine

            @bg_thread
            def worker(self, loop):
                # a reference handed to the bridge is NOT a call
                loop.call_soon_threadsafe(self.deliver)
        """)
    assert kept == []


def test_cd101_pragma_waives_with_reason():
    src = _CD101_BAD.replace(
        "self.deliver()",
        "self.deliver()  # lint: ok-CD101 shutdown fallback: loop gone")
    kept, suppressed = lint(src)
    assert kept == []
    assert rules_of(suppressed) == ["CD101"]


def test_cd101_ignores_unannotated_paths():
    # only annotated callers/callees are judged — scripts/tests and
    # unannotated emqx_tpu code never produce findings
    kept, _ = lint("""\
        class C:
            def deliver(self):
                pass
            def worker(self):
                self.deliver()
        """)
    assert kept == []


# -- CD102: shared-attribute writes outside the lock -----------------------

_CD102_BAD = """\
    import threading
    from emqx_tpu.concurrency import shared_state

    @shared_state(lock="_lock", attrs=("_buf",))
    class W:
        def __init__(self):
            self._lock = threading.Lock()
            self._buf = []

        def append(self, x):
            self._buf.append(x)
    """

def test_cd102_fires_on_unlocked_mutation():
    kept, _ = lint(_CD102_BAD)
    assert rules_of(kept) == ["CD102"]


def test_cd102_silent_under_lock_and_alias():
    kept, _ = lint("""\
        import threading
        from emqx_tpu.concurrency import shared_state

        @shared_state(lock="_lock", attrs=("_buf",))
        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._buf = []

            def append(self, x):
                with self._lock:
                    self._buf.append(x)

            def swap(self):
                lk = self._lock
                with lk:                    # the Metrics alias idiom
                    batch, self._buf = self._buf, []
                return batch

            def _drain_locked(self):
                # the _locked suffix: caller holds the lock
                self._buf.clear()
        """)
    assert kept == []


def test_cd102_init_exempt_and_pragma():
    src = _CD102_BAD.replace(
        "self._buf.append(x)",
        "self._buf.append(x)  # lint: ok-CD102 single-writer mode")
    kept, suppressed = lint(src)
    assert kept == []
    assert rules_of(suppressed) == ["CD102"]


# -- CD103/CD104: async misuse ---------------------------------------------

def test_cd103_unawaited_coroutine():
    kept, _ = lint("""\
        class C:
            async def flush(self):
                pass

            async def run(self):
                self.flush()
        """)
    assert rules_of(kept) == ["CD103"]


def test_cd103_silent_when_awaited():
    kept, _ = lint("""\
        class C:
            async def flush(self):
                pass

            async def run(self):
                await self.flush()
        """)
    assert kept == []


def test_cd104_dropped_create_task():
    kept, _ = lint("""\
        def go(loop, coro):
            loop.create_task(coro)
        """)
    assert rules_of(kept) == ["CD104"]


def test_cd104_silent_when_retained():
    kept, _ = lint("""\
        TASKS = set()

        def go(loop, coro):
            t = loop.create_task(coro)
            TASKS.add(t)
            t.add_done_callback(TASKS.discard)
        """)
    assert kept == []


# -- RD201..RD204: metrics / gauge registries ------------------------------

def _metrics_ctx():
    ctx = Context()
    ctx.metric_names = {"messages.received", "retained.count"}
    ctx.gauge_metrics = {"retained.count"}
    ctx.stats_keys = {"connections.count"}
    ctx.docs_observability = (
        "counters: `messages.*` and `retained.count` here")
    return ctx


def test_rd201_undeclared_metric_name():
    kept, _ = lint("""\
        def f(self):
            self.metrics.inc("messages.typo_counter")
        """, ctx=_metrics_ctx())
    assert "RD201" in rules_of(kept)


def test_rd202_undocumented_metric_and_glob_coverage():
    ctx = _metrics_ctx()
    ctx.metric_names.add("wal.appends")
    kept, _ = lint("""\
        def f(self):
            self.metrics.inc("wal.appends")      # not in docs
            self.metrics.inc("messages.received")  # glob-covered
        """, ctx=ctx)
    assert rules_of(kept) == ["RD202"]
    assert kept[0].line == 2


def test_rd203_dec_outside_gauge_metrics():
    kept, _ = lint("""\
        def f(self):
            self.metrics.dec("messages.received")
            self.metrics.dec("retained.count")    # audited gauge: ok
        """, ctx=_metrics_ctx())
    assert rules_of(kept) == ["RD203"]


def test_rd204_unregistered_stats_gauge():
    kept, _ = lint("""\
        def f(stats):
            stats.setstat("connections.count", 1)
            stats.setstat("mystery.gauge", 2)
        """, ctx=_metrics_ctx())
    assert rules_of(kept) == ["RD204"]


def test_metrics_rules_skip_dynamic_names_and_foreign_receivers():
    kept, _ = lint("""\
        def f(self, key):
            self.metrics.inc(f"cluster.{key}")   # dynamic: skipped
            self._gc.inc(1, 2)                   # not a Metrics
        """, ctx=_metrics_ctx())
    assert kept == []


# -- RD211..RD214: fault-point catalog -------------------------------------

def _faults_ctx():
    ctx = Context()
    ctx.fault_points = {"device.walk": 10, "net.delay": 20}
    ctx.docs_robustness = "| `device.walk` | site | raise | sim |"
    ctx.tests_text = 'faults.arm("device.walk")'
    return ctx


def test_rd211_fire_site_outside_catalog():
    kept, _ = lint("""\
        from emqx_tpu import faults

        def f():
            if faults.enabled:
                faults.fire("device.typo")
        """, ctx=_faults_ctx())
    assert "RD211" in rules_of(kept)


def test_rd212_213_214_catalog_cross_checks():
    ctx = _faults_ctx()
    # device.walk: fired, documented, tested. net.delay: fired but
    # neither documented nor tested -> RD212 + RD213
    kept, _ = lint("""\
        from emqx_tpu import faults as _faults

        def f():
            _faults.fire("device.walk")
            _faults.fire("net.delay")
        """, ctx=ctx)
    assert sorted(rules_of(kept)) == ["RD212", "RD213"]
    # an unfired catalog point -> RD214 (plus its doc/test gaps)
    ctx2 = _faults_ctx()
    kept2, _ = lint("""\
        from emqx_tpu import faults

        def f():
            faults.fire("device.walk")
        """, ctx=ctx2)
    assert "RD214" in rules_of(kept2)
    assert all(f.rule in ("RD212", "RD213", "RD214")
               for f in kept2)


# -- RD221/RD222: closed-schema config vs example toml ---------------------

def _config_ctx():
    ctx = Context()
    ctx.schema = {"durability": {
        "enabled": ("emqx_tpu/durability.py", 5),
        "fsync": ("emqx_tpu/durability.py", 6),
    }}
    ctx.toml_keys = {"durability": {"enabled": 171, "wal_shardz": 191}}
    return ctx


def test_rd221_schema_key_missing_from_toml():
    kept, _ = lint("x = 1\n", ctx=_config_ctx())
    assert "RD221" in rules_of(kept)
    f = next(f for f in kept if f.rule == "RD221")
    assert "fsync" in f.msg


def test_rd222_toml_key_unknown_to_schema():
    kept, _ = lint("x = 1\n", ctx=_config_ctx())
    assert "RD222" in rules_of(kept)
    f = next(f for f in kept if f.rule == "RD222")
    assert "wal_shardz" in f.msg


def test_config_clean_when_in_lockstep():
    ctx = _config_ctx()
    ctx.toml_keys = {"durability": {"enabled": 1, "fsync": 2}}
    kept, _ = lint("x = 1\n", ctx=ctx)
    assert kept == []


def test_toml_loader_reads_commented_defaults_and_skips_prose():
    ctx = Context()
    import tempfile
    from pathlib import Path
    with tempfile.TemporaryDirectory() as d:
        p = Path(d) / "ex.toml"
        p.write_text(textwrap.dedent("""\
            [durability]
            enabled = false
            # fsync = true
            # `false` restores the legacy path byte-for-byte.
            # false = legacy per-delivery walk prose
            """))
        ctx.root = Path(d)
        ctx.toml_path = "ex.toml"
        from analysis import config_drift
        config_drift.load_toml(ctx)
    assert set(ctx.toml_keys["durability"]) == {"enabled", "fsync"}


# -- RD231/RD232: telemetry stages -----------------------------------------

def _stages_ctx():
    ctx = Context()
    ctx.stages = ("match", "fetch")
    ctx.stages_loc = ("emqx_tpu/telemetry.py", 104)
    return ctx


def test_rd231_unknown_stage_observed():
    kept, _ = lint("""\
        def f(pb, t0):
            pb.span.add("fetchh", t0)
        """, ctx=_stages_ctx())
    assert "RD231" in rules_of(kept)


def test_rd232_stage_with_no_observe_site():
    kept, _ = lint("""\
        def f(pb, t0):
            pb.span.add("match", t0)
        """, ctx=_stages_ctx())
    assert "RD232" in rules_of(kept)
    assert "fetch" in [f.msg for f in kept
                       if f.rule == "RD232"][0]


def test_stage_rules_ignore_set_add_and_cover_all_sites():
    ctx = _stages_ctx()
    kept, _ = lint("""\
        def f(pb, tel, seen, t0, ms):
            seen.add("not-a-stage")        # a set, not a span
            pb.span.add("match", t0)
            tel.observe_stage("fetch", ms)
        """, ctx=ctx)
    assert kept == []


# -- DP301: device purity in ops/ ------------------------------------------

def test_dp301_fires_on_host_sync_constructs():
    kept, _ = lint("""\
        import jax
        import jax.numpy as jnp

        def walk(x):
            a = x.sum().item()
            b = jax.device_get(x)
            c = float(jnp.max(x))
            x.block_until_ready()
            return a, b, c
        """, path="emqx_tpu/ops/kernel.py")
    assert rules_of(kept) == ["DP301"] * 4


def test_dp301_scoped_to_ops_and_whitelisted_seams():
    src = """\
        import jax

        def fetch_seam(x):
            return jax.device_get(x)
        """
    # outside ops/: not judged
    kept, _ = lint(src, path="emqx_tpu/broker.py")
    assert kept == []
    # inside ops/ but whitelisted as a fetch seam
    ctx = Context()
    ctx.device_whitelist = {"fetch_seam"}
    kept, _ = lint(src, path="emqx_tpu/ops/kernel.py", ctx=ctx)
    assert kept == []


def test_dp301_silent_on_numpy_host_math():
    kept, _ = lint("""\
        import numpy as np

        def plan(counts):
            return int(counts.sum()) + int(np.max(counts))
        """, path="emqx_tpu/ops/plan.py")
    assert kept == []


# -- pragma engine ---------------------------------------------------------

def test_lnt001_pragma_without_reason():
    kept, _ = lint("""\
        def f(x=[]):  # lint: ok-B006
            return x
        """)
    assert "LNT001" in rules_of(kept)
    # the unwaived finding still reports
    assert "B006" in rules_of(kept)


def test_lnt002_stale_pragma():
    kept, _ = lint("""\
        def f(x=None):  # lint: ok-B006 not mutable anymore
            return x
        """)
    assert rules_of(kept) == ["LNT002"]


def test_pragma_on_preceding_comment_line():
    kept, suppressed = lint("""\
        def f(
            # lint: ok-B006 fixture default, never mutated
            x=[],
        ):
            return x
        """)
    assert kept == []
    assert rules_of(suppressed) == ["B006"]


def test_pragma_multi_rule_and_docstring_immunity():
    kept, suppressed = lint('''\
        """Docs may quote `# lint: ok-CD102 reason` without waiving."""

        def f(x=[]):  # lint: ok-B006,F811 fixture default
            return x
        ''')
    assert kept == []
    assert rules_of(suppressed) == ["B006"]


def test_single_rule_mode_disables_stale_check():
    kept, _ = lint("""\
        def f(x=None):  # lint: ok-B006 would be stale in full runs
            return x
        """, rule="E711")
    assert kept == []


# -- the real tree ---------------------------------------------------------

@pytest.mark.slow
def test_full_tree_gate_is_clean():
    """The whole repo passes its own gate — including the legacy-path
    waivers (single-loop ingress fast paths, Metrics single-writer
    mode) staying live, reasoned, and non-stale."""
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "lint.py")],
        cwd=ROOT, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr


def test_rule_catalog_is_complete_and_documented():
    rules = analysis.all_rules()
    for rid in ("F401", "CD101", "CD102", "CD103", "CD104", "RD201",
                "RD211", "RD221", "RD231", "DP301", "LNT001",
                "LNT002"):
        assert rid in rules
    doc = open(os.path.join(ROOT, "docs", "ANALYSIS.md")).read()
    for rid in rules:
        assert rid in doc, f"rule {rid} missing from docs/ANALYSIS.md"
