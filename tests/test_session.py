"""Session-layer tests — modeled on reference emqx_session_SUITE,
emqx_mqueue_SUITE, emqx_inflight_SUITE, emqx_pqueue_SUITE."""

import pytest

from emqx_tpu.broker import Broker
from emqx_tpu.inflight import Inflight, KeyExists
from emqx_tpu.mqueue import MQueue
from emqx_tpu.pqueue import PQueue
from emqx_tpu.session import (
    PUBREL_MARKER, RC_PACKET_IDENTIFIER_IN_USE,
    RC_PACKET_IDENTIFIER_NOT_FOUND, RC_RECEIVE_MAXIMUM_EXCEEDED,
    RC_QUOTA_EXCEEDED, Session, SessionError)
from emqx_tpu.types import Message, SubOpts


def _m(topic="t", qos=1, **kw):
    return Message(topic=topic, qos=qos, **kw)


# -- pqueue ----------------------------------------------------------------

def test_pqueue_fifo_and_priority():
    q = PQueue()
    q.push("a")
    q.push("b")
    q.push("hi", priority=10)
    assert q.pop() == (True, "hi")
    assert q.pop() == (True, "a")
    assert q.pop() == (True, "b")
    assert q.pop() == (False, None)


def test_pqueue_plen():
    q = PQueue()
    q.push("a", 1)
    q.push("b", 1)
    q.push("c", 2)
    assert q.plen(1) == 2 and q.plen(2) == 1 and q.plen(3) == 0
    assert len(q) == 3


# -- inflight --------------------------------------------------------------

def test_inflight_basic():
    inf = Inflight(max_size=2)
    inf.insert(1, "a")
    with pytest.raises(KeyExists):
        inf.insert(1, "dup")
    inf.insert(2, "b")
    assert inf.is_full()
    inf.update(2, "b2")
    assert inf.lookup(2) == "b2"
    inf.delete(1)
    assert not inf.is_full()
    assert inf.keys() == [2]


# -- mqueue ----------------------------------------------------------------

def test_mqueue_qos0_dropped_unless_stored():
    q = MQueue(max_len=10, store_qos0=False)
    dropped = q.push(_m(qos=0))
    assert dropped is not None and len(q) == 0
    q2 = MQueue(max_len=10, store_qos0=True)
    assert q2.push(_m(qos=0)) is None and len(q2) == 1


def test_mqueue_drop_oldest_when_full():
    q = MQueue(max_len=2)
    m1, m2, m3 = _m(payload=b"1"), _m(payload=b"2"), _m(payload=b"3")
    assert q.push(m1) is None
    assert q.push(m2) is None
    dropped = q.push(m3)
    assert dropped is m1  # oldest of the class dropped
    assert q.dropped == 1
    assert q.pop() is m2
    assert q.pop() is m3


def test_mqueue_priorities():
    q = MQueue(max_len=10, priorities={"hi": 5}, default_priority=0)
    q.push(_m(topic="lo"))
    q.push(_m(topic="hi"))
    assert q.pop().topic == "hi"
    assert q.pop().topic == "lo"


def test_mqueue_unbounded():
    q = MQueue(max_len=0)
    for i in range(5000):
        assert q.push(_m()) is None
    assert len(q) == 5000


# -- session QoS flows -----------------------------------------------------

def test_qos1_flow():
    b = Broker()
    s = Session("c1", broker=b)
    s.subscribe("t", SubOpts(qos=1))
    b.publish(_m(qos=1))
    [(pid, msg)] = s.drain_outbox()
    assert pid == 1 and msg.qos == 1
    assert s.puback(pid).id == msg.id
    assert len(s.inflight) == 0
    with pytest.raises(SessionError) as e:
        s.puback(pid)
    assert e.value.rc == RC_PACKET_IDENTIFIER_NOT_FOUND


def test_qos2_outbound_flow():
    b = Broker()
    s = Session("c1", broker=b)
    s.subscribe("t", SubOpts(qos=2))
    b.publish(_m(qos=2))
    [(pid, _msg)] = s.drain_outbox()
    s.pubrec(pid)
    with pytest.raises(SessionError) as e:
        s.pubrec(pid)  # second pubrec: already pubrel state
    assert e.value.rc == RC_PACKET_IDENTIFIER_IN_USE
    with pytest.raises(SessionError):
        s.puback(pid)
    s.pubcomp(pid)
    assert len(s.inflight) == 0


def test_qos2_inbound_awaiting_rel():
    b = Broker()
    s = Session("c1", broker=b, max_awaiting_rel=2)
    s.publish(10, _m(qos=2))
    with pytest.raises(SessionError) as e:
        s.publish(10, _m(qos=2))  # duplicate packet id
    assert e.value.rc == RC_PACKET_IDENTIFIER_IN_USE
    s.publish(11, _m(qos=2))
    with pytest.raises(SessionError) as e:
        s.publish(12, _m(qos=2))  # window full
    assert e.value.rc == RC_RECEIVE_MAXIMUM_EXCEEDED
    s.pubrel(10)
    with pytest.raises(SessionError):
        s.pubrel(10)
    s.publish(12, _m(qos=2))


def test_qos_downgrade_and_upgrade():
    b = Broker()
    s = Session("c1", broker=b)
    s.subscribe("t", SubOpts(qos=0))
    b.publish(_m(qos=2))
    [(pid, msg)] = s.drain_outbox()
    assert pid is None and msg.qos == 0  # min(sub 0, pub 2)
    up = Session("c2", broker=b, upgrade_qos=True)
    up.subscribe("t", SubOpts(qos=2))
    b.publish(_m(qos=0))
    [(pid2, msg2)] = up.drain_outbox()
    assert msg2.qos == 2 and pid2 == 1


def test_inflight_full_overflows_to_mqueue_then_dequeues():
    b = Broker()
    s = Session("c1", broker=b, max_inflight=2, max_mqueue_len=10)
    s.subscribe("t", SubOpts(qos=1))
    for _ in range(5):
        b.publish(_m(qos=1))
    sent = s.drain_outbox()
    assert len(sent) == 2
    assert len(s.mqueue) == 3
    s.puback(sent[0][0])
    [(pid3, _)] = s.drain_outbox()  # dequeue refills the window
    assert len(s.mqueue) == 2
    assert pid3 == 3


def test_retry_sets_dup_and_reemits():
    b = Broker()
    s = Session("c1", broker=b, retry_interval=0.0)
    s.subscribe("t", SubOpts(qos=1))
    b.publish(_m(qos=1))
    [(pid, msg)] = s.drain_outbox()
    s.retry()
    [(pid2, msg2)] = s.drain_outbox()
    assert pid2 == pid and msg2.get_flag("dup")


def test_retry_pubrel():
    b = Broker()
    s = Session("c1", broker=b, retry_interval=0.0)
    s.subscribe("t", SubOpts(qos=2))
    b.publish(_m(qos=2))
    [(pid, _)] = s.drain_outbox()
    s.pubrec(pid)
    s.retry()
    assert s.drain_outbox() == [(PUBREL_MARKER, pid)]


def test_awaiting_rel_expiry():
    b = Broker()
    s = Session("c1", broker=b, await_rel_timeout=0.0)
    s.publish(5, _m(qos=2))
    s.expire_awaiting_rel()
    assert s.awaiting_rel == {}
    assert b.metrics.val("messages.dropped.expired") == 1


def test_max_subscriptions_quota():
    b = Broker()
    s = Session("c1", broker=b, max_subscriptions=1)
    s.subscribe("a")
    with pytest.raises(SessionError) as e:
        s.subscribe("b")
    assert e.value.rc == RC_QUOTA_EXCEEDED
    s.subscribe("a", SubOpts(qos=1))  # resubscribe ok


def test_takeover_resume_replay():
    b = Broker()
    s = Session("c1", broker=b, max_inflight=4)
    s.subscribe("t", SubOpts(qos=1))
    b.publish(_m(qos=1, payload=b"x"))
    [(pid, _)] = s.drain_outbox()
    # old connection dies; session taken over
    s.takeover()
    assert b.publish(_m(qos=1)) == 0  # detached
    s.resume(b)
    assert b.publish(_m(qos=1, payload=b"y")) == 1
    s.drain_outbox()
    s.replay()
    replayed = s.drain_outbox()
    assert any(p == pid and m.get_flag("dup") for p, m in replayed
               if p != PUBREL_MARKER)


def test_packet_id_wraps_and_skips_live():
    b = Broker()
    s = Session("c1", broker=b, max_inflight=3)
    s.next_pkt_id = 0xFFFF
    s.subscribe("t", SubOpts(qos=1))
    b.publish(_m(qos=1))
    b.publish(_m(qos=1))
    pids = [p for p, _ in s.drain_outbox()]
    assert pids == [0xFFFF, 1]


def test_shared_delivery_enriched():
    b = Broker()
    s = Session("c1", broker=b)
    s.subscribe("$share/g/t", SubOpts(qos=1))
    b.publish(_m(qos=1))
    [(pid, msg)] = s.drain_outbox()
    assert pid == 1 and msg.qos == 1


def test_share_suffix_map_replaces_linear_scan():
    # _enrich resolves shared subopts via the reverse share-suffix
    # map (one dict fetch), not a scan over every subscription
    s = Session("c1")
    for i in range(50):
        s.subscriptions[f"noise/{i}"] = SubOpts()
    s.subscribe("$share/g/a/b", SubOpts(qos=2, subid=7))
    s.subscribe("$queue/q/only", SubOpts(qos=1))
    assert s._share_keys == {"a/b": "$share/g/a/b",
                             "q/only": "$queue/q/only"}
    m = s._enrich("a/b", _m(topic="a/b", qos=2))
    assert m.qos == 2
    assert m.get_header("properties")["Subscription-Identifier"] == 7
    m = s._enrich("q/only", _m(topic="q/only", qos=1))
    assert m.qos == 1


def test_share_suffix_map_collision_first_wins_then_falls_back():
    s = Session("c1")
    s.subscribe("$share/g1/t/x", SubOpts(qos=1))
    s.subscribe("$share/g2/t/x", SubOpts(qos=2))
    # first subscription wins, matching the old scan's insertion-
    # order pick
    assert s._share_keys["t/x"] == "$share/g1/t/x"
    assert s._enrich("t/x", _m(topic="t/x", qos=2)).qos == 1
    s.unsubscribe("$share/g1/t/x")
    # the surviving group takes over the bare filter
    assert s._share_keys["t/x"] == "$share/g2/t/x"
    assert s._enrich("t/x", _m(topic="t/x", qos=2)).qos == 2
    s.unsubscribe("$share/g2/t/x")
    assert s._share_keys == {}


def test_share_suffix_map_survives_wire_roundtrip():
    from emqx_tpu.session import Session as S

    s = Session("c1")
    s.subscribe("$share/g/w/t", SubOpts(qos=1))
    s2 = S.from_wire(s.to_wire())
    assert s2._share_keys == {"w/t": "$share/g/w/t"}
    assert s2._enrich("w/t", _m(topic="w/t", qos=1)).qos == 1
