"""Cluster tests: route replication, cross-node forwarding,
node-down cleanup — single-process multi-node over the LocalTransport
seam (the reference's fake-remote-node strategy, SURVEY §4)."""

from emqx_tpu.cluster import Cluster, LocalTransport
from emqx_tpu.node import Node
from emqx_tpu.types import Message


class Q:
    def __init__(self, cid="q"):
        self.client_id = cid
        self.inbox = []

    def deliver(self, t, m):
        self.inbox.append((t, m))


def _mk_cluster(n=2):
    transport = LocalTransport()
    nodes = [Node(name=f"n{i}", boot_listeners=False) for i in range(n)]
    clusters = [Cluster(node, transport) for node in nodes]
    for c in clusters[1:]:
        clusters[0].join(c)
        for other in clusters[1:]:
            if other is not c:
                c.join(other)
    return nodes, clusters


def test_route_replication():
    (n0, n1), _ = _mk_cluster(2)
    s = Q()
    n0.broker.subscribe(s, "rep/+")
    # the route is visible from both nodes
    assert n0.router.has_route("rep/+")
    assert n1.router.has_route("rep/+")
    assert [r.dest for r in n1.router.match_routes("rep/x")] == ["n0"]
    n0.broker.unsubscribe(s, "rep/+")
    assert not n1.router.has_route("rep/+")


def test_cross_node_forwarding():
    (n0, n1), _ = _mk_cluster(2)
    s0, s1 = Q("on0"), Q("on1")
    n0.broker.subscribe(s0, "t/#")
    n1.broker.subscribe(s1, "t/#")
    # publish at n1: local dispatch + one forward to n0
    delivered = n1.broker.publish(Message(topic="t/1", payload=b"x"))
    assert delivered == 1  # local count (remote async)
    assert len(s1.inbox) == 1
    assert len(s0.inbox) == 1
    assert s0.inbox[0][1].payload == b"x"


def test_forward_count_is_per_filter_node():
    (n0, n1), _ = _mk_cluster(2)
    s0 = Q()
    n0.broker.subscribe(s0, "a/#")
    n0.broker.subscribe(s0, "a/b")
    n1.broker.publish(Message(topic="a/b"))
    # two matched filters, both routed to n0 → two dispatches
    assert len(s0.inbox) == 2
    assert n1.metrics.val("messages.forward") == 2


def test_shared_sub_across_nodes():
    (n0, n1), _ = _mk_cluster(2)
    s0 = Q("w0")
    n0.broker.subscribe(s0, "$share/g/jobs")
    # publish on the other node: group route forwards to n0
    n1.broker.publish(Message(topic="jobs", payload=b"j"))
    assert len(s0.inbox) == 1


def test_shared_group_spanning_nodes_delivers_once():
    """One delivery per group cluster-wide, even with members on
    multiple nodes (the reference's shared-dispatch contract)."""
    (n0, n1), _ = _mk_cluster(2)
    s0, s1 = Q("w0"), Q("w1")
    n0.broker.subscribe(s0, "$share/g/jobs")
    n1.broker.subscribe(s1, "$share/g/jobs")
    for _ in range(6):
        n1.broker.publish(Message(topic="jobs"))
    total = len(s0.inbox) + len(s1.inbox)
    assert total == 6
    # round-robin over nodes: both sides got some
    assert len(s0.inbox) == 3 and len(s1.inbox) == 3


def test_join_is_transitive():
    transport = LocalTransport()
    a, b, c = (Node(name=x, boot_listeners=False) for x in "abc")
    ca, cb, cc = (Cluster(n, transport) for n in (a, b, c))
    cb.join(cc)                     # {b, c}
    s = Q()
    c.broker.subscribe(s, "t/2")    # route exists on b and c
    ca.join(cb)                     # a joins {b, c} via b
    assert sorted(cc.members) == ["a", "b", "c"]
    assert sorted(ca.members) == ["a", "b", "c"]
    assert a.router.has_route("t/2")  # pre-existing route synced to a
    a.broker.publish(Message(topic="t/2"))
    assert len(s.inbox) == 1
    # and future routes reach a too
    s2 = Q()
    c.broker.subscribe(s2, "t/3")
    assert a.router.has_route("t/3")


def test_leave_purges_both_directions():
    (n0, n1), (c0, c1) = _mk_cluster(2)
    s0, s1 = Q(), Q()
    n0.broker.subscribe(s0, "mine/#")
    n1.broker.subscribe(s1, "theirs/#")
    c0.leave()
    # leaver's routes purged on the remaining node
    assert not n1.router.has_route("mine/#")
    # remaining node's routes purged on the leaver
    assert not n0.router.has_route("theirs/#")
    n0.broker.publish(Message(topic="theirs/x"))
    assert s1.inbox == []


def test_refcounted_local_subs_replicate_once():
    """Two local subscribers on one filter = one replicated route;
    unsubscribing one must NOT delete the peer's copy."""
    (n0, n1), _ = _mk_cluster(2)
    s1, s2 = Q("a"), Q("b")
    n0.broker.subscribe(s1, "rc/t")
    n0.broker.subscribe(s2, "rc/t")
    assert n1.router.has_route("rc/t")
    n0.broker.unsubscribe(s1, "rc/t")
    assert n1.router.has_route("rc/t")  # still one local subscriber
    n0.broker.unsubscribe(s2, "rc/t")
    assert not n1.router.has_route("rc/t")


def test_tracer_isolated_between_nodes():
    (n0, n1), _ = _mk_cluster(2)
    sink = n0.tracer.start_trace("topic", "x/#")
    n1.broker.publish(Message(topic="x/1"))
    assert sink == []  # other node's traffic must not bleed in
    n0.broker.publish(Message(topic="x/1"))
    assert len(sink) == 1
    n0.tracer.stop_trace("topic", "x/#")


def test_nodedown_cleanup():
    (n0, n1), (c0, c1) = _mk_cluster(2)
    s0 = Q()
    n0.broker.subscribe(s0, "gone/+")
    assert n1.router.has_route("gone/+")
    c1.handle_nodedown("n0")
    assert not n1.router.has_route("gone/+")
    assert n1.broker.publish(Message(topic="gone/x")) == 0
    assert "n0" not in c1.members


def test_leave_broadcasts_nodedown():
    (n0, n1), (c0, c1) = _mk_cluster(2)
    s0 = Q()
    n0.broker.subscribe(s0, "bye/#")
    c0.leave()
    assert not n1.router.has_route("bye/#")


def test_three_node_replication():
    (n0, n1, n2), _ = _mk_cluster(3)
    s = Q()
    n2.broker.subscribe(s, "three/+")
    assert n0.router.has_route("three/+")
    assert n1.router.has_route("three/+")
    n0.broker.publish(Message(topic="three/x"))
    assert len(s.inbox) == 1


def test_join_syncs_existing_routes():
    transport = LocalTransport()
    a = Node(name="a", boot_listeners=False)
    b = Node(name="b", boot_listeners=False)
    ca, cb = Cluster(a, transport), Cluster(b, transport)
    s = Q()
    a.broker.subscribe(s, "pre/existing")  # before join
    ca.join(cb)
    assert b.router.has_route("pre/existing")
    b.broker.publish(Message(topic="pre/existing"))
    assert len(s.inbox) == 1


# -- cluster clientid registry + cross-node takeover ------------------------

def test_registry_replicates_client_location():
    nodes, clusters = _mk_cluster(2)
    n0, n1 = nodes
    sess, present = n0.cm.open_session("c1", True, channel=object())
    assert not present
    assert clusters[0].locate_client("c1") == "n0"
    assert clusters[1].locate_client("c1") == "n0"


def test_cross_node_takeover_moves_session_and_subs():
    nodes, clusters = _mk_cluster(2)
    n0, n1 = nodes
    chan0 = object()
    sess, _ = n0.cm.open_session("mv", True, channel=chan0,
                                 expiry_interval=300)
    from emqx_tpu.types import SubOpts
    sess.subscribe("mv/t", SubOpts(qos=1))
    # detach on n0 (persistent session stays there)
    n0.cm.connection_closed("mv", chan0, sess, 300)
    # publish while away queues into the detached session via n0
    # (qos1: offline qos0 is dropped by default, like the reference)
    n0.broker.publish(Message(topic="mv/t", payload=b"away", qos=1))
    # reconnect on the OTHER node with clean_start=False
    sess2, present = n1.cm.open_session("mv", False, channel=object())
    assert present and sess2 is sess
    assert "mv/t" in sess2.subscriptions
    assert clusters[0].locate_client("mv") == "n1"
    assert clusters[1].locate_client("mv") == "n1"
    # n0 no longer holds the subscriber; n1's broker delivers now
    assert sess2 not in n0.broker.subscribers("mv/t")
    assert sess2 in n1.broker.subscribers("mv/t")
    # the while-away message survived the move (mqueue)
    sess2.replay()
    payloads = [m.payload for pid, m in sess2.drain_outbox()
                if hasattr(m, "payload")]
    assert b"away" in payloads


def test_cross_node_clean_start_discards_remote_session():
    nodes, clusters = _mk_cluster(2)
    n0, n1 = nodes
    chan0 = object()
    sess, _ = n0.cm.open_session("cs", True, channel=chan0,
                                 expiry_interval=300)
    sess.subscribe("cs/t", None)
    n0.cm.connection_closed("cs", chan0, sess, 300)
    assert n0.cm.session_count() == 1
    sess2, present = n1.cm.open_session("cs", True, channel=object())
    assert not present and sess2 is not sess
    # old detached session was discarded on n0
    assert "cs" not in n0.cm._detached
    assert clusters[1].locate_client("cs") == "n1"


def test_nodedown_purges_registry():
    nodes, clusters = _mk_cluster(2)
    n0, n1 = nodes
    n0.cm.open_session("gone", True, channel=object())
    assert clusters[1].locate_client("gone") == "n0"
    clusters[1].handle_nodedown("n0")
    assert clusters[1].locate_client("gone") is None


def test_shared_group_weighted_by_member_count():
    """A node with 3 members gets 3x the deliveries of a node with 1
    (the reference picks over the replicated member table,
    src/emqx_shared_sub.erl:229-244 — node-level uniform round-robin
    would skew per-member load 3:1 the other way)."""
    (n0, n1), _ = _mk_cluster(2)
    heavy = [Q(f"h{i}") for i in range(3)]
    for s in heavy:
        n0.broker.subscribe(s, "$share/g/work")
    light = Q("l0")
    n1.broker.subscribe(light, "$share/g/work")
    for _ in range(40):
        n1.broker.publish(Message(topic="work"))
    n0_total = sum(len(s.inbox) for s in heavy)
    assert n0_total + len(light.inbox) == 40
    assert n0_total == 30, (n0_total, len(light.inbox))  # 3:1 split
    # and within n0 the local strategy spreads over its members
    assert all(len(s.inbox) == 10 for s in heavy)


def test_shared_weight_updates_on_unsubscribe():
    (n0, n1), _ = _mk_cluster(2)
    a, b = Q("a"), Q("b")
    n0.broker.subscribe(a, "$share/g/w2")
    n0.broker.subscribe(b, "$share/g/w2")
    c = Q("c")
    n1.broker.subscribe(c, "$share/g/w2")
    n0.broker.unsubscribe(b, "$share/g/w2")
    for _ in range(10):
        n1.broker.publish(Message(topic="w2"))
    # 1:1 after the unsubscribe dropped n0's weight to 1
    assert len(a.inbox) == 5 and len(c.inbox) == 5, \
        (len(a.inbox), len(b.inbox), len(c.inbox))


def test_ban_replication_cluster_wide():
    """A ban created on one node rejects connections on every node
    (the reference's emqx_banned is a replicated Mnesia table); the
    delete lifts it everywhere; a new joiner receives the table."""
    (n0, n1), (c0, c1) = _mk_cluster(2)
    n0.broker.banned.create("clientid", "evil", duration=600)
    assert n1.broker.banned.check(clientid="evil")
    n1.broker.banned.delete("clientid", "evil")
    assert not n0.broker.banned.check(clientid="evil")
    # join sync: a third node learns existing bans
    n0.broker.banned.create("peerhost", "10.0.0.9")
    n2 = Node(name="n2", boot_listeners=False)
    c2 = Cluster(n2, c0.transport)
    c0.join(c2)
    assert n2.broker.banned.check(peerhost="10.0.0.9")


def test_ban_merge_longer_ban_wins():
    """Join-sync must never let a stale short ban clobber a permanent
    one (apply() merges longest-wins; expired rules never install)."""
    import time as _t

    from emqx_tpu.banned import Banned

    b = Banned()
    b.create("clientid", "x")          # permanent
    b.apply("clientid", "x", "peer", "", _t.time() + 5)  # shorter
    assert b.look_up("clientid", "x").until is None  # permanent kept
    b.apply("clientid", "x", "peer", "", _t.time() - 5)  # expired
    assert b.look_up("clientid", "x").until is None
    b2 = Banned()
    b2.create("clientid", "y", duration=5)
    b2.apply("clientid", "y", "peer", "", None)  # longer (forever)
    assert b2.look_up("clientid", "y").until is None  # upgraded


def test_live_ban_create_overwrites_cluster_wide():
    """A live create must replace the rule EVERYWHERE (an operator
    shortening a permanent ban wins), while join-sync merges; mixed
    semantics would leave the tables permanently divergent."""
    (n0, n1), _ = _mk_cluster(2)
    n0.broker.banned.create("clientid", "z")            # permanent
    assert n1.broker.banned.look_up("clientid", "z").until is None
    n1.broker.banned.create("clientid", "z", duration=60)  # shorten
    r0 = n0.broker.banned.look_up("clientid", "z")
    r1 = n1.broker.banned.look_up("clientid", "z")
    assert r0.until is not None and r1.until is not None
    assert abs(r0.until - r1.until) < 1.0  # convergent


def test_flapping_ban_never_downgrades_operator_ban():
    """A flapping auto-ban (short) must not replace a permanent
    operator ban — its live-create would replicate the downgrade
    cluster-wide."""
    from emqx_tpu.banned import Banned
    from emqx_tpu.flapping import Flapping, FlappingConfig

    b = Banned()
    b.create("clientid", "vip-banned")  # operator: permanent
    f = Flapping(banned=b,
                 config=FlappingConfig(max_count=2, window=60,
                                       ban_time=5))
    for _ in range(3):
        f.disconnected("vip-banned", "1.2.3.4")
    rule = b.look_up("clientid", "vip-banned")
    assert rule is not None and rule.until is None  # still permanent


def test_ban_apply_expired_overwrite_deletes():
    import time as _t

    from emqx_tpu.banned import Banned

    b = Banned()
    b.create("clientid", "q")  # permanent
    # an overwrite that expired in transit must DELETE (the
    # originator's table has expired it too), not no-op
    b.apply("clientid", "q", "op", "", _t.time() - 1, overwrite=True)
    assert b.look_up("clientid", "q") is None


def test_partition_heal_rejoin_resyncs_routes():
    """A real partition (transport severed) makes each side's next
    replication cast fail → local nodedown purge; a re-join resyncs
    BOTH directions, including subscriptions made during the
    partition — the reference's mnesia-down → ekka re-join recovery
    (SURVEY §3.5)."""
    (n0, n1), (c0, c1) = _mk_cluster(2)
    transport = c0.transport
    s0, s1 = Q(), Q()
    n0.broker.subscribe(s0, "part/a")
    n1.broker.subscribe(s1, "part/b")
    # sever the link: the shared in-process transport drops both
    # handlers, so every cast now raises ConnectionError
    transport.unregister("n0")
    transport.unregister("n1")
    # subscriptions DURING the partition fail to replicate; each
    # side's failed cast triggers its local nodedown purge
    s0b, s1b = Q(), Q()
    n0.broker.subscribe(s0b, "part/during0")
    n1.broker.subscribe(s1b, "part/during1")
    assert not n1.router.has_route("part/a")       # n1 purged n0
    assert not n0.router.has_route("part/b")       # n0 purged n1
    assert not n1.router.has_route("part/during0")
    assert not n0.router.has_route("part/during1")
    # heal: transport restored, n1 re-joins n0
    transport.register("n0", c0)
    transport.register("n1", c1)
    c1.join(c0)
    for router, flt in [(n1.router, "part/a"),
                        (n1.router, "part/during0"),
                        (n0.router, "part/b"),
                        (n0.router, "part/during1")]:
        assert router.has_route(flt), flt
    n1.broker.publish(Message(topic="part/a"))
    n1.broker.publish(Message(topic="part/during0"))
    n0.broker.publish(Message(topic="part/b"))
    n0.broker.publish(Message(topic="part/during1"))
    assert len(s0.inbox) == 1
    assert len(s0b.inbox) == 1
    assert len(s1.inbox) == 1
    assert len(s1b.inbox) == 1


def test_nodedown_mid_forward_no_crash():
    """Publishing to a route whose node died between match and
    forward must not raise — the forwarder seam swallows a dead
    destination (gen_rpc cast semantics: best-effort async)."""
    (n0, n1), (c0, c1) = _mk_cluster(2)
    s1 = Q()
    n1.broker.subscribe(s1, "dying/+")
    # kill n1 from the transport's perspective AFTER n0 learned the
    # route: n0 still forwards at match time and must survive the
    # ConnectionError the dead peer raises
    c0.transport.unregister("n1")
    n = n0.broker.publish(Message(topic="dying/x"))
    assert n == 0          # no local subscribers
    assert s1.inbox == []  # and the dead peer got nothing


def test_retained_store_replicates_cluster_wide():
    """Retained messages behave like the reference plugin's Mnesia
    store: a retain on one node is visible to subscribers joining on
    any node; empty-payload delete replicates; a joiner syncs the
    existing store."""
    from emqx_tpu.modules.retainer import RetainerModule

    (n0, n1), (c0, c1) = _mk_cluster(2)
    r0 = n0.modules.load(RetainerModule)
    r1 = n1.modules.load(RetainerModule)
    n0.broker.publish(Message(topic="ret/x", payload=b"v",
                              flags={"retain": True}))
    assert r1._store["ret/x"].payload == b"v"   # replicated
    # delete replicates
    n0.broker.publish(Message(topic="ret/x", payload=b"",
                              flags={"retain": True}))
    assert "ret/x" not in r1._store
    # join sync: a third node gets the current store
    n0.broker.publish(Message(topic="ret/y", payload=b"w",
                              flags={"retain": True}))
    n2 = Node(name="n2", boot_listeners=False)
    c2 = Cluster(n2, c0.transport)
    r2 = n2.modules.load(RetainerModule)
    c2.join(c0)
    assert r2._store["ret/y"].payload == b"w"
