"""O(delta) automaton patching: parity against full re-flattens.

The patcher must produce an automaton the match kernel cannot
distinguish from a fresh flatten of the same filter set (only state
ids differ, which the kernel never observes). Reference semantics:
src/emqx_trie.erl:82-116 insert/delete are O(depth) row updates.
"""

import random

import numpy as np
import pytest

from emqx_tpu.oracle import TrieOracle
from emqx_tpu.ops.csr import build_automaton
from emqx_tpu.ops.match import match_batch
from emqx_tpu.ops.patch import AutoPatcher, PatchOverflow
from emqx_tpu.ops.tokenize import WordTable, encode_batch

WORDS = ["a", "b", "c", "dd", "ee", "sensor", "x"]


def _rand_filter(rng):
    depth = rng.randint(1, 5)
    ws = []
    for i in range(depth):
        p = rng.random()
        if p < 0.2:
            ws.append("+")
        elif p < 0.3 and i == depth - 1:
            ws.append("#")
        else:
            ws.append(rng.choice(WORDS))
    return "/".join(ws)


def _match_set(auto, table, fids_rev, topic):
    ids, n, sysm = encode_batch(table, [topic] * 8, 8)
    res = match_batch(auto, ids, n, sysm, k=32, m=64)
    row = np.asarray(res.ids)[0]
    assert not bool(np.asarray(res.overflow)[0])
    return {fids_rev[j] for j in row if j >= 0}


def _build(filters, table, caps=(None, None)):
    trie = TrieOracle()
    fids = {}
    for f in filters:
        trie.insert(f)
        fids[f] = len(fids)
        for w in f.split("/"):
            if w not in ("+", "#"):
                table.intern(w)
    auto = build_automaton(trie, fids, table,
                           state_capacity=caps[0], edge_capacity=caps[1])
    return auto, fids


def test_patched_matches_equal_fresh_flatten():
    rng = random.Random(7)
    table = WordTable()
    base = sorted({_rand_filter(rng) for _ in range(40)})
    # padded capacity so ~25 patches fit without overflow
    auto, fids = _build(base, table, caps=(512, 512))
    patcher = AutoPatcher(auto, table.intern)

    live = dict(fids)
    extra = sorted({_rand_filter(rng) for _ in range(60)}
                   - set(base))[:25]
    for f in extra:
        fid = len(live)
        live[f] = fid
        patcher.insert(f, fid)
    drops = rng.sample(base, 8)
    for f in drops:
        assert patcher.delete(f)
        del live[f]
    patched = patcher.apply_updates(auto)

    # fresh flatten of the same live set = ground truth
    t2 = WordTable()
    fresh, fresh_fids = _build(sorted(live), t2)
    rev_p = {v: k for k, v in live.items()}
    rev_f = {v: k for k, v in fresh_fids.items()}
    for _ in range(200):
        topic = "/".join(rng.choice(WORDS)
                         for _ in range(rng.randint(1, 5)))
        got = _match_set(patched, table, rev_p, topic)
        want = _match_set(fresh, t2, rev_f, topic)
        assert got == want, (topic, got, want)


def test_patch_is_incremental_not_queued_forever():
    table = WordTable()
    auto, fids = _build(["a/b"], table, caps=(64, 64))
    p = AutoPatcher(auto, table.intern)
    p.insert("a/c", 1)
    assert p.dirty
    out = p.apply_updates(auto)
    assert not p.dirty
    # original buffers untouched (double-buffering)
    rev = {0: "a/b", 1: "a/c"}
    assert _match_set(out, table, rev, "a/c") == {"a/c"}
    assert _match_set(auto, table, rev, "a/c") == set()


def test_overflow_marks_broken_and_blocks_apply():
    table = WordTable()
    auto, fids = _build(["a"], table)  # min capacity (16)
    p = AutoPatcher(auto, table.intern)
    with pytest.raises(PatchOverflow):
        # deep filter: exhausts the 16-state capacity mid-walk
        p.insert("/".join(f"w{i}" for i in range(20)), 1)
    assert p.broken
    with pytest.raises(PatchOverflow):
        p.insert("b", 2)
    with pytest.raises(PatchOverflow):
        p.delete("a")
    with pytest.raises(AssertionError):
        p.apply_updates(auto)  # partial queue must never be applied


def test_delete_missing_filter_returns_false():
    table = WordTable()
    auto, _ = _build(["x/y", "x/+"], table, caps=(64, 64))
    p = AutoPatcher(auto, table.intern)
    assert not p.delete("x/z")
    assert not p.delete("x/y/z")
    assert not p.delete("q/#")
    assert not p.dirty
    assert p.delete("x/+")
    assert p.tombstones == 1


def test_delete_then_reinsert_same_filter_single_drain():
    """Both writes target the same automaton slot; the drain must
    dedup by index (last wins) — repeated indices in one .at[].set
    apply in implementation-defined order."""
    table = WordTable()
    auto, fids = _build(["a/b", "c"], table, caps=(64, 64))
    p = AutoPatcher(auto, table.intern)
    assert p.delete("a/b")
    p.insert("a/b", fids["a/b"])  # same drain as the delete
    out = p.apply_updates(auto)
    rev = {v: k for k, v in fids.items()}
    assert _match_set(out, table, rev, "a/b") == {"a/b"}
    assert _match_set(out, table, rev, "c") == {"c"}


def test_wide_mode_split_churn_parity():
    """Deep-chain (wide-layout) patching: inserts that diverge
    mid-chain SPLIT compressed edges; deletes tombstone; the patched
    automaton holds exact oracle parity and the hop bound grows so
    deepened walks still emit (never silently miss)."""
    from emqx_tpu.ops.csr import (attach_walk_tables,
                                  compress_automaton, device_view)
    from emqx_tpu.ops.match import walk_params

    rng = random.Random(3)
    vocab = [f"v{i}" for i in range(9)]

    def deep_filter():
        d = rng.randint(1, 12)
        ws = [rng.choice(vocab) for _ in range(d)]
        if rng.random() < 0.25:
            ws = ws[: rng.randint(1, d)] + ["#"]
        return "/".join(ws)

    base = sorted({deep_filter() for _ in range(200)})
    trie, table, fids = TrieOracle(), WordTable(), {}
    for f in base:
        trie.insert(f)
        fids[f] = len(fids)
        for w in f.split("/"):
            if w not in ("+", "#"):
                table.intern(w)
    raw = build_automaton(trie, fids, table, skip_hash=True,
                          state_capacity=1 << 13,
                          edge_capacity=1 << 13)
    auto, edges = compress_automaton(raw, force_mode="wide",
                                     state_capacity=1 << 13)
    auto = attach_walk_tables(auto, edges, edge_capacity=1 << 13)
    assert auto.wt_take > 1
    p = AutoPatcher(auto, table.intern)
    dev = device_view(auto)

    extra = sorted({deep_filter() for _ in range(250)} - set(base))
    for f in extra:
        trie.insert(f)
        fids[f] = len(fids)
        p.insert(f, fids[f])
    for f in rng.sample(base, 60):
        trie.delete(f)
        assert p.delete(f), f
    assert p.splits > 0  # the churn actually exercised splits
    dev = p.apply_updates(dev)

    topics = ["/".join(rng.choice(vocab)
                       for _ in range(rng.randint(1, 12)))
              for _ in range(400)]
    ids, n, sysm = encode_batch(table, topics, 16)
    wp = walk_params(auto, ids.shape[1])
    # the patcher's grown bound, exactly as the Router reads it
    wp["steps"] = int(p.hops_for_level[
        min(ids.shape[1], len(p.hops_for_level) - 1)])
    res = match_batch(dev, ids, n, sysm, k=8, **wp)
    out = np.asarray(res.ids)
    ovf = np.asarray(res.overflow)
    rev = {v: k for k, v in fids.items()}
    for i, t in enumerate(topics):
        assert not ovf[i], t
        got = sorted(rev[j] for j in out[i] if j >= 0)
        assert got == sorted(trie.match(t)), t


def test_wide_mode_stale_steps_flags_overflow():
    """A walk compiled with the PRE-patch hop bound must flag the
    deepened topics as overflow (exact host fallback) rather than
    silently missing their matches."""
    from emqx_tpu.ops.csr import (attach_walk_tables,
                                  compress_automaton, device_view)
    from emqx_tpu.ops.match import walk_params

    base = ["root/" + "/".join(["c"] * 9)]  # one long chain
    trie, table, fids = TrieOracle(), WordTable(), {}
    for f in base:
        trie.insert(f)
        fids[f] = len(fids)
        for w in f.split("/"):
            table.intern(w)
    raw = build_automaton(trie, fids, table, skip_hash=True,
                          state_capacity=1 << 10,
                          edge_capacity=1 << 10)
    auto, edges = compress_automaton(raw, force_mode="wide",
                                     state_capacity=1 << 10)
    auto = attach_walk_tables(auto, edges, edge_capacity=1 << 10)
    p = AutoPatcher(auto, table.intern)
    stale = walk_params(auto, 16)  # bound BEFORE the deepening patch
    # diverge mid-chain: splits lengthen the path beyond the bound
    for i, newf in enumerate(
            ["root/c/c/x1/y/z", "root/c/c/c/c/x2/y/z",
             "root/c/c/c/c/c/c/x3/y/z"]):
        trie.insert(newf)
        fids[newf] = len(fids)
        p.insert(newf, fids[newf])
    assert p.hops_grown
    dev = p.apply_updates(device_view(auto))
    topic = "root/c/c/c/c/x2/y/z"
    ids, n, sysm = encode_batch(table, [topic] * 4, 16)
    res_stale = match_batch(dev, ids, n, sysm, k=4, **stale)
    fresh = dict(stale)
    fresh["steps"] = int(p.hops_for_level[
        min(ids.shape[1], len(p.hops_for_level) - 1)])
    res_fresh = match_batch(dev, ids, n, sysm, k=4, **fresh)
    rev = {v: k for k, v in fids.items()}
    got_fresh = sorted(rev[j]
                       for j in np.asarray(res_fresh.ids)[0] if j >= 0)
    assert got_fresh == [topic], got_fresh
    if not bool(np.asarray(res_stale.overflow)[0]):
        # stale bound happened to suffice — then results must agree
        got = sorted(rev[j]
                     for j in np.asarray(res_stale.ids)[0] if j >= 0)
        assert got == got_fresh


def test_hop_fallbacks_trigger_compaction_signal():
    """ADVICE r5: host fallbacks observed while the hop bound is
    stale count toward needs_compaction alongside splits/tombstones
    — a patch-deepened automaton rebuilds long before 1024 splits."""
    table = WordTable()
    auto, fids = _build(["a/b"], table, caps=(64, 64))
    p = AutoPatcher(auto, table.intern)
    p.note_hop_fallbacks(5000)
    assert not p.needs_compaction(10)  # hops never grew: not counted
    p.insert("a/b/c/d/e", 1)  # deepens the walk -> hops_grown
    assert p.hops_grown
    p.note_hop_fallbacks(500)
    assert not p.needs_compaction(10)
    p.note_hop_fallbacks(600)  # 1100 > max(1024, live)
    assert p.needs_compaction(10)


def test_router_note_match_fallbacks_schedules_rebuild():
    import time

    from emqx_tpu.router import MatcherConfig, Router

    # stale-hop fallback accounting lives on the patch-in-place
    # path's mirror — pin it with delta off (delta mode never splits,
    # so the stale-hop regime cannot arise there)
    r = Router(MatcherConfig(device_min_filters=0, delta=False),
               node="n")
    r.add_route("a/b")
    r.match_filters(["a/b"])  # first flatten + live patcher
    rebuilds = r.stats()["rebuilds"]
    # force the stale-hop regime, then report a fallback storm
    r._patcher.hops_grown = True
    r.note_match_fallbacks(2000)
    for _ in range(200):  # background compaction thread
        if r.stats()["rebuilds"] > rebuilds:
            break
        time.sleep(0.05)
    assert r.stats()["rebuilds"] > rebuilds
    # the fresh patcher starts clean
    assert r._patcher.hop_fallbacks == 0
