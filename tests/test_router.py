"""Router tests — modeled on reference test/emqx_router_SUITE.erl:
add/delete routes, match_routes, cluster cleanup, plus device/oracle
agreement through the public API.
"""

from emqx_tpu.router import MatcherConfig, Router
from emqx_tpu.types import Route


def _mk(use_device=True):
    return Router(MatcherConfig(use_device=use_device), node="node1")


def test_add_delete_route():
    r = _mk()
    r.add_route("a/b/c")
    r.add_route("a/b/c")  # refcounted per (topic, dest)
    r.add_route("a/+/b", dest="node2")
    assert r.has_route("a/b/c")
    r.delete_route("a/b/c")
    assert r.has_route("a/b/c")  # one ref left
    r.delete_route("a/b/c")
    assert not r.has_route("a/b/c")
    r.delete_route("a/+/b", dest="node2")
    assert r.topics() == []


def test_match_routes():
    r = _mk()
    r.add_route("a/b/c")
    r.add_route("a/+/c", dest="node2")
    r.add_route("a/#", dest="node3")
    r.add_route("x/y")
    got = sorted((rt.topic, rt.dest) for rt in r.match_routes("a/b/c"))
    assert got == [("a/#", "node3"), ("a/+/c", "node2"), ("a/b/c", "node1")]
    assert r.match_routes("nope") == []


def test_match_after_mutation_rebuilds():
    r = _mk()
    r.add_route("s/+")
    assert [rt.topic for rt in r.match_routes("s/1")] == ["s/+"]
    r.add_route("s/#")
    assert sorted(rt.topic for rt in r.match_routes("s/1")) == ["s/#", "s/+"]
    r.delete_route("s/+")
    assert [rt.topic for rt in r.match_routes("s/1")] == ["s/#"]
    assert r.stats()["topics.count"] == 1


def test_filter_id_recycling():
    r = _mk(use_device=False)
    r.add_route("a")
    fid = r.filter_id("a")
    r.delete_route("a")
    r.add_route("b")
    assert r.filter_id("b") == fid  # recycled
    r.add_route("c")
    assert r.filter_id("c") != fid


def test_cleanup_routes_on_nodedown():
    r = _mk()
    r.add_route("a/b", dest="dead")
    r.add_route("a/b")
    r.add_route("x/#", dest="dead")
    r.cleanup_routes("dead")
    assert [rt.dest for rt in r.match_routes("a/b")] == ["node1"]
    assert r.match_routes("x/1") == []


def test_shared_group_dest():
    r = _mk()
    r.add_route("t/1", dest=("g1", "node1"))
    assert r.match_routes("t/1") == [Route("t/1", ("g1", "node1"))]


def test_deep_topic_falls_back_to_oracle():
    r = _mk()
    r.add_route("a/#")
    deep = "/".join(["a"] + ["x"] * 64)  # > max_levels
    assert [rt.topic for rt in r.match_routes(deep)] == ["a/#"]


def test_sys_topic_routing():
    r = _mk()
    r.add_route("#")
    r.add_route("$SYS/#")
    assert [rt.topic for rt in r.match_routes("$SYS/x")] == ["$SYS/#"]
    assert sorted(rt.topic for rt in r.match_routes("plain")) == ["#"]
