"""Router tests — modeled on reference test/emqx_router_SUITE.erl:
add/delete routes, match_routes, cluster cleanup, plus device/oracle
agreement through the public API.
"""

from emqx_tpu.router import MatcherConfig, Router
from emqx_tpu.types import Route


def _mk(use_device=True, **kw):
    kw.setdefault("device_min_filters", 0)
    return Router(MatcherConfig(use_device=use_device, **kw),
                  node="node1")


def test_add_delete_route():
    r = _mk()
    r.add_route("a/b/c")
    r.add_route("a/b/c")  # refcounted per (topic, dest)
    r.add_route("a/+/b", dest="node2")
    assert r.has_route("a/b/c")
    r.delete_route("a/b/c")
    assert r.has_route("a/b/c")  # one ref left
    r.delete_route("a/b/c")
    assert not r.has_route("a/b/c")
    r.delete_route("a/+/b", dest="node2")
    assert r.topics() == []


def test_match_routes():
    r = _mk()
    r.add_route("a/b/c")
    r.add_route("a/+/c", dest="node2")
    r.add_route("a/#", dest="node3")
    r.add_route("x/y")
    got = sorted((rt.topic, rt.dest) for rt in r.match_routes("a/b/c"))
    assert got == [("a/#", "node3"), ("a/+/c", "node2"), ("a/b/c", "node1")]
    assert r.match_routes("nope") == []


def test_match_after_mutation_rebuilds():
    r = _mk()
    r.add_route("s/+")
    assert [rt.topic for rt in r.match_routes("s/1")] == ["s/+"]
    r.add_route("s/#")
    assert sorted(rt.topic for rt in r.match_routes("s/1")) == ["s/#", "s/+"]
    r.delete_route("s/+")
    assert [rt.topic for rt in r.match_routes("s/1")] == ["s/#"]
    assert r.stats()["topics.count"] == 1


def test_filter_id_recycles_immediately_in_host_regime():
    # no automaton was ever published: nothing holds an id map, so a
    # freed id recycles at once (round-4 soak: the old unconditional
    # quarantine grew ~200K ids/min under host-regime churn)
    r = _mk(use_device=False)
    r.add_route("a")
    fid = r.filter_id("a")
    r.delete_route("a")
    r.add_route("b")
    assert r.filter_id("b") == fid


def test_filter_id_quarantines_within_published_generation():
    # once an automaton generation is published, its id map is
    # append-only + tombstone-only: a concurrent matcher must never
    # see fid retranslate until the next flatten swaps the map
    r = _mk(use_device=False)
    r.add_route("a")
    r.rebuild()  # publish a generation
    fid = r.filter_id("a")
    r.delete_route("a")
    r.add_route("b")
    assert r.filter_id("b") != fid
    r.rebuild()  # generation swap releases the quarantine
    r.add_route("c")
    assert r.filter_id("c") == fid  # recycled across generations


def test_cleanup_routes_on_nodedown():
    r = _mk()
    r.add_route("a/b", dest="dead")
    r.add_route("a/b")
    r.add_route("x/#", dest="dead")
    r.cleanup_routes("dead")
    assert [rt.dest for rt in r.match_routes("a/b")] == ["node1"]
    assert r.match_routes("x/1") == []


def test_shared_group_dest():
    r = _mk()
    r.add_route("t/1", dest=("g1", "node1"))
    assert r.match_routes("t/1") == [Route("t/1", ("g1", "node1"))]


def test_deep_topic_falls_back_to_oracle():
    r = _mk()
    r.add_route("a/#")
    deep = "/".join(["a"] + ["x"] * 64)  # > max_levels
    assert [rt.topic for rt in r.match_routes(deep)] == ["a/#"]


def test_sys_topic_routing():
    r = _mk()
    r.add_route("#")
    r.add_route("$SYS/#")
    assert [rt.topic for rt in r.match_routes("$SYS/x")] == ["$SYS/#"]
    assert sorted(rt.topic for rt in r.match_routes("plain")) == ["#"]


# -- O(delta) patch path (ops/patch.py wired through the router) ------------

def test_patches_avoid_rebuild():
    """Route churn after the first flatten goes through the patcher:
    new filters match without a full re-flatten (the round-1 verdict's
    churn-stall item). Pins the patch-in-place path explicitly
    (``delta=False``; delta mode has its own suite, test_delta.py)."""
    r = _mk(delta=False)
    for i in range(20):
        r.add_route(f"seed/{i}")
    r.match_routes("seed/1")  # first flatten (pow2-padded capacity)
    base = r.stats()["rebuilds"]
    for i in range(10):  # fits the padded headroom: pure patches
        r.add_route(f"c{i}")
    for i in range(10):
        assert [rt.topic for rt in r.match_routes(f"c{i}")] == [f"c{i}"]
    st = r.stats()
    assert st["rebuilds"] == base, "patching must not trigger rebuilds"
    assert st["patches"] >= 10


def test_patch_delete_tombstones():
    r = _mk()
    for i in range(8):
        r.add_route(f"d/{i}")
    r.match_routes("d/0")  # flatten
    base = r.stats()["rebuilds"]
    r.delete_route("d/3")
    assert r.match_routes("d/3") == []
    assert [rt.topic for rt in r.match_routes("d/4")] == ["d/4"]
    assert r.stats()["rebuilds"] == base


def test_patch_overflow_falls_back_to_rebuild():
    """Exceeding the padded capacity mid-churn re-flattens (with
    doubled capacity) and keeps matching correct (patch-in-place
    path: ``delta=False``)."""
    r = _mk(delta=False)
    r.add_route("p/0")
    r.match_routes("p/0")
    # way past the min capacity of the first tiny flatten
    for i in range(1, 200):
        r.add_route(f"p/{i}/q/{i}")
    assert [rt.topic for rt in r.match_routes("p/7/q/7")] == ["p/7/q/7"]
    st = r.stats()
    assert st["rebuilds"] >= 2  # at least one overflow re-flatten
    # after the re-flatten (doubled capacity) churn patches again
    r.add_route("post/rebuild")
    assert [rt.topic for rt in r.match_routes("post/rebuild")] \
        == ["post/rebuild"]


def test_patch_reuses_freed_id_across_generations():
    """A fid recycled after a rebuild patches into the automaton and
    matches the NEW filter only."""
    r = _mk()
    r.add_route("old/filter")
    r.match_routes("old/filter")
    r.delete_route("old/filter")
    r.rebuild()
    r.add_route("new/filter")  # recycles old's fid via the patcher
    assert [rt.topic for rt in r.match_routes("new/filter")] \
        == ["new/filter"]
    assert r.match_routes("old/filter") == []


def test_published_snapshot_is_stable_across_churn():
    """A matcher-held (auto, map) snapshot stays translation-safe
    while routes churn underneath it."""
    r = _mk()
    r.add_route("keep/a")
    r.add_route("gone/b")
    auto, id_map, epoch = r.automaton()
    fid_gone = r.filter_id("gone/b")
    r.delete_route("gone/b")      # tombstone: map[fid] -> None
    for i in range(10):
        r.add_route(f"more/{i}")  # appends, never rewrites fid_gone
    assert id_map[fid_gone] is None or id_map[fid_gone] == "gone/b"
    assert id_map[r.filter_id("keep/a")] == "keep/a"


def test_quarantine_bounded_when_falling_back_to_host_regime():
    """A router that crossed the device threshold once and dropped
    below it must not pin freed ids forever — but an oscillating
    filter count must not pay a re-flatten per crossing either:
    reclaim_host_regime drops the stale automaton only once the
    quarantine outgrows host_reclaim_pending (round-4 leak fix with
    hysteresis)."""
    r = _mk(device_min_filters=4, host_reclaim_pending=8)
    for i in range(6):
        r.add_route(f"fb/{i}")
    assert r.use_device_now()
    r.rebuild()  # device-regime generation published
    for i in range(5):
        r.delete_route(f"fb/{i}")  # below threshold, quarantined
    assert not r.use_device_now()
    r.reclaim_host_regime()  # under the bound: hysteresis holds
    assert r._auto is not None and len(r._pending_free) == 5
    for i in range(10):  # churn past the bound
        r.add_route(f"fb2/{i}")
        r.delete_route(f"fb2/{i}")
    r.reclaim_host_regime()
    assert r._auto is None
    assert r._pending_free == []
    # host-regime churn now recycles in place
    cap = len(r._id_to_filter)
    for i in range(50):
        r.add_route(f"x/{i}")
        r.delete_route(f"x/{i}")
    assert len(r._id_to_filter) == cap
    # and crossing back up re-flattens cleanly with exact matching
    for i in range(6):
        r.add_route(f"up/{i}/+")
    assert r.use_device_now()
    [m] = r.match_filters(["up/3/x"])
    assert m == ["up/3/+"]
