"""Device fan-out wiring: subscriber-id registry + CSR/bitmap dispatch
through the product Broker (the emqx_broker_helper analogue;
reference behavior: src/emqx_broker_helper.erl:55,63-100 and the shard
dispatch src/emqx_broker.erl:283-309)."""


from emqx_tpu.broker import Broker
from emqx_tpu.broker_helper import FanoutManager, SubRegistry, unpack_sids
from emqx_tpu.router import MatcherConfig
from emqx_tpu.types import Message, SubOpts


class Rec:
    def __init__(self, client_id="c"):
        self.client_id = client_id
        self.got = []

    def deliver(self, flt, msg):
        self.got.append((flt, msg.topic))


def test_registry_dense_ids_and_quarantine():
    reg = SubRegistry()
    a, b = object(), object()
    ia, ib = reg.register(a), reg.register(b)
    assert {ia, ib} == {0, 1}
    assert reg.register(a) == ia  # idempotent
    assert reg.lookup(ia) is a
    reg.release(a)
    assert reg.lookup(ia) is None
    # freed id must NOT recycle while an in-flight device batch could
    # still gather it: recycling is TIME-gated (QUARANTINE_S), not
    # snapshot-gated (round-4: pipelined batches resolve sids against
    # the live registry)
    c = object()
    ic = reg.register(c)
    assert ic == 2
    reg.flush_free()  # too young: still quarantined
    d = object()
    assert reg.register(d) == 3
    # past the dwell the id recycles
    reg._quarantine[0] = (reg._quarantine[0][0],
                          reg._quarantine[0][1] - reg.QUARANTINE_S - 1)
    reg.flush_free()
    e = object()
    assert reg.register(e) == ia  # now recycled
    assert reg.count() == 4 and reg.capacity() == 4


def test_manager_state_small_and_big_split():
    man = FanoutManager(threshold=4, use_device=False)
    subs = [object() for _ in range(6)]
    for s in subs:
        man.subscribe("big/t", s)
    man.subscribe("small/t", subs[0])
    st = man.state(epoch=1, id_map=["big/t", "small/t"])
    assert st.bm is not None and st.fan is not None
    assert st.big_fids == {0}
    assert st.bm.big_row[0] == 0 and st.bm.big_row[1] == -1
    got = set(unpack_sids(st.bm.bitmaps[0]))
    assert got == {man.registry.sid(s) for s in subs}
    # CSR row for the small filter
    lo, hi = st.fan.row_ptr[1], st.fan.row_ptr[2]
    assert list(st.fan.sub_ids[lo:hi]) == [man.registry.sid(subs[0])]
    # cached until membership or epoch changes
    assert man.state(1, ["big/t", "small/t"]) is st
    man.unsubscribe("small/t", subs[0])
    st2 = man.state(1, ["big/t", "small/t"])
    assert st2 is not st


def _dev_broker(**kw):
    kw.setdefault("device_min_filters", 0)
    return Broker(config=MatcherConfig(**kw))


def test_broker_small_fanout_via_device_gather():
    b = _dev_broker()
    s1, s2 = Rec("c1"), Rec("c2")
    b.subscribe(s1, "home/+/temp")
    b.subscribe(s2, "home/kitchen/#")
    n = b.publish(Message(topic="home/kitchen/temp"))
    assert n == 2
    assert s1.got == [("home/+/temp", "home/kitchen/temp")]
    assert s2.got == [("home/kitchen/#", "home/kitchen/temp")]
    # the device tables were actually built (fan path, no bitmaps)
    st = b.helper._state
    assert st is not None and st.fan is not None and st.bm is None


def test_broker_bitmap_path_5k_subscribers():
    """VERDICT round-1 item 2: >threshold fan-out must flow through
    the bitmap tables in the product broker, Python only in the
    delivery tail."""
    b = _dev_broker()
    subs = [Rec(f"c{i}") for i in range(5000)]
    for s in subs:
        b.subscribe(s, "bcast/all")
    small = Rec("small")
    b.subscribe(small, "bcast/+")
    n = b.publish(Message(topic="bcast/all"))
    assert n == 5001
    st = b.helper._state
    assert st is not None and st.bm is not None
    assert len(st.big_fids) == 1
    assert all(s.got == [("bcast/all", "bcast/all")] for s in subs)
    assert small.got == [("bcast/+", "bcast/all")]
    # unsubscribe prunes the bitmap row
    for s in subs[:4500]:
        b.unsubscribe(s, "bcast/all")
    n = b.publish(Message(topic="bcast/all"))
    assert n == 501
    st = b.helper._state
    assert st.bm is None  # back under threshold: CSR path


def test_broker_two_big_filters_per_subscription_delivery():
    """Two >threshold filters matching one topic: the union bitmap is
    re-filtered per filter's member set, so an overlapping member gets
    one delivery PER subscription (reference semantics: dispatch per
    {Topic, SubPid} pair per matched route)."""
    b = _dev_broker(fanout_threshold=4)
    g1 = [Rec(f"a{i}") for i in range(6)]
    g2 = [Rec(f"b{i}") for i in range(6)]
    both = Rec("both")
    for s in g1:
        b.subscribe(s, "big/+")
    for s in g2:
        b.subscribe(s, "big/#")
    b.subscribe(both, "big/+")
    b.subscribe(both, "big/#")
    n = b.publish(Message(topic="big/x"))
    st = b.helper._state
    assert st is not None and len(st.big_fids) == 2
    assert n == 14  # 6 + 6 + 2 (overlap delivers per subscription)
    assert sorted(both.got) == [("big/#", "big/x"), ("big/+", "big/x")]
    assert all(s.got == [("big/+", "big/x")] for s in g1)
    assert all(s.got == [("big/#", "big/x")] for s in g2)


def test_broker_nl_option_on_device_path():
    b = _dev_broker()
    s = Rec("me")
    b.subscribe(s, "a/b", SubOpts(nl=True))
    other = Rec("other")
    b.subscribe(other, "a/b")
    n = b.publish(Message(topic="a/b", from_="me"))
    assert n == 1 and s.got == [] and other.got
    assert b.metrics.val("delivery.dropped.no_local") == 1


def test_broker_overflow_fallback_matches_host():
    """Per-message delivery slots exceeded → host dispatch fallback
    (same deliveries, exact parity)."""
    b = _dev_broker(fanout_d=8)
    subs = [Rec(f"c{i}") for i in range(20)]  # > d=8, < threshold
    for s in subs:
        b.subscribe(s, "x/y")
    n = b.publish(Message(topic="x/y"))
    assert n == 20
    assert all(s.got for s in subs)


def test_sid_not_recycled_across_pending_state():
    """A released subscriber id is quarantined until the next table
    rebuild — a fresh subscriber never aliases an old sid in tables
    still live."""
    b = _dev_broker()
    a = Rec("a")
    b.subscribe(a, "t/1")
    b.publish(Message(topic="t/1"))  # builds tables referencing a's sid
    sid_a = b.helper.registry.sid(a)
    b.unsubscribe(a, "t/1")
    c = Rec("c")
    b.subscribe(c, "t/2")
    # c must not get a's sid before any rebuild happened
    assert b.helper.registry.sid(c) != sid_a or \
        b.helper._state is None
    n = b.publish(Message(topic="t/2"))
    assert n == 1 and c.got == [("t/2", "t/2")]
    assert a.got == [("t/1", "t/1")]  # nothing after its unsubscribe


def test_pack_budget_overflow_repacks():
    """Fan-out total past the packed-transfer budget: publish_fetch
    re-packs with the next pow2 bucket — all deliveries still land."""
    b = _dev_broker(pack_q=1)  # tiny budget: 1 sub/msg expected
    subs = [Rec(f"c{i}") for i in range(300)]
    for s in subs:
        b.subscribe(s, "o/flow")
    pb = b.publish_begin([Message(topic="o/flow")])
    assert not pb.done
    pq0 = pb.pq
    b.publish_fetch(pb)
    assert pb.pq > pq0  # budget grew
    [n] = b.publish_finish(pb)
    assert n == 300
    assert all(s.got == [("o/flow", "o/flow")] for s in subs)


def test_threshold_policy_host_vs_device():
    """Below device_min_filters the publish path never touches the
    device (pb.done from publish_begin); at/above it dispatches."""
    from emqx_tpu.router import MatcherConfig

    b = Broker(config=MatcherConfig(device_min_filters=3))
    s1, s2 = Rec("c1"), Rec("c2")
    b.subscribe(s1, "th/a")
    b.subscribe(s2, "th/+")
    pb = b.publish_begin([Message(topic="th/a")])
    assert pb.done and pb.results == [2]  # host path, already routed
    assert not b.router.use_device_now()
    b.subscribe(s1, "th/c")  # 3rd filter: crosses the threshold
    assert b.router.use_device_now()
    pb2 = b.publish_begin([Message(topic="th/a")])
    assert not pb2.done
    b.publish_fetch(pb2)
    assert b.publish_finish(pb2) == [2]


def test_pack_budget_overflow_remembered_across_batches():
    """A grown packed budget persists per batch bucket: the second
    batch starts at the grown budget and needs no re-pack round."""
    b = _dev_broker(pack_q=1)
    subs = [Rec(f"c{i}") for i in range(100)]
    for s in subs:
        b.subscribe(s, "o/mem")
    pb1 = b.publish_begin([Message(topic="o/mem")])
    b.publish_fetch(pb1)
    grown = pb1.pq
    assert b.publish_finish(pb1) == [100]
    pb2 = b.publish_begin([Message(topic="o/mem")])
    assert pb2.pq == grown  # learned, no overflow round this time
    b.publish_fetch(pb2)
    assert pb2.pq == grown
    assert b.publish_finish(pb2) == [100]


def test_pad_rows_do_not_inflate_packed_totals():
    """Wildcard filters match the batch's pad topic; the pack step
    must see those phantom rows blanked or the packed totals (and
    learned budgets) scale with the bucket, not the batch."""
    b = _dev_broker()
    s = Rec("w")
    b.subscribe(s, "#")
    b.subscribe(s, "+/pad")
    pb = b.publish_begin([Message(topic="real/topic")])
    assert not pb.done
    b.publish_fetch(pb)
    # exactly ONE live row's matches/fan-out, no pad-row inflation
    assert int(pb.m_ptr[-1]) == 1          # only '#' matches
    assert int(pb.f_ptr[-1]) == 1
    assert b.publish_finish(pb) == [1]
    assert s.got == [("#", "real/topic")]


def test_pack_rows_zero_does_not_hang():
    """pack_rows=0 must not wedge publish_fetch's pow2 growth loop."""
    b = _dev_broker(pack_rows=0, fanout_threshold=4)
    subs = [Rec(f"c{i}") for i in range(8)]
    for s in subs:
        b.subscribe(s, "bm/zero")
    n = b.publish(Message(topic="bm/zero"))
    assert n == 8


def test_duplicate_topics_in_batch_each_deliver():
    """Hot topics collapse to one device row; every logical message
    still delivers (the inverse index expands per message)."""
    b = _dev_broker()
    s = Rec("dup")
    b.subscribe(s, "hot/+")
    msgs = [Message(topic="hot/a") for _ in range(5)] + \
        [Message(topic="hot/b")] + \
        [Message(topic="hot/a")]
    pb = b.publish_begin(msgs)
    assert not pb.done
    assert pb.inv == [0, 0, 0, 0, 0, 1, 0]
    b.publish_fetch(pb)
    assert b.publish_finish(pb) == [1] * 7
    assert s.got.count(("hot/+", "hot/a")) == 6
    assert s.got.count(("hot/+", "hot/b")) == 1


def test_fanout_budget_learned_growth():
    """The fused sparse expansion has no per-message slot cap — a
    heavy fan-out overflows the global q budget once, the budget
    doubles and sticks, and deliveries are always complete."""
    b = _dev_broker(pack_q=1)
    subs = [Rec(f"c{i}") for i in range(20)]
    for s in subs:
        b.subscribe(s, "grow/d")
    for _ in range(3):
        assert b.publish(Message(topic="grow/d")) == 20  # always right
    bucket = next(iter(b._pack_budgets))
    assert b._pack_budgets[bucket][1] >= 20  # q grew past the need


def test_active_k_learned_boost():
    """An overflow-storm batch (active set > K for most topics)
    doubles the router's effective K; matching stays exact via host
    fallback meanwhile."""
    from emqx_tpu.router import MatcherConfig, Router

    r = Router(MatcherConfig(active_k=2, device_min_filters=0),
               node="n")
    b = Broker(router=r)
    recs = []
    # '+'-heavy filters: the active set fans out past K=2 by level 2
    for flt in ("+/+/x", "a/+/x", "+/b/x", "a/b/x", "+/+/+", "a/+/+"):
        rec = Rec(flt)
        recs.append(rec)
        b.subscribe(rec, flt)
    assert r.effective_k() == 2
    n = b.publish(Message(topic="a/b/x"))
    assert n == 6  # exact despite overflow (host fallback)
    assert r.effective_k() > 2  # boosted for the next batch
    n = b.publish(Message(topic="a/b/x"))
    assert n == 6
