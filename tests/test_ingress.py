"""Ingress batching: per-tick PUBLISH aggregation into one device
call, with QoS acks deferred to the batch flush (SURVEY §2.2 row 1;
accumulator semantics after src/emqx_batch.erl:1-91)."""

import asyncio

from emqx_tpu.broker import Broker
from emqx_tpu.ingress import IngressBatcher
from emqx_tpu.node import Node
from emqx_tpu.types import Message
from mqtt_client import TestClient


class Rec:
    def __init__(self, cid="r"):
        self.client_id = cid
        self.got = []

    def deliver(self, f, m):
        self.got.append(m.topic)


async def test_tick_aggregation_one_device_call():
    b = Broker()
    s = Rec()
    b.subscribe(s, "t/+")
    bat = IngressBatcher(b, batch_size=100)
    futs = [bat.submit(Message(topic=f"t/{i}")) for i in range(5)]
    assert all(f is not None for f in futs)
    assert bat.flushes == 0  # nothing flushed inside this tick
    await asyncio.sleep(0)   # next loop iteration -> call_soon flush
    counts = [await f for f in futs]
    assert counts == [1] * 5
    assert bat.flushes == 1  # 5 messages, ONE publish_batch
    assert s.got == [f"t/{i}" for i in range(5)]


async def test_size_triggered_flush():
    b = Broker()
    s = Rec()
    b.subscribe(s, "x")
    bat = IngressBatcher(b, batch_size=3)
    f1 = bat.submit(Message(topic="x"))
    f2 = bat.submit(Message(topic="x"))
    f3 = bat.submit(Message(topic="x"))  # cap hit: flush inline
    assert f3.done() and f1.done() and f2.done()
    assert bat.flushes == 1 and bat.max_batch == 3
    assert await f1 == 1 and await f2 == 1 and await f3 == 1


def test_submit_without_loop_falls_back():
    b = Broker()
    bat = IngressBatcher(b)
    assert bat.submit(Message(topic="t")) is None  # sync caller path


async def test_live_batched_acks_all_qos():
    """Real sockets end to end: QoS0/1/2 publishes flow through the
    batcher (Node default), acks complete at flush, deliveries
    arrive."""
    n = Node(boot_listeners=False)
    lst = n.add_listener(port=0)
    await n.start()
    try:
        sub = TestClient("sub", version=5)
        await sub.connect(port=lst.port)
        await sub.subscribe("a/#", qos=2)
        pub = TestClient("pub", version=5)
        await pub.connect(port=lst.port)
        await pub.publish("a/zero", b"0", qos=0)
        await pub.publish("a/one", b"1", qos=1)    # PUBACK deferred
        await pub.publish("a/two", b"2", qos=2)    # PUBREC deferred
        topics = sorted([(await sub.recv()).topic for _ in range(3)])
        assert topics == ["a/one", "a/two", "a/zero"]
        assert n.ingress.submitted == 3
        assert n.ingress.flushes >= 1
        await pub.disconnect()
        await sub.disconnect()
    finally:
        await n.stop()


async def test_live_concurrent_publishers_batch_together():
    """Publishes from many connections in the same tick share one
    flush (the whole point of ingress batching)."""
    n = Node(boot_listeners=False, batch_linger_ms=5.0)
    lst = n.add_listener(port=0)
    await n.start()
    try:
        sub = TestClient("sub")
        await sub.connect(port=lst.port)
        await sub.subscribe("c/+")
        pubs = []
        for i in range(8):
            p = TestClient(f"p{i}")
            await p.connect(port=lst.port)
            pubs.append(p)
        # fire all QoS1 publishes concurrently: acks gate on the flush
        await asyncio.gather(*(
            p.publish(f"c/{i}", b"x", qos=1)
            for i, p in enumerate(pubs)))
        got = sorted([(await sub.recv()).topic for _ in range(8)])
        assert got == sorted(f"c/{i}" for i in range(8))
        assert n.ingress.submitted == 8
        # linger collects across connections: strictly fewer flushes
        # than messages
        assert n.ingress.flushes < 8
        for p in pubs:
            await p.disconnect()
        await sub.disconnect()
    finally:
        await n.stop()


async def test_ack_order_preserved_with_error_acks():
    """MQTT-4.6.0: a rejected PUBLISH's ack must not overtake the
    deferred ack of an earlier accepted one."""
    import asyncio as aio

    from emqx_tpu.mqtt import constants as C
    from emqx_tpu.mqtt.packet import Publish

    n = Node(boot_listeners=False, batch_linger_ms=20.0)
    lst = n.add_listener(port=0)
    await n.start()
    try:
        c = TestClient("c", version=5)
        await c.connect(port=lst.port)
        # pid=7 QoS2 accepted (PUBREC defers to flush); then pid=7
        # again -> PACKET_IDENTIFIER_IN_USE error PUBREC, which must
        # queue BEHIND the first ack despite being ready instantly
        await c.send(Publish(topic="q/t", qos=2, packet_id=7))
        await c.send(Publish(topic="q/t", qos=2, packet_id=7))
        a1 = await aio.wait_for(c.acks.get(), 5)
        a2 = await aio.wait_for(c.acks.get(), 5)
        assert a1.type == C.PUBREC and a2.type == C.PUBREC
        assert a1.reason_code in (0x00, 0x10)   # no-matching-subs ok
        assert a2.reason_code == 0x91           # identifier in use
        c.writer.close()
    finally:
        await n.stop()


async def test_flush_failure_sends_no_ack():
    """A failed device batch must NOT be acked — the QoS1 client's
    retransmit is the recovery path (at-least-once)."""
    import asyncio as aio

    from emqx_tpu.mqtt.packet import Publish

    n = Node(boot_listeners=False)
    lst = n.add_listener(port=0)
    await n.start()
    try:
        c = TestClient("c", version=4)
        await c.connect(port=lst.port)

        def boom(msgs, defer_host=False):
            raise RuntimeError("device gone")

        orig = n.broker.publish_begin
        n.broker.publish_begin = boom
        await c.send(Publish(topic="a/b", qos=1, packet_id=3))
        with __import__("pytest").raises(aio.TimeoutError):
            await aio.wait_for(c.acks.get(), 0.3)
        # broker recovers -> the retransmit is acked
        n.broker.publish_begin = orig
        await c.send(Publish(topic="a/b", qos=1, packet_id=3, dup=True))
        ack = await aio.wait_for(c.acks.get(), 5)
        assert ack.packet_id == 3
        c.writer.close()
    finally:
        await n.stop()


async def test_flush_error_resolves_futures():
    class Boom(Broker):
        def publish_begin(self, msgs, defer_host=False):
            raise RuntimeError("device gone")

    bat = IngressBatcher(Boom(), batch_size=2)
    f1 = bat.submit(Message(topic="t"))
    f2 = bat.submit(Message(topic="t"))
    assert f1.done() and isinstance(f1.exception(), RuntimeError)
    assert f2.done() and isinstance(f2.exception(), RuntimeError)


# -- pipelined (three-phase) flushes ---------------------------------


def _dev_broker(**kw):
    from emqx_tpu.router import MatcherConfig
    kw.setdefault("device_min_filters", 0)
    return Broker(config=MatcherConfig(**kw))


async def test_device_path_flush_is_async():
    """Above the device threshold the flush pipeline runs begin →
    (executor) fetch → finish; futures resolve with correct counts."""
    b = _dev_broker()
    s = Rec()
    b.subscribe(s, "t/+")
    bat = IngressBatcher(b, batch_size=100)
    futs = [bat.submit(Message(topic=f"t/{i}")) for i in range(5)]
    await asyncio.sleep(0)  # tick flush -> async completion
    counts = [await f for f in futs]
    assert counts == [1] * 5
    assert sorted(s.got) == sorted(f"t/{i}" for i in range(5))


async def test_ordered_delivery_across_batches():
    """Batch N+1 must not deliver before batch N even when its fetch
    finishes first (per-publisher in-order semantics)."""
    import time

    b = _dev_broker()
    s = Rec()
    b.subscribe(s, "o/+")
    orig_fetch = b.publish_fetch
    delays = {"o/first": 0.15}

    def slow_fetch(pb):
        d = max((delays.get(m.topic, 0.0) for _, m in pb.live),
                default=0.0)
        if d:
            time.sleep(d)
        orig_fetch(pb)

    b.publish_fetch = slow_fetch
    bat = IngressBatcher(b, batch_size=1, max_inflight=4)
    f1 = bat.submit(Message(topic="o/first"))
    f2 = bat.submit(Message(topic="o/second"))
    await asyncio.gather(f1, f2)
    assert s.got == ["o/first", "o/second"]


async def test_inflight_cap_accumulates_bigger_batches():
    """With all pipeline slots busy, arrivals accumulate and flush as
    one bigger batch when a slot frees (backpressure = batch growth)."""
    import time

    b = _dev_broker()
    s = Rec()
    b.subscribe(s, "p/+")
    orig_fetch = b.publish_fetch

    def slow_fetch(pb):
        time.sleep(0.05)
        orig_fetch(pb)

    b.publish_fetch = slow_fetch
    bat = IngressBatcher(b, batch_size=1, max_inflight=1)
    futs = [bat.submit(Message(topic=f"p/{i}")) for i in range(10)]
    await asyncio.gather(*futs)
    assert sorted(s.got) == sorted(f"p/{i}" for i in range(10))
    assert bat.flushes < 10  # accumulation happened
    assert bat.max_batch > 1


async def test_node_stop_drains_inflight():
    n = Node(boot_listeners=False)
    await n.start()
    s = Rec()
    n.broker.subscribe(s, "d/+")
    n.ingress.submit(Message(topic="d/1"), want_result=False)
    await n.stop()
    assert s.got == ["d/1"]


async def test_host_path_batch_ordered_behind_device_batch():
    """A flush that would take the host path (threshold crossed
    downward mid-pipeline) must still deliver AFTER the in-flight
    device batch — begin defers host routing behind the chain."""
    import time

    from emqx_tpu.router import MatcherConfig

    b = Broker(config=MatcherConfig(device_min_filters=2))
    s1, s2 = Rec("r1"), Rec("r2")
    b.subscribe(s1, "h/a")
    b.subscribe(s2, "h/b")  # 2 filters -> device path
    orig_fetch = b.publish_fetch

    def slow_fetch(pb):
        time.sleep(0.1)
        orig_fetch(pb)

    b.publish_fetch = slow_fetch
    bat = IngressBatcher(b, batch_size=1, max_inflight=4)
    f1 = bat.submit(Message(topic="h/a"))      # device, slow fetch
    await asyncio.sleep(0)
    b.unsubscribe(s2, "h/b")  # drop below threshold -> host path next
    f2 = bat.submit(Message(topic="h/a"))      # host path, instant
    await asyncio.gather(f1, f2)
    assert len(s1.got) == 2  # both delivered, in submission order
    # f2 resolved only after f1 (chained), so ordering held
    assert await f1 == 1 and await f2 == 1


async def test_drain_waits_for_inflight_before_flushing_queue():
    """drain() must complete in-flight batches BEFORE publishing the
    messages that queued behind them."""
    import time

    b = _dev_broker()
    s = Rec()
    b.subscribe(s, "z/+")
    orig_fetch = b.publish_fetch

    def slow_fetch(pb):
        time.sleep(0.1)
        orig_fetch(pb)

    b.publish_fetch = slow_fetch
    bat = IngressBatcher(b, batch_size=1, max_inflight=1)
    bat.submit(Message(topic="z/old"), want_result=False)
    await asyncio.sleep(0)      # old batch enters the pipeline
    bat.submit(Message(topic="z/new"), want_result=False)  # queued
    await bat.drain()
    assert s.got == ["z/old", "z/new"]


async def test_flush_during_completion_cannot_reorder_or_double_resolve():
    """Regression (ISSUE 3 satellite): _complete's slot-free flush
    used to run RE-ENTRANTLY inside the finishing batch's completion,
    before that batch's own futures resolved — a flush that resolves
    synchronously there (e.g. publish_begin raising) completed NEWER
    publishes' futures ahead of the older batch's, breaking ack
    order. The flush must be scheduled for after resolution."""
    import time

    b = _dev_broker()
    s = Rec()
    b.subscribe(s, "r/+")
    orig_fetch = b.publish_fetch

    def slow_fetch(pb):
        time.sleep(0.05)
        orig_fetch(pb)

    b.publish_fetch = slow_fetch
    orig_begin = b.publish_begin
    calls = [0]

    def begin(msgs, defer_host=False):
        calls[0] += 1
        if calls[0] == 2:
            raise RuntimeError("boom")  # batch B fails at begin
        return orig_begin(msgs, defer_host=defer_host)

    b.publish_begin = begin
    bat = IngressBatcher(b, batch_size=100, max_inflight=1)
    order = []
    fa = bat.submit(Message(topic="r/a"))
    fa.add_done_callback(lambda f: order.append("A"))
    await asyncio.sleep(0)        # batch A enters the pipeline
    fb = bat.submit(Message(topic="r/b"))   # queues behind A
    fb.add_done_callback(lambda f: order.append("B"))
    await asyncio.wait({fa, fb})
    await asyncio.sleep(0)        # drain done-callbacks
    assert await fa == 1
    assert isinstance(fb.exception(), RuntimeError)
    # A's future resolved before B's, and each exactly once
    assert order == ["A", "B"]
