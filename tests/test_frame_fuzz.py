"""Deep serialize∘parse property fuzzing over the full packet space.

The reference runs PropEr generators over every packet type × proto
version (test/props/prop_emqx_frame.erl:26-55). This suite is that
generator by hand: all 15 control packet types, valid v5 properties
drawn from the property table per packet type, wills, unicode
topics, QoS variants — roundtripped across v3.1 / v3.1.1 / v5 — plus
an adversarial pass: random byte corruption must surface as
FrameError/FrameTooLarge (or a clean parse), never a crash.
"""

import random

import pytest

from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.frame import (FrameError, FrameTooLarge, Parser,
                                 serialize)
from emqx_tpu.mqtt.packet import (Auth, Connack, Connect, Disconnect,
                                  Pingreq, Pingresp, PubAck, Publish,
                                  Suback, Subscribe, Unsuback,
                                  Unsubscribe)
from emqx_tpu.mqtt.props import (BINARY, BYTE, FOUR_BYTE, PROPS, TWO_BYTE,
                                 UTF8, UTF8_PAIR, VARINT)

VERSIONS = (C.MQTT_V3, C.MQTT_V4, C.MQTT_V5)

_TOPIC_WORDS = ["a", "b", "sensor", "温度", "x-y_z", "0", "ß"]


def _topic(rng, wild=False):
    words = [rng.choice(_TOPIC_WORDS)
             for _ in range(rng.randint(1, 6))]
    if wild and rng.random() < 0.4:
        words[rng.randrange(len(words))] = "+"
    if wild and rng.random() < 0.2:
        words[-1] = "#"
    return "/".join(words)


def _prop_value(rng, wire_type):
    if wire_type == BYTE:
        return rng.randint(0, 1)
    if wire_type == TWO_BYTE:
        return rng.randint(1, 0xFFFF)
    if wire_type == FOUR_BYTE:
        return rng.randint(1, 0xFFFFFFFF)
    if wire_type == VARINT:
        return rng.randint(1, 0x0FFFFFFF)
    if wire_type == BINARY:
        return rng.randbytes(rng.randint(0, 16))
    if wire_type == UTF8:
        return _topic(rng)
    if wire_type == UTF8_PAIR:
        return [(f"k{i}", f"v{i}") for i in range(rng.randint(1, 3))]
    raise AssertionError(wire_type)


# properties the codec normalizes rather than echoing verbatim
_SKIP_PROPS = {"Subscription-Identifier"}


def _props_for(rng, pkt_type):
    """Random VALID property dict for a packet type."""
    out = {}
    for pid, (name, wt, allowed) in PROPS.items():
        if name in _SKIP_PROPS:
            continue
        if allowed is not None and pkt_type not in allowed:
            continue
        if rng.random() < 0.35:
            out[name] = _prop_value(rng, wt)
    return out


def gen_packet(rng, version):
    v5 = version == C.MQTT_V5
    t = rng.choice(["connect", "connack", "publish", "ack", "subscribe",
                    "suback", "unsubscribe", "unsuback", "pingreq",
                    "pingresp", "disconnect", "auth"])
    if t == "connect":
        will = rng.random() < 0.5
        return Connect(
            proto_ver=version,
            proto_name=C.PROTOCOL_NAMES[version],
            client_id="cli-%d" % rng.randint(0, 999),
            clean_start=bool(rng.randint(0, 1)),
            keepalive=rng.randint(0, 0xFFFF),
            username=rng.choice([None, "user"]),
            password=rng.choice([None, b"pw\x00\xff"]),
            will_flag=will,
            will_qos=rng.randint(0, 2) if will else 0,
            will_retain=bool(rng.randint(0, 1)) if will else False,
            will_topic=_topic(rng) if will else None,
            will_payload=rng.randbytes(rng.randint(0, 32))
            if will else b"",
            will_props=_props_for(rng, C.PUBLISH)
            if (will and v5) else {},
            properties=_props_for(rng, C.CONNECT) if v5 else {},
        )
    if t == "connack":
        return Connack(
            session_present=bool(rng.randint(0, 1)),
            reason_code=rng.choice([0, 0x80, 0x85, 0x87]),
            properties=_props_for(rng, C.CONNACK) if v5 else {})
    if t == "publish":
        qos = rng.randint(0, 2)
        props = _props_for(rng, C.PUBLISH) if v5 else {}
        props.pop("Topic-Alias", None)  # alias0 is a protocol error
        if v5 and rng.random() < 0.5:
            props["Topic-Alias"] = rng.randint(1, 0xFFFF)
        return Publish(
            topic=_topic(rng), qos=qos,
            retain=bool(rng.randint(0, 1)),
            dup=bool(rng.randint(0, 1)) if qos else False,
            packet_id=rng.randint(1, 0xFFFF) if qos else None,
            payload=rng.randbytes(rng.randint(0, 64)),
            properties=props)
    if t == "ack":
        ptype = rng.choice([C.PUBACK, C.PUBREC, C.PUBREL, C.PUBCOMP])
        return PubAck(
            type=ptype, packet_id=rng.randint(1, 0xFFFF),
            reason_code=rng.choice([0, 0x10, 0x80]) if v5 else 0,
            properties={"Reason-String": "r"}
            if (v5 and rng.random() < 0.3) else {})
    if t == "subscribe":
        props = {}
        if v5 and rng.random() < 0.5:
            props["Subscription-Identifier"] = rng.randint(1, 1000)
        return Subscribe(
            packet_id=rng.randint(1, 0xFFFF),
            topic_filters=[
                (_topic(rng, wild=True),
                 {"qos": rng.randint(0, 2), "nl": rng.randint(0, 1),
                  "rap": rng.randint(0, 1), "rh": rng.randint(0, 2)})
                for _ in range(rng.randint(1, 5))],
            properties=props)
    if t == "suback":
        return Suback(
            packet_id=rng.randint(1, 0xFFFF),
            reason_codes=[rng.choice([0, 1, 2, 0x80])
                          for _ in range(rng.randint(1, 5))],
            properties=_props_for(rng, C.SUBACK) if v5 else {})
    if t == "unsubscribe":
        return Unsubscribe(
            packet_id=rng.randint(1, 0xFFFF),
            topic_filters=[_topic(rng, wild=True)
                           for _ in range(rng.randint(1, 5))])
    if t == "unsuback":
        return Unsuback(
            packet_id=rng.randint(1, 0xFFFF),
            reason_codes=[rng.choice([0, 0x11, 0x80])
                          for _ in range(rng.randint(1, 5))]
            if v5 else [],
            properties=_props_for(rng, C.UNSUBACK) if v5 else {})
    if t == "pingreq":
        return Pingreq()
    if t == "pingresp":
        return Pingresp()
    if t == "disconnect":
        return Disconnect(
            reason_code=rng.choice([0, 0x04, 0x81, 0x9C]) if v5 else 0,
            properties=_props_for(rng, C.DISCONNECT) if v5 else {})
    return Auth(reason_code=rng.choice([0, 0x18, 0x19]),
                properties=_props_for(rng, C.AUTH) if v5 else {})


def _normalize(pkt, version):
    """Fields the wire legitimately does not carry for a version."""
    v5 = version == C.MQTT_V5
    if not v5:
        pkt.properties = {}
        if isinstance(pkt, Connect):
            pkt.will_props = {}
        if isinstance(pkt, (PubAck, Disconnect, Auth)):
            pkt.reason_code = 0
        if isinstance(pkt, Unsuback):
            pkt.reason_codes = []
        if isinstance(pkt, Subscribe):
            # v3/v4 carry only (filter, qos)
            pkt.topic_filters = [
                (f, {"qos": o["qos"], "nl": 0, "rap": 0, "rh": 0})
                for f, o in pkt.topic_filters]
    return pkt


@pytest.mark.parametrize("version", VERSIONS)
def test_exhaustive_roundtrip(version):
    """serialize∘parse == id for every packet type with randomized
    valid contents (2000 packets per protocol version)."""
    rng = random.Random(1000 + version)
    parser = Parser(version=version)
    for i in range(2000):
        pkt = gen_packet(rng, version)
        if isinstance(pkt, (Auth,)) and version != C.MQTT_V5:
            continue  # AUTH exists only in v5
        data = serialize(pkt, version)
        if isinstance(pkt, Connect):
            parser = Parser()  # fresh parser negotiates on CONNECT
        got = parser.feed(data)
        assert len(got) == 1, (i, pkt)
        want = _normalize(pkt, version)
        assert got[0] == want, (i, version, want, got[0])


def test_roundtrip_stream_interleaved_versions_fragmented():
    """A long stream of random packets split at random byte
    boundaries parses identically to whole-packet feeds."""
    rng = random.Random(77)
    for version in VERSIONS:
        pkts = [gen_packet(rng, version) for _ in range(100)]
        pkts = [p for p in pkts
                if not (isinstance(p, Auth) and version != C.MQTT_V5)
                and not isinstance(p, Connect)]
        blob = b"".join(serialize(p, version) for p in pkts)
        parser = Parser(version=version)
        got = []
        i = 0
        while i < len(blob):
            n = rng.randint(1, 40)
            got.extend(parser.feed(blob[i:i + n]))
            i += n
        assert [type(g) for g in got] == [type(p) for p in pkts]
        assert got == [_normalize(p, version) for p in pkts]


def test_corruption_never_crashes_parser():
    """Adversarial bytes: flip/truncate/extend random packets — the
    parser must either parse cleanly or raise its own error types,
    never IndexError/KeyError/UnicodeDecodeError."""
    rng = random.Random(31337)
    for version in VERSIONS:
        for _ in range(1500):
            pkt = gen_packet(rng, version)
            if isinstance(pkt, Auth) and version != C.MQTT_V5:
                continue
            data = bytearray(serialize(pkt, version))
            mode = rng.random()
            if mode < 0.4 and data:      # flip 1-4 bytes
                for _ in range(rng.randint(1, 4)):
                    k = rng.randrange(len(data))
                    data[k] ^= rng.randint(1, 255)
            elif mode < 0.7:             # truncate
                data = data[:rng.randrange(max(1, len(data)))]
            else:                        # append garbage
                data += rng.randbytes(rng.randint(1, 16))
            parser = Parser(version=version, max_size=1 << 20)
            try:
                parser.feed(bytes(data))
            except (FrameError, FrameTooLarge):
                pass  # the contract: typed errors only


def test_pure_garbage_streams():
    rng = random.Random(4242)
    for _ in range(300):
        parser = Parser(version=C.MQTT_V5, max_size=1 << 16)
        try:
            parser.feed(rng.randbytes(rng.randint(1, 512)))
        except (FrameError, FrameTooLarge):
            pass


def test_native_scanner_parity_with_python_parser():
    """The C frame scanner (opt-in fast path) must produce EXACTLY
    the Python parser's packets for valid streams, across versions,
    QoS levels, chunk boundaries, and packet types."""
    import random

    import pytest

    from emqx_tpu.mqtt import constants as C
    from emqx_tpu.mqtt import frame as F
    from emqx_tpu.mqtt.packet import (Connect, Pingreq, PubAck, Publish,
                                      Subscribe)
    from emqx_tpu.ops import native

    if not native.available():
        pytest.skip("native library unavailable")

    rng = random.Random(99)
    for ver in (C.MQTT_V4, C.MQTT_V5):
        pkts = [Connect(client_id="fz", clean_start=True,
                        proto_ver=ver)]
        for i in range(80):
            r = rng.random()
            if r < 0.6:
                props = ({"Message-Expiry-Interval": 9}
                         if ver == C.MQTT_V5 and rng.random() < 0.3
                         else {})
                qos = rng.choice([0, 0, 1, 2])
                pkts.append(Publish(
                    topic=f"fz/{i}/t", qos=qos,
                    packet_id=(i + 1 if qos else None),
                    retain=bool(rng.random() < 0.2),
                    properties=props,
                    payload=bytes(rng.randbytes(rng.randrange(64)))))
            elif r < 0.8:
                pkts.append(PubAck(type=C.PUBACK, packet_id=i + 1))
            elif r < 0.9:
                pkts.append(Subscribe(
                    packet_id=i + 1,
                    topic_filters=[(f"fz/{i}/+", {"qos": 1})]))
            else:
                pkts.append(Pingreq())
        stream = b"".join(F.serialize(p, ver) for p in pkts)
        for chunk in (1, 7, 1024, len(stream)):
            py = F.Parser(version=ver)
            nat = F.Parser(version=ver)
            nat._NATIVE_MIN = 0  # force the native path regardless
            saved = F._scan
            got_py, got_nat = [], []
            try:
                F._scan = False
                for o in range(0, len(stream), chunk):
                    got_py += py.feed(stream[o:o + chunk])
                F._scan = None
                import os
                os.environ["EMQX_TPU_NATIVE_FRAME"] = "1"
                F._get_scan()
                assert F._scan is not False
                for o in range(0, len(stream), chunk):
                    got_nat += nat.feed(stream[o:o + chunk])
            finally:
                F._scan = saved
                os.environ.pop("EMQX_TPU_NATIVE_FRAME", None)
            assert got_py == got_nat, (ver, chunk)


# -- 3-way differential: NativeParser vs Parser vs the indie codec ---------
#
# Three independent implementations of the same wire format: the C++
# incremental parser (native/emqx_native.cpp through NativeParser),
# the pure-Python Parser, and tests/indie_mqtt.py (a from-scratch
# codec with its own reading of the spec). A mirrored misreading
# between the two in-tree engines fails against indie; a native-port
# bug fails against Python. Compared: parsed packets on valid
# streams, error CLASS + message + retained-buffer length on
# malformed input, and resume behavior at EVERY byte split.

from emqx_tpu.mqtt.frame import NativeParser
from emqx_tpu.ops import native as _nat

needs_native_parser = pytest.mark.skipif(
    not _nat.has_frame_parser(),
    reason="native frame parser not built")


def _feed_outcome(parser, chunks):
    """(\"ok\", packets) or (error class name, message, pending bytes)
    — the full observable surface of a feed sequence."""
    got = []
    try:
        for c in chunks:
            got.extend(parser.feed(c))
    except (FrameError, FrameTooLarge) as e:
        return (type(e).__name__, str(e), parser.pending())
    return ("ok", got)


def _pending(parser):
    return parser.pending()


@needs_native_parser
@pytest.mark.parametrize("version", [4, 5])
def test_differential_indie_built_stream(version):
    """Client→server stream built by the INDIE codec: both in-tree
    parsers must agree with each other AND with indie's intent."""
    from tests import indie_mqtt as im

    rng = random.Random(505 + version)
    parts = [im.build_connect("diff", version=version)]
    intents = []  # (topic, payload, qos, pkt_id) per PUBLISH, in order
    for i in range(120):
        r = rng.random()
        if r < 0.5:
            qos = rng.choice([0, 0, 1, 2])
            topic = f"d/{i}/{rng.choice(_TOPIC_WORDS)}"
            payload = rng.randbytes(rng.randrange(96))
            pid = i + 1 if qos else None
            parts.append(im.build_publish(
                topic, payload, qos=qos, pkt_id=pid, version=version,
                retain=bool(rng.random() < 0.2)))
            intents.append((topic, payload, qos, pid))
        elif r < 0.7:
            parts.append(im.build_subscribe(
                i + 1, [(f"d/{i}/+", rng.randint(0, 2))],
                version=version))
        elif r < 0.8:
            parts.append(im.build_puback_like(
                C.PUBACK, i + 1, version=version))
        elif r < 0.9:
            parts.append(im.build_pingreq())
        else:
            parts.append(im.build_unsubscribe(
                i + 1, [f"d/{i}/#"], version=version))
    stream = b"".join(parts)

    for chunk in (1, 3, 17, 256, len(stream)):
        py = Parser()
        nat = NativeParser()
        chunks = [stream[o:o + chunk]
                  for o in range(0, len(stream), chunk)]
        op, on = _feed_outcome(py, chunks), _feed_outcome(nat, chunks)
        assert op == on, (version, chunk)
        assert op[0] == "ok"
        pubs = [p for p in op[1] if isinstance(p, Publish)]
        got_intents = [(p.topic, p.payload, p.qos, p.packet_id)
                       for p in pubs]
        assert got_intents == intents, (version, chunk)


@needs_native_parser
def test_differential_resume_at_every_byte_split():
    """One stream, split at EVERY byte boundary into two feeds: both
    parsers must return the whole-feed reference packet list from
    every resume point."""
    rng = random.Random(808)
    pkts = []
    for i in range(12):
        pkts.append(gen_packet(rng, C.MQTT_V4))
    pkts = [p for p in pkts if not isinstance(p, (Connect, Auth))]
    pkts.append(Publish(topic="r/s", qos=1, packet_id=7,
                        payload=b"tail" * 20))
    stream = b"".join(serialize(p, C.MQTT_V4) for p in pkts)
    ref = Parser(version=C.MQTT_V4).feed(stream)
    assert len(ref) == len(pkts)
    for i in range(len(stream) + 1):
        py = Parser(version=C.MQTT_V4)
        nat = NativeParser(version=C.MQTT_V4)
        gp = py.feed(stream[:i]) + py.feed(stream[i:])
        gn = nat.feed(stream[:i]) + nat.feed(stream[i:])
        assert gp == ref, i
        assert gn == ref, i
        assert _pending(py) == _pending(nat) == 0, i


@needs_native_parser
@pytest.mark.parametrize("version", VERSIONS)
def test_differential_error_classes_on_malformed(version):
    """Corrupted streams: both engines must agree on the FULL
    outcome — packets when clean, else error class, error message,
    and how many bytes stay buffered (raise-before-consume)."""
    rng = random.Random(31991 + version)
    for trial in range(600):
        good = [gen_packet(rng, version) for _ in range(2)]
        good = [p for p in good
                if not isinstance(p, (Connect, Auth))]
        victim = gen_packet(rng, version)
        if isinstance(victim, (Connect, Auth)):
            victim = Publish(topic="v/t", payload=b"x")
        data = bytearray(serialize(victim, version))
        mode = rng.random()
        if mode < 0.4 and data:
            for _ in range(rng.randint(1, 4)):
                k = rng.randrange(len(data))
                data[k] ^= rng.randint(1, 255)
        elif mode < 0.7:
            data = data[:rng.randrange(max(1, len(data)))]
        else:
            data += rng.randbytes(rng.randint(1, 16))
        blob = (b"".join(serialize(p, version) for p in good)
                + bytes(data))
        py = Parser(version=version, max_size=1 << 20)
        nat = NativeParser(version=version, max_size=1 << 20)
        op = _feed_outcome(py, [blob])
        on = _feed_outcome(nat, [blob])
        if op[0] == "ok":
            assert on == op, (trial, op, on)
        else:
            # class + message must match; buffered remainder too
            assert on[0] == op[0], (trial, op, on)
            assert on[1] == op[1], (trial, op, on)
            assert on[2] == op[2], (trial, op, on)


@needs_native_parser
def test_differential_server_to_client_against_indie():
    """Server→client frames serialized by the repo: both in-tree
    parsers and the indie decoder must extract the same fields."""
    from tests import indie_mqtt as im

    rng = random.Random(2718)
    for version in (C.MQTT_V4, C.MQTT_V5):
        pkts = []
        for _ in range(60):
            p = gen_packet(rng, version)
            if isinstance(p, (Connect, Subscribe, Unsubscribe,
                              Pingreq)):
                continue
            if isinstance(p, Auth) and version != C.MQTT_V5:
                continue
            pkts.append(p)
        blob = b"".join(serialize(p, version) for p in pkts)
        got_py = Parser(version=version).feed(blob)
        got_nat = NativeParser(version=version).feed(blob)
        assert got_py == got_nat
        # indie's framing + decode over the same bytes
        iv = 5 if version == C.MQTT_V5 else 4
        off, got_indie = 0, []
        while off < len(blob):
            ptype, flags = blob[off] >> 4, blob[off] & 0x0F
            rl, noff = im.dec_varint(blob, off + 1)
            body = blob[noff:noff + rl]
            got_indie.append(im.decode(ptype, flags, body, iv))
            off = noff + rl
        assert len(got_indie) == len(got_py)
        for mine, theirs in zip(got_py, got_indie):
            if isinstance(mine, Publish):
                assert (mine.topic, mine.payload, mine.qos,
                        mine.retain) == (theirs.topic, theirs.payload,
                                         theirs.qos, theirs.retain)
                if mine.qos:
                    assert mine.packet_id == theirs.pkt_id
            elif isinstance(mine, Connack):
                assert (mine.session_present, mine.reason_code) == \
                    (theirs.session_present, theirs.rc)
            elif isinstance(mine, PubAck):
                assert mine.packet_id == theirs.pkt_id
                if version == C.MQTT_V5:
                    assert mine.reason_code == theirs.rc
            elif isinstance(mine, (Suback, Unsuback)):
                assert mine.packet_id == theirs.pkt_id
                assert list(mine.reason_codes) == theirs.rcs
            elif isinstance(mine, Disconnect) and version == C.MQTT_V5:
                assert mine.reason_code == theirs.rc
