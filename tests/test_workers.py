"""SO_REUSEPORT worker-pool front door (emqx_tpu.workers): N OS
processes share one MQTT port, clustered, so a subscriber accepted by
one worker receives publishes ingested by any other (the reference's
esockd acceptor pool role, src/emqx_listeners.erl:43-81, rebuilt as
process sharding over the cluster plane)."""

import asyncio
import socket

import pytest

from emqx_tpu.mqtt import constants as C
from emqx_tpu.workers import WorkerPool
from tests.mqtt_client import TestClient

needs_reuseport = pytest.mark.skipif(
    not hasattr(socket, "SO_REUSEPORT"), reason="no SO_REUSEPORT")


@needs_reuseport
def test_worker_pool_cross_worker_delivery():
    async def main():
        with WorkerPool(2, port=0,
                        platform="cpu", cookie="wk-test") as pool:
            port = pool.port
            # many connections: the kernel hashes each 4-tuple to a
            # worker, so subscribers and publishers spread over both
            subs = []
            for i in range(6):
                s = TestClient(f"wsub{i}", version=C.MQTT_V5)
                await s.connect(port=port)
                await s.subscribe("wk/+", qos=0)
                subs.append(s)
            await asyncio.sleep(0.7)  # route replication settles
            pub = TestClient("wpub", version=C.MQTT_V5)
            await pub.connect(port=port)
            for k in range(3):
                await pub.publish(f"wk/{k}", f"m{k}".encode(), qos=1)
            got = []
            for s in subs:
                for _ in range(3):
                    m = await s.recv(30)
                    got.append((s.client_id, m.topic, m.payload))
            assert len(got) == 18  # 6 subs x 3 publishes
            stats = pool.stats()
            total_conns = sum(c for c, _ in stats)
            assert total_conns == 7, stats
            # deliveries happened on whichever workers own the subs
            assert sum(d for _, d in stats) >= 18, stats
            for c in subs + [pub]:
                await c.close()

    asyncio.run(main())


@needs_reuseport
def test_worker_pool_same_clientid_across_workers():
    """The distributed clientid lock holds across the worker pool:
    a duplicate clientid through the shared port ends with exactly
    one live session."""
    async def main():
        with WorkerPool(2, port=0,
                        platform="cpu", cookie="wk-test2") as pool:
            c1 = TestClient("wdup", version=C.MQTT_V5)
            await c1.connect(port=pool.port)
            # force a distinct 4-tuple (new source port) so the second
            # connect may land on the other worker
            c2 = TestClient("wdup", version=C.MQTT_V5)
            await c2.connect(port=pool.port)
            await asyncio.sleep(0.7)
            stats = pool.stats()
            assert sum(c for c, _ in stats) == 1, stats
            await c2.close()

    asyncio.run(main())


@needs_reuseport
def test_worker_killed_mid_traffic_cluster_recovers():
    """SIGKILL one worker while clients are live: the survivor keeps
    serving, the dead worker's routes purge after the probe declares
    nodedown, and fresh clients (re)connecting through the shared
    port get full delivery (reference failure story: nodedown route
    purge, src/emqx_router_helper.erl:135-144, driven end-to-end)."""
    async def main():
        with WorkerPool(2, port=0,
                        platform="cpu", cookie="wk-kill") as pool:
            port = pool.port
            subs = []
            for i in range(6):
                s = TestClient(f"kr{i}", version=C.MQTT_V5)
                await s.connect(port=port)
                await s.subscribe("kr/+", qos=1)
                subs.append(s)
            await asyncio.sleep(0.7)
            pub = TestClient("krpub", version=C.MQTT_V5)
            await pub.connect(port=port)
            await pub.publish("kr/a", b"before", qos=1, timeout=30)
            for s in subs:
                assert (await s.recv(30)).payload == b"before"

            pool.procs[1].kill()  # hard death, no goodbye
            # probe (3 attempts, backoff) must declare nodedown and
            # purge the dead worker's routes on the survivor
            await asyncio.sleep(4.0)

            # clients that were on the dead worker lost their socket;
            # survivors must still respond
            live = []
            for s in subs:
                try:
                    await s.ping(timeout=3)
                    live.append(s)
                except Exception:
                    pass
            # a fresh subscriber lands on the survivor (only binder
            # left on the port)
            fresh = TestClient("kr-new", version=C.MQTT_V5)
            await fresh.connect(port=port)
            await fresh.subscribe("kr/+", qos=1)
            pub2 = TestClient("krpub2", version=C.MQTT_V5)
            await pub2.connect(port=port)
            await pub2.publish("kr/b", b"after", qos=1, timeout=30)
            assert (await fresh.recv(30)).payload == b"after"
            for s in live:
                assert (await s.recv(30)).payload == b"after"
            for c in live + [fresh, pub2]:
                try:
                    await c.close()
                except Exception:
                    pass

    asyncio.run(main())


@needs_reuseport
def test_restart_worker_rejoins_cluster():
    """WorkerPool.restart_worker replaces a dead worker in place and
    the replacement rejoins through a SURVIVING peer (losing the
    original seed must not strand the pool — membership is a mesh)."""
    async def main():
        with WorkerPool(2, port=0,
                        platform="cpu", cookie="wk-rs") as pool:
            port = pool.port
            pool.procs[0].kill()  # kill the SEED worker
            import time as _t
            _t.sleep(0.5)
            pool.restart_worker(0)  # must reseed via worker 1
            await asyncio.sleep(1.0)
            # cross-worker delivery through the rebuilt pool: spread
            # connections until both workers hold at least one, then
            # publish — every subscriber sees it regardless of owner
            subs = []
            for i in range(6):
                s = TestClient(f"rs{i}", version=C.MQTT_V5)
                await s.connect(port=port)
                await s.subscribe("rs/t", qos=1)
                subs.append(s)
            await asyncio.sleep(0.7)
            pub = TestClient("rspub", version=C.MQTT_V5)
            await pub.connect(port=port)
            await pub.publish("rs/t", b"rebuilt", qos=1, timeout=30)
            for s in subs:
                assert (await s.recv(30)).payload == b"rebuilt"
            stats = pool.stats()
            assert all(p.poll() is None for p in pool.procs), stats
            for c in subs + [pub]:
                await c.close()

    asyncio.run(main())
