"""SO_REUSEPORT worker-pool front door (emqx_tpu.workers): N OS
processes share one MQTT port, clustered, so a subscriber accepted by
one worker receives publishes ingested by any other (the reference's
esockd acceptor pool role, src/emqx_listeners.erl:43-81, rebuilt as
process sharding over the cluster plane)."""

import asyncio
import socket

import pytest

from emqx_tpu.mqtt import constants as C
from emqx_tpu.workers import WorkerPool
from tests.mqtt_client import TestClient

needs_reuseport = pytest.mark.skipif(
    not hasattr(socket, "SO_REUSEPORT"), reason="no SO_REUSEPORT")


@needs_reuseport
def test_worker_pool_cross_worker_delivery():
    async def main():
        with WorkerPool(2, port=0,
                        platform="cpu", cookie="wk-test") as pool:
            port = pool.port
            # many connections: the kernel hashes each 4-tuple to a
            # worker, so subscribers and publishers spread over both
            subs = []
            for i in range(6):
                s = TestClient(f"wsub{i}", version=C.MQTT_V5)
                await s.connect(port=port)
                await s.subscribe("wk/+", qos=0)
                subs.append(s)
            await asyncio.sleep(0.7)  # route replication settles
            pub = TestClient("wpub", version=C.MQTT_V5)
            await pub.connect(port=port)
            for k in range(3):
                await pub.publish(f"wk/{k}", f"m{k}".encode(), qos=1)
            got = []
            for s in subs:
                for _ in range(3):
                    m = await s.recv(30)
                    got.append((s.client_id, m.topic, m.payload))
            assert len(got) == 18  # 6 subs x 3 publishes
            stats = pool.stats()
            total_conns = sum(c for c, _ in stats)
            assert total_conns == 7, stats
            # deliveries happened on whichever workers own the subs
            assert sum(d for _, d in stats) >= 18, stats
            for c in subs + [pub]:
                await c.close()

    asyncio.run(main())


@needs_reuseport
def test_worker_pool_same_clientid_across_workers():
    """The distributed clientid lock holds across the worker pool:
    a duplicate clientid through the shared port ends with exactly
    one live session."""
    async def main():
        with WorkerPool(2, port=0,
                        platform="cpu", cookie="wk-test2") as pool:
            c1 = TestClient("wdup", version=C.MQTT_V5)
            await c1.connect(port=pool.port)
            # force a distinct 4-tuple (new source port) so the second
            # connect may land on the other worker
            c2 = TestClient("wdup", version=C.MQTT_V5)
            await c2.connect(port=pool.port)
            await asyncio.sleep(0.7)
            stats = pool.stats()
            assert sum(c for c, _ in stats) == 1, stats
            await c2.close()

    asyncio.run(main())
