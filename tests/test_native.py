"""Native engine parity vs the Python oracle + CSR builder."""

import random

import numpy as np
import pytest

from emqx_tpu import topic as T
from emqx_tpu.oracle import TrieOracle
from emqx_tpu.ops import native
from emqx_tpu.ops.match import match_batch
from emqx_tpu.ops.tokenize import WordTable, encode_batch

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def _random_filter(rng, maxlen=6):
    words = ["a", "b", "c", "d", "e", "x", "yy", "z0", "$s", ""]
    n = rng.randint(1, maxlen)
    ws = []
    for i in range(n):
        r = rng.random()
        if r < 0.2:
            ws.append("+")
        elif r < 0.3 and i == n - 1:
            ws.append("#")
        else:
            ws.append(rng.choice(words))
    return "/".join(ws)


def _random_name(rng):
    words = ["a", "b", "c", "d", "e", "x", "yy", "z0", "$s", "", "new"]
    return "/".join(rng.choice(words) for _ in range(rng.randint(1, 6)))


def test_native_match_parity_random():
    rng = random.Random(3)
    eng = native.NativeEngine()
    oracle = TrieOracle()
    filters = sorted({_random_filter(rng) for _ in range(500)})
    fids = {f: i for i, f in enumerate(filters)}
    for f in filters:
        eng.insert(f, fids[f])
        oracle.insert(f)
    inv = {v: k for k, v in fids.items()}
    for _ in range(600):
        name = _random_name(rng)
        got = sorted(inv[i] for i in eng.match(name))
        expect = sorted(oracle.match(name))
        assert got == expect, (name, got, expect)


def test_native_insert_delete_parity():
    rng = random.Random(5)
    eng = native.NativeEngine()
    oracle = TrieOracle()
    refs = {}
    next_id = [0]

    def fid(f):
        if f not in refs:
            refs[f] = next_id[0]
            next_id[0] += 1
        return refs[f]

    live = {}
    for _ in range(600):
        f = _random_filter(rng)
        if f in live and rng.random() < 0.5:
            eng.delete(f)
            oracle.delete(f)
            live[f] -= 1
            if live[f] == 0:
                del live[f]
        else:
            eng.insert(f, fid(f))
            oracle.insert(f)
            live[f] = live.get(f, 0) + 1
        if rng.random() < 0.25:
            name = _random_name(rng)
            inv = {v: k for k, v in refs.items()}
            got = sorted(inv[i] for i in eng.match(name))
            assert got == sorted(oracle.match(name)), name
    assert eng.num_filters() == len(live)


def test_native_flatten_device_parity():
    """Native CSR arrays drive the device kernel identically to the
    Python-built ones."""
    rng = random.Random(11)
    filters = sorted({_random_filter(rng) for _ in range(300)})
    fids = {f: i for i, f in enumerate(filters)}
    # python build
    table = WordTable()
    oracle = TrieOracle()
    for f in filters:
        oracle.insert(f)
        for w in T.words(f):
            table.intern(w)
    # native build
    eng = native.NativeEngine()
    for f in filters:
        eng.insert(f, fids[f])
    auto_n = eng.flatten()

    topics = [_random_name(rng) for _ in range(64)]
    ids_n, n_n, sys_n = eng.encode_batch(topics, 8)
    res = match_batch(auto_n, ids_n, n_n, sys_n, k=64, m=128)
    inv = {v: k for k, v in fids.items()}
    mid = np.asarray(res.ids)
    ovf = np.asarray(res.overflow)
    for i, t in enumerate(topics):
        if ovf[i]:
            continue
        got = sorted(inv[j] for j in mid[i] if j >= 0)
        assert got == sorted(oracle.match(t)), t


def test_native_encode_matches_python():
    eng = native.NativeEngine()
    table = WordTable()
    # the native engine pre-interns '+'/'#' at trie construction
    table.intern("+")
    table.intern("#")
    for f in ["a/b/c", "x//y", "$SYS/z"]:
        for w in f.split("/"):
            eng.intern(w)
            table.intern(w)
    topics = ["a/b/c", "x//y", "$SYS/z", "unknown/word", "a",
              "/".join(["d"] * 40), "$SYS/" + "/".join(["d"] * 40)]
    ids_n, n_n, sys_n = eng.encode_batch(topics, 16)
    ids_p, n_p, sys_p = encode_batch(table, topics, 16)
    assert (ids_n == ids_p).all()
    assert (n_n == n_p).all()
    assert (sys_n == sys_p).all()


def test_native_match_grows_past_cap():
    """The fallback matcher must return ALL matches even when the
    initial output buffer is smaller than the match count."""
    eng = native.NativeEngine()
    eng.insert("m/1", 0)
    eng.insert("m/+", 1)
    eng.insert("m/#", 2)
    eng.insert("#", 3)
    got = eng.match("m/1", cap=2)  # cap < 4 matches
    assert sorted(got) == [0, 1, 2, 3]


def test_native_churn_prunes_nodes():
    """Unique-filter churn must not grow the trie without bound."""
    eng = native.NativeEngine()
    eng.insert("keep/#", 0)
    s0, e0 = eng.counts()
    for i in range(2000):
        f = f"reply/client-{i}/inbox"
        eng.insert(f, 1)
        eng.delete(f)
    s1, e1 = eng.counts()
    assert (s1, e1) == (s0, e0)
    # matching still exact after churn
    assert list(eng.match("keep/x")) == [0]
    assert list(eng.match("reply/client-5/inbox")) == []


def test_native_flatten_capacity_growth():
    eng = native.NativeEngine()
    eng.insert("a/b", 0)
    a1 = eng.flatten()
    eng.insert("a/+/c", 1)
    a2 = eng.flatten(state_capacity=a1.row_ptr.shape[0] - 1,
                     edge_capacity=a1.edge_word.shape[0])
    assert a2.n_states >= a1.n_states


def test_native_o1_counts_match_dfs_oracle():
    """trie_counts is now O(1) incremental arithmetic (live-node and
    live-edge counters maintained on insert/delete-prune); the old
    DFS stays exported as the oracle — they must agree after any
    randomized churn, since every flatten sizes its capacities from
    these numbers."""
    rng = random.Random(11)
    eng = native.NativeEngine()
    live = {}
    for step in range(4000):
        if live and rng.random() < 0.45:
            f = rng.choice(list(live))
            eng.delete(f)
            del live[f]
        else:
            f = _random_filter(rng)
            if f not in live:
                eng.insert(f, len(live))
                live[f] = True
        if step % 500 == 0:
            assert eng.counts() == eng.counts_scan()
    assert eng.counts() == eng.counts_scan()
    # drain everything: back to the root-only trie
    for f in list(live):
        eng.delete(f)
    assert eng.counts() == eng.counts_scan() == (1, 0)
