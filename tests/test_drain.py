"""Zero-downtime operations: graceful drain, custody hand-off,
rolling cluster restart (docs/OPERATIONS.md, emqx_tpu/drain.py).

The acceptance properties: a draining node refuses new CONNECTs with
a redirect (0x9C + Server-Reference on v5), moves its live clients in
paced waves whose budget adapts to the receiving peer's overload
level, suppresses wills exactly like the cm takeover path (custody
moves, sessions do not die), never trips the flapping auto-ban, and
hands persistent-session custody to the target zero-RPO
(digest-verified, exactly-one-holder) — so a 3-node rolling restart
under live durable QoS1 traffic loses and duplicates nothing.

Multi-node-in-one-process over real sockets, the
tests/test_cluster_heal.py harness shape.
"""

import asyncio
import concurrent.futures
import os
import threading
import time

import pytest

from emqx_tpu.cluster import ClusterConfig
from emqx_tpu.drain import DrainConfig
from emqx_tpu.durability import DurabilityConfig
from emqx_tpu.flapping import Flapping, FlappingConfig
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import reason_codes as RC
from emqx_tpu.node import Node
from emqx_tpu.replication import sessions_digest
from emqx_tpu.session import Session
from emqx_tpu.types import Message, SubOpts
from emqx_tpu.zone import Zone

from tests.mqtt_client import TestClient


def _fast_cluster(**kw) -> ClusterConfig:
    base = dict(heartbeat_interval_s=0.1, heartbeat_timeout_s=0.5,
                suspect_after=1, down_after=4, ok_after=1,
                anti_entropy_interval_s=1.0, call_timeout_s=3.0,
                redial_backoff_s=0.1, redial_backoff_max_s=0.5)
    base.update(kw)
    return ClusterConfig(**base)


async def _await(pred, timeout=20.0, msg="condition not met in time"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(msg)


async def _mk_node(name, tmp_path, cookie, peers=(), zone=None,
                   drain_kw=None, durable=True, join_port=None,
                   cluster_kw=None, port=0, cluster_port=0):
    """One started node with a TCP listener and a socket cluster
    transport; ``peers`` are the durability standbys by node name.
    Fixed ``port``/``cluster_port`` let a restart rebind the SAME
    addresses — what a production rolling restart does (ephemeral
    re-binds make every peer's pooled links and address book stale
    at once, which is an artifact, not the scenario)."""
    dur = None
    if durable:
        dur = DurabilityConfig(
            enabled=True, dir=str(tmp_path / name), fsync=False,
            standbys=tuple(peers), ack_quorum=1 if peers else 0,
            quorum_timeout_ms=500.0, repl_ack_timeout_s=2.0)
    node = Node(name=name, boot_listeners=False, durability=dur,
                drain=DrainConfig(**(drain_kw or {})))
    node.add_listener(port=port, zone=zone)
    node.enable_cluster(port=cluster_port, cookie=cookie,
                        config=_fast_cluster(**(cluster_kw or {})))
    await node.start()
    if join_port is not None:
        await asyncio.get_running_loop().run_in_executor(
            None, node.cluster.join_remote, "127.0.0.1", join_port)
    return node


async def _stop_all(*nodes):
    for node in nodes:
        try:
            await node.stop()
        except Exception:
            pass


# -- CONNECT gate ---------------------------------------------------------

async def test_drain_rejects_new_connects(tmp_path):
    """DRAINING refuses new CONNECTs: v5 gets 0x9C Use-Another-Server
    + Server-Reference, v3.1.1 the server-unavailable compat code;
    the node.state gauge and the node_draining alarm flip."""
    node = Node(boot_listeners=False)
    node.add_listener(port=0)
    await node.start()
    try:
        node.ctl.run(["drain", "start", "--ref", "10.0.0.9:1883"])
        assert node.node_state == 1
        assert any(a.name == "node_draining"
                   for a in node.alarms.get_alarms("activated"))
        port = node.listeners[0].port
        c5 = TestClient("drv5", version=C.MQTT_V5)
        await c5.connect(port=port)
        assert c5.connack.reason_code == RC.USE_ANOTHER_SERVER
        assert c5.connack.properties.get("Server-Reference") \
            == "10.0.0.9:1883"
        c4 = TestClient("drv4", version=C.MQTT_V4)
        await c4.connect(port=port)
        assert c4.connack.reason_code == 3  # server unavailable
        assert node.metrics.val("drain.rejected.connects") == 2
        node.ctl.run(["drain", "stop"])
        assert node.node_state == 0
        assert not any(a.name == "node_draining"
                       for a in node.alarms.get_alarms("activated"))
        ok = TestClient("drv5b", version=C.MQTT_V5)
        await ok.connect(port=port)
        assert ok.connack.reason_code == RC.SUCCESS
        await ok.close()
    finally:
        await _stop_all(node)


# -- redirect waves -------------------------------------------------------

async def test_drain_redirect_wave_v5_and_will_suppressed(tmp_path):
    """A live v5 client is redirected with DISCONNECT 0x9C +
    Server-Reference; its will does NOT fire (custody hand-off, the
    cm takeover contract) and its persistent session detaches
    intact."""
    node = Node(boot_listeners=False,
                drain=DrainConfig(wave_interval_s=0.05))
    node.add_listener(port=0)
    await node.start()
    published = []
    node.hooks.add("message.publish",
                   lambda msg: published.append(msg.topic))
    try:
        c = TestClient(
            "will5", version=C.MQTT_V5, clean_start=False,
            properties={"Session-Expiry-Interval": 300},
            will_topic="wills/t", will_payload=b"dead")
        await c.connect(port=node.listeners[0].port)
        await c.subscribe("keep/me", qos=1)
        node.ctl.run(["drain", "start", "--ref", "peer:1883"])
        pkt = await asyncio.wait_for(c.acks.get(), 10)
        assert getattr(pkt, "type", None) == C.DISCONNECT
        assert pkt.reason_code == RC.USE_ANOTHER_SERVER
        assert pkt.properties.get("Server-Reference") == "peer:1883"
        await _await(lambda: "will5" in node.cm._detached, 10,
                     "session did not detach")
        sess = node.cm._detached["will5"][0]
        assert "keep/me" in sess.subscriptions
        assert "wills/t" not in published, \
            "drain redirect fired the will"
        await _await(lambda: node.metrics.val("drain.redirects") == 1,
                     10, "redirect not counted")
        await _await(lambda: node.drain.time_to_empty_s is not None,
                     10, "drain never emptied")
    finally:
        node.ctl.run(["drain", "stop"])
        await _stop_all(node)


async def test_drain_wave_budget_adapts_to_target_overload(tmp_path):
    """Wave pacing (docs/OPERATIONS.md): the disconnect budget probes
    the receiving peer's overload level — CRITICAL defers the whole
    wave, recovery lets it proceed."""
    n0 = await _mk_node("bw0", tmp_path, "ck-bw", durable=False)
    n1 = await _mk_node("bw1", tmp_path, "ck-bw", durable=False,
                        join_port=n0.cluster.transport.port)
    try:
        await _await(lambda: len(n0.cluster.members) == 2, 10,
                     "join did not converge")
        c = TestClient("bwc", version=C.MQTT_V5)
        await c.connect(port=n0.listeners[0].port)
        # the target reports CRITICAL: waves must defer
        n1.overload.cfg.clear_ticks = 10 ** 6  # hold the level
        n1.overload.level = 2
        n0.drain.cfg.wave_interval_s = 0.05
        n0.drain.start(target="bw1")
        await _await(
            lambda: n0.metrics.val("drain.waves.deferred") >= 2, 10,
            "waves did not defer against a critical target")
        assert n0.metrics.val("drain.redirects") == 0
        assert not c.reader.at_eof()
        # the target recovers: the held wave proceeds
        n1.overload.level = 0
        await _await(lambda: n0.metrics.val("drain.redirects") == 1,
                     10, "wave did not resume after recovery")
    finally:
        n0.drain.stop()
        await _stop_all(n0, n1)


# -- flapping exemption (satellite) ---------------------------------------

def test_flapping_exempts_server_initiated():
    """Unit pin: ``drained``/``server_shutdown`` disconnects never
    count toward the flap threshold; untagged ones still do."""
    f = Flapping(config=FlappingConfig(max_count=2, window=60.0))
    f.disconnected("c1", reason="drained")
    f.disconnected("c1", reason="server_shutdown")
    assert "c1" not in f._tracks
    f.disconnected("c1", reason="sock_closed")
    f.disconnected("c1")  # untagged legacy call counts too
    assert "c1" not in f._tracks  # hit max_count=2 -> track cleared


async def test_drain_does_not_trip_flapping_ban(tmp_path):
    """Regression (satellite): drain a node whose zone has flapping
    armed at the tightest threshold — zero bans locally AND on the
    receiving peer (bans replicate cluster-wide; a drain that banned
    its own fleet would break every redirected reconnect)."""
    zone = Zone(name="flapz", enable_flapping_detect=True)
    n0 = await _mk_node("fl0", tmp_path, "ck-fl", zone=zone,
                        durable=False,
                        drain_kw={"wave_interval_s": 0.05})
    n1 = await _mk_node("fl1", tmp_path, "ck-fl", durable=False,
                        join_port=n0.cluster.transport.port)
    # any single counted disconnect bans
    n0.broker.flapping.config = FlappingConfig(max_count=1)
    try:
        await _await(lambda: len(n0.cluster.members) == 2, 10,
                     "join did not converge")
        c = TestClient("flapc", version=C.MQTT_V4, clean_start=False)
        await c.connect(port=n0.listeners[0].port)
        n0.drain.start(target="fl1")
        await _await(lambda: n0.metrics.val("drain.redirects") == 1,
                     10, "client was not redirected")
        await asyncio.sleep(0.2)  # let any ban replicate
        assert n0.banned.check(clientid="flapc") is False
        assert n1.banned.check(clientid="flapc") is False
        # the exemption is reason-scoped, not a disabled detector: a
        # client-side abort right after reconnecting still counts
        c2 = TestClient("flapc", version=C.MQTT_V4, clean_start=False)
        await c2.connect(port=n1.listeners[0].port)
        await c2.close()
    finally:
        n0.drain.stop()
        await _stop_all(n0, n1)


# -- v3.1.1 clients (satellite) ------------------------------------------

async def test_drain_v311_reconnects_on_peer_session_intact(tmp_path):
    """v3.1.1 has no server DISCONNECT / Server-Reference: a drained
    v4 client sees a plain close, reconnects to the peer, and finds
    its session through the cluster registry — subscription state
    and queued QoS1 messages intact."""
    n0 = await _mk_node("v30", tmp_path, "ck-v3",
                        peers=("v31",),
                        drain_kw={"wave_interval_s": 0.05})
    n1 = await _mk_node("v31", tmp_path, "ck-v3",
                        join_port=n0.cluster.transport.port)
    try:
        await _await(lambda: len(n0.cluster.members) == 2, 10,
                     "join did not converge")
        c = TestClient("v3c", version=C.MQTT_V4, clean_start=False)
        await c.connect(port=n0.listeners[0].port)
        await c.subscribe("v3/t", qos=1)
        n0.drain.start(target="v31")
        # plain close: EOF, no DISCONNECT packet on the wire
        await _await(lambda: c.reader.at_eof(), 10,
                     "v3 client was not closed")
        assert c.acks.empty()
        # custody hand-off completes before the reconnect
        await _await(lambda: n0.drain.time_to_empty_s is not None,
                     15, "drain did not finish")
        assert n0.drain.handoff_ok is True
        # a QoS1 publish while the client is away queues in the
        # handed session on the PEER
        n1.broker.publish(Message(topic="v3/t", payload=b"queued",
                                  qos=1))
        c2 = TestClient("v3c", version=C.MQTT_V4, clean_start=False)
        await c2.connect(port=n1.listeners[0].port)
        assert c2.connack.session_present is True
        m = await c2.recv(10)
        assert m.topic == "v3/t" and m.payload == b"queued"
        await c2.close()
    finally:
        n0.drain.stop()
        await _stop_all(n0, n1)


# -- custody hand-off -----------------------------------------------------

async def test_drain_handoff_custody_digest_exact(tmp_path):
    """The voluntary zero-RPO failover: detached persistent sessions
    (subscriptions + queued QoS1 state) hand to the target through
    the replication machinery — digest-verified, registry repointed,
    exactly one holder left, routes remapped, and the local journal
    records the closes so a restart resurrects nothing stale."""
    n0 = await _mk_node("hc0", tmp_path, "ck-hc", peers=("hc1",))
    n1 = await _mk_node("hc1", tmp_path, "ck-hc",
                        join_port=n0.cluster.transport.port)
    try:
        await _await(lambda: len(n0.cluster.members) == 2, 10,
                     "join did not converge")
        cids = [f"dev{i}" for i in range(5)]
        for i, cid in enumerate(cids):
            s = Session(cid, broker=n0.broker, clean_start=False)
            n0.durability.session_opened(s, 300.0)
            s.subscribe(f"fleet/{i}/+", SubOpts(qos=1))
            n0.cm._detached[cid] = (s, time.time(), 300.0)
            n0.cluster.client_up(cid)
        n0.broker.publish(Message(topic="fleet/1/x", payload=b"m1",
                                  qos=1))
        n0.durability.on_batch()
        pre = sessions_digest(n0, cids)
        n0.drain.start(target="hc1")
        await _await(lambda: n0.drain.time_to_empty_s is not None,
                     20, "drain did not finish")
        assert n0.drain.handoff_ok is True
        assert n0.drain.handed_off == 5
        assert n0.metrics.val("drain.handoff.sessions") == 5
        # digest-exact on the target, byte-for-byte
        assert sessions_digest(n1, cids) == pre
        # exactly one holder + registry custody on both members
        assert not any(c in n0.cm._detached for c in cids)
        assert all(c in n1.cm._detached for c in cids)
        for cl in (n0.cluster, n1.cluster):
            assert all(cl._registry.get(c) == "hc1" for c in cids)
        # routes moved: target owns them, the drained node does not
        assert n1.router.route_refs("fleet/1/+", "hc1") == 1
        assert n0.router.route_refs("fleet/1/+", "hc0") == 0
        # the journal agrees: a recovery of the drained node's dir
        # resurrects NO handed session (rolling restarts come back
        # clean instead of double-holding)
        await n0.stop()
        n0b = Node(name="hc0", boot_listeners=False,
                   durability=DurabilityConfig(
                       enabled=True, dir=str(tmp_path / "hc0"),
                       fsync=False))
        n0b.durability.recover()
        assert not n0b.cm._detached
        n0b.durability.wal.close()
    finally:
        await _stop_all(n0, n1)


# -- graceful stop with a drain target (satellite) ------------------------

async def test_node_stop_with_drain_target_redirects(tmp_path):
    """Node.stop with a configured drain target sends v5 clients
    DISCONNECT 0x9C + Server-Reference (not 0x8B) and suppresses
    wills — the listener close is itself a redirect."""
    node = Node(boot_listeners=False,
                drain=DrainConfig(target="peer-b",
                                  server_ref="10.1.1.2:1883"))
    node.add_listener(port=0)
    await node.start()
    published = []
    node.hooks.add("message.publish",
                   lambda msg: published.append(msg.topic))
    c = TestClient(
        "stopc", version=C.MQTT_V5, clean_start=False,
        properties={"Session-Expiry-Interval": 300},
        will_topic="wills/stop", will_payload=b"dead")
    await c.connect(port=node.listeners[0].port)
    await node.stop()
    pkt = await asyncio.wait_for(c.acks.get(), 10)
    assert getattr(pkt, "type", None) == C.DISCONNECT
    assert pkt.reason_code == RC.USE_ANOTHER_SERVER
    assert pkt.properties.get("Server-Reference") == "10.1.1.2:1883"
    assert "wills/stop" not in published, \
        "drain-target stop fired the will"


async def test_node_stop_without_target_keeps_0x8b(tmp_path):
    """The legacy durable graceful stop is unchanged: no drain
    target -> 0x8B Server-Shutting-Down."""
    node = Node(boot_listeners=False,
                durability=DurabilityConfig(
                    enabled=True, dir=str(tmp_path / "d8b"),
                    fsync=False))
    node.add_listener(port=0)
    await node.start()
    c = TestClient("c8b", version=C.MQTT_V5)
    await c.connect(port=node.listeners[0].port)
    await node.stop()
    pkt = await asyncio.wait_for(c.acks.get(), 10)
    assert getattr(pkt, "type", None) == C.DISCONNECT
    assert pkt.reason_code == RC.SERVER_SHUTTING_DOWN


# -- config + validation --------------------------------------------------

def test_drain_config_validation():
    with pytest.raises(ValueError):
        DrainConfig(wave_size=0)
    with pytest.raises(ValueError):
        DrainConfig(wave_interval_s=0)
    with pytest.raises(ValueError):
        DrainConfig(handoff_timeout_s=0)
    from emqx_tpu.config import ConfigError, parse_config
    with pytest.raises(ConfigError):
        parse_config({"drain": {"no_such_knob": 1}})
    with pytest.raises(ConfigError):
        parse_config({"drain": {"wave_size": "many"}})
    cfg = parse_config({"drain": {"wave_size": 5,
                                  "on_sigterm": True}})
    assert cfg.drain.wave_size == 5 and cfg.drain.on_sigterm


def test_drain_start_needs_valid_target():
    node = Node(boot_listeners=False)
    with pytest.raises(ValueError):
        # no running loop
        node.drain.start()


# -- the rolling-restart chaos proof --------------------------------------

class _NodeHost:
    """One broker node on its OWN event loop + thread — the shape a
    production deployment has (one loop per broker process). On a
    single shared loop, a cross-node session pull from inside a
    CONNECT handler deadlocks against the target's owner-loop
    dispatch until the call timeout; per-node loops are the real
    topology the rolling restart runs on."""

    def __init__(self) -> None:
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self.loop.run_forever, daemon=True)
        self.thread.start()
        self.node = None

    async def run(self, coro, timeout=60.0):
        """Await ``coro`` on this host's loop from the test loop."""
        return await asyncio.wait_for(
            asyncio.wrap_future(
                asyncio.run_coroutine_threadsafe(coro, self.loop)),
            timeout)

    def call(self, fn, timeout=30.0):
        """Run sync ``fn()`` on this host's loop; return its result."""
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _go():
            try:
                fut.set_result(fn())
            except BaseException as e:
                fut.set_exception(e)

        self.loop.call_soon_threadsafe(_go)
        return fut.result(timeout)

    def close(self) -> None:
        try:
            self.loop.call_soon_threadsafe(self.loop.stop)
            self.thread.join(2.0)
        except Exception:
            pass


async def test_rolling_restart_3node(tmp_path):
    """The tentpole proof (docs/OPERATIONS.md "Rolling cluster
    restart"): a 3-node quorum-replicated cluster is restarted
    node-by-node — drain to the next peer, stop, boot fresh from
    disk, rejoin — under LIVE durable QoS1 traffic. Zero lost, zero
    duplicated: ``sorted(got) == sorted(sent)`` over unique seqs,
    every repeated delivery carries the DUP flag (a protocol-correct
    inflight redelivery across a custody move, the at-least-once
    contract's own definition of "not a duplicate"), and all five
    replicated planes digest byte-equal after the last rejoin.

    Seeded/paced via ROLLING_MSGS (default 60) so scripts/ci.sh can
    run a bounded smoke."""
    cookie = "ck-roll"
    names = ["rr0", "rr1", "rr2"]
    peers = {n: tuple(x for x in names if x != n) for n in names}
    drain_kw = {"wave_interval_s": 0.1, "handoff_timeout_s": 20.0}
    # starvation-tolerant detector: on this shared-CPU harness a
    # node restart can stall every thread for hundreds of ms, and a
    # hair-trigger down_after would declare LIVE peers dead mid-roll
    # (spurious promotion/purge noise that is a harness artifact,
    # not broker behavior — the PR 13 soak notes pin this class)
    cluster_kw = {"heartbeat_interval_s": 0.2,
                  "heartbeat_timeout_s": 1.0,
                  "suspect_after": 2, "down_after": 25,
                  "ok_after": 1}
    hosts = {n: _NodeHost() for n in names}
    nodes = {}
    nodes["rr0"] = await hosts["rr0"].run(_mk_node(
        "rr0", tmp_path, cookie, peers=peers["rr0"],
        drain_kw=drain_kw, cluster_kw=cluster_kw))
    for n in names[1:]:
        nodes[n] = await hosts[n].run(_mk_node(
            n, tmp_path, cookie, peers=peers[n], drain_kw=drain_kw,
            cluster_kw=cluster_kw,
            join_port=nodes["rr0"].cluster.transport.port))
    ports = {n: nodes[n].listeners[0].port for n in names}

    total = int(os.environ.get("ROLLING_MSGS", "60"))
    phase = ["setup"]
    moves: list = []
    sent: list = []
    got: list = []  # unique seqs, arrival order
    seen: set = set()
    dup_violations: list = []
    session_losses: list = []
    roll_done = asyncio.Event()
    pub_done = asyncio.Event()
    sub_ready = asyncio.Event()

    async def _connect(cid, name, **kw):
        c = TestClient(cid, version=C.MQTT_V4, clean_start=False,
                       **kw)
        await c.connect(port=ports[name], timeout=10.0)
        return c

    attempts: list = []

    async def _reconnect(cid, avoid):
        attempts.append((round(time.time() % 1000, 2), phase[0],
                         cid, "reconnect-start", avoid, None))
        for _ in range(150):
            for name in names:
                if name == avoid and len(names) > 1:
                    continue
                try:
                    attempts.append((round(time.time() % 1000, 2),
                                     phase[0], cid, "dialing", name,
                                     None))
                    c = await _connect(cid, name)
                    attempts.append((round(time.time() % 1000, 2),
                                     phase[0], cid, name,
                                     hex(c.connack.reason_code),
                                     c.connack.session_present))
                    if c.connack.reason_code == 0:
                        moves.append((phase[0], cid, avoid, name,
                                      c.connack.session_present))
                        if not c.connack.session_present:
                            view = {}
                            for x in names:
                                try:
                                    view[x] = (
                                        nodes[x].cluster._registry
                                        .get(cid),
                                        cid in nodes[x].cm._detached,
                                        cid in nodes[x].cm._channels)
                                except Exception:
                                    view[x] = "gone"
                            session_losses.append(
                                (phase[0], cid, name, view, moves[:]))
                        return c, name
                    await c.close()
                except (ConnectionError, OSError,
                        asyncio.TimeoutError, AssertionError) as e:
                    attempts.append((round(time.time() % 1000, 2),
                                     phase[0], cid, name, repr(e)[:60],
                                     None))
            await asyncio.sleep(0.2)
        raise AssertionError(f"{cid} could not reconnect anywhere")

    async def subscriber():
        """Auto-acking QoS1 subscriber that follows the roll: on a
        drain close it reconnects to any live node and resumes its
        persistent session (no resubscribe — the session carries
        it)."""
        home = "rr0"
        c = await _connect("roll-sub", home)
        await c.subscribe("roll/t", qos=1)
        sub_ready.set()
        stall = 0
        while not (pub_done.is_set() and sent
                   and len(seen) >= len(sent)):
            try:
                m = await asyncio.wait_for(c.inbox.get(), 0.3)
                stall = 0
            except asyncio.TimeoutError:
                stall += 1
                # dead either via FIN (at_eof) or RST (the read loop
                # exits on ConnectionResetError without an EOF feed)
                if c.reader.at_eof() or (c._task is not None
                                         and c._task.done()):
                    c, home = await _reconnect("roll-sub", home)
                    stall = 0
                elif stall >= 15:
                    # a persistent stall on a seemingly-live link:
                    # reconnect-and-resume, exactly what a real
                    # client's keepalive/backoff logic does after a
                    # cluster roll — the persistent session replays
                    # whatever queued while the link was dark. A
                    # message the broker actually LOST cannot be
                    # produced by this resume, so the zero-loss
                    # assertion keeps its teeth.
                    await c.close()
                    c, home = await _reconnect("roll-sub", None)
                    stall = 0
                continue
            seq = int(m.payload)
            rx.append((round(time.time() % 1000, 2), seq,
                       bool(m.dup)))
            if seq in seen:
                if not m.dup:
                    dup_violations.append(seq)
                continue
            seen.add(seq)
            got.append(seq)
        await c.close()

    async def publisher():
        """Acked QoS1 publisher spanning the WHOLE roll: each seq's
        PUBACK is awaited; a drain redirect (acks flushed BEFORE the
        DISCONNECT — the drain ordering contract) means an unacked
        seq is safe to republish on the next node. Publishes at
        least ``total`` messages and keeps going until the roll
        completes."""
        from emqx_tpu.mqtt.packet import Publish as P
        from emqx_tpu.mqtt.packet import PubAck
        await sub_ready.wait()  # a pre-subscription publish has no
        # matching subscriber — not a custody property
        home = "rr2"
        c = await _connect("roll-pub", home)
        seq = 0
        while not (roll_done.is_set() and seq >= total):
            sent.append(seq)
            while True:
                try:
                    pid = c.next_pkt_id()
                    await c.send(P(topic="roll/t",
                                   payload=str(seq).encode(),
                                   qos=1, packet_id=pid))
                    acked = False
                    while True:
                        ack = await asyncio.wait_for(c.acks.get(),
                                                     5.0)
                        if isinstance(ack, PubAck) \
                                and ack.type == C.PUBACK \
                                and ack.packet_id == pid:
                            ack_rcs[seq] = ack.reason_code
                            acked = True
                            break
                        if getattr(ack, "type", None) \
                                == C.DISCONNECT:
                            break  # redirect: owed acks were
                            # flushed first, this pid was not among
                            # them -> republish
                    if acked:
                        break
                except (ConnectionError, OSError,
                        asyncio.TimeoutError):
                    pass
                c, home = await _reconnect("roll-pub", home)
            seq += 1
            await asyncio.sleep(0.02)
        pub_done.set()
        await c.close()

    timeline: list = []
    ack_rcs: dict = {}
    rx: list = []

    async def sampler():
        last = None
        while not roll_done.is_set():
            snap = {}
            for x in names:
                try:
                    ids = []
                    ent = nodes[x].cm._detached.get("roll-sub")
                    if ent is not None:
                        ids.append(("det", id(ent[0]) % 100000,
                                    ent[0].connected,
                                    len(ent[0].mqueue)))
                    ch = nodes[x].cm._channels.get("roll-sub")
                    s = getattr(ch, "session", None)
                    if s is not None:
                        ids.append(("live", id(s) % 100000,
                                    s.connected, len(s.mqueue)))
                    wired = tuple(sorted(
                        id(s) % 100000 for s in
                        nodes[x].broker._subscribers.get(
                            "roll/t", {})))
                    snap[x] = (
                        tuple(sorted(str(r.dest) for r in
                                     nodes[x].router.lookup_routes(
                                         "roll/t"))),
                        tuple(ids), wired)
                except Exception:
                    snap[x] = "gone"
            state = (phase[0], repr(snap),
                     len(sent), len(seen))
            if state[:2] != (last[:2] if last else None):
                timeline.append((round(time.time() % 1000, 2),)
                                + state)
            last = state
            await asyncio.sleep(0.05)

    sub_task = asyncio.create_task(subscriber())
    pub_task = asyncio.create_task(publisher())
    sampler_task = asyncio.create_task(sampler())
    try:
        # traffic must be demonstrably flowing before the roll
        await asyncio.wait_for(sub_ready.wait(), 20)
        await _await(lambda: len(seen) >= 5, 20,
                     "no traffic before the roll")
        # one full roll: drain -> stop -> restart-from-disk -> rejoin
        for i, name in enumerate(names):
            target = names[(i + 1) % 3]
            phase[0] = f"drain-{name}"
            node = nodes[name]
            attempts.append((round(time.time() % 1000, 2), phase[0],
                             "pre",
                             {x: (sorted(nodes[x].cm._channels),
                                  sorted(nodes[x].cm._detached))
                              for x in names}))
            hosts[name].call(
                lambda n=node, t=target: n.ctl.run(
                    ["drain", "start", "--target", t]))
            await _await(
                lambda: node.drain.time_to_empty_s is not None,
                60, f"drain of {name} did not finish")
            phase[0] = f"restart-{name}"
            cport = node.cluster.transport.port
            await hosts[name].run(node.stop())
            hosts[name].close()
            # the upgrade restart: same name, same disk, SAME ports
            hosts[name] = _NodeHost()
            fresh = await hosts[name].run(_mk_node(
                name, tmp_path, cookie, peers=peers[name],
                drain_kw=drain_kw, cluster_kw=cluster_kw,
                port=ports[name], cluster_port=cport,
                join_port=nodes[target].cluster.transport.port))
            nodes[name] = fresh
            ports[name] = fresh.listeners[0].port
            await _await(
                lambda: all(len(nodes[x].cluster.members) == 3
                            for x in names),
                30, f"membership did not re-converge after {name}")
            # a real roll waits for fleet health before the next
            # node: both clients must be live again somewhere
            try:
                await _await(
                    lambda: any("roll-sub" in nodes[x].cm._channels
                                for x in names)
                    and any("roll-pub" in nodes[x].cm._channels
                            for x in names),
                    30, f"clients did not re-home after {name}")
            except AssertionError as e:
                raise AssertionError(
                    f"{e}\nattempts={attempts}") from None
        roll_done.set()
        await asyncio.wait_for(pub_task, 120)
        try:
            await asyncio.wait_for(sub_task, 60)
        except asyncio.TimeoutError:
            sub_task.cancel()  # messages missing: the asserts below
            # name exactly which seqs were lost
        assert not session_losses, (
            f"persistent session lost across the roll: "
            f"{session_losses}\nattempts={attempts}")
        if sorted(got) != sorted(sent):
            dump = {}
            for x in names:
                try:
                    sess = None
                    ent = nodes[x].cm._detached.get("roll-sub")
                    if ent is not None:
                        sess = ent[0]
                    ch = nodes[x].cm._channels.get("roll-sub")
                    if ch is not None:
                        sess = getattr(ch, "session", None)
                    dump[x] = {
                        "routes": [(r.topic, r.dest) for r in
                                   nodes[x].router.lookup_routes(
                                       "roll/t")],
                        "det": sorted(nodes[x].cm._detached),
                        "chan": sorted(nodes[x].cm._channels),
                        "fwd_dropped": nodes[x].metrics.val(
                            "cluster.forward.dropped"),
                        "sub_sess": None if sess is None else {
                            "mq": [int(m.payload) for _p, q in
                                   sess.mqueue.snapshot()
                                   for m in q][:15],
                            "inflight": [
                                (pid, int(v[0].payload)
                                 if not isinstance(v[0], str)
                                 else v[0])
                                for pid, v in
                                sess.inflight.to_list()][:15],
                            "subs": sorted(sess.subscriptions),
                        },
                    }
                except Exception as e:
                    dump[x] = repr(e)
            lost = sorted(set(sent) - set(got))
            raise AssertionError(
                f"lost={lost[:10]} "
                f"extra={sorted(set(got) - set(sent))[:10]} "
                f"lost_rcs={[(s, ack_rcs.get(s)) for s in lost[:10]]} "
                f"moves={moves} dump={dump}\n"
                f"attempts={attempts}\n"
                f"rx_tail={rx[-25:]}\n"
                + "\n".join(repr(t) for t in timeline))
        assert not dup_violations, (
            f"non-DUP duplicate deliveries: {dup_violations[:10]}")
        # exactly one holder of the subscriber session cluster-wide
        holders = [n for n in names
                   if "roll-sub" in nodes[n].cm._detached
                   or "roll-sub" in nodes[n].cm._channels]
        assert len(holders) == 1, holders
        # all five replicated planes byte-equal after the last rejoin
        def _converged():
            digs = [nodes[n].cluster.plane_digests() for n in names]
            return all(d == digs[0] for d in digs[1:])
        await _await(_converged, 30,
                     "plane digests did not converge after the roll")
    finally:
        for t in (sub_task, pub_task, sampler_task):
            t.cancel()
        for name in names:
            try:
                await hosts[name].run(nodes[name].stop(), timeout=20)
            except Exception:
                pass
            hosts[name].close()
