"""Compact full-stack soak: concurrent publishers, subscriber churn,
and a mid-stream takeover, with a zero-QoS1-loss assertion.

The reference's takeover suite streams traffic through one session
(test/emqx_takeover_SUITE.erl); this drives the whole node — ingress
batcher, device match, fan-out, sessions — under concurrent load to
catch interaction bugs no single-feature suite sees.
"""

import asyncio

from emqx_tpu.mqtt import constants as C
from tests.helpers import broker_node, node_port as _port
from tests.mqtt_client import TestClient

N_PUBS = 4
MSGS_PER_PUB = 40


async def test_soak_mixed_load_no_qos1_loss():
    async with broker_node() as node:
        port = _port(node)

        # durable subscriber whose session will be taken over mid-run
        sub = TestClient("soak-sub", version=C.MQTT_V5,
                        properties={"Session-Expiry-Interval": 7200})
        await sub.connect(port=port)
        await sub.subscribe("soak/+/data", qos=1)

        # churner adds/removes unrelated filters the whole time
        churner = TestClient("soak-churn")
        await churner.connect(port=port)

        async def churn():
            for i in range(30):
                await churner.subscribe(f"churn/{i}/+")
                if i % 3 == 2:
                    await churner.unsubscribe(f"churn/{i - 1}/+")
                await asyncio.sleep(0.01)

        async def publish_stream(k):
            pub = TestClient(f"soak-pub{k}")
            await pub.connect(port=port)
            for i in range(MSGS_PER_PUB):
                await pub.publish(f"soak/{k}/data",
                                  f"{k}:{i}".encode(), qos=1,
                                  timeout=60)
            await pub.disconnect()

        got = set()
        takeover_done = asyncio.Event()

        async def drain_with_takeover():
            nonlocal sub
            while len(got) < N_PUBS * MSGS_PER_PUB:
                try:
                    m = await asyncio.wait_for(sub.inbox.get(), 30)
                except asyncio.TimeoutError:
                    break
                got.add(m.payload)
                if len(got) == N_PUBS * MSGS_PER_PUB // 3 \
                        and not takeover_done.is_set():
                    takeover_done.set()
                    # same clientid reconnects: kicks the old
                    # connection, resumes the session, replays
                    newc = TestClient(
                        "soak-sub", version=C.MQTT_V5,
                        clean_start=False,
                        properties={"Session-Expiry-Interval": 7200})
                    ack = await newc.connect(port=port, timeout=30)
                    assert ack.session_present
                    # the old client object may hold delivered-and-
                    # auto-acked messages in its inbox: the broker is
                    # done with them, so the TEST must not drop them
                    while not sub.inbox.empty():
                        got.add(sub.inbox.get_nowait().payload)
                    sub = newc

        await asyncio.gather(
            churn(), drain_with_takeover(),
            *(publish_stream(k) for k in range(N_PUBS)))
        # drain the tail after the publishers finish
        deadline = asyncio.get_running_loop().time() + 30
        while len(got) < N_PUBS * MSGS_PER_PUB and \
                asyncio.get_running_loop().time() < deadline:
            try:
                m = await asyncio.wait_for(sub.inbox.get(), 5)
                got.add(m.payload)
            except asyncio.TimeoutError:
                pass

        want = {f"{k}:{i}".encode()
                for k in range(N_PUBS) for i in range(MSGS_PER_PUB)}
        missing = want - got
        assert not missing, \
            f"lost {len(missing)} QoS1 messages: {sorted(missing)[:8]}"
        assert takeover_done.is_set()
        await sub.disconnect()
        await churner.disconnect()


X_PUBS = 3
X_MSGS = 30


async def test_soak_cross_node_no_qos1_loss():
    """Two-node variant over real MQTT sockets: subscribers on node B,
    publishers on node A, route churn throughout — every QoS1 message
    must cross the cluster seam, with no duplicates."""
    from emqx_tpu.cluster import Cluster, LocalTransport
    from emqx_tpu.node import Node

    transport = LocalTransport()
    a = Node(name="soakA", boot_listeners=False)
    b = Node(name="soakB", boot_listeners=False)
    a.add_listener(port=0)
    b.add_listener(port=0)
    await a.start()
    await b.start()
    ca, cb = Cluster(a, transport), Cluster(b, transport)
    ca.join(cb)
    try:
        sub = TestClient("xsub")
        await sub.connect(port=b.listeners[0].port)
        await sub.subscribe("xn/+/d", qos=1)
        churner = TestClient("xchurn")
        await churner.connect(port=b.listeners[0].port)

        async def churn():
            for i in range(25):
                await churner.subscribe(f"xc/{i}")
                await asyncio.sleep(0.01)

        async def stream(k):
            pub = TestClient(f"xpub{k}")
            await pub.connect(port=a.listeners[0].port)
            for i in range(X_MSGS):
                await pub.publish(f"xn/{k}/d", f"{k}:{i}".encode(),
                                  qos=1, timeout=60)
            await pub.disconnect()

        got = {}

        async def drain():
            want_n = X_PUBS * X_MSGS
            deadline = asyncio.get_running_loop().time() + 60
            while len(got) < want_n and \
                    asyncio.get_running_loop().time() < deadline:
                try:
                    m = await asyncio.wait_for(sub.inbox.get(), 5)
                    got[m.payload] = got.get(m.payload, 0) + 1
                except asyncio.TimeoutError:
                    pass

        await asyncio.gather(churn(), drain(),
                             *(stream(k) for k in range(X_PUBS)))
        # tail-drain so a late duplicate would be counted, not raced
        await asyncio.sleep(0.5)
        while not sub.inbox.empty():
            m = sub.inbox.get_nowait()
            got[m.payload] = got.get(m.payload, 0) + 1
        want = {f"{k}:{i}".encode()
                for k in range(X_PUBS) for i in range(X_MSGS)}
        missing = want - set(got)
        assert not missing, f"lost across nodes: {sorted(missing)[:8]}"
        dups = {p: n for p, n in got.items() if n > 1}
        assert not dups, f"duplicate cross-node deliveries: {dups}"
        await sub.disconnect()
        await churner.disconnect()
    finally:
        await a.stop()
        await b.stop()


async def test_soak_device_regime_pipeline_no_loss():
    """Same mixed load, but forced through the DEVICE publish path
    (threshold 0, small batches, deep pipelining): three-phase
    begin/fetch/finish, topic dedup, learned budgets and route churn
    all interleave — QoS1 must still be lossless and in order."""
    from emqx_tpu.router import MatcherConfig

    async with broker_node(
            matcher=MatcherConfig(device_min_filters=0, pack_q=1,
                                  active_k=4),
            batch_size=8) as node:
        port = _port(node)
        sub = TestClient("dsoak-sub", version=C.MQTT_V5,
                         properties={"Session-Expiry-Interval": 3600})
        await sub.connect(port=port)
        await sub.subscribe("dsoak/+/data", qos=1)

        churner = TestClient("dsoak-churn")
        await churner.connect(port=port)

        async def churn():
            for i in range(20):
                await churner.subscribe(f"dchurn/{i}/+/x")
                if i % 2:
                    await churner.unsubscribe(f"dchurn/{i - 1}/+/x")
                await asyncio.sleep(0.005)

        async def publish(pid):
            p = TestClient(f"dsoak-pub{pid}")
            await p.connect(port=port)
            # duplicate topics across publishers exercise the dedup
            for i in range(MSGS_PER_PUB):
                await p.publish(f"dsoak/{i % 5}/data",
                                f"{pid}:{i}".encode(), qos=1,
                                timeout=60)
            await p.disconnect()

        tasks = [asyncio.ensure_future(churn())] + [
            asyncio.ensure_future(publish(i)) for i in range(N_PUBS)]
        got = []
        want = N_PUBS * MSGS_PER_PUB
        try:
            while len(got) < want:
                m = await sub.recv(timeout=30)
                got.append(m.payload.decode())
            await asyncio.gather(*tasks)
        finally:
            for t in tasks:
                t.cancel()
        assert sorted(got) == sorted(
            f"{p}:{i}" for p in range(N_PUBS)
            for i in range(MSGS_PER_PUB))
        # per-publisher order preserved through the pipelined batches
        for p in range(N_PUBS):
            seq = [int(x.split(":")[1]) for x in got
                   if x.startswith(f"{p}:")]
            assert seq == sorted(seq)
        await sub.disconnect()
        await churner.disconnect()
