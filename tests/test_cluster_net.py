"""Two-OS-process cluster over the socket transport.

The reference's gen_rpc data plane carries deliveries between real
nodes (src/emqx_rpc.erl:33-60); these tests prove the repo's
SocketTransport does the same: a subprocess node joins over TCP,
routes replicate both ways, publishes forward across the wire, and a
peer death purges its routes (emqx_router_helper:135-144 semantics).
"""

import asyncio
import os
import subprocess
import sys

import pytest

from emqx_tpu.cluster import Cluster
from emqx_tpu.cluster_net import SocketTransport
from emqx_tpu.types import Message

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import asyncio, sys
import jax
jax.config.update("jax_platforms", "cpu")
from emqx_tpu.node import Node
from emqx_tpu.cluster import Cluster
from emqx_tpu.cluster_net import SocketTransport
from emqx_tpu.modules.retainer import RetainerModule
from emqx_tpu.types import Message


class Sub:
    def deliver(self, topic, msg):
        print(f"GOT {topic} {msg.payload.decode()}", flush=True)


async def main():
    cookie = sys.argv[1]
    n = Node(name="nodeB", boot_listeners=False)
    await n.start()
    ret = n.modules.load(RetainerModule)
    tr = SocketTransport("nodeB", cookie=cookie)
    tr.serve()
    cl = Cluster(n, transport=tr)
    n.broker.subscribe(Sub(), "x/+")
    print(f"READY {tr.port}", flush=True)
    loop = asyncio.get_running_loop()
    reader = asyncio.StreamReader()
    await loop.connect_read_pipe(
        lambda: asyncio.StreamReaderProtocol(reader), sys.stdin)
    while True:
        line = await reader.readline()
        if not line:
            break
        parts = line.decode().split()
        if parts[0] == "PUB":
            n.broker.publish(
                Message(topic=parts[1], payload=parts[2].encode()))
        elif parts[0] == "RETAINED?":
            keys = ",".join(sorted(t for t, _ in ret.entries()))
            print(f"RETAINED {keys or '-'}", flush=True)
        elif parts[0] == "QUIT":
            break
    await n.stop()
    tr.close()


asyncio.run(main())
"""


class Recorder:
    def __init__(self):
        self.got = asyncio.Queue()

    def deliver(self, topic, msg):
        self.got.put_nowait((topic, msg.payload))


def _spawn_child(cookie):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-c", CHILD, cookie],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, env=env, cwd=REPO)


async def _read_line(proc, prefix, timeout=90.0):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        line = await asyncio.wait_for(
            loop.run_in_executor(None, proc.stdout.readline),
            max(0.1, deadline - loop.time()))
        if not line:
            raise AssertionError(f"child closed stdout awaiting {prefix}")
        text = line.decode().strip()
        if text.startswith(prefix):
            return text


def test_two_process_cluster_replicate_forward_nodedown():
    from emqx_tpu.node import Node

    async def main():
        proc = _spawn_child("secret-1")
        try:
            ready = await _read_line(proc, "READY")
            peer_port = int(ready.split()[1])

            a = Node(name="nodeA", boot_listeners=False)
            await a.start()
            tr = SocketTransport("nodeA", cookie="secret-1")
            tr.serve()
            cl = Cluster(a, transport=tr)

            cl.join_remote("127.0.0.1", peer_port)
            assert sorted(cl.members) == ["nodeA", "nodeB"]
            # B's route arrived during the join route-sync
            await asyncio.sleep(0.5)
            assert a.router.has_dest("x/+", "nodeB"), \
                a.router.topics()

            # A -> B forward: publish here, B's subscriber prints
            a.broker.publish(Message(topic="x/9", payload=b"ping"))
            got = await _read_line(proc, "GOT")
            assert got == "GOT x/+ ping" or got.startswith("GOT x/")

            # B -> A forward: subscribe here AFTER the join (tests
            # live replication, not just the join sync)
            rec = Recorder()
            a.broker.subscribe(rec, "y/#")
            await asyncio.sleep(0.5)  # route_add cast propagation
            proc.stdin.write(b"PUB y/2 pong\n")
            proc.stdin.flush()
            topic, payload = await asyncio.wait_for(rec.got.get(), 30)
            assert payload == b"pong"

            # nodedown: child exits -> link EOF -> probe (fails fast
            # against a closed port) -> purge. Poll: the probe takes
            # a few hundred ms by design (transient drops must not
            # purge a live member)
            proc.stdin.write(b"QUIT\n")
            proc.stdin.flush()
            proc.wait(timeout=30)
            deadline = asyncio.get_running_loop().time() + 15
            while a.router.has_dest("x/+", "nodeB"):
                assert asyncio.get_running_loop().time() < deadline, \
                    "nodedown purge never happened"
                await asyncio.sleep(0.25)
            assert cl.members == ["nodeA"]

            await a.stop()
            tr.close()
        finally:
            if proc.poll() is None:
                proc.kill()
    asyncio.run(main())


def test_cookie_mismatch_rejected():
    async def main():
        proc = _spawn_child("right-cookie")
        try:
            ready = await _read_line(proc, "READY")
            peer_port = int(ready.split()[1])
            tr = SocketTransport("nodeX", cookie="wrong-cookie")
            tr.serve()
            with pytest.raises(ConnectionError):
                tr.call_addr(("127.0.0.1", peer_port), "cluster_info")
            tr.close()
        finally:
            if proc.poll() is None:
                proc.kill()
    asyncio.run(main())


def test_cross_process_session_takeover():
    """A persistent session created on the subprocess node (with a
    queued message) moves to this process over the socket transport —
    the session object travels pickled through the takeover call
    (emqx_cm:takeover_session RPC, src/emqx_cm.erl:263-272)."""
    async def main():
        proc = _spawn_child2("secret-2")
        try:
            ready = await _read_line(proc, "READY")
            peer_cl, peer_mqtt = int(ready.split()[1]), int(ready.split()[2])

            from emqx_tpu.node import Node
            a = Node(name="nodeA2", boot_listeners=False)
            a.add_listener(port=0)
            await a.start()
            tr = SocketTransport("nodeA2", cookie="secret-2")
            tr.serve()
            cl = Cluster(a, transport=tr)
            cl.join_remote("127.0.0.1", peer_cl)

            # a persistent session on B: subscribe, disconnect, then
            # B queues a message into the detached session
            from mqtt_client import TestClient
            from emqx_tpu.mqtt import constants as MC
            c1 = TestClient("mover", version=MC.MQTT_V5,
                            properties={"Session-Expiry-Interval": 7200})
            await c1.connect(port=peer_mqtt)
            await c1.subscribe("tk/t", qos=1)
            await c1.disconnect()
            proc.stdin.write(b"PUB tk/t queued-on-b\n")
            proc.stdin.flush()
            # client_up replication is an async cast: the takeover
            # can only find the session once A's registry has it
            deadline = asyncio.get_running_loop().time() + 30
            while cl.locate_client("mover") != "nodeB2":
                assert asyncio.get_running_loop().time() < deadline, \
                    "registry entry never replicated"
                await asyncio.sleep(0.2)
            await asyncio.sleep(0.5)  # let the queued PUB land too

            # reconnect on A: cross-node takeover pulls the pickled
            # session (queued message included) over the wire
            c2 = TestClient("mover", version=MC.MQTT_V5,
                            clean_start=False,
                            properties={"Session-Expiry-Interval": 7200})
            ack = await c2.connect(port=a.listeners[0].port, timeout=30)
            assert ack.session_present, "session not found via registry"
            m = await asyncio.wait_for(c2.inbox.get(), 30)
            assert m.payload == b"queued-on-b"
            await c2.disconnect()

            proc.stdin.write(b"QUIT\n")
            proc.stdin.flush()
            proc.wait(timeout=30)
            await a.stop()
            tr.close()
        finally:
            if proc.poll() is None:
                proc.kill()
    asyncio.run(main())


CHILD2 = r"""
import asyncio, sys
import jax
jax.config.update("jax_platforms", "cpu")
from emqx_tpu.node import Node
from emqx_tpu.cluster import Cluster
from emqx_tpu.cluster_net import SocketTransport
from emqx_tpu.types import Message


async def main():
    cookie = sys.argv[1]
    n = Node(name="nodeB2", boot_listeners=False)
    n.add_listener(port=0)
    await n.start()
    tr = SocketTransport("nodeB2", cookie=cookie)
    tr.serve()
    cl = Cluster(n, transport=tr)
    print(f"READY {tr.port} {n.listeners[0].port}", flush=True)
    reader = asyncio.StreamReader()
    await asyncio.get_running_loop().connect_read_pipe(
        lambda: asyncio.StreamReaderProtocol(reader), sys.stdin)
    while True:
        line = await reader.readline()
        if not line:
            break
        parts = line.decode().split()
        if parts[0] == "PUB":
            n.broker.publish(
                Message(topic=parts[1], payload=parts[2].encode()))
        elif parts[0] == "RETAINED?":
            keys = ",".join(sorted(t for t, _ in ret.entries()))
            print(f"RETAINED {keys or '-'}", flush=True)
        elif parts[0] == "QUIT":
            break
    await n.stop()
    tr.close()


asyncio.run(main())
"""


def _spawn_child2(cookie):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-c", CHILD2, cookie],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, env=env, cwd=REPO)


async def test_retained_replicates_over_socket_transport():
    """Retained store replication crosses the real wire: a retain on
    the parent lands in the subprocess node's store (pickled Message
    over the length-prefixed frame protocol), and a delete clears it."""
    from emqx_tpu.modules.retainer import RetainerModule
    from emqx_tpu.node import Node

    cookie = "retain-net"
    proc = _spawn_child(cookie)
    try:
        ready = await _read_line(proc, "READY")
        child_port = int(ready.split()[1])

        n = Node(name="nodeA", boot_listeners=False)
        await n.start()
        n.modules.load(RetainerModule)
        tr = SocketTransport("nodeA", cookie=cookie)
        tr.serve()
        cl = Cluster(n, transport=tr)
        cl.join_remote("127.0.0.1", child_port)

        n.broker.publish(Message(topic="keep/me", payload=b"v",
                                 flags={"retain": True}))
        await asyncio.sleep(0.5)
        proc.stdin.write(b"RETAINED?\n")
        proc.stdin.flush()
        line = await _read_line(proc, "RETAINED")
        assert line == "RETAINED keep/me"

        n.broker.publish(Message(topic="keep/me", payload=b"",
                                 flags={"retain": True}))
        await asyncio.sleep(0.5)
        proc.stdin.write(b"RETAINED?\n")
        proc.stdin.flush()
        line = await _read_line(proc, "RETAINED")
        assert line == "RETAINED -"

        proc.stdin.write(b"QUIT\n")
        proc.stdin.flush()
        await n.stop()
        tr.close()
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()


def test_buffered_cast_survives_immediate_close():
    """leave()'s nodedown announcement rides the cast buffer; a
    close() racing the scheduled flush must still drain it (the
    _closing gate stops the normal flush machinery, so _shutdown
    performs one bounded best-effort flush before the task sweep) —
    otherwise peers only learn of our exit via the slower
    link-monitor path."""
    import time

    from emqx_tpu.cluster_net import SocketTransport

    got = []

    class FakeCluster:
        def handle_rpc(self, op, *args):
            got.append((op, args))
            return True

    a = SocketTransport("a", cookie="k")
    b = SocketTransport("b", cookie="k")
    try:
        a.serve()
        hb, pb = b.serve()
        b.cluster = FakeCluster()
        a.register_peer("b", hb, pb)
        a.cast("b", "nodedown", "a")
        a.close()  # immediately: the buffered cast must still land
        deadline = time.time() + 3
        while not got and time.time() < deadline:
            time.sleep(0.05)
        assert got and got[0][0] == "nodedown", got
    finally:
        a.close()
        b.close()
