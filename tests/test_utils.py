"""Utility substrate: batch, sequence, pmon, misc pipeline, guid
(emqx_batch / emqx_sequence / emqx_pmon / emqx_misc / emqx_guid
parity)."""

import asyncio
import time

from emqx_tpu.utils.batch import AsyncBatcher, Batch
from emqx_tpu.utils.guid import guid_timestamp, new_guid
from emqx_tpu.utils.misc import ERROR, OK, pipeline, run_fold
from emqx_tpu.utils.pmon import PMon
from emqx_tpu.utils.sequence import Sequence


# -- batch ------------------------------------------------------------------

def test_batch_size_trigger():
    committed = []
    b = Batch(batch_size=3, commit_fun=committed.append)
    assert b.push(1) is None and b.push(2) is None
    b.push(3)
    assert committed == [[1, 2, 3]] and len(b) == 0


def test_batch_flush_and_due():
    b = Batch(batch_size=100, linger_ms=0.0)
    assert b.flush() is None
    b.push("x")
    assert b.due()  # linger 0: due immediately
    assert b.flush() == ["x"]
    assert not b.due()


async def test_async_batcher_linger():
    committed = []
    ab = AsyncBatcher(committed.append, batch_size=100, linger_ms=5.0)
    ab.start()
    ab.push(1)
    ab.push(2)
    await asyncio.sleep(0.1)
    assert committed == [[1, 2]]
    ab.push(3)
    ab.stop()
    assert committed == [[1, 2], [3]]  # stop flushes the remainder


# -- sequence ---------------------------------------------------------------

def test_sequence_nextval_reclaim():
    s = Sequence()
    assert s.nextval("t") == 1
    assert s.nextval("t") == 2
    assert s.nextval("u") == 1
    assert s.currval("t") == 2
    assert s.reclaim("t") == 1
    assert s.reclaim("t") == 0
    assert s.currval("t") == 0          # deleted at zero
    assert s.reclaim("ghost") == 0


# -- pmon -------------------------------------------------------------------

def test_pmon_explicit_down_batch_erase():
    pm = PMon()
    pm.monitor("a", {"x": 1})
    pm.monitor("b", {"x": 2})
    assert pm.count() == 2 and pm.find("a") == {"x": 1} and "a" in pm
    pm.notify_down("a")
    pm.notify_down("ghost")  # unknown: ignored
    assert pm.erase_all() == [("a", {"x": 1})]
    assert pm.count() == 1 and "a" not in pm
    pm.demonitor("b")
    assert pm.count() == 0


async def test_pmon_task_completion():
    pm = PMon()

    async def short():
        return 42

    t = asyncio.get_event_loop().create_task(short())
    pm.monitor("conn1", "val", task=t)
    await t
    await asyncio.sleep(0)  # let the done callback run
    assert pm.erase_all() == [("conn1", "val")]


# -- misc pipeline ----------------------------------------------------------

def test_pipeline_ok_chain():
    funs = [
        lambda p, s: None,                       # keep
        lambda p, s: (OK, p + 1),                # new packet
        lambda p, s: (OK, p * 2, s + "b"),       # both
    ]
    assert pipeline(funs, 1, "a") == (OK, 4, "ab")


def test_pipeline_error_halts():
    calls = []
    funs = [
        lambda p, s: (OK, p + 1),
        lambda p, s: (ERROR, "denied"),
        lambda p, s: calls.append(1),
    ]
    assert pipeline(funs, 0, "s") == (ERROR, "denied", "s")
    assert calls == []


def test_pipeline_error_with_state():
    funs = [lambda p, s: (ERROR, "bad", "new_state")]
    assert pipeline(funs, 0, "old") == (ERROR, "bad", "new_state")


def test_run_fold():
    funs = [lambda acc, s: acc + s, lambda acc, s: acc * 2]
    assert run_fold(funs, 1, 3) == 8


# -- guid (emqx_guid_SUITE parity: uniqueness + time ordering) --------------

def test_guid_unique_and_monotonic():
    ids = [new_guid() for _ in range(10_000)]
    assert len(set(ids)) == len(ids)
    # time-ordered layout: ids generated in sequence never decrease
    assert all(a < b for a, b in zip(ids, ids[1:]))


def test_guid_timestamp_roundtrip():
    before = time.time()
    g = new_guid()
    after = time.time()
    # 128-bit layout: ts_us(64) | entropy(32) | seq(32)
    assert g < (1 << 128)
    assert before - 1e-3 <= guid_timestamp(g) <= after + 1e-3


def test_guid_thread_safety():
    import threading

    out: list = []
    lock = threading.Lock()

    def gen():
        local = [new_guid() for _ in range(2_000)]
        with lock:
            out.extend(local)

    threads = [threading.Thread(target=gen) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(set(out)) == len(out)
