"""Distributed per-clientid lock (emqx_cm_locker / ekka_locker
quorum, src/emqx_cm_locker.erl:41-49 taken at emqx_cm.erl:209-236):
racing session opens for the SAME clientid serialize cluster-wide so
exactly one session survives — in-process, across two OS processes,
and under link loss."""

import asyncio
import os
import subprocess
import sys
import threading
import time

from emqx_tpu.cluster import Cluster, LocalTransport
from emqx_tpu.node import Node

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeChan:
    def __init__(self, cid):
        self.client_id = cid
        self.killed = False

    def kick(self, discard=False):
        self.killed = True

    def takeover_begin(self):
        return None

    def takeover_end(self, rc):
        self.killed = True


def _mk_cluster(n=2):
    transport = LocalTransport()
    nodes = [Node(name=f"n{i}", boot_listeners=False) for i in range(n)]
    clusters = [Cluster(node, transport) for node in nodes]
    for c in clusters[1:]:
        clusters[0].join(c)
    return nodes, clusters


def test_locker_grant_reentrant_and_lease():
    _, (ca, cb) = _mk_cluster(2)
    lk = ca.locker
    assert lk.grant("c1", "n0")
    assert lk.grant("c1", "n0")          # owner-reentrant
    assert not lk.grant("c1", "n1")      # held by n0
    lk.release_local("c1", "n1")         # wrong owner: no-op
    assert not lk.grant("c1", "n1")
    lk.release_local("c1", "n0")
    assert lk.grant("c1", "n1")          # free now
    # lease expiry frees an abandoned grant
    with lk._lock:
        owner, _ = lk._table["c1"]
        lk._table["c1"] = (owner, time.time() - 1)
    assert lk.grant("c1", "n0")
    assert lk.sweep() == 0  # grant refreshed the lease
    # a dead node's grants drop on nodedown (monitored-lock cleanup)
    assert lk.grant("c2", "n1")
    assert lk.drop_owner("n1") == 1
    assert lk.grant("c2", "n0")


def test_locker_quorum_acquire_release():
    _, (ca, cb) = _mk_cluster(2)
    assert ca.locker.acquire("q1")
    # held: the peer cannot acquire (bounded retries, then False)
    import emqx_tpu.cm_locker as M
    old = M.ACQUIRE_TIMEOUT
    M.ACQUIRE_TIMEOUT = 0.3
    try:
        assert not cb.locker.acquire("q1")
    finally:
        M.ACQUIRE_TIMEOUT = old
    ca.locker.release("q1")
    assert cb.locker.acquire("q1")
    cb.locker.release("q1")


def test_inprocess_race_exactly_one_session_survives():
    """Two nodes race clean-start opens for one clientid from
    concurrent threads; after both complete, exactly ONE live
    channel exists cluster-wide (emqx_cm.erl:209-236's guarantee)."""
    (n0, n1), _ = _mk_cluster(2)
    results = []

    def open_on(node, tag):
        chan = FakeChan("dup")
        sess, present = node.cm.open_session("dup", True, chan)
        results.append((tag, chan))

    for round_ in range(5):
        t0 = threading.Thread(target=open_on, args=(n0, "a"))
        t1 = threading.Thread(target=open_on, args=(n1, "b"))
        t0.start()
        t1.start()
        t0.join(10)
        t1.join(10)
        live = [n for n in (n0, n1)
                if n.cm.lookup_channel("dup") is not None]
        assert len(live) == 1, (round_, [n.name for n in live])
        # and the survivor's channel was never killed
        surv = live[0].cm.lookup_channel("dup")
        assert not surv.killed
        # cleanup for the next round
        live[0].cm.discard_session("dup")
        results.clear()


CHILD = r"""
import asyncio, sys, threading
import jax
jax.config.update("jax_platforms", "cpu")
from emqx_tpu.node import Node
from emqx_tpu.cluster import Cluster
from emqx_tpu.cluster_net import SocketTransport


class FakeChan:
    def __init__(self, cid):
        self.client_id = cid
        self.killed = False

    def kick(self, discard=False):
        self.killed = True

    def takeover_begin(self):
        return None

    def takeover_end(self, rc):
        self.killed = True


async def main():
    cookie = sys.argv[1]
    n = Node(name="nodeB", boot_listeners=False)
    await n.start()
    tr = SocketTransport("nodeB", cookie=cookie)
    tr.serve()
    cl = Cluster(n, transport=tr)
    print(f"READY {tr.port}", flush=True)
    loop = asyncio.get_running_loop()
    reader = asyncio.StreamReader()
    await loop.connect_read_pipe(
        lambda: asyncio.StreamReaderProtocol(reader), sys.stdin)
    while True:
        line = await reader.readline()
        if not line:
            break
        parts = line.decode().split()
        if parts[0] == "OPEN":
            cid = parts[1]
            def _open():
                n.cm.open_session(cid, True, FakeChan(cid))
                print(f"OPENED {cid}", flush=True)
            # open on a worker thread: the RPCs inside must not
            # deadlock against this loop serving inbound RPCs
            await loop.run_in_executor(None, _open)
        elif parts[0] == "HAVE?":
            chan = n.cm.lookup_channel(parts[1])
            print(f"HAVE {'yes' if chan is not None else 'no'}",
                  flush=True)
        elif parts[0] == "QUIT":
            break
    await n.stop()
    tr.close()


asyncio.run(main())
"""


def _spawn_child(cookie):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-c", CHILD, cookie],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, env=env, cwd=REPO)


async def _read_line(proc, prefix, timeout=90.0):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        line = await asyncio.wait_for(
            loop.run_in_executor(None, proc.stdout.readline),
            max(0.1, deadline - loop.time()))
        if not line:
            raise AssertionError(f"child closed stdout awaiting {prefix}")
        text = line.decode().strip()
        if text.startswith(prefix):
            return text


def test_two_process_race_and_link_loss():
    """The VERDICT r2 'done' criterion: two OS processes race the
    same clientid — exactly one session survives; then the peer dies
    (link loss) and the survivor side can still open the clientid
    (quorum over the shrunk LIVE membership)."""
    from emqx_tpu.cluster_net import SocketTransport

    async def main():
        proc = _spawn_child("lock-cookie")
        try:
            ready = await _read_line(proc, "READY")
            peer_port = int(ready.split()[1])

            a = Node(name="nodeA", boot_listeners=False)
            await a.start()
            tr = SocketTransport("nodeA", cookie="lock-cookie")
            tr.serve()
            cl = Cluster(a, transport=tr)
            cl.join_remote("127.0.0.1", peer_port)
            assert sorted(cl.members) == ["nodeA", "nodeB"]

            # race: child opens + parent opens, same clientid, as
            # close to simultaneously as two processes get
            loop = asyncio.get_running_loop()
            proc.stdin.write(b"OPEN dup\n")
            proc.stdin.flush()
            chan = FakeChan("dup")

            def _open():
                a.cm.open_session("dup", True, chan)

            await loop.run_in_executor(None, _open)
            await _read_line(proc, "OPENED")
            await asyncio.sleep(0.5)  # registry casts settle

            proc.stdin.write(b"HAVE? dup\n")
            proc.stdin.flush()
            child_has = (await _read_line(proc, "HAVE")) == "HAVE yes"
            parent_has = a.cm.lookup_channel("dup") is not None
            assert child_has != parent_has, (
                "exactly one session must survive",
                child_has, parent_has)

            # link loss: kill the peer outright; the survivor must
            # still be able to open the clientid in bounded time
            # (unreachable peer -> nodedown -> quorum over the
            # remaining live membership)
            proc.kill()
            proc.wait(timeout=15)
            t0 = time.monotonic()
            chan2 = FakeChan("dup")
            await loop.run_in_executor(
                None, lambda: a.cm.open_session("dup", True, chan2))
            assert time.monotonic() - t0 < 10.0
            assert a.cm.lookup_channel("dup") is chan2
            assert cl.members == ["nodeA"]

            await a.stop()
            tr.close()
        finally:
            if proc.poll() is None:
                proc.kill()

    asyncio.run(main())
