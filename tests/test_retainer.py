"""Retained messages (built-in module; the reference delegates to the
separate emqx_retainer plugin app): store/replace/delete, delivery on
subscribe with Retain-Handling 0/1/2, retain flag semantics
(MQTT 3.3.1-6/-7/-8), wildcard matching, shared-sub exclusion,
message expiry."""

import asyncio

import pytest

from emqx_tpu.modules.retainer import RetainerModule
from emqx_tpu.mqtt import constants as C
from emqx_tpu.node import Node
from tests.mqtt_client import TestClient


async def _node():
    n = Node(boot_listeners=False)
    n.modules.load(RetainerModule)
    lst = n.add_listener(port=0)
    await n.start()
    return n, lst.port


async def test_retained_delivered_on_subscribe_with_flag():
    n, port = await _node()
    try:
        pub = TestClient("rpub", version=C.MQTT_V5)
        await pub.connect(port=port)
        await pub.publish("ret/a", b"v1", qos=1, retain=True)
        await pub.publish("ret/b/c", b"v2", qos=1, retain=True)

        sub = TestClient("rsub", version=C.MQTT_V5)
        await sub.connect(port=port)
        await sub.subscribe("ret/#", qos=1)
        got = {}
        for _ in range(2):
            m = await sub.recv(5)
            got[m.topic] = (m.payload, m.retain)
        # retained delivery keeps retain=1 even without RAP
        assert got == {"ret/a": (b"v1", True),
                       "ret/b/c": (b"v2", True)}
        await pub.close()
        await sub.close()
    finally:
        await n.stop()


async def test_retained_replace_and_delete():
    n, port = await _node()
    try:
        pub = TestClient("rpub", version=C.MQTT_V5)
        await pub.connect(port=port)
        await pub.publish("ret/x", b"old", qos=1, retain=True)
        await pub.publish("ret/x", b"new", qos=1, retain=True)

        s1 = TestClient("rs1", version=C.MQTT_V5)
        await s1.connect(port=port)
        await s1.subscribe("ret/x")
        assert (await s1.recv(5)).payload == b"new"

        # empty retained payload deletes (MQTT-3.3.1-6)
        await pub.publish("ret/x", b"", qos=1, retain=True)
        s2 = TestClient("rs2", version=C.MQTT_V5)
        await s2.connect(port=port)
        await s2.subscribe("ret/x")
        with pytest.raises(asyncio.TimeoutError):
            await s2.recv(0.4)
        assert n.metrics.val("retained.count") == 0
        for c in (pub, s1, s2):
            await c.close()
    finally:
        await n.stop()


async def test_retain_handling_options():
    """rh=2 never sends; rh=1 sends only for NEW subscriptions
    (MQTT 3.8.3.1)."""
    n, port = await _node()
    try:
        pub = TestClient("rpub", version=C.MQTT_V5)
        await pub.connect(port=port)
        await pub.publish("rh/t", b"r", qos=1, retain=True)

        sub = TestClient("rsub", version=C.MQTT_V5)
        await sub.connect(port=port)
        await sub.subscribe(("rh/t", {"qos": 1, "nl": 0, "rap": 0,
                                      "rh": 2}))
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(0.4)
        # rh=1, first subscribe (it exists already → resub) …
        await sub.subscribe(("rh/t", {"qos": 1, "nl": 0, "rap": 0,
                                      "rh": 1}))
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(0.4)  # resub: not sent
        # rh=0 always sends
        await sub.subscribe(("rh/t", {"qos": 1, "nl": 0, "rap": 0,
                                      "rh": 0}))
        assert (await sub.recv(5)).payload == b"r"
        # rh=1 on a genuinely new subscription sends
        fresh = TestClient("rfresh", version=C.MQTT_V5)
        await fresh.connect(port=port)
        await fresh.subscribe(("rh/t", {"qos": 1, "nl": 0, "rap": 0,
                                        "rh": 1}))
        assert (await fresh.recv(5)).payload == b"r"
        for c in (pub, sub, fresh):
            await c.close()
    finally:
        await n.stop()


async def test_retained_not_sent_to_shared_subscription():
    n, port = await _node()
    try:
        pub = TestClient("rpub", version=C.MQTT_V5)
        await pub.connect(port=port)
        await pub.publish("sh/t", b"r", qos=1, retain=True)
        sub = TestClient("rshare", version=C.MQTT_V5)
        await sub.connect(port=port)
        await sub.subscribe("$share/g/sh/t", qos=1)
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(0.4)
        await pub.close()
        await sub.close()
    finally:
        await n.stop()


async def test_retained_normal_routing_unaffected():
    """A retained PUBLISH still routes to live subscribers (with
    retain cleared for rap=0 — it is a live delivery, not a retained
    one)."""
    n, port = await _node()
    try:
        sub = TestClient("live", version=C.MQTT_V5)
        await sub.connect(port=port)
        await sub.subscribe("lv/t", qos=1)
        pub = TestClient("rpub", version=C.MQTT_V5)
        await pub.connect(port=port)
        await pub.publish("lv/t", b"now", qos=1, retain=True)
        m = await sub.recv(5)
        assert m.payload == b"now" and not m.retain
        await pub.close()
        await sub.close()
    finally:
        await n.stop()


async def test_retained_expiry_not_delivered():
    n, port = await _node()
    try:
        pub = TestClient("rpub", version=C.MQTT_V5)
        await pub.connect(port=port)
        await pub.publish("exp/t", b"shortlived", qos=1, retain=True,
                          props={"Message-Expiry-Interval": 1})
        await asyncio.sleep(1.2)
        sub = TestClient("rsub", version=C.MQTT_V5)
        await sub.connect(port=port)
        await sub.subscribe("exp/t")
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(0.4)
        await pub.close()
        await sub.close()
    finally:
        await n.stop()


async def test_store_bounds():
    n = Node(boot_listeners=False)
    mod = n.modules.load(RetainerModule, env={"max_retained": 2})
    lst = n.add_listener(port=0)
    await n.start()
    try:
        pub = TestClient("rpub", version=C.MQTT_V5)
        await pub.connect(port=lst.port)
        await pub.publish("b/1", b"x", qos=1, retain=True)
        await pub.publish("b/2", b"x", qos=1, retain=True)
        await pub.publish("b/3", b"x", qos=1, retain=True)  # dropped
        assert n.metrics.val("retained.count") == 2
        assert n.metrics.val("retained.dropped") == 1
        assert mod.info() == {"retained": 2}
        await pub.close()
    finally:
        await n.stop()


def test_apply_remote_timestamp_lww_and_expiry():
    """JOIN sync is timestamp-LWW (a stale snapshot never clobbers a
    newer value); LIVE replication applies in arrival order (a
    lagging clock must not get its updates dropped cluster-wide);
    expired entries never enter the store remotely."""
    import time as _t

    from emqx_tpu.types import Message as M

    n = Node(boot_listeners=False)
    mod = n.modules.load(RetainerModule)
    newer = M(topic="t", payload=b"new", flags={"retain": True})
    older = M(topic="t", payload=b"old", flags={"retain": True},
              timestamp=newer.timestamp - 60)
    mod.apply_remote("t", newer, sync=True)
    mod.apply_remote("t", older, sync=True)  # stale sync: ignored
    assert mod._store["t"].payload == b"new"
    # live replication: arrival order wins even with an older clock
    mod.apply_remote("t", older)
    assert mod._store["t"].payload == b"old"
    mod.apply_remote("t", M(topic="t", payload=b"newest",
                            flags={"retain": True},
                            timestamp=newer.timestamp + 60),
                     sync=True)
    assert mod._store["t"].payload == b"newest"
    expired = M(topic="e", payload=b"x", flags={"retain": True},
                timestamp=_t.time() - 100,
                headers={"properties": {"Message-Expiry-Interval": 1}})
    mod.apply_remote("e", expired)
    assert "e" not in mod._store
    mod.apply_remote("t", None)
    assert mod._store == {}
    assert n.metrics.val("retained.count") == 0
    # tombstone: a later stale sync cannot resurrect the deletion
    mod.apply_remote("t", older, sync=True)
    assert "t" not in mod._store
    # sync tombstone drops an older stored value
    mod.apply_remote("z", older.copy(), sync=True) or None
    mod._store["z2"] = M(topic="z2", payload=b"x",
                         flags={"retain": True},
                         timestamp=_t.time() - 50)
    mod.apply_tombstone("z2", _t.time())
    assert "z2" not in mod._store


def test_remote_delete_uses_origin_timestamp():
    """A replicated delete carries the DELETING message's timestamp;
    the receiver's tombstone must use it (not local wall-clock), so
    join-sync LWW stays consistent under clock skew."""
    import time as _t

    from emqx_tpu.types import Message as M

    n = Node(boot_listeners=False)
    mod = n.modules.load(RetainerModule)
    t_del = _t.time() - 300  # deleting node's clock is 5 min behind
    mod.apply_remote("t", None, ts=t_del)
    assert mod._tombstones["t"] == t_del
    # a value newer than the (old-clock) delete survives join sync
    newer = M(topic="t", payload=b"survives", flags={"retain": True})
    mod.apply_remote("t", newer, sync=True)
    assert mod._store["t"].payload == b"survives"
    # tombstones stay monotone: an older delete ts can't move it back
    mod.apply_remote("t2", None, ts=100.0)
    mod.apply_remote("t2", None, ts=50.0)
    assert mod._tombstones["t2"] == 100.0


def test_apply_remote_enforces_max_payload():
    """A peer with a larger payload limit must not replicate
    oversize messages into this node's store."""
    from emqx_tpu.types import Message as M

    n = Node(boot_listeners=False)
    mod = n.modules.load(RetainerModule, env={"max_payload": 8})
    big = M(topic="big", payload=b"x" * 9, flags={"retain": True})
    mod.apply_remote("big", big)
    assert "big" not in mod._store
    assert n.metrics.val("retained.dropped") == 1
    ok = M(topic="ok", payload=b"x" * 8, flags={"retain": True})
    mod.apply_remote("ok", ok)
    assert mod._store["ok"].payload == b"x" * 8


# -- RetainIndex: the device-side reverse index ------------------------------

def _host_matches(topics, flt):
    from emqx_tpu import topic as T

    return sorted(t for t in topics if T.match(t, flt))


def test_retain_index_device_parity_random():
    """Force the device path (threshold=0) and pin exact parity with
    the host oracle over random stores/deletes — including $-topics
    (root-wildcard exclusion), deep names (> L levels, host side
    set), and re-used slots after deletes."""
    import random

    from emqx_tpu.modules.retainer import RetainIndex

    rng = random.Random(42)
    words = ["a", "b", "c", "d", "sensor", "west", "$SYS", "$priv"]
    idx = RetainIndex()
    live = set()

    def rand_topic():
        depth = rng.randint(1, 20)  # some exceed L=16
        return "/".join(rng.choice(words) for _ in range(depth))

    for _ in range(400):
        t = rand_topic()
        idx.add(t)
        live.add(t)
    # delete a third, re-add some (slot reuse)
    dead = rng.sample(sorted(live), 130)
    for t in dead:
        idx.remove(t)
        live.discard(t)
    for t in dead[:40]:
        idx.add(t)
        live.add(t)
    assert len(idx) == len(live)

    filters = ["#", "+/+", "a/#", "+/west/+", "sensor/+/c",
               "$SYS/#", "$SYS/+", "a/b/c", "+/+/+/+/#",
               "/".join(["+"] * 18)]
    for flt in filters:
        got = sorted(idx.match(flt, device_threshold=0))
        assert got == _host_matches(live, flt), flt


def test_retain_index_grow_and_clear():
    from emqx_tpu.modules.retainer import RetainIndex

    idx = RetainIndex()
    n = RetainIndex.GROW + 10  # force a capacity double
    for i in range(n):
        idx.add(f"grow/{i}")
    assert len(idx) == n
    assert sorted(idx.match("grow/+", device_threshold=0)) == sorted(
        f"grow/{i}" for i in range(n))
    idx.clear()
    assert len(idx) == 0
    assert idx.match("#", device_threshold=0) == []


async def test_retainer_wildcard_lookup_via_device_index():
    """Module integration: with the device threshold forced to 0, a
    wildcard subscribe resolves retained messages through the index
    and delivers exactly the matching set."""
    n, _port_ = await _node()
    try:
        ret = n.modules._loaded["retainer"]
        ret.index_device_threshold = 0

        from emqx_tpu.types import Message

        for t in ("home/k/temp", "home/l/temp", "home/k/hum", "$SYS/x"):
            n.publish(Message(topic=t, payload=b"v",
                              flags={"retain": True}))
        sess = _FakeSession()
        chan = type("Chan", (), {"session": sess})()
        n.cm._channels["ridx"] = chan
        ret.on_subscribed({"clientid": "ridx"}, "home/+/temp",
                          {"qos": 0})
        # replay batches through the accumulator: delivery lands at
        # the end of the current loop tick (PR 19)
        await asyncio.sleep(0)
        assert [f for f, _ in sess.got] == ["home/+/temp"] * 2
        assert sorted(m.topic for _, m in sess.got) == [
            "home/k/temp", "home/l/temp"]
    finally:
        await n.stop()


class _FakeSession:
    def __init__(self):
        self.got = []

    def deliver(self, f, m):
        self.got.append((f, m))


def test_retain_index_word_table_bounded_under_churn():
    """Name churn must not grow the intern table forever (refcounted
    words + compaction), and filter lookups never intern."""
    from emqx_tpu.modules.retainer import RetainIndex

    idx = RetainIndex()
    # loop-less (library) usage: the inline BACKSTOP fires once dead
    # words cross 65536; the periodic sweep task compacts far sooner
    for i in range(70_000):
        t = f"churn/{i}/x"
        idx.add(t)
        idx.remove(t)
    assert len(idx) == 0
    assert len(idx._table) < 65_536 + 4096
    # filter match with unseen words doesn't intern
    before = len(idx._table)
    idx.add("keep/a")
    idx.match("never/+/seen/#", device_threshold=0)
    assert len(idx._table) <= before + 2  # only keep/a's words


def test_retain_index_device_patch_interleaved():
    """Store mutations between subscribes patch the cached device
    matrix (dirty rows) — parity must hold across interleaved
    add/remove/match, including slot reuse."""
    import random

    from emqx_tpu import topic as T
    from emqx_tpu.modules.retainer import RetainIndex

    rng = random.Random(9)
    idx = RetainIndex()
    live = set()
    for i in range(300):
        t = f"a/{rng.randint(0, 50)}/b{i}"
        idx.add(t)
        live.add(t)
    idx.match("a/#", device_threshold=0)  # builds the device cache
    for step in range(30):
        # mutate a few rows, then match — exercises the patch path
        for _ in range(3):
            if live and rng.random() < 0.5:
                t = rng.choice(sorted(live))
                idx.remove(t)
                live.discard(t)
            else:
                t = f"a/{rng.randint(0, 50)}/n{step}_{rng.randint(0, 9)}"
                idx.add(t)
                live.add(t)
        flt = rng.choice(["a/#", "a/+/+", "+/3/#", "#"])
        got = sorted(idx.match(flt, device_threshold=0))
        want = sorted(t for t in live if T.match(t, flt))
        assert got == want, (step, flt)


async def test_retain_index_compact_async_cooperative():
    """Chunked compaction swaps table+matrix without changing match
    results, and aborts cleanly when a mutation lands mid-rebuild."""
    from emqx_tpu import topic as T
    from emqx_tpu.modules.retainer import RetainIndex

    idx = RetainIndex()
    for i in range(6000):
        idx.add(f"c/{i}/x")
    for i in range(5000):
        idx.remove(f"c/{i}/x")
    live = {f"c/{i}/x" for i in range(5000, 6000)}
    assert idx._compact_due()
    assert await idx.compact_async(chunk=256)
    assert len(idx._table) < 3000  # dead words gone
    got = sorted(idx.match("c/+/x", device_threshold=0))
    assert got == sorted(live)
    # mutation mid-rebuild aborts (epoch guard): simulate by patching
    for i in range(6000, 12000):
        idx.add(f"m/{i}/x")
    for i in range(6000, 11900):
        idx.remove(f"m/{i}/x")
    assert idx._compact_due()
    import asyncio

    task = asyncio.get_event_loop().create_task(
        idx.compact_async(chunk=64))
    await asyncio.sleep(0)  # let the first chunk run
    idx.add("mid/rebuild")
    assert await task is False  # aborted, retried next sweep
    got = sorted(idx.match("#", device_threshold=0))
    want = sorted(t for t in (live | {f"m/{i}/x" for i in range(11900, 12000)} | {"mid/rebuild"}) if T.match(t, "#"))
    assert got == want
