"""Trie oracle tests — ported from reference test/emqx_trie_SUITE.erl
(t_match/t_match2/t_match3, t_empty, t_delete*) plus randomized
cross-checks against emqx_tpu.topic.match.
"""

import random

from emqx_tpu import topic as T
from emqx_tpu.oracle import TrieOracle


def test_match():
    t = TrieOracle()
    t.insert("sensor/1/metric/2")
    t.insert("sensor/+/#")
    t.insert("sensor/#")
    assert sorted(t.match("sensor/1")) == sorted(["sensor/+/#", "sensor/#"])


def test_match2():
    t = TrieOracle()
    t.insert("#")
    t.insert("+/#")
    t.insert("+/+/#")
    assert sorted(t.match("a/b/c")) == sorted(["#", "+/#", "+/+/#"])
    assert t.match("$SYS/broker/zenmq") == []


def test_match3():
    t = TrieOracle()
    for f in ["d/#", "a/b/c", "a/b/+", "a/#", "#", "$SYS/#"]:
        t.insert(f)
    assert len(t.match("a/b/c")) == 4
    assert t.match("$SYS/a/b/c") == ["$SYS/#"]


def test_match_terminal_and_hash_at_end():
    t = TrieOracle()
    t.insert("sensor")
    t.insert("sensor/#")
    # '#' matches the parent level too
    assert sorted(t.match("sensor")) == sorted(["sensor", "sensor/#"])
    assert t.match("sensor/1") == ["sensor/#"]


def test_empty():
    t = TrieOracle()
    assert t.is_empty()
    t.insert("topic/x/#")
    assert not t.is_empty()
    t.delete("topic/x/#")
    assert t.is_empty()


def test_delete():
    t = TrieOracle()
    t.insert("sensor/1/#")
    t.insert("sensor/1/metric/2")
    t.insert("sensor/1/metric/3")
    t.delete("sensor/1/metric/2")
    t.delete("sensor/1/metric")  # not present — no-op
    t.delete("sensor/1/metric")
    assert t.match("sensor/1/metric/3") == ["sensor/1/metric/3", "sensor/1/#"] or \
        sorted(t.match("sensor/1/metric/3")) == sorted(["sensor/1/metric/3", "sensor/1/#"])
    assert "sensor/1/#" in t
    assert "sensor/1/metric/2" not in t


def test_delete2():
    t = TrieOracle()
    t.insert("sensor")
    t.insert("sensor/1/metric/2")
    t.insert("sensor/+/metric/3")
    t.delete("sensor")
    t.delete("sensor/1/metric/2")
    t.delete("sensor/+/metric/3")
    t.delete("sensor/+/metric/3")
    assert t.is_empty()
    assert t.match("sensor/1/metric/2") == []


def test_delete3():
    t = TrieOracle()
    t.insert("sensor/+")
    t.insert("sensor/+/metric/2")
    t.insert("sensor/+/metric/3")
    t.delete("sensor/+/metric/2")
    t.delete("sensor/+/metric/3")
    t.delete("sensor")
    t.delete("sensor/+")
    t.delete("sensor/+/unknown")
    assert t.is_empty()


def test_refcounted_insert():
    t = TrieOracle()
    assert t.insert("a/b/#")
    assert not t.insert("a/b/#")  # second insert refs, not duplicates
    t.delete("a/b/#")
    assert "a/b/#" in t
    t.delete("a/b/#")
    assert "a/b/#" not in t
    assert t.is_empty()


def _random_word(rng):
    return rng.choice(["a", "b", "c", "d", "x", "yy", "z0", "$s", ""])


def _random_filter(rng):
    n = rng.randint(1, 6)
    ws = []
    for i in range(n):
        r = rng.random()
        if r < 0.15:
            ws.append("+")
        elif r < 0.25 and i == n - 1:
            ws.append("#")
        else:
            ws.append(_random_word(rng))
    return "/".join(ws)


def _random_name(rng):
    return "/".join(_random_word(rng) for _ in range(rng.randint(1, 6)))


def test_random_parity_with_topic_match():
    """Oracle.match must agree with emqx_topic-style match/2 for every
    (name, filter) pair — the same invariant the reference relies on
    between emqx_trie and emqx_topic."""
    rng = random.Random(42)
    filters = list({_random_filter(rng) for _ in range(300)})
    t = TrieOracle()
    for f in filters:
        t.insert(f)
    for _ in range(500):
        name = _random_name(rng)
        expect = sorted(f for f in filters if T.match(name, f))
        got = sorted(t.match(name))
        assert got == expect, (name, got, expect)


def test_random_insert_delete_parity():
    rng = random.Random(7)
    t = TrieOracle()
    refs = {}  # filter -> refcount (insert/delete are refcounted)
    for _ in range(800):
        f = _random_filter(rng)
        if f in refs and rng.random() < 0.5:
            t.delete(f)
            refs[f] -= 1
            if refs[f] == 0:
                del refs[f]
        else:
            t.insert(f)
            refs[f] = refs.get(f, 0) + 1
        if rng.random() < 0.2:
            name = _random_name(rng)
            expect = sorted(x for x in refs if T.match(name, x))
            assert sorted(t.match(name)) == expect
    for f, n in list(refs.items()):
        for _ in range(n):
            t.delete(f)
    assert t.is_empty()
