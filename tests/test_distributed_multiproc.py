"""REAL multi-process distributed bring-up: two OS processes join one
jax.distributed world (coordinator over localhost, the multi-host
control plane of SURVEY §2.3's TPU mapping) and run the actual
sharded publish step over the GLOBAL mesh — cross-process collectives
(Gloo on CPU, ICI/DCN on pods) carrying the trie-shard all-gather.

This is the seam the single-process suites cannot cover:
``tests/test_sharded.py`` proves the mesh program on 8 virtual
devices inside ONE process; here the same program spans processes,
each contributing 2 local devices, and every process verifies its
addressable slice of the output against the host oracle.

Pattern follows tests/test_cm_locker.py: the test spawns workers as
subprocesses running THIS file with --worker.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _worker(pid: int, nproc: int, addr: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from emqx_tpu.oracle import TrieOracle
    from emqx_tpu.ops.tokenize import WordTable, encode_batch
    from emqx_tpu.parallel import distributed
    from emqx_tpu.parallel.mesh import make_mesh
    from emqx_tpu.parallel.sharded import (build_sharded,
                                           build_sharded_fanout,
                                           place_batch, place_sharded,
                                           publish_step, shard_filters)

    assert distributed.initialize(coordinator_address=addr,
                                  num_processes=nproc, process_id=pid)
    # bring-up marker: the harness only retries failures that happen
    # BEFORE this line (the coordinator port-race window)
    print(f"WORKER {pid} INIT OK", flush=True)
    n_global = len(jax.devices())
    assert n_global == 4, n_global  # 2 procs x 2 local devices

    # identical deterministic build on every process (multi-process
    # device_put requires same host data everywhere)
    import random
    rng = random.Random(7)
    words = ["a", "b", "c", "d", "s1", "s2"]
    filters = set()
    while len(filters) < 60:
        depth = rng.randint(1, 4)
        ws = []
        for i in range(depth):
            r = rng.random()
            if r < 0.2:
                ws.append("+")
            elif r < 0.3 and i == depth - 1:
                ws.append("#")
            else:
                ws.append(rng.choice(words))
        filters.add("/".join(ws))
    filters = sorted(filters)
    fids = {f: i for i, f in enumerate(filters)}
    table = WordTable()
    for f in filters:
        for w in f.split("/"):
            table.intern(w)
    oracle = TrieOracle()
    for f in filters:
        oracle.insert(f)

    n_data, n_trie = 2, 2
    mesh = distributed.global_mesh(n_data=n_data, n_trie=n_trie)
    assert dict(mesh.shape) == {"data": 2, "trie": 2}
    shards = shard_filters(filters, n_trie)
    auto = build_sharded(shards, fids, table)
    rows = [{fids[f]: [fids[f] * 10] for f in shard} for shard in shards]
    fan = build_sharded_fanout(rows, len(filters))

    B = 16
    topics = ["/".join(rng.choice(words)
                       for _ in range(rng.randint(1, 4)))
              for _ in range(B)]
    ids_np, n_np, sys_np = encode_batch(table, topics, 8)

    auto_d = place_sharded(mesh, auto)
    fan_d = place_sharded(mesh, fan)
    b = place_batch(mesh, ids_np, n_np, sys_np)
    ids, subs, src, _bm, ovf, movf, stats = publish_step(
        mesh, auto_d, fan_d, *b, k=32, m=32, d=64)

    # every process checks the batch rows it can address: exact
    # match-set parity with the oracle, and the fan-out subscriber
    # slots derived from those matches
    checked = 0
    for shard in ids.addressable_shards:
        sl = shard.index[0]
        data = np.asarray(shard.data)
        for local_i, row in enumerate(data):
            topic = topics[sl.start + local_i]
            got = {int(x) for x in row if x >= 0}
            want = {fids[f] for f in oracle.match(topic)}
            assert got == want, (topic, got, want)
            checked += 1
    for shard in subs.addressable_shards:
        sl = shard.index[0]
        data = np.asarray(shard.data)
        for local_i, row in enumerate(data):
            topic = topics[sl.start + local_i]
            got = {int(x) for x in row if x >= 0}
            want = {fids[f] * 10 for f in oracle.match(topic)}
            assert got == want, (topic, got, want)
    assert not np.asarray(
        jax.device_get(movf.addressable_shards[0].data)).any()
    print(f"WORKER {pid} PARITY OK rows={checked}", flush=True)


def _run_world(addr: str):
    """Spawn the 2-process world on ``addr``; returns (procs, outs).
    A hang is killed (both workers — the world is dead) and shows up
    as a nonzero returncode, never an exception."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("JAX_PLATFORMS", None)  # worker pins cpu via jax.config
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--worker", str(pid), "2", addr],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env, cwd=REPO) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                # one hung worker means the world is dead — kill
                # BOTH now so the second doesn't get its own fresh
                # 180s budget
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                out, _ = p.communicate()
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


#: failure signatures of the coordinator-port race — ONLY these are
#: retried; a genuine distributed-parity failure (worker assertion)
#: must fail the test on its first occurrence, not be re-rolled
_PORT_RACE_SIGNS = ("Address already in use", "Connection refused",
                    "failed to connect", "UNAVAILABLE",
                    "DEADLINE_EXCEEDED")


def test_two_process_distributed_publish_parity():
    # the probed-free port races: between close() and the
    # coordinator's bind the kernel can hand it out as an ephemeral
    # source port (observed as a one-in-many suite flake) — the
    # coordinator address must be known before spawn, so the fix is
    # a fresh port per attempt, not SO_REUSEADDR
    for _attempt in range(3):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs, outs = _run_world(f"127.0.0.1:{port}")
        if all(p.returncode == 0 for p in procs):
            break
        # retry ONLY a bring-up failure (some worker never passed
        # INIT — the coordinator port-race window) that also carries
        # a connect-failure signature. A failure AFTER formation
        # (parity assertion, deadlock mid-step) must fail here, not
        # be re-rolled until it passes.
        during_bringup = any("INIT OK" not in out for out in outs)
        retryable = during_bringup and any(
            sig in out for out in outs for sig in _PORT_RACE_SIGNS)
        if not retryable:
            break  # a real failure: surface it immediately
    if any("Multiprocess computations aren't implemented" in out
           for out in outs):
        # capability gap, not a regression: this jax build's CPU
        # backend has no multi-process collectives at all, so the
        # two-host world cannot form regardless of our code
        pytest.skip("jax CPU backend lacks multiprocess computations")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"WORKER {pid} PARITY OK" in out, out[-3000:]


if __name__ == "__main__" and "--worker" in sys.argv:
    i = sys.argv.index("--worker")
    sys.path.insert(0, REPO)
    _worker(int(sys.argv[i + 1]), int(sys.argv[i + 2]),
            sys.argv[i + 3])
