"""Channel FSM fuzzing: random packet sequences must never crash.

The reference exercises its FSM with mocked collaborators
(test/emqx_channel_SUITE.erl, SURVEY §4 tier 3); this suite goes
further and throws randomized, partially nonsensical — but
well-formed — packet sequences at the sans-IO channel. Contract under
fuzz: handle_in never raises, a closed channel stays silent, every
returned object is a serializable packet, and QoS1 publishes on a
live session are always acked exactly once.
"""

import random

from emqx_tpu.broker import Broker
from emqx_tpu.channel import Channel
from emqx_tpu.cm import ConnectionManager
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.frame import serialize
from emqx_tpu.mqtt.packet import (Auth, Connect, Disconnect, Packet,
                                  Pingreq, PubAck, Publish, Subscribe,
                                  Unsubscribe)

TOPICS = ["a", "a/b", "s/+/x", "q/#", "$SYS/x", "", "a//b", "#", "+"]


def _connect_pkt(rng, version):
    return Connect(proto_ver=version,
                   proto_name=C.PROTOCOL_NAMES[version],
                   client_id=f"fz{rng.randrange(3)}",
                   clean_start=bool(rng.randrange(2)),
                   keepalive=rng.randrange(0, 120))


def _rand_packet(rng, version, pid_pool):
    t = rng.randrange(9)
    if t == 0:
        return _connect_pkt(rng, version)
    if t == 1:
        qos = rng.randrange(3)
        return Publish(topic=rng.choice(TOPICS), qos=qos,
                       retain=bool(rng.randrange(2)),
                       packet_id=rng.randint(1, 20) if qos else None,
                       payload=rng.randbytes(rng.randrange(16)))
    if t == 2:
        return Subscribe(packet_id=rng.randint(1, 20), topic_filters=[
            (rng.choice(TOPICS),
             {"qos": rng.randrange(3), "nl": rng.randrange(2),
              "rap": 0, "rh": 0})
            for _ in range(rng.randint(1, 3))])
    if t == 3:
        return Unsubscribe(packet_id=rng.randint(1, 20),
                           topic_filters=[rng.choice(TOPICS)])
    if t == 4:
        # acks for ids the server may or may not know
        ptype = rng.choice([C.PUBACK, C.PUBREC, C.PUBREL, C.PUBCOMP])
        pid = rng.choice(pid_pool) if pid_pool and rng.random() < 0.5 \
            else rng.randint(1, 20)
        return PubAck(type=ptype, packet_id=pid)
    if t == 5:
        return Pingreq()
    if t == 6:
        return Disconnect(reason_code=rng.choice([0, 4]))
    if t == 7:
        return Auth()
    return Publish(topic="$SYS/fake", qos=0, payload=b"spoof")


def _run_sequence(seed, version, n_packets=120):
    """Returns the number of packets processed by CONNECTED channels
    — callers assert the fuzz actually reaches depth. A random
    duplicate CONNECT / DISCONNECT / protocol error closes a channel;
    the sequence continues on a fresh one (real brokers see endless
    reconnects), so all n_packets are always consumed."""
    rng = random.Random(seed)
    broker = Broker()
    cm = ConnectionManager(broker=broker)
    chan = Channel(broker, cm)
    pid_pool = []
    depth = 0
    i = 0
    while i < n_packets:
        if chan.closed:
            # a closed channel stays silent forever...
            assert not chan.handle_in(Pingreq()), (seed, i)
            # ...and the fuzz continues on a fresh connection
            chan = Channel(broker, cm)
            pid_pool = []
        if chan.state == "idle" and rng.random() < 0.9:
            # mostly connect first — an IDLE channel rejects anything
            # else by closing, which would keep every sequence at
            # depth ~1 (the non-CONNECT-first path still gets its 10%)
            pkt = _connect_pkt(rng, version)
        else:
            pkt = _rand_packet(rng, version, pid_pool)
        i += 1
        if chan.state == "connected":
            depth += 1
        out = chan.handle_in(pkt)
        out = list(out or []) + list(chan.handle_deliver() or [])
        for o in out:
            assert isinstance(o, Packet), (seed, i, o)
            data = serialize(o, chan.proto_ver)  # wire-encodable
            assert isinstance(data, (bytes, bytearray))
            if isinstance(o, Publish) and o.qos:
                pid_pool.append(o.packet_id)
    # cleanup never raises either
    if not chan.closed:
        chan._shutdown()
    return depth


def test_fsm_random_sequences_v4():
    total = sum(_run_sequence(seed, C.MQTT_V4) for seed in range(40))
    assert total > 40 * 40  # the fuzz must spend real time CONNECTED


def test_fsm_random_sequences_v5():
    total = sum(_run_sequence(1000 + s, C.MQTT_V5) for s in range(40))
    assert total > 40 * 40


def test_fsm_random_sequences_v3():
    total = sum(_run_sequence(2000 + s, C.MQTT_V3) for s in range(20))
    assert total > 20 * 40


def test_qos1_publish_always_acked_once_when_connected():
    rng = random.Random(777)
    broker = Broker()
    cm = ConnectionManager(broker=broker)
    chan = Channel(broker, cm)
    chan.handle_in(Connect(proto_ver=C.MQTT_V4, client_id="ack1"))
    assert chan.state == "connected"
    for i in range(50):
        pid = rng.randint(1, 0xFFFF)
        out = chan.handle_in(Publish(topic="t", qos=1, packet_id=pid,
                                     payload=b"x"))
        acks = [o for o in out
                if isinstance(o, PubAck) and o.type == C.PUBACK]
        assert len(acks) == 1 and acks[0].packet_id == pid, (i, out)
