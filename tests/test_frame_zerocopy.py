"""Read-path allocation regression tests (PR 18 "Native front door").

The pure-Python parser used to `del self._buf[:consumed]` once per
packet — B packets in one read shifted the remaining buffer B times,
O(B·buflen) for a pipelined read. It now parses at a moving offset
and compacts ONCE per feed. These tests pin that with an instrumented
bytearray (counting bytes shifted by compaction and bytes
materialized by slicing), so a regression to per-packet deletes or
double-copy slicing fails loudly rather than showing up as a
mysterious throughput cliff under pipelined load.

Also here: the oversize guard. A fixed header *claiming* 256 MB must
be rejected from the 5 header bytes alone — neither parser may
buffer toward the announced length (that's a remote-controlled
allocation primitive at fleet scale).
"""

import tracemalloc

import pytest

from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.frame import (FrameTooLarge, Parser, make_parser,
                                 serialize)
from emqx_tpu.mqtt.packet import Pingreq, Publish

from emqx_tpu.ops import native as nat


class CountingBuf(bytearray):
    """bytearray that counts compaction-shifted and slice-copied
    bytes (int indexing is free; slices and del-slices are the
    O(n) operations the zero-copy rewrite bounds)."""

    shifted = 0   # bytes moved left by `del buf[:k]`
    sliced = 0    # bytes materialized by `buf[i:j]`

    def __delitem__(self, key):
        if isinstance(key, slice):
            start, stop, _ = key.indices(len(self))
            CountingBuf.shifted += len(self) - stop
        super().__delitem__(key)

    def __getitem__(self, key):
        if isinstance(key, slice):
            start, stop, _ = key.indices(len(self))
            CountingBuf.sliced += stop - start
        return super().__getitem__(key)

    @classmethod
    def reset(cls):
        cls.shifted = cls.sliced = 0


def _py_parser(**kw) -> Parser:
    """A Parser pinned to the pure-Python path (no C scanner) with an
    instrumented buffer."""
    p = Parser(**kw)
    p._NATIVE_MIN = 1 << 60        # instance override: never go native
    p._buf = CountingBuf()
    CountingBuf.reset()
    return p


def test_pipelined_read_compacts_once():
    """B packets in one read: one compaction of O(buflen), not B
    del-shifts of O(B·buflen)."""
    B = 200
    blob = serialize(Pingreq(), C.MQTT_V4) * B
    p = _py_parser()
    out = p.feed(blob)
    assert len(out) == B
    # the single end-of-feed compaction consumes the whole buffer, so
    # zero bytes remain to shift; per-packet deletes would have
    # shifted ~B²/2 · framelen bytes
    assert CountingBuf.shifted == 0, CountingBuf.shifted
    assert len(p._buf) == 0


def test_pipelined_read_with_trailing_partial():
    """Same, with a partial frame behind the batch: the one
    compaction shifts only the partial tail."""
    B = 100
    blob = serialize(Pingreq(), C.MQTT_V4) * B + b"\x30"  # partial PUBLISH
    p = _py_parser()
    out = p.feed(blob)
    assert len(out) == B
    assert CountingBuf.shifted == 1  # just the orphan header byte
    assert len(p._buf) == 1


def test_large_publish_across_reads_costs_o_len():
    """A PUBLISH spanning N reads: total slice+shift work is O(len),
    not O(N·len) — the body is materialized exactly once, when
    complete."""
    payload = b"x" * (512 * 1024)
    frame = serialize(Publish(topic="t", payload=payload), C.MQTT_V4)
    p = _py_parser()
    chunk = 32 * 1024
    out = []
    for off in range(0, len(frame), chunk):
        out.extend(p.feed(frame[off:off + chunk]))
    assert len(out) == 1 and out[0].payload == payload
    total = CountingBuf.shifted + CountingBuf.sliced
    # one body materialization + one (empty) compaction; O(N·len)
    # would be ~16 frames' worth (= len(frame) * nchunks / 2) here
    assert total <= 2 * len(frame), (total, len(frame))


def test_python_parser_rejects_claimed_giant_header():
    """5 header bytes claiming 256 MB raise at header-decode time;
    nothing is buffered toward the claim."""
    p = Parser(max_size=1024 * 1024)
    header = bytes([0x30]) + b"\xff\xff\xff\x7f"  # RL = 268435455
    tracemalloc.start()
    with pytest.raises(FrameTooLarge):
        p.feed(header)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert peak < 1024 * 1024, peak
    # raise-before-consume: the poisonous frame stays buffered (the
    # connection is closing anyway), but it's 5 bytes, not 256 MB
    assert len(p._buf) == len(header)


@pytest.mark.skipif(not nat.has_frame_parser(),
                    reason="native frame parser not built")
def test_native_parser_rejects_claimed_giant_header():
    p = make_parser(max_size=1024 * 1024, mode="native")
    assert type(p).__name__ == "NativeParser"
    header = bytes([0x30]) + b"\xff\xff\xff\x7f"
    tracemalloc.start()
    with pytest.raises(FrameTooLarge):
        p.feed(header)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert peak < 1024 * 1024, peak
    assert p.pending() == len(header)
