"""Slow-consumer guard (reference: listener send_timeout +
send_timeout_close): the QoS0 fan-out path writes without draining,
so a subscriber that stops reading must be disconnected once its
write buffer sits past high_watermark for send_timeout seconds —
not grow server memory without bound."""

import asyncio

from emqx_tpu.node import Node
from emqx_tpu.zone import Zone
from tests.mqtt_client import TestClient


async def test_slow_consumer_closed_and_fast_one_survives():
    zone = Zone(name="slowtest", send_timeout=1.0,
                high_watermark=64 * 1024, allow_anonymous=True)
    n = Node(boot_listeners=False, zone=zone)
    lst = n.add_listener(port=0, zone=zone)
    await n.start()
    try:
        slow = TestClient("slow", version=4)
        await slow.connect(port=lst.port)
        await slow.subscribe("blast/#", qos=0)
        fast = TestClient("fast", version=4)
        await fast.connect(port=lst.port)
        await fast.subscribe("blast/#", qos=0)
        # wedge the slow client: stop its read loop so TCP backs up
        slow._task.cancel()
        pub = TestClient("pub", version=4)
        await pub.connect(port=lst.port)
        # kernel socket buffers (client recv + server send) absorb
        # a few MB before the USER-SPACE write buffer grows — blast
        # well past that
        payload = b"x" * 16384
        for i in range(2000):  # ~32MB
            await pub.publish(f"blast/{i % 7}", payload, qos=0)
            if i % 50 == 0:
                await asyncio.sleep(0)
        # within ~send_timeout the guard must close the slow channel
        for _ in range(80):
            await asyncio.sleep(0.1)
            if n.cm.lookup_channel("slow") is None:
                break
        assert n.cm.lookup_channel("slow") is None, \
            "slow consumer not closed"
        assert n.metrics.val("connections.closed.slow_consumer") >= 1
        # the fast subscriber is still connected and functional
        assert n.cm.lookup_channel("fast") is not None
        await pub.publish("blast/final", b"done", qos=0)
        got = await asyncio.wait_for(fast.inbox.get(), 10)
        while got.topic != "blast/final":
            got = await asyncio.wait_for(fast.inbox.get(), 10)
        await pub.disconnect()
        await fast.disconnect()
    finally:
        await n.stop()


async def test_kick_of_wedged_consumer_aborts_within_timeout():
    """A graceful close (kick/takeover path) of a peer that refuses
    to drain must abort within send_timeout instead of holding the
    socket, the connection task, and Listener.stop forever."""
    zone = Zone(name="kicktest", send_timeout=1.0,
                high_watermark=64 * 1024, allow_anonymous=True)
    n = Node(boot_listeners=False, zone=zone)
    lst = n.add_listener(port=0, zone=zone)
    await n.start()
    try:
        slow = TestClient("wedged", version=4)
        await slow.connect(port=lst.port)
        await slow.subscribe("k/#", qos=0)
        slow._task.cancel()
        pub = TestClient("kpub", version=4)
        await pub.connect(port=lst.port)
        # park ~2MB in the victim's buffers (below the guard's
        # trigger odds on kernel-buffer-only, but enough that a
        # graceful close cannot flush to a non-reading peer fast)
        payload = b"y" * 16384
        for i in range(1200):
            await pub.publish(f"k/{i % 3}", payload, qos=0)
            if i % 50 == 0:
                await asyncio.sleep(0)
        n.cm.kick_session("wedged")
        for _ in range(60):
            await asyncio.sleep(0.1)
            if n.cm.lookup_channel("wedged") is None:
                break
        assert n.cm.lookup_channel("wedged") is None, "kick hung"
        await pub.disconnect()
    finally:
        # node.stop() itself would hang if the close leaked
        await asyncio.wait_for(n.stop(), 15)
