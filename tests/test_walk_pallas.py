"""VMEM-resident Pallas walk + path compression (ISSUE 16).

Three contracts pinned here:

  1. **Pallas-vs-lax byte identity** on CPU interpret mode — same
     ids, counts, overflow flags, bit for bit, on both table layouts
     (narrow / wide) and both packing modes.
  2. **Native-vs-numpy compression parity** — the C++ ``csr_compress``
     chain fuser must reproduce ``csr.compress_automaton`` exactly
     (same edges, same renumbering, same hop bounds, same wt).
  3. **Compressed-walk property suite** — randomized topic/filter
     fuzz (``+``/``#``/``$share``, deep literal spines, single-char
     and empty levels) against the host ``TrieOracle`` across
     add/delete churn, delta flatten, devloss rebuild and checkpoint
     round-trip, with the router's dispatch seam forced through the
     Pallas kernel.
"""

import random

import numpy as np
import pytest

from emqx_tpu import topic as T
from emqx_tpu.oracle import TrieOracle
from emqx_tpu.ops.csr import (attach_walk_tables, build_automaton,
                              compress_automaton)
from emqx_tpu.ops.match import match_batch, walk_params
from emqx_tpu.ops.tokenize import WordTable, encode_batch
from emqx_tpu.ops.walk_pallas import (fetch_walk_result,
                                      match_batch_pallas, walk_variant)
from emqx_tpu.router import MatcherConfig, Router


def _build(filters, mode=None):
    trie = TrieOracle()
    table = WordTable()
    fids = {}
    for f in filters:
        trie.insert(f)
        fids[f] = len(fids)
        for w in T.words(f):
            table.intern(w)
    if mode is None:
        auto = build_automaton(trie, fids, table)
    else:
        raw = build_automaton(trie, fids, table, skip_hash=True)
        auto, edges = compress_automaton(raw, force_mode=mode)
        auto = attach_walk_tables(auto, edges)
    inv = {v: k for k, v in fids.items()}
    return trie, table, auto, inv


def _rand_word(rng):
    return rng.choice(["a", "b", "c", "sensor", "x", "y1", "q", ""])


def _rand_filters(rng, n, deep=True):
    out = set()
    while len(out) < n:
        r = rng.random()
        if r < 0.1:
            out.add("$share/g/%s/%s" % (_rand_word(rng),
                                        _rand_word(rng)))
            continue
        if deep and r < 0.35:
            # deep literal spine, sometimes '#'-capped
            depth = rng.randint(8, 16)
            ws = ["s%d" % rng.randint(0, 2) for _ in range(depth)]
            if rng.random() < 0.4:
                ws[-1] = "#"
            out.add("/".join(ws))
            continue
        depth = rng.randint(1, 6)
        ws = []
        for i in range(depth):
            rr = rng.random()
            if rr < 0.2:
                ws.append("+")
            elif rr < 0.28 and i == depth - 1:
                ws.append("#")
            else:
                ws.append(_rand_word(rng))
        out.add("/".join(ws))
    return sorted(out)


def _rand_topics(rng, n, L=16):
    out = []
    for _ in range(n):
        if rng.random() < 0.4:
            depth = rng.randint(8, L)
            out.append("/".join("s%d" % rng.randint(0, 2)
                                for _ in range(depth)))
        else:
            out.append("/".join(_rand_word(rng)
                                for _ in range(rng.randint(1, 6))))
    return out


# -- 1. Pallas vs lax byte identity ----------------------------------------


@pytest.mark.parametrize("mode", ["narrow", "wide"])
@pytest.mark.parametrize("pack_ids", [True, False])
def test_pallas_lax_byte_identity(mode, pack_ids):
    rng = random.Random(20160 + pack_ids)
    filters = _rand_filters(rng, 150)
    topics = _rand_topics(rng, 32)
    trie, table, auto, inv = _build(filters, mode=mode)
    ids, n, sysm = encode_batch(table, topics, 16)
    kw = dict(k=16, m=64, pack_ids=pack_ids,
              **walk_params(auto, ids.shape[1]))
    ref = match_batch(auto, ids, n, sysm, **kw)
    got = match_batch_pallas(auto, ids, n, sysm, interpret=True, **kw)
    r_ids, r_cnt, r_ovf = fetch_walk_result(ref)
    g_ids, g_cnt, g_ovf = fetch_walk_result(got)
    np.testing.assert_array_equal(g_ids, r_ids)
    np.testing.assert_array_equal(g_cnt, r_cnt)
    np.testing.assert_array_equal(g_ovf, r_ovf)


def test_pallas_overflow_and_sys_semantics():
    """Edge semantics must survive the kernel port: tiny K overflow
    flags, $SYS root masking, topics past max_levels."""
    filters = ["#", "+/#", "$SYS/#", "a/+/c", "a/b/c", "a/b/#"]
    trie, table, auto, inv = _build(filters, mode="narrow")
    topics = ["a/b/c", "$SYS/broker", "a/x/c", "q",
              "/".join(["d"] * 40)]
    ids, n, sysm = encode_batch(table, topics, 16)
    kw = dict(k=2, m=8, pack_ids=True, **walk_params(auto, 16))
    ref = match_batch(auto, ids, n, sysm, **kw)
    got = match_batch_pallas(auto, ids, n, sysm, interpret=True, **kw)
    for a, b in zip(fetch_walk_result(got), fetch_walk_result(ref)):
        np.testing.assert_array_equal(a, b)
    # the >16-level topic must be flagged, not truncated
    assert bool(fetch_walk_result(got)[2][-1])


def test_walk_variant_dispatch(monkeypatch):
    monkeypatch.delenv("EMQX_TPU_WALK", raising=False)
    assert walk_variant() == "lax"  # CPU test backend
    monkeypatch.setenv("EMQX_TPU_WALK", "pallas")
    assert walk_variant() == "pallas"
    monkeypatch.setenv("EMQX_TPU_WALK", "lax")
    assert walk_variant() == "lax"


# -- 2. native chain-fuser parity ------------------------------------------


def test_native_compress_parity():
    native = pytest.importorskip("emqx_tpu.ops.native")
    if not native.available():
        pytest.skip("native library unavailable")
    rng = random.Random(31)
    eng = native.NativeEngine()
    filters = _rand_filters(rng, 250)
    for i, f in enumerate(filters):
        eng.insert(f, i)
    got = eng.flatten()
    v1 = eng.flatten(skip_hash=True)
    # the native path must have taken the C++ fuser (deep spines ⇒
    # wide mode), and its output must be byte-identical to numpy
    assert got.wt_take > 1
    from emqx_tpu.ops.csr import finalize_automaton
    want = finalize_automaton(v1)
    for field in want._fields:
        a, b = getattr(got, field), getattr(want, field)
        if a is None or isinstance(a, (int, np.integer)):
            assert a == b, field
        else:
            a, b = np.asarray(a), np.asarray(b)
            assert a.shape == b.shape and a.dtype == b.dtype, field
            np.testing.assert_array_equal(a, b, err_msg=field)


def test_native_compress_narrow_fallback():
    native = pytest.importorskip("emqx_tpu.ops.native")
    if not native.available():
        pytest.skip("native library unavailable")
    eng = native.NativeEngine()
    for i, f in enumerate(["a/b", "a/+", "c"]):  # shallow ⇒ narrow
        eng.insert(f, i)
    auto = eng.flatten()
    assert auto.wt_take == 1
    from emqx_tpu.ops.csr import finalize_automaton
    want = finalize_automaton(eng.flatten(skip_hash=True))
    np.testing.assert_array_equal(np.asarray(auto.wt),
                                  np.asarray(want.wt))


# -- 3. compressed-walk property suite -------------------------------------


def _mk(**kw):
    kw.setdefault("device_min_filters", 0)
    kw.setdefault("min_batch", 8)
    return Router(MatcherConfig(**kw), node="node1")


def _assert_parity(r, oracle, topics, tag=""):
    got = r.match_filters(topics)
    for t, row in zip(topics, got):
        assert sorted(row) == sorted(oracle.match(t)), (tag, t)


@pytest.mark.parametrize("delta,match_cache", [
    (False, False), (True, False), (False, True), (True, True)])
def test_compressed_walk_churn_parity(delta, match_cache):
    """Wide-table walk parity vs the oracle across add/delete churn,
    delta-on/off × cache-on/off — the tables stay in wide
    (chain-fused) mode throughout because of the deep spines."""
    rng = random.Random(777)
    r = _mk(delta=delta, match_cache=match_cache,
            delta_max_filters=10_000)
    oracle = TrieOracle()
    live = {}
    for f in _rand_filters(rng, 80):
        r.add_route(f)
        oracle.insert(f)
        live[f] = True
    probe = _rand_topics(rng, 10) + ["$share/g/a/b", "//", "s0"]
    _assert_parity(r, oracle, probe, "warm")
    assert r.walk_info()["mode"] == "wide"
    assert r.walk_info()["chains"] > 0
    for step in range(60):
        if live and rng.random() < 0.45:
            f = rng.choice(sorted(live))
            r.delete_route(f)
            oracle.delete(f)
            del live[f]
        else:
            f = _rand_filters(rng, 1)[0]
            if f not in live:
                r.add_route(f)
                oracle.insert(f)
                live[f] = True
        if step % 12 == 0:
            _assert_parity(r, oracle, probe, f"churn@{step}")
    r.rebuild()
    _assert_parity(r, oracle, probe, "post-rebuild")


def test_compressed_walk_devloss_and_checkpoint(tmp_path):
    """Wide tables must survive the PR 14 lifecycle: devloss rebuild
    re-fuses chains on the fresh backend, checkpoint round-trip
    restores the compressed layout bit-compatibly."""
    from emqx_tpu import checkpoint

    rng = random.Random(99)
    r = _mk(match_cache=False)
    oracle = TrieOracle()
    for f in _rand_filters(rng, 60):
        r.add_route(f)
        oracle.insert(f)
    probe = _rand_topics(rng, 8)
    _assert_parity(r, oracle, probe, "pre")
    assert r.walk_info()["mode"] == "wide"
    # devloss: suspend (host fallback must stay exact) then rebuild
    r.suspend_device()
    _assert_parity(r, oracle, probe, "suspended")
    r.rebuild_device_state()
    _assert_parity(r, oracle, probe, "post-devloss")
    assert r.walk_info()["mode"] == "wide"
    # checkpoint round-trip into a fresh router
    path = str(tmp_path / "walk.npz")
    checkpoint.save(r, path)
    r2 = _mk(match_cache=False)
    checkpoint.load(r2, path)
    _assert_parity(r2, oracle, probe, "restored")
    assert r2.walk_info()["mode"] == "wide"


def test_rewarm_plan_covers_deep_buckets():
    """Devloss rewarm must replay every observed level-bucket shape
    (each is its own compile family): a router that served 16-level
    traffic gets a 16-level warm spine per bucket (ISSUE 16)."""
    from emqx_tpu.ops.warmup import warm_plan, warm_topics

    r = _mk()
    for f in ["a/b", "/".join(["s0"] * 16)]:
        r.add_route(f)
    r.match_filters(["a/b"])
    r.match_filters(["/".join(["s0"] * 16)])
    seen = r.observed_levels()
    assert 16 in seen
    plan = warm_plan([8, 64], 8, levels=seen)
    # every (bucket, level) pair present; the first topic of a deep
    # batch carries exactly the deep level count (depth_bucket keys
    # the compile on the batch's deepest topic)
    depths = {(b, len(topics[0].split("/"))) for b, topics in plan}
    for b in (8, 64):
        for lv in seen:
            assert (b, lv) in depths
    assert len(warm_topics(64, 8, levels=16)) == 33  # bucket select


@pytest.mark.slow
def test_pallas_dispatch_through_router(monkeypatch):
    """The dispatch seam end-to-end: force the Pallas kernel (CPU ⇒
    interpret mode) through Router.match_filters and hold oracle
    parity, including a mid-test mutation + re-flatten."""
    monkeypatch.setenv("EMQX_TPU_WALK", "pallas")
    rng = random.Random(5150)
    r = _mk(match_cache=False, active_k=8, min_batch=4)
    oracle = TrieOracle()
    for f in _rand_filters(rng, 40):
        r.add_route(f)
        oracle.insert(f)
    probe = _rand_topics(rng, 4)
    assert r.walk_info()["variant"] == "pallas"
    _assert_parity(r, oracle, probe, "pallas-warm")
    f = "mid/flight/route"
    r.add_route(f)
    oracle.insert(f)
    _assert_parity(r, oracle, probe + [f.replace("+", "a")],
                   "pallas-churn")
