"""Cast-coalescing semantics of the socket transport (round 4).

The data-plane rework batched outbound casts per peer and made
inbound casts non-blocking; these tests pin the contracts the
code-review pass flagged as easy to regress:

- per-peer ORDER: casts arrive in issue order, and a call issued
  after casts to the same peer is observed AFTER them (the clientid
  locker's release-then-acquire pattern depends on this);
- a wedged peer (accepts, then stops reading) must not head-of-line
  block casts to healthy peers;
- the per-peer outbound buffer is capped: a flood to a wedged peer
  sheds instead of growing without bound.
"""

import socket
import struct
import threading
import time

from emqx_tpu.cluster_net import SocketTransport, _LEN


class RecordingCluster:
    """Stands in for Cluster: records inbound RPCs in arrival order."""

    def __init__(self):
        self.ops = []
        self.lock = threading.Lock()

    def handle_rpc(self, op, *args):
        with self.lock:
            self.ops.append((op, args))
        return "ok"


def _pair(name_a="A", name_b="B"):
    ta = SocketTransport(name_a, cookie="ck")
    tb = SocketTransport(name_b, cookie="ck")
    ta.cluster = RecordingCluster()
    tb.cluster = RecordingCluster()
    ta.serve()
    tb.serve()
    ta._peers[name_b] = ("127.0.0.1", tb.port)
    tb._peers[name_a] = ("127.0.0.1", ta.port)
    return ta, tb


def _wait_for(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_cast_burst_ordered_and_call_after_casts():
    ta, tb = _pair()
    try:
        for i in range(200):
            ta.cast("B", "op", i)
        # the call must drain the same peer's buffered casts first
        assert ta.call("B", "marker") == "ok"
        assert _wait_for(lambda: len(tb.cluster.ops) == 201)
        ops = tb.cluster.ops
        assert ops[-1][0] == "marker", ops[-5:]
        assert [a[0] for _, a in ops[:-1]] == list(range(200))
    finally:
        ta.close()
        tb.close()


class WedgedPeer:
    """Accepts the hello handshake, replies OK, then stops reading —
    the kernel eventually backpressures the sender's socket."""

    def __init__(self):
        import pickle

        self._pickle = pickle
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(1)
        self.port = self.sock.getsockname()[1]
        self._conn = None
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        conn, _ = self.sock.accept()
        self._conn = conn
        head = b""
        while len(head) < 4:
            head += conn.recv(4 - len(head))
        (n,) = _LEN.unpack(head)
        body = b""
        while len(body) < n:
            body += conn.recv(n - len(body))
        reply = self._pickle.dumps(("reply", 0, True))
        conn.sendall(_LEN.pack(len(reply)) + reply)
        # ... and never read again: outbound bytes to us now pile up

    def close(self):
        for s in (self._conn, self.sock):
            try:
                s.close()
            except Exception:
                pass


def test_wedged_peer_does_not_block_healthy_casts():
    ta, tb = _pair()
    wedged = WedgedPeer()
    try:
        ta._peers["W"] = ("127.0.0.1", wedged.port)
        big = b"x" * (1 << 20)
        # fill W's pipe far past the socket buffers: the flush task
        # for W parks in drain()
        for _ in range(8):
            ta.cast("W", "blob", big)
        time.sleep(0.3)
        # healthy peer must still receive promptly
        for i in range(20):
            ta.cast("B", "op", i)
        assert _wait_for(lambda: len(tb.cluster.ops) == 20, 10), \
            f"healthy peer starved: {len(tb.cluster.ops)}/20"
    finally:
        wedged.close()
        ta.close()
        tb.close()


def test_cast_buffer_cap_sheds_instead_of_growing():
    ta, tb = _pair()
    wedged = WedgedPeer()
    try:
        ta._peers["W"] = ("127.0.0.1", wedged.port)
        ta._CAST_BUF_MAX = 256 * 1024  # instance override
        big = b"x" * (64 * 1024)
        for _ in range(64):  # 4MB issued at a 256KB cap
            ta.cast("W", "blob", big)
        with ta._cast_lock:
            buffered = sum(len(b) for b in ta._cast_buf.values())
        assert buffered <= ta._CAST_BUF_MAX + (1 << 17), buffered
        # and the transport is still functional toward healthy peers
        ta.cast("B", "op", 1)
        assert _wait_for(lambda: len(tb.cluster.ops) == 1, 10)
    finally:
        wedged.close()
        ta.close()
        tb.close()


def test_garbage_and_oversized_frames_do_not_kill_transport():
    """A peer that speaks garbage (bad pickle, absurd length prefix)
    gets dropped; the transport keeps serving legit peers."""
    ta, tb = _pair()
    try:
        # garbage bytes straight at B's transport port
        s1 = socket.create_connection(("127.0.0.1", tb.port))
        s1.sendall(b"\xde\xad\xbe\xef" * 16)
        s1.close()
        # 4GB length prefix: must be refused, not allocated
        s2 = socket.create_connection(("127.0.0.1", tb.port))
        s2.sendall(struct.pack(">I", 0xFFFFFFF0))
        time.sleep(0.2)
        s2.close()
        # transport still works for the real peer
        ta.cast("B", "op", 1)
        assert ta.call("B", "marker") == "ok"
        assert _wait_for(lambda: len(tb.cluster.ops) == 2)
    finally:
        ta.close()
        tb.close()
