"""Durable broker: journal + atomic checkpoints + exact crash
recovery (docs/DURABILITY.md).

The acceptance property: for every armed storage fault point in the
kill matrix, restart recovers routes, retained messages and
persistent sessions exactly — QoS1/2 unacked redelivered with DUP,
only in-flight QoS0 may be lost — and ``[durability] enabled =
false`` is pinned to today's behavior.
"""

import asyncio
import os

import pytest

from emqx_tpu import checkpoint, faults
from emqx_tpu.durability import DurabilityConfig
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.packet import Disconnect
from emqx_tpu.node import Node
from emqx_tpu.session import Session
from emqx_tpu.types import Message, SubOpts

from tests.mqtt_client import TestClient


def dcfg(tmp_path, **kw):
    kw.setdefault("fsync", False)  # tmpfs-friendly; fsync path has
    # its own fault-injection coverage in tests/test_wal.py
    return DurabilityConfig(enabled=True,
                            dir=str(tmp_path / "dur"), **kw)


def mknode(tmp_path, **kw):
    kw.setdefault("durability", dcfg(tmp_path))
    kw.setdefault("load_default_modules", True)
    kw.setdefault("boot_listeners", False)
    return Node(**kw)


async def crash(node):
    """kill -9 analogue: tear the in-process node down WITHOUT the
    graceful durability path — no final checkpoint, no detach
    records; only what already reached the journal survives."""
    node.broker.durability = None
    node.cm.durability = None
    node.durability = None
    await node.stop()


class _Chan:
    """Minimal channel holder so the cm registry (and therefore
    checkpoint snapshots) see the session as live."""

    def __init__(self, s):
        self.session = s
        self.client_id = s.client_id


def durable_session(node, cid, expiry=300.0):
    s = Session(cid, broker=node.broker, clean_start=False)
    node.durability.session_opened(s, expiry)
    node.cm.register_channel(cid, _Chan(s))
    return s


def state_model(node):
    """Comparable durable-state fingerprint of a node."""
    sessions = {}
    for cid, (s, _ts, _exp) in node.cm._detached.items():
        sessions[cid] = {
            "subs": {k: (o.qos, o.nl, o.share)
                     for k, o in s.subscriptions.items()},
            "inflight": sorted(
                (pid, (v[0] if isinstance(v[0], str)
                       else (v[0].topic, bytes(v[0].payload))))
                for pid, v in s.inflight.to_list()),
            "mqueue": [(m.topic, bytes(m.payload))
                       for _p, q in s.mqueue.snapshot() for m in q],
            "awaiting_rel": sorted(s.awaiting_rel),
            "next_pkt_id": s.next_pkt_id,
        }
    ret = node.modules._loaded.get("retainer")
    retained = {t: bytes(m.payload)
                for t, m in (ret._store.items() if ret else ())}
    return {"routes": node.router.route_table(),
            "retained": retained, "sessions": sessions}


# -- disabled-mode pin ----------------------------------------------------

async def test_disabled_mode_is_inert(tmp_path):
    n = Node(boot_listeners=False,
             durability=DurabilityConfig(
                 enabled=False, dir=str(tmp_path / "off")))
    assert n.durability is None
    assert n.broker.durability is None and n.cm.durability is None
    await n.start()
    s = Session("c", broker=n.broker)
    s.subscribe("a/b", SubOpts(qos=1))
    assert n.broker.publish(Message(topic="a/b", qos=1)) == 1
    assert s._dur is None and not s.durable
    await n.stop()
    assert not os.path.exists(str(tmp_path / "off"))
    for m in ("wal.appends", "wal.fsyncs", "checkpoint.saves",
              "recovery.replayed"):
        assert n.metrics.val(m) == 0


async def test_durability_on_delivery_parity(tmp_path):
    """Durability ON must not change what subscribers receive."""
    got = {}
    for mode in ("off", "on"):
        n = mknode(tmp_path / mode) if mode == "on" else Node(
            boot_listeners=False, load_default_modules=True)
        await n.start()
        s = Session("c", broker=n.broker)
        if mode == "on":
            n.durability.session_opened(s, 300.0)
        s.subscribe("p/+", SubOpts(qos=1))
        counts = [n.broker.publish(
            Message(topic=f"p/{i}", payload=bytes([i]), qos=q))
            for i, q in enumerate((0, 1, 2))]
        got[mode] = (counts,
                     [(pid, m.topic) for pid, m in s.outbox],
                     sorted(p for p, _ in s.inflight.to_list()))
        await n.stop()
    assert got["on"] == got["off"]


# -- the full crash round-trip -------------------------------------------

async def test_crash_recovers_routes_retained_sessions(tmp_path):
    n = mknode(tmp_path)
    await n.start()
    # live durable session with unacked QoS1/2 inflight + QoS2 recv
    live = durable_session(n, "live")
    live.subscribe("fleet/+/state", SubOpts(qos=1))
    live.subscribe("$share/g/fleet/cmd", SubOpts(qos=2))
    # detached durable session accumulating an mqueue
    det = durable_session(n, "away")
    det.subscribe("fleet/9/state", SubOpts(qos=1))
    n.cm._detached["away"] = (det, 1e18, 300.0)  # placed manually,
    det.connected = False                        # then detached
    n.durability.session_detached(det)
    # a clean (non-durable) subscriber whose refs must prune away
    class Clean:
        client_id = "clean"

        def deliver(self, f, m):
            pass
    n.broker.subscribe(Clean(), "fleet/+/state")
    # retained store + a delete (tombstone must survive too)
    n.broker.publish(Message(topic="fleet/1/state", payload=b"up",
                             qos=1, flags={"retain": True}))
    n.broker.publish(Message(topic="fleet/2/state", payload=b"x",
                             flags={"retain": True}))
    n.broker.publish(Message(topic="fleet/2/state", payload=b"",
                             flags={"retain": True}))  # clear
    # QoS1 into the live window + the detached mqueue
    n.broker.publish(Message(topic="fleet/9/state", payload=b"q",
                             qos=1))
    live.record_awaiting_rel(7)  # inbound QoS2 two-phase state
    assert len(live.inflight) == 2  # retained pub + fleet/9
    n.durability.on_batch()

    # expected model: live session compares as it will look DETACHED
    n.cm._detached["live"] = (live, 0, 300.0)
    want = state_model(n)
    del n.cm._detached["live"]
    # prune expectation: clean's extra ref on fleet/+/state goes
    want["routes"]["fleet/+/state"][n.broker.node] -= 1

    await crash(n)
    n2 = mknode(tmp_path)
    await n2.start()
    got = state_model(n2)
    assert got == want
    rec = n2.durability.last_recovery
    assert rec["sessions"] == 2 and rec["pruned_refs"] == 1
    assert not rec["degraded"]
    # matching actually works against the restored automaton/trie
    assert set(n2.router.match_filters(["fleet/5/state"])[0]) == \
        {"fleet/+/state"}
    ret = n2.modules._loaded.get("retainer")
    assert "fleet/2/state" in ret._tombstones
    await n2.stop()


async def test_double_recovery_is_idempotent(tmp_path):
    """Recover → crash again with NO new ops → recover: identical
    state (every journal record idempotent, baseline checkpoint
    exact)."""
    n = mknode(tmp_path)
    await n.start()
    s = durable_session(n, "c1")
    s.subscribe("a/+", SubOpts(qos=1))
    s.subscribe("a/+", SubOpts(qos=2))  # resubscribe: opts change
    n.broker.publish(Message(topic="a/x", payload=b"r", qos=1,
                             flags={"retain": True}))
    n.durability.on_batch()
    n.cm._detached["c1"] = (s, 0, 300.0)
    want = state_model(n)
    del n.cm._detached["c1"]
    await crash(n)
    models = []
    for _ in range(2):
        n2 = mknode(tmp_path)
        await n2.start()
        models.append(state_model(n2))
        await crash(n2)
    assert models[0] == want
    assert models[1] == want


# -- the kill matrix ------------------------------------------------------

def _matrix_workload(n, phase2=False):
    """Deterministic durable workload; ``phase2`` adds the ops whose
    survival depends on the armed fault."""
    s = durable_session(n, "m1")
    s.subscribe("w/+", SubOpts(qos=1))
    n.broker.publish(Message(topic="w/1", payload=b"a", qos=1,
                             flags={"retain": True}))
    n.durability.on_batch()
    if phase2:
        # pid-neutral phase-2 ops (no QoS>0 delivery): their survival
        # is exactly what each armed fault decides
        s.subscribe("w2/#", SubOpts(qos=1))
        n.broker.publish(Message(topic="r/2", payload=b"b",
                                 flags={"retain": True}))
    return s


@pytest.mark.parametrize("scenario", [
    "clean", "before_flush", "torn_tail", "fsync_error_recovers",
    "mid_checkpoint", "stale_journal_ignored"])
async def test_kill_matrix(tmp_path, scenario):
    n = mknode(tmp_path)
    await n.start()
    lose_phase2 = scenario in ("before_flush", "torn_tail")
    s = _matrix_workload(n, phase2=(scenario != "clean"))
    if scenario == "clean":
        pass
    elif scenario == "before_flush":
        pass  # phase-2 ops stay in the unflushed buffer — lost
    elif scenario == "torn_tail":
        # the flush that would land phase 2 short-writes (crash
        # mid-append): the torn tail truncates at replay, alarmed
        with faults.injected("wal.append", times=1):
            n.durability.on_batch()
    elif scenario == "fsync_error_recovers":
        with faults.injected("wal.fsync", times=1):
            n.durability.on_batch()
        assert n.durability.wal.degraded
        n.durability.wal._retry_at = 0.0
        n.durability.on_batch()  # backoff elapsed: retry lands all
        assert not n.durability.wal.degraded
    elif scenario == "mid_checkpoint":
        n.durability.on_batch()
        with faults.injected("checkpoint.rename", times=1):
            out = n.durability.checkpoint_now()
        assert "error" in out
        assert n.durability.counters["checkpoint.errors"] == 1
    elif scenario == "stale_journal_ignored":
        n.durability.on_batch()
        n.durability.checkpoint_now()  # commits; journals truncate
        # a leftover pre-manifest journal (crash mid-truncate) must
        # be ignored by recovery, not replayed over newer state
        stale = os.path.join(n.durability.cfg.dir, "journal-0.wal")
        from emqx_tpu import wal as _w
        w = _w.Wal(stale, fsync=False)
        w.append(("route", "stale/#", n.broker.node, 9))
        w.flush()
        w.close()
    # expected durable state (session compares as detached)
    if lose_phase2:
        # the phase-2 records never reached disk: expectation rolls
        # back to the phase-1 flush point
        s.unsubscribe("w2/#")
        ret = n.modules._loaded.get("retainer")
        ret._restoring = True
        ret._pop("r/2")
        ret._restoring = False
    n.cm._detached["m1"] = (s, 0, 300.0)
    want = state_model(n)
    del n.cm._detached["m1"]
    want["routes"].pop("stale/#", None)
    await crash(n)

    n2 = mknode(tmp_path)
    await n2.start()
    got = state_model(n2)
    assert got == want, scenario
    rec = n2.durability.last_recovery
    if scenario == "torn_tail":
        assert rec["torn_journals"] == 1
        assert any(a.name == "journal_torn_tail"
                   for a in n2.alarms.get_alarms("activated"))
    else:
        assert rec["torn_journals"] == 0
    await n2.stop()


# -- live socket paths ----------------------------------------------------

async def test_reconnect_after_crash_session_present_dup(tmp_path):
    n = mknode(tmp_path, boot_listeners=True)
    n.add_listener(port=0)
    await n.start()
    port = n.listeners[0].port
    sub = TestClient("dev", version=C.MQTT_V5, clean_start=True,
                     auto_ack=False,
                     properties={"Session-Expiry-Interval": 300})
    await sub.connect(port=port)
    await sub.subscribe("d/t", qos=1)
    pub = TestClient("pub", version=C.MQTT_V5)
    await pub.connect(port=port)
    for i in range(3):
        await pub.publish("d/t", str(i).encode(), qos=1, timeout=60)
    for _ in range(3):
        await sub.recv(30)  # delivered, deliberately unacked
    await asyncio.sleep(0)
    n.durability.on_batch()  # the batch flush a crash can't outrun
    await crash(n)
    await sub.close()
    await pub.close()

    n2 = mknode(tmp_path, boot_listeners=True)
    n2.add_listener(port=0)
    await n2.start()
    sub2 = TestClient("dev", version=C.MQTT_V5, clean_start=False,
                      properties={"Session-Expiry-Interval": 300})
    ack = await sub2.connect(port=n2.listeners[0].port, timeout=30)
    assert ack.session_present, \
        "recovered persistent session must CONNACK session-present"
    got = {}
    for _ in range(3):
        m = await sub2.recv(30)
        got[m.payload] = m.dup
    assert sorted(got) == [b"0", b"1", b"2"]
    assert all(got.values()), f"redelivery must set DUP: {got}"
    await sub2.close()
    await n2.stop()


async def test_graceful_shutdown_0x8b_and_clean_recovery(tmp_path):
    n = mknode(tmp_path, boot_listeners=True)
    n.add_listener(port=0)
    await n.start()
    cli = TestClient("gs", version=C.MQTT_V5, clean_start=True,
                     properties={"Session-Expiry-Interval": 300})
    await cli.connect(port=n.listeners[0].port)
    await cli.subscribe("g/t", qos=1)
    stop = asyncio.create_task(n.stop())
    pkt = await asyncio.wait_for(cli.acks.get(), 30)
    assert isinstance(pkt, Disconnect)
    assert pkt.reason_code == 0x8B  # Server-Shutting-Down
    await stop
    await cli.close()
    m = checkpoint.read_manifest(n.durability.cfg.dir)
    assert m is not None and m["clean_shutdown"]

    n2 = mknode(tmp_path)
    await n2.start()
    rec = n2.durability.last_recovery
    # a graceful stop checkpointed everything: nothing to replay
    assert rec["replayed_records"] == 0 and rec["sessions"] == 1
    assert "gs" in n2.cm._detached
    await n2.stop()


# -- expiry / lifecycle edges --------------------------------------------

async def test_session_expired_while_down_not_resurrected(tmp_path):
    n = mknode(tmp_path)
    await n.start()
    s = durable_session(n, "gone", expiry=0.05)
    s.expiry_interval = 0.05
    s.subscribe("e/+", SubOpts(qos=1))
    s.connected = False
    n.cm._detached["gone"] = (s, 0, 0.05)
    n.durability.session_detached(s)
    n.durability.on_batch()
    await crash(n)
    await asyncio.sleep(0.1)
    n2 = mknode(tmp_path)
    await n2.start()
    assert "gone" not in n2.cm._detached
    assert n2.durability.last_recovery["sessions"] == 0
    # its route refs pruned with it
    assert n2.router.route_refs("e/+", n2.broker.node) == 0
    await n2.stop()


async def test_session_close_is_durable(tmp_path):
    n = mknode(tmp_path)
    await n.start()
    s = durable_session(n, "bye")
    s.subscribe("b/+", SubOpts(qos=1))
    n.durability.on_batch()
    n.cm._detached["bye"] = (s, 0, 300.0)
    n.cm.discard_session("bye")  # clean-start discard journals close
    n.durability.on_batch()
    await crash(n)
    n2 = mknode(tmp_path)
    await n2.start()
    assert "bye" not in n2.cm._detached
    assert n2.router.route_refs("b/+", n2.broker.node) == 0
    await n2.stop()


async def test_checkpoint_truncates_journal_and_bounds_replay(
        tmp_path):
    n = mknode(tmp_path)
    await n.start()
    s = durable_session(n, "c")
    for i in range(8):
        s.subscribe(f"t/{i}", SubOpts(qos=1))
    n.durability.on_batch()
    gen0 = n.durability.gen
    out = n.durability.checkpoint_now()
    assert out["generation"] == gen0 + 1
    d = n.durability.cfg.dir
    journals = [f for f in os.listdir(d) if f.startswith("journal-")]
    assert len(journals) == 1  # superseded segments truncated
    m = checkpoint.read_manifest(d)
    assert m["generation"] == out["generation"]
    assert os.path.exists(os.path.join(d, m["router"]))
    assert os.path.exists(os.path.join(d, m["state"]))
    n.cm._detached["c"] = (s, 0, 300.0)
    want = state_model(n)
    del n.cm._detached["c"]
    await crash(n)
    n2 = mknode(tmp_path)
    await n2.start()
    assert n2.durability.last_recovery["replayed_records"] == 0
    assert state_model(n2) == want
    await n2.stop()


async def test_wal_write_failed_alarm_raises_and_clears(tmp_path):
    n = mknode(tmp_path)
    await n.start()
    s = durable_session(n, "a1")
    with faults.injected("wal.fsync", times=1):
        s.subscribe("x/+", SubOpts(qos=1))
        n.durability.on_batch()
    n.durability.drain_events(n.alarms)
    assert any(a.name == "wal_write_failed"
               for a in n.alarms.get_alarms("activated"))
    n.durability.wal._retry_at = 0.0
    n.durability.on_batch()  # recovery flush
    n.durability.drain_events(n.alarms)
    assert not any(a.name == "wal_write_failed"
                   for a in n.alarms.get_alarms("activated"))
    await n.stop()


# -- config / ctl surfaces ------------------------------------------------

def test_config_durability_section():
    from emqx_tpu.config import ConfigError, parse_config
    cfg = parse_config({"durability": {
        "enabled": True, "dir": "data/d", "fsync": False,
        "flush_interval_ms": 20, "checkpoint_interval_s": 60,
        "checkpoint_min_records": 1000}})
    assert cfg.durability.enabled and cfg.durability.dir == "data/d"
    assert cfg.durability.flush_interval_ms == 20.0
    with pytest.raises(ConfigError):
        parse_config({"durability": {"enabeld": True}})
    with pytest.raises(ConfigError):
        parse_config({"durability": {"enabled": "yes"}})
    with pytest.raises(ConfigError):
        parse_config({"durability": {"flush_interval_ms": 0}})
    with pytest.raises(ConfigError):
        parse_config({"durability": {"dir": 7}})


async def test_ctl_durability_command(tmp_path):
    import json
    n = mknode(tmp_path)
    await n.start()
    s = durable_session(n, "c")
    s.subscribe("q/+", SubOpts(qos=1))
    n.durability.on_batch()
    out = json.loads(n.ctl.run(["durability"]))
    assert out["enabled"] and out["generation"] >= 1
    assert out["journal"]["records"] >= 1
    assert out["last_recovery"]["generation"] >= 0
    out2 = json.loads(n.ctl.run(["durability", "checkpoint"]))
    assert out2["generation"] == out["generation"] + 1
    off = Node(boot_listeners=False)
    assert "not enabled" in off.ctl.run(["durability"])
    await n.stop()


async def test_stats_gauges_and_metric_fold(tmp_path):
    n = mknode(tmp_path)
    await n.start()
    s = durable_session(n, "c")
    s.subscribe("s/+", SubOpts(qos=1))
    n.durability.on_batch()
    n.stats.tick()
    assert n.metrics.val("wal.appends") >= 2  # state + sub + route
    assert n.metrics.val("checkpoint.saves") >= 1
    assert n.metrics.val("wal.group.commits") >= 1
    allstats = n.stats.all()
    assert allstats["journal.records"] >= 1
    assert "checkpoint.age_s" in allstats
    assert allstats["durability.generation"] >= 1
    await n.stop()


# -- sharded WAL: full-node round trips (docs/DURABILITY.md) --------------


async def test_sharded_crash_recovery_exact_and_idempotent(tmp_path):
    """The full crash round-trip with 4 journal shards: routes,
    retained (incl. a tombstone), and persistent sessions recover
    byte-exactly, and a second recovery with no new ops is a no-op."""
    n = mknode(tmp_path, durability=dcfg(tmp_path, wal_shards=4))
    await n.start()
    assert n.durability.wal.n == 4
    s = durable_session(n, "sh1")
    for i in range(12):
        s.subscribe(f"sh/{i}/+", SubOpts(qos=1))
    n.broker.publish(Message(topic="sh/1/r", payload=b"keep", qos=1,
                             flags={"retain": True}))
    n.broker.publish(Message(topic="sh/2/r", payload=b"x",
                             flags={"retain": True}))
    n.broker.publish(Message(topic="sh/2/r", payload=b"",
                             flags={"retain": True}))  # tombstone
    n.durability.on_batch()
    # records actually spread over several shard files
    d = n.durability.cfg.dir
    shard_files = [f for f in os.listdir(d)
                   if f.startswith("journal-") and f.count("-") == 2]
    assert len(shard_files) == 4
    n.cm._detached["sh1"] = (s, 0, 300.0)
    want = state_model(n)
    del n.cm._detached["sh1"]
    await crash(n)
    models = []
    for _ in range(2):
        n2 = mknode(tmp_path, durability=dcfg(tmp_path, wal_shards=4))
        await n2.start()
        models.append(state_model(n2))
        await crash(n2)
    assert models[0] == want and models[1] == want


async def test_sharded_torn_tail_loses_only_that_shard(tmp_path):
    """A torn tail (crash mid-append) in ONE shard truncates that
    shard's unsynced records; sibling shards' records from the same
    batch survive — per-shard kill semantics. Retained topics carry
    the probe (they have no cross-record coupling; route loss would
    also legitimately cascade through session-consistency pruning)."""
    from emqx_tpu.durability import journal_key
    from emqx_tpu.wal import shard_of

    n = mknode(tmp_path, durability=dcfg(tmp_path, wal_shards=2))
    await n.start()
    n.broker.publish(Message(topic="base/r", payload=b"p1",
                             flags={"retain": True}))
    n.durability.on_batch()
    # two phase-2 retained topics whose journal keys hash apart
    t_a = t_b = None
    i = 0
    while t_a is None or t_b is None:
        t = f"t2/{i}"
        idx = shard_of(journal_key(("retain", t, None, 0.0)), 2)
        if idx == 0 and t_a is None:
            t_a = t
        elif idx == 1 and t_b is None:
            t_b = t
        i += 1
    n.broker.publish(Message(topic=t_a, payload=b"a",
                             flags={"retain": True}))
    n.broker.publish(Message(topic=t_b, payload=b"b",
                             flags={"retain": True}))
    # the flush short-writes ONE frame: exactly one shard tears and
    # re-buffers its whole batch; the sibling's batch commits
    with faults.injected("wal.append", times=1):
        n.durability.on_batch()
    await crash(n)
    n2 = mknode(tmp_path, durability=dcfg(tmp_path, wal_shards=2))
    await n2.start()
    rec = n2.durability.last_recovery
    assert rec["torn_journals"] == 1
    ret = n2.modules._loaded.get("retainer")
    assert bytes(ret._store["base/r"].payload) == b"p1"
    survived = [t for t in (t_a, t_b) if t in ret._store]
    # one shard tore, the other committed — sharded mode must not
    # lose the whole batch to one torn shard
    assert len(survived) == 1, survived
    await n2.stop()


def test_replay_order_insensitive_across_shard_interleavings(
        tmp_path):
    """Property: per-key shard affinity + absolute refcounts +
    full-state sessions + LWW retained make ANY merge of per-shard-
    ordered streams converge to the same state (docs/DURABILITY.md
    "Merge rule")."""
    import random as _random

    from emqx_tpu.durability import journal_key
    from emqx_tpu.wal import shard_of

    rng = _random.Random(42)
    ops = []
    refs = {}
    for i in range(300):
        kind = rng.choice(["route", "route", "retain", "sess"])
        if kind == "route":
            flt = f"p/{rng.randrange(12)}/+"
            dest = rng.choice(["n1", ("g", "n1")])
            key = (flt, dest)
            refs[key] = max(0, refs.get(key, 0)
                            + rng.choice([1, 1, -1]))
            ops.append(("route", flt, dest, refs[key]))
        elif kind == "retain":
            t = f"r/{rng.randrange(8)}"
            if rng.random() < 0.25:
                ops.append(("retain", t, None, float(i)))
            else:
                ops.append(("retain", t,
                            Message(topic=t, payload=bytes([i % 251])),
                            float(i)))
        else:
            cid = f"c{rng.randrange(6)}"
            ops.append(("sess.state", cid, None,
                        {"subscriptions": {}, "seq": i}))
    for shards in (1, 2, 4, 8):
        # split into per-shard streams by journal key…
        streams = [[] for _ in range(shards)]
        for op in ops:
            streams[shard_of(journal_key(op), shards)].append(op)
        outcomes = set()
        for trial in range(6):
            # …and re-merge in a random interleaving that preserves
            # only per-shard order (what recovery's file-order replay
            # and any crash-rotation split can produce)
            mrng = _random.Random(trial)
            cursors = [0] * shards
            sessions, retained, tombs = {}, {}, {}
            route_state = {}
            live = [s for s in range(shards) if streams[s]]
            while live:
                s = mrng.choice(live)
                op = streams[s][cursors[s]]
                cursors[s] += 1
                if cursors[s] >= len(streams[s]):
                    live.remove(s)
                if op[0] == "route":
                    route_state[(op[1], op[2])] = op[3]
                elif op[0] == "retain":
                    if op[2] is None:
                        retained.pop(op[1], None)
                        tombs[op[1]] = max(tombs.get(op[1], 0.0),
                                           op[3])
                    else:
                        retained[op[1]] = op[2]
                else:
                    sessions[op[1]] = op[3]["seq"]
            outcomes.add(repr((
                sorted(route_state.items(), key=repr),
                sorted((t, bytes(m.payload)) for t, m
                       in retained.items()),
                sorted(tombs.items()), sorted(sessions.items()))))
        assert len(outcomes) == 1, \
            f"shards={shards}: merge order changed the outcome"


# -- incremental checkpoints (docs/DURABILITY.md) -------------------------


async def test_incremental_checkpoint_tracks_churn_not_table(
        tmp_path):
    """A delta generation carries only the keys touched since the
    last generation — the structural form of the 'cost tracks churn,
    not table size' contract."""
    n = mknode(tmp_path, durability=dcfg(tmp_path,
                                         checkpoint_full_every=8))
    await n.start()
    s = durable_session(n, "big")
    for i in range(200):
        s.subscribe(f"tbl/{i}", SubOpts(qos=1))
    n.durability.on_batch()
    out_full = n.durability.checkpoint_now(full=True)
    assert out_full["kind"] == "full"
    # small churn against the big table
    for i in range(5):
        s.subscribe(f"churn/{i}", SubOpts(qos=1))
    n.broker.publish(Message(topic="churn/r", payload=b"v",
                             flags={"retain": True}))
    n.durability.on_batch()
    out = n.durability.checkpoint_now()
    assert out["kind"] == "delta"
    # the delta names only the churned keys: 5 routes + 1 retained +
    # 1 dirty session state — nowhere near the 200-route table
    assert out["records"] <= 12, out
    d = n.durability.cfg.dir
    blob = checkpoint.load_state(
        os.path.join(d, f"delta-{out['generation']}.bin"))
    assert blob["kind"] == "delta"
    kinds = [r[0] for r in blob["records"]]
    assert kinds.count("route") == 5
    assert kinds.count("retain") == 1
    # recovery from base + delta + journal is exact
    n.cm._detached["big"] = (s, 0, 300.0)
    want = state_model(n)
    del n.cm._detached["big"]
    await crash(n)
    n2 = mknode(tmp_path, durability=dcfg(tmp_path,
                                          checkpoint_full_every=8))
    await n2.start()
    assert state_model(n2) == want
    assert n2.durability.last_recovery.get("delta_records", 0) >= 6
    await n2.stop()


async def test_incremental_chain_rebases_to_full(tmp_path):
    """checkpoint_full_every bounds the chain: the Nth generation is
    a full rebase and the delta files are cleaned up."""
    n = mknode(tmp_path, durability=dcfg(tmp_path,
                                         checkpoint_full_every=3))
    await n.start()
    s = durable_session(n, "c")
    gens = []
    for i in range(6):
        s.subscribe(f"g/{i}", SubOpts(qos=1))
        n.durability.on_batch()
        gens.append(n.durability.checkpoint_now())
    kinds = [g["kind"] for g in gens]
    # recovery baseline was full; chain: delta, delta, FULL, delta…
    assert kinds == ["delta", "delta", "full", "delta", "delta",
                     "full"]
    d = n.durability.cfg.dir
    leftover = [f for f in os.listdir(d) if f.startswith("delta-")]
    assert leftover == []  # last gen was full: chain cleaned
    n.cm._detached["c"] = (s, 0, 300.0)
    want = state_model(n)
    del n.cm._detached["c"]
    await crash(n)
    n2 = mknode(tmp_path, durability=dcfg(tmp_path,
                                          checkpoint_full_every=3))
    await n2.start()
    assert state_model(n2) == want
    await n2.stop()


async def test_crash_during_incremental_checkpoint(tmp_path):
    """checkpoint.rename during a DELTA generation: the previous
    manifest stays authoritative, the rotated journal still holds
    every record, the swapped dirty keys re-merge — recovery AND the
    next delta are both exact."""
    n = mknode(tmp_path, durability=dcfg(tmp_path,
                                         checkpoint_full_every=8))
    await n.start()
    s = durable_session(n, "mc")
    s.subscribe("a/1", SubOpts(qos=1))
    n.durability.on_batch()
    n.durability.checkpoint_now(full=True)
    s.subscribe("a/2", SubOpts(qos=1))
    n.durability.on_batch()
    with faults.injected("checkpoint.rename", times=1):
        out = n.durability.checkpoint_now()
    assert "error" in out
    assert n.durability.counters["checkpoint.errors"] == 1
    # the dirty keys merged back: the NEXT delta still carries a/2
    out2 = n.durability.checkpoint_now()
    assert out2["kind"] == "delta"
    blob = checkpoint.load_state(os.path.join(
        n.durability.cfg.dir, f"delta-{out2['generation']}.bin"))
    assert any(r[0] == "route" and r[1] == "a/2"
               for r in blob["records"])
    n.cm._detached["mc"] = (s, 0, 300.0)
    want = state_model(n)
    del n.cm._detached["mc"]
    await crash(n)
    n2 = mknode(tmp_path, durability=dcfg(tmp_path))
    await n2.start()
    assert state_model(n2) == want
    await n2.stop()


async def test_clean_shutdown_checkpoint_is_full(tmp_path):
    """Graceful stop always rebases: the final manifest is a full
    generation with no delta chain (failback never walks a chain)."""
    n = mknode(tmp_path, durability=dcfg(tmp_path,
                                         checkpoint_full_every=8))
    await n.start()
    s = durable_session(n, "fs")
    s.subscribe("f/+", SubOpts(qos=1))
    n.durability.on_batch()
    n.durability.checkpoint_now()  # a delta in the chain
    await n.stop()
    m = checkpoint.read_manifest(str(tmp_path / "dur"))
    assert m["clean_shutdown"] and m["deltas"] == []
    assert m["base_generation"] == m["generation"]


def test_config_new_durability_knobs():
    from emqx_tpu.config import ConfigError, parse_config
    cfg = parse_config({"durability": {
        "enabled": True, "wal_shards": 4,
        "group_commit_window_ms": 2.5, "checkpoint_full_every": 4,
        "standby": "peer@host", "repl_ack_timeout_s": 2.0,
        "repl_lag_alarm_records": 500,
        "repl_lag_clear_records": 50}})
    assert cfg.durability.wal_shards == 4
    assert cfg.durability.group_commit_window_ms == 2.5
    assert cfg.durability.standby == "peer@host"
    for bad in ({"wal_shards": -1}, {"checkpoint_full_every": 0},
                {"group_commit_window_ms": -1},
                {"repl_ack_timeout_s": 0},
                {"repl_lag_alarm_records": 10,
                 "repl_lag_clear_records": 100},
                {"standby": 7}, {"wal_shards": True}):
        with pytest.raises(ConfigError):
            parse_config({"durability": dict({"enabled": True},
                                             **bad)})


async def test_pre_arm_buffer_drops_are_counted(tmp_path):
    """Satellite: records shed by the pre-recovery bounded buffer
    fold into wal.degraded.dropped instead of vanishing."""
    from emqx_tpu.durability import DurabilityManager

    n = Node(boot_listeners=False, load_default_modules=True)
    cfg = dcfg(tmp_path, max_buffer_records=3)
    dur = DurabilityManager(n, cfg)
    n.durability = dur
    for i in range(8):
        dur._append(("sess.close", f"c{i}"))
    assert len(dur._pending_ops) == 3
    assert dur._pending_dropped == 5
    dur.recover()  # arms the journal; drained buffer is bounded
    dur.fold_metrics(n.metrics)
    assert n.metrics.val("wal.degraded.dropped") == 5
    dur.wal.close()
