"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's single-node CT strategy (SURVEY §4): the full
match/dispatch logic runs on one host; multi-chip behaviour is
exercised on a virtual device mesh (xla_force_host_platform_device_count)
exactly as the driver's dryrun does.

Env vars must be set before jax initializes a backend; this
environment also registers a TPU ("axon") PJRT plugin whose
sitecustomize forces jax_platforms, so we additionally override via
jax.config (which wins over the env var).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import asyncio  # noqa: E402
import inspect  # noqa: E402

import jax  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _zone_isolation():
    """The zone registry is process-global (the reference's ETS
    snapshot); tests that register zones (config-file suite) must
    not leak them — a poisoned 'default' zone (tiny max_packet_size)
    breaks unrelated suites in run-order-dependent ways."""
    from emqx_tpu import zone
    saved = dict(zone._zones)
    yield
    zone._zones.clear()
    zone._zones.update(saved)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tests the tier-1 filter (-m 'not slow') "
        "skips; the full ci.sh pytest run includes them")


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (no pytest-asyncio in
    this image)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {name: pyfuncitem.funcargs[name]
                  for name in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(fn(**kwargs))
        return True
    return None


if jax.config.jax_platforms != "cpu" or len(jax.devices()) < 8:
    from jax.extend.backend import clear_backends

    clear_backends()
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)

