"""PROXY protocol v1/v2 listener support (reference: esockd's
proxy_protocol listener option, etc/emqx.conf
listener.tcp.*.proxy_protocol) — a fronting load balancer prepends
the real client address; ACLs/bans/flapping/logs must see it."""

import asyncio
import struct

import pytest

from emqx_tpu.connection import read_proxy_header
from emqx_tpu.node import Node
from tests.mqtt_client import TestClient


def _feed(data: bytes) -> asyncio.StreamReader:
    r = asyncio.StreamReader()
    r.feed_data(data)
    r.feed_eof()
    return r


async def test_v1_header_parsed():
    r = _feed(b"PROXY TCP4 203.0.113.7 10.0.0.1 54321 1883\r\nrest")
    assert await read_proxy_header(r) == ("203.0.113.7", 54321)
    assert await r.read() == b"rest"  # header fully consumed, no more


async def test_v1_unknown_keeps_socket_peer():
    r = _feed(b"PROXY UNKNOWN\r\nX")
    assert await read_proxy_header(r) is None
    assert await r.read() == b"X"


async def test_v1_garbage_rejected():
    with pytest.raises(ValueError):
        await read_proxy_header(_feed(b"PROXY TCP4 nonsense\r\n"))
    with pytest.raises(Exception):
        await read_proxy_header(_feed(b"GET / HTTP/1.1\r\n\r\n"))


def _ppv2(fam: int, body: bytes, cmd: int = 1) -> bytes:
    return (b"\r\n\r\n\x00\r\nQUIT\n"
            + struct.pack("!BBH", 0x20 | cmd, fam << 4 | 1, len(body))
            + body)


async def test_v2_inet_parsed():
    body = (bytes([203, 0, 113, 9]) + bytes([10, 0, 0, 1])
            + struct.pack("!HH", 61000, 1883))
    r = _feed(_ppv2(1, body) + b"tail")
    assert await read_proxy_header(r) == ("203.0.113.9", 61000)
    assert await r.read() == b"tail"


async def test_v2_inet6_parsed():
    src = bytes(15) + bytes([1])      # ::1
    dst = bytes(15) + bytes([2])
    body = src + dst + struct.pack("!HH", 7000, 1883)
    r = _feed(_ppv2(2, body))
    assert await read_proxy_header(r) == ("::1", 7000)


async def test_v2_local_keeps_socket_peer():
    r = _feed(_ppv2(0, b"", cmd=0) + b"t")
    assert await read_proxy_header(r) is None
    assert await r.read() == b"t"


async def test_listener_end_to_end_proxy_peername():
    """A client behind the 'LB' (header prepended before CONNECT):
    the channel's peername is the header's address, visible through
    the connection-info surface; a bare client on the same listener
    is rejected (no header)."""
    n = Node(boot_listeners=False)
    lst = n.add_listener(port=0, proxy_protocol=True,
                         proxy_protocol_timeout=1.0)
    await n.start()
    try:
        port = lst.port

        cli = TestClient("pp1", version=4)
        reader, writer = await asyncio.open_connection("127.0.0.1",
                                                       port)
        # the 'LB' prepends the PROXY line before MQTT CONNECT flows
        writer.write(b"PROXY TCP4 198.51.100.23 127.0.0.1 4242 1883\r\n")
        await writer.drain()
        await cli.connect_over(reader, writer)
        chan = n.cm.lookup_channel("pp1")
        assert chan is not None, "channel registered"
        assert chan.peername == ("198.51.100.23", 4242), chan.peername
        await cli.disconnect()

        # no header -> closed within the timeout
        bare = TestClient("pp2", version=4)
        with pytest.raises(Exception):
            await bare.connect(port=port, timeout=3)
    finally:
        await n.stop()


async def test_v2_reserved_command_and_truncation_rejected():
    body = bytes([203, 0, 113, 9, 10, 0, 0, 1]) + struct.pack(
        "!HH", 61000, 1883)
    with pytest.raises(ValueError):
        await read_proxy_header(_feed(_ppv2(1, body, cmd=2)))
    with pytest.raises(ValueError):  # truncated INET block
        await read_proxy_header(_feed(_ppv2(1, body[:8])))


async def test_v1_family_mismatch_rejected():
    with pytest.raises(ValueError):
        await read_proxy_header(
            _feed(b"PROXY TCP4 ::1 ::1 1 2\r\n"))


def test_config_rejects_bad_proxy_settings(tmp_path):
    from emqx_tpu.config import ConfigError, load_config

    p = tmp_path / "c.toml"
    p.write_text('[[listeners]]\ntype = "ws"\nport = 1\n'
                 'proxy_protocol = true\n')
    with pytest.raises(ConfigError):
        load_config(str(p))
    p.write_text('[[listeners]]\ntype = "tcp"\nport = 1\n'
                 'proxy_protocol = true\nproxy_protocol_timeout = 0\n')
    with pytest.raises(ConfigError):
        load_config(str(p))


async def test_fuzz_parser_never_hangs_or_crashes():
    """Random garbage (including truncated PP2 sigs and PROXY-
    prefixed noise) must terminate in ValueError / IncompleteReadError
    / a peername tuple — no unexpected exception type. (The wait_for
    is a belt for await-based stalls; a non-yielding loop would hang
    the suite itself, which CI treats as failure.)"""
    import random

    rng = random.Random(5)
    cases = []
    for _ in range(300):
        n = rng.randrange(0, 40)
        cases.append(bytes(rng.randrange(256) for _ in range(n)))
    for i in range(100):
        cases.append(b"PROXY " + bytes(
            rng.randrange(256) for _ in range(rng.randrange(0, 120))))
        cases.append(b"\r\n\r\n\x00\r\nQUIT\n" + bytes(
            rng.randrange(256) for _ in range(rng.randrange(0, 60))))
    for data in cases:
        r = _feed(data)
        try:
            res = await asyncio.wait_for(read_proxy_header(r), 2.0)
            assert res is None or (isinstance(res, tuple)
                                   and len(res) == 2)
        except (ValueError, asyncio.IncompleteReadError):
            pass
