"""Listener accept controls (reference: esockd options in
etc/emqx.conf): ordered allow/deny access rules
(listener.*.access.N), accept-rate limiting (max_conn_rate), and
TLS-cert-derived usernames (peer_cert_as_username)."""

import asyncio

import pytest

from emqx_tpu.connection import check_access, parse_access_rules
from emqx_tpu.node import Node
from tests.mqtt_client import TestClient


def test_access_rule_parsing_and_matching():
    rules = parse_access_rules(
        ["deny 10.0.0.0/8", "allow 127.0.0.1", "allow all"])
    assert check_access(rules, "10.1.2.3") is False
    assert check_access(rules, "127.0.0.1") is True
    assert check_access(rules, "203.0.113.5") is True
    # first match wins; no match denies
    only = parse_access_rules(["allow 192.0.2.0/24"])
    assert check_access(only, "192.0.2.9") is True
    assert check_access(only, "198.51.100.1") is False
    with pytest.raises(ValueError):
        parse_access_rules(["permit all"])
    with pytest.raises(ValueError):
        parse_access_rules(["allow 300.1.1.1"])


async def test_listener_access_denies_socket_peer():
    n = Node(boot_listeners=False)
    lst = n.add_listener(port=0,
                         access_rules=["deny 127.0.0.1", "allow all"])
    await n.start()
    try:
        cli = TestClient("denied")
        with pytest.raises(Exception):
            await cli.connect(port=lst.port, timeout=3)
    finally:
        await n.stop()

    n2 = Node(boot_listeners=False)
    lst2 = n2.add_listener(port=0, access_rules=["allow 127.0.0.1"])
    await n2.start()
    try:
        cli = TestClient("allowed")
        ack = await cli.connect(port=lst2.port)
        assert ack.reason_code == 0
        await cli.disconnect()
    finally:
        await n2.stop()


async def test_max_conn_rate_limits_accept_burst():
    n = Node(boot_listeners=False)
    lst = n.add_listener(port=0, max_conn_rate=2)
    await n.start()
    try:
        async def attempt(i):
            cli = TestClient(f"rate{i}")
            try:
                await cli.connect(port=lst.port, timeout=2)
                return cli
            except Exception:
                return None

        # a simultaneous burst: bucket burst == rate == 2, refill is
        # negligible within the burst window
        results = await asyncio.gather(*[attempt(i) for i in range(8)])
        ok = [c for c in results if c is not None]
        assert 1 <= len(ok) <= 4, len(ok)
        assert len(results) - len(ok) >= 4, len(ok)
        for c in ok:
            await c.disconnect()
    finally:
        await n.stop()


async def test_peer_cert_as_username(tmp_path):
    """Two-way TLS with peer_cert_as_username = cn: the CONNECT
    carries no username, yet the channel's username (and ACL/ban
    identity) is the client cert's CN."""
    from emqx_tpu.tls import TlsOptions, make_client_context

    # optional cryptography dep: only this cert-backed test skips
    from tests.certs import generate_cert_chain

    certs = generate_cert_chain(str(tmp_path))
    n = Node(boot_listeners=False)
    lst = n.add_tls_listener(
        port=0,
        tls_options=TlsOptions(certfile=certs["cert"],
                               keyfile=certs["key"],
                               cacertfile=certs["cacert"],
                               verify="verify_peer",
                               fail_if_no_peer_cert=True),
        peer_cert_as_username="cn")
    await n.start()
    try:
        ctx = make_client_context(
            cacertfile=certs["cacert"],
            certfile=certs["client_cert"], keyfile=certs["client_key"])
        cli = TestClient("certuser")
        ack = await cli.connect(host="127.0.0.1", port=lst.port,
                                ssl=ctx)
        assert ack.reason_code == 0
        chan = n.cm.lookup_channel("certuser")
        assert chan is not None
        assert chan.username == "test-client", chan.username
        assert chan.clientinfo["username"] == "test-client"
        await cli.disconnect()
    finally:
        await n.stop()


def test_config_validates_listener_access(tmp_path):
    from emqx_tpu.config import ConfigError, load_config

    p = tmp_path / "c.toml"
    p.write_text('[[listeners]]\ntype = "tcp"\nport = 1\n'
                 'access = ["frobnicate all"]\n')
    with pytest.raises(ConfigError):
        load_config(str(p))
    p.write_text('[[listeners]]\ntype = "ws"\nport = 1\n'
                 'access = ["allow all"]\n')
    with pytest.raises(ConfigError):
        load_config(str(p))
    p.write_text('[[listeners]]\ntype = "tcp"\nport = 1\n'
                 'peer_cert_as_username = "cn"\n')
    with pytest.raises(ConfigError):
        load_config(str(p))
    p.write_text('[[listeners]]\ntype = "tcp"\nport = 1\n'
                 'access = ["deny 10.0.0.0/8", "allow all"]\n'
                 'max_conn_rate = 100\n')
    cfg = load_config(str(p))
    assert cfg.listeners[0].access == ["deny 10.0.0.0/8", "allow all"]
    assert cfg.listeners[0].max_conn_rate == 100


def test_access_v4_mapped_v6_unmapped():
    rules = parse_access_rules(["deny 10.0.0.0/8", "allow all"])
    assert check_access(rules, "::ffff:10.1.2.3") is False
    assert check_access(rules, "::ffff:203.0.113.5") is True


def test_config_rejects_unenforceable_combos(tmp_path):
    from emqx_tpu.config import ConfigError, load_config

    p = tmp_path / "c.toml"
    p.write_text('[[listeners]]\ntype = "ws"\nport = 1\n'
                 'max_conn_rate = 5\n')
    with pytest.raises(ConfigError):
        load_config(str(p))
    # peer_cert_as_username without verify_peer: certless clients
    # would keep self-asserted usernames
    cert = tmp_path / "c.pem"; cert.write_text("x")
    p.write_text(f'[[listeners]]\ntype = "ssl"\nport = 1\n'
                 f'certfile = "{cert}"\nkeyfile = "{cert}"\n'
                 f'peer_cert_as_username = "cn"\n')
    with pytest.raises(ConfigError):
        load_config(str(p))
