"""TLS / WSS listener suites.

Mirrors the reference's SSL client coverage
(test/emqx_client_SUITE.erl:78-86: one-way and two-way cert connects
over esockd mqtt:ssl) plus a WSS round-trip; certificates are
generated per-session by :mod:`tests.certs`.
"""

import asyncio
import ssl

import pytest

from emqx_tpu.mqtt.packet import Connack, Publish
from emqx_tpu.node import Node
from emqx_tpu.tls import TlsOptions, make_client_context, make_server_context

from certs import generate_cert_chain
from mqtt_client import TestClient


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    return generate_cert_chain(str(tmp_path_factory.mktemp("certs")))


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


async def _tls_node(certs, **tls_kw):
    n = Node(boot_listeners=False)
    n.add_tls_listener(port=0, tls_options=TlsOptions(
        certfile=certs["cert"], keyfile=certs["key"],
        cacertfile=certs["cacert"], **tls_kw))
    await n.start()
    return n, n.listeners[0].port


def test_tls_connect_publish_roundtrip(certs):
    """One-way TLS: server cert verified by the client CA; full
    subscribe/publish/deliver round-trip over the encrypted socket."""
    async def main():
        n, port = await _tls_node(certs)
        ctx = make_client_context(cacertfile=certs["cacert"])
        try:
            sub = TestClient("tls-sub")
            pub = TestClient("tls-pub")
            ack = await sub.connect(host="127.0.0.1", port=port, ssl=ctx)
            assert isinstance(ack, Connack) and ack.reason_code == 0
            await pub.connect(host="127.0.0.1", port=port, ssl=ctx)
            await sub.subscribe("secure/t", qos=1)
            await pub.publish("secure/t", b"over-tls", qos=1)
            msg = await asyncio.wait_for(sub.inbox.get(), 5.0)
            assert isinstance(msg, Publish)
            assert msg.payload == b"over-tls"
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await n.stop()
    run(main())


def test_tls_two_way_cert(certs):
    """verify_peer + fail_if_no_peer_cert: a client presenting the CA-
    signed cert connects; peercert lands in the channel; a client
    without a cert is rejected during the handshake."""
    async def main():
        n, port = await _tls_node(
            certs, verify="verify_peer", fail_if_no_peer_cert=True)
        try:
            good = TestClient("mutual-ok")
            ctx = make_client_context(
                cacertfile=certs["cacert"],
                certfile=certs["client_cert"], keyfile=certs["client_key"])
            ack = await good.connect(host="127.0.0.1", port=port, ssl=ctx)
            assert ack.reason_code == 0
            [chan] = [c.channel for c in n.listeners[0]._conns]
            assert chan.peercert, "peer certificate not captured"
            subject = dict(
                x for rdn in chan.peercert["subject"] for x in rdn)
            assert subject["commonName"] == "test-client"
            await good.disconnect()

            # pin TLS1.2 so the missing-cert alert lands inside the
            # handshake (TLS1.3 defers it past the client Finished,
            # surfacing as a post-handshake connection drop instead)
            bare = make_client_context(cacertfile=certs["cacert"])
            bare.maximum_version = ssl.TLSVersion.TLSv1_2
            with pytest.raises((ssl.SSLError, ConnectionError)):
                await TestClient("mutual-no-cert").connect(
                    host="127.0.0.1", port=port, ssl=bare)
        finally:
            await n.stop()
    run(main())


def test_tls_rejects_untrusted_server(certs, tmp_path):
    """A client that trusts a different CA refuses the handshake —
    proves the listener really serves the configured chain."""
    other = generate_cert_chain(str(tmp_path))

    async def main():
        n, port = await _tls_node(certs)
        try:
            ctx = make_client_context(cacertfile=other["cacert"])
            with pytest.raises(ssl.SSLError):
                await TestClient("wrong-ca").connect(
                    host="127.0.0.1", port=port, ssl=ctx)
        finally:
            await n.stop()
    run(main())


def test_wss_roundtrip(certs):
    """WSS: MQTT over WebSocket over TLS (reference https:wss)."""
    from test_ws import WsTestClient

    async def main():
        n = Node(boot_listeners=False)
        n.add_wss_listener(port=0, tls_options=TlsOptions(
            certfile=certs["cert"], keyfile=certs["key"]))
        await n.start()
        port = n.listeners[0].port
        ctx = make_client_context(cacertfile=certs["cacert"])
        try:
            from emqx_tpu.mqtt.packet import Suback, Subscribe
            c = WsTestClient("wss-c1")
            ack = await c.connect(port, ssl=ctx)
            assert isinstance(ack, Connack) and ack.reason_code == 0
            await c.send_mqtt(Subscribe(
                packet_id=1, topic_filters=[("wss/t", {"qos": 0})]))
            sa = await asyncio.wait_for(c.acks.get(), 5.0)
            assert isinstance(sa, Suback)
            await c.send_mqtt(Publish(topic="wss/t", payload=b"wss-payload"))
            msg = await asyncio.wait_for(c.inbox.get(), 5.0)
            assert msg.payload == b"wss-payload"
            await c.close()
        finally:
            await n.stop()
    run(main())


def test_tls_options_context_shape(certs):
    """Context construction honors verify/fail_if_no_peer_cert and
    min-version knobs without a live socket."""
    ctx = make_server_context(TlsOptions(
        certfile=certs["cert"], keyfile=certs["key"],
        cacertfile=certs["cacert"], verify="verify_peer",
        fail_if_no_peer_cert=True, tls_version="tlsv1.3"))
    assert ctx.verify_mode == ssl.CERT_REQUIRED
    assert ctx.minimum_version == ssl.TLSVersion.TLSv1_3

    lax = make_server_context(TlsOptions(
        certfile=certs["cert"], keyfile=certs["key"],
        verify="verify_none"))
    assert lax.verify_mode == ssl.CERT_NONE


def test_psk_seam_wiring(certs):
    """PSK resolver is attached to the context on 3.13+; on older
    interpreters the context still builds and the host-side lookup
    seam answers through the hook chain (src/emqx_psk.erl:31)."""
    from emqx_tpu.hooks import Hooks
    from emqx_tpu.psk import PskAuth

    hooks = Hooks()
    psk = PskAuth(hooks, {"dev1": b"sekrit"})
    ctx = make_server_context(TlsOptions(
        certfile=certs["cert"], keyfile=certs["key"], psk=psk))
    assert isinstance(ctx, ssl.SSLContext)
    assert psk.lookup("dev1") == b"sekrit"
    assert psk.lookup("nobody") is None
