"""End-to-end message tracing (emqx_tpu/tracing.py): deterministic
sampling, span lifecycle across the publish seams, the disabled-mode
byte-identity pin, ring overflow accounting, slow-subscriber
ranking/expiry/alarm, trace-context continuity across loops and a
2-node cluster forward, Chrome trace-event export, the per-loop lag
gauges, and the observability satellites (tracer topic stamping,
profile-stop error handling, [tracing] config schema + reload
classification)."""

import asyncio
import json

import pytest

from emqx_tpu.alarm import AlarmManager
from emqx_tpu.broker import Broker
from emqx_tpu.config import ConfigError, parse_config
from emqx_tpu.metrics import Metrics
from emqx_tpu.monitors import SysMon
from emqx_tpu.node import Node
from emqx_tpu.router import MatcherConfig, Router
from emqx_tpu.tracer import Tracer
from emqx_tpu.tracing import (TRACE_HEADER, SlowSubs, Tracing,
                              TracingConfig)
from emqx_tpu.types import Message

from helpers import broker_node, node_port
from mqtt_client import TestClient


class Q:
    def __init__(self, client_id="c"):
        self.client_id = client_id
        self.inbox = []

    def deliver(self, topic, msg):
        self.inbox.append((topic, msg))


def _wire(broker: Broker, cfg: TracingConfig = None,
          **trc_kw) -> Tracing:
    """Manual Node-style wiring for standalone Broker tests."""
    trc = Tracing(cfg or TracingConfig(sample_rate=1.0), **trc_kw)
    broker.tracing = trc
    return trc


def _device_broker(**mk) -> Broker:
    mk.setdefault("device_min_filters", 0)
    return Broker(router=Router(MatcherConfig(**mk), node="node1"))


# -- deterministic sampling -----------------------------------------------


def test_sampling_is_deterministic_and_rate_shaped():
    t0 = Tracing(TracingConfig(sample_rate=0.5))
    t1 = Tracing(TracingConfig(sample_rate=0.5))
    mids = list(range(10_000))
    picks = [m for m in mids if t0.sampled(m)]
    # every instance (== every node of a cluster) picks the same set
    assert picks == [m for m in mids if t1.sampled(m)]
    assert 0.4 < len(picks) / len(mids) < 0.6
    # the rate endpoints are exact
    assert not any(Tracing(TracingConfig(sample_rate=0.0)).sampled(m)
                   for m in mids)
    assert all(Tracing(TracingConfig(sample_rate=1.0)).sampled(m)
               for m in mids)


def test_sample_rate_is_live_reloadable():
    trc = Tracing(TracingConfig(sample_rate=0.0))
    assert not trc.active and not trc.sampled(7)
    trc.config.sample_rate = 1.0  # what apply_reload does
    assert trc.active and trc.sampled(7)
    from emqx_tpu.reload import classification

    table = classification()["tracing"]
    assert table["sample_rate"] == "reloadable"
    assert table["slow_subs_threshold_ms"] == "reloadable"
    assert table["ring_size"] == "boot_only"
    assert table["enabled"] == "boot_only"


def test_stamp_is_idempotent_and_keeps_foreign_context():
    trc = Tracing(TracingConfig(sample_rate=1.0), node="here")
    msg = Message(topic="t")
    ctx = trc.stamp(msg)
    assert ctx is not None and ctx["tid"] == msg.id
    assert msg.headers[TRACE_HEADER] is ctx
    # a context that arrived with the message (cluster forward) wins
    assert trc.stamp(msg) is ctx
    foreign = {"tid": 99, "t0": 1.0, "node": "there"}
    msg2 = Message(topic="t", headers={TRACE_HEADER: foreign})
    assert trc.stamp(msg2) is foreign


# -- disabled mode: byte-identical dispatch, zero span allocations --------


def _run_workload(broker):
    subs = [Q(f"c{i}") for i in range(3)]
    broker.subscribe(subs[0], "w/+/x")
    broker.subscribe(subs[1], "w/1/x")
    broker.subscribe(subs[2], "w/#")
    out = []
    for _ in range(3):
        out.append(broker.publish_batch(
            [Message(topic="w/1/x"), Message(topic="w/2/x"),
             Message(topic="other")]))
    return out, [[t for t, _ in s.inbox] for s in subs]


def test_sample_rate_zero_is_byte_identical_and_allocates_nothing():
    b_off = _device_broker(match_cache_slots=64)
    trc = _wire(b_off, TracingConfig(sample_rate=0.0))
    b_ref = _device_broker(match_cache_slots=64)  # tracing = None
    got_off = _run_workload(b_off)
    got_ref = _run_workload(b_ref)
    assert got_off == got_ref  # results AND per-sub delivery streams
    # zero span allocations: no ring was ever registered, no batch
    # ever carried trace state, no message was ever stamped
    assert trc._rings == []
    assert trc.drain_tick() == 0 and trc.spans_total == 0
    pb = b_off.publish_begin([Message(topic="w/1/x")])
    assert pb.tbatch is None
    b_off.publish_fetch(pb)
    b_off.publish_finish(pb)


def test_sampled_mode_same_dispatch_results_as_reference():
    b_on = _device_broker(match_cache_slots=64)
    trc = _wire(b_on, TracingConfig(sample_rate=1.0))
    b_ref = _device_broker(match_cache_slots=64)
    assert _run_workload(b_on) == _run_workload(b_ref)
    assert trc.drain_tick() > 0  # and the spans actually recorded


# -- span lifecycle on the broker seams -----------------------------------


def test_host_path_records_the_batch_span_chain():
    b = Broker()  # default config: few filters -> host regime
    trc = _wire(b)
    s = Q()
    b.subscribe(s, "a/+")
    assert b.publish_batch([Message(topic="a/x"),
                            Message(topic="a/y")]) == [1, 1]
    trc.drain_tick()
    stages = [rec[1] for rec in trc._export]
    for stage in ("ingress", "match", "dispatch", "publish"):
        assert stages.count(stage) == 1, (stage, stages)
    # batch spans carry every sampled message's trace id
    tids_per = {rec[1]: rec[0] for rec in trc._export}
    assert len(tids_per["publish"]) == 2


def test_device_path_chunked_finish_closes_trace_batch_once():
    b = _device_broker(match_cache=False)
    trc = _wire(b)
    s = Q()
    b.subscribe(s, "t/+")
    msgs = [Message(topic=f"t/{i}") for i in range(8)]
    pb = b.publish_begin(msgs)
    assert pb.tbatch is not None
    b.publish_fetch(pb)
    for lo in range(0, len(pb.live), 3):
        b.publish_finish_chunk(pb, lo, min(lo + 3, len(pb.live)))
    pb.done = True
    assert pb.results == [1] * 8
    assert pb.tbatch is None  # closed exactly at the last chunk
    trc.drain_tick()
    stages = [rec[1] for rec in trc._export]
    assert stages.count("publish") == 1
    assert stages.count("dispatch") == 1
    assert stages.count("serialize") <= 1


def test_ring_overflow_drops_and_counts_instead_of_blocking():
    m = Metrics()
    b = Broker()
    trc = _wire(b, TracingConfig(sample_rate=1.0, ring_size=2),
                metrics=m)
    s = Q()
    b.subscribe(s, "r")
    for _ in range(5):  # 4 spans per batch >> ring_size 2
        b.publish_batch([Message(topic="r")])
    assert trc.drain_tick() == 2  # the ring never grew past cap
    assert trc.dropped_total > 0
    assert m.val("tracing.dropped") == trc.dropped_total
    assert m.val("tracing.spans") == 2


# -- slow subscribers -----------------------------------------------------


def test_slow_subs_ranking_ewma_and_expiry():
    cfg = TracingConfig(slow_subs_top=2, slow_subs_expiry_s=10.0)
    ss = SlowSubs(cfg)
    ss.fold("fast", 1.0, now_w=100.0)
    for lat in (800.0, 900.0):
        ss.fold("slow1", lat, now_w=100.0)
    ss.fold("slow2", 400.0, now_w=100.0)
    rows = ss.top()
    assert len(rows) == 2  # bounded by slow_subs_top
    assert rows[0][0] == "slow1" and rows[1][0] == "slow2"
    assert rows[0][2] == 900.0 and rows[0][3] == 2  # max, count
    # EWMA: the average moved toward the second sample
    assert 800.0 < rows[0][1] < 900.0
    # expiry: an idle clientid drops off the next tick
    ss.fold("slow2", 400.0, now_w=111.0)
    ss.tick(now_w=111.0)  # 100.0 + 10s < 111 -> fast/slow1 expire
    assert set(ss.clients) == {"slow2"}


def test_slow_subs_table_is_bounded_under_clientid_fanin():
    cfg = TracingConfig(slow_subs_top=10)
    ss = SlowSubs(cfg)
    for i in range(1000):
        ss.fold(f"c{i}", float(i), now_w=5.0)
    ss.tick(now_w=5.0)
    assert len(ss.clients) <= max(64, cfg.slow_subs_top * 8)
    # the worst averages survived the bound
    assert ss.top(1)[0][0] == "c999"


def test_slow_subs_sustained_breach_alarm_and_clear():
    alarms = AlarmManager(node="t@test")
    cfg = TracingConfig(slow_subs_threshold_ms=100.0,
                        slow_subs_alarm_ticks=2)
    ss = SlowSubs(cfg, alarms=alarms)
    ss.fold("laggard", 500.0, now_w=1.0)
    ss.tick(now_w=1.0)
    assert not alarms.get_alarms("activated")  # streak 1 < 2
    ss.fold("laggard", 500.0, now_w=2.0)
    ss.tick(now_w=2.0)
    active = alarms.get_alarms("activated")
    assert [a.name for a in active] == ["slow_subs"]
    assert active[0].details["clientid"] == "laggard"
    # recovery: the table empties (expiry) -> streak 0 -> deactivate
    ss.reset()
    ss.tick(now_w=3.0)
    assert not alarms.get_alarms("activated")
    assert [a.name for a in alarms.get_alarms("deactivated")] \
        == ["slow_subs"]


def test_drain_folds_flush_spans_into_slow_subs_and_stats():
    from emqx_tpu.stats import Stats

    m, stats = Metrics(), Stats()
    trc = Tracing(TracingConfig(sample_rate=1.0,
                                slow_subs_threshold_ms=0.0),
                  metrics=m)
    msg = Message(topic="t")
    ctx = trc.stamp(msg)
    trc.flush_mark(ctx, "c-slow")
    trc.drain_tick(stats)
    assert [r[0] for r in trc.slow.top()] == ["c-slow"]
    assert m.val("slow_subs.flushes") == 1
    assert m.val("slow_subs.breaches") == 1  # threshold 0: any flush
    assert stats.getstat("slow_subs.tracked") == 1
    assert stats.getstat("tracing.spans.pending") == 1


# -- Chrome trace-event export --------------------------------------------


def test_export_writes_valid_chrome_trace_json(tmp_path):
    b = Broker()
    trc = _wire(b)
    s = Q()
    b.subscribe(s, "e/+")
    b.publish_batch([Message(topic="e/1")])
    trc.drain_tick()
    path = str(tmp_path / "trace.json")
    n = trc.export(path)
    doc = json.load(open(path))
    assert set(doc) == {"traceEvents", "displayTimeUnit", "otherData"}
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    ms = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert n == len(xs) + len(ms)
    assert {e["name"] for e in xs} == {"ingress", "match", "dispatch",
                                       "publish"}
    for e in xs:
        assert e["ts"] >= 0 and e["dur"] >= 0  # µs, rebased
        assert e["args"]["trace"]
    # writer threads are named via metadata events
    assert {e["name"] for e in ms} == {"thread_name"}
    assert trc.reset() is None and trc._export == []


# -- satellites: tracer topic stamping, profile stop ----------------------


class _Pkt:
    def __init__(self, topic=None):
        self.topic = topic

    def __repr__(self):
        return f"PUBLISH({self.topic})"


def test_trace_packet_stamps_topic_when_packet_has_one():
    tr = Tracer()
    by_topic = tr.start_trace("topic", "tp/#")
    by_client = tr.start_trace("clientid", "c7")
    # a PUBLISH packet carries its topic -> the topic filter sees it
    tr.trace_packet("SEND", "c7", _Pkt(topic="tp/1"))
    assert len(by_topic) == 1 and len(by_client) == 1
    # a topic-less packet (CONNECT/PINGREQ) still hits clientid traces
    tr.trace_packet("RECV", "c7", "PINGREQ")
    assert len(by_topic) == 1 and len(by_client) == 2


class _Reg:
    def __init__(self, node=None):
        self.cmds = {}
        self.node = node

    def register_command(self, name, fn, usage=""):
        self.cmds[name] = fn


def test_profile_stop_failure_returns_text_not_traceback(monkeypatch):
    import jax

    from emqx_tpu import profiling

    class _N:
        tracing = Tracing(TracingConfig())

    reg = _Reg(node=_N())
    profiling.register_ctl(reg)
    # a stop whose underlying trace jax never started must come back
    # as operator text with the registry cleared, not a traceback
    profiling._active["dir"] = "/tmp/ghost"

    def _boom():
        raise RuntimeError("No profile session active")

    monkeypatch.setattr(jax.profiler, "stop_trace", _boom)
    out = reg.cmds["profile"](["stop"])
    assert "profile stop failed" in out
    assert profiling._active["dir"] is None
    assert reg.cmds["profile"](["stop"]) == "not tracing"


def test_profile_loops_subcommands_drive_the_sampler():
    from emqx_tpu import profiling

    class _N:
        tracing = Tracing(TracingConfig(profile_interval_ms=1.0))

    reg = _Reg(node=_N())
    profiling.register_ctl(reg)
    p = reg.cmds["profile"]
    assert p(["loops", "stop"]) == "loop profiler not running"
    assert "sampling every" in p(["loops", "start"])
    assert "already running" in p(["loops", "start"])
    import time as _t
    _t.sleep(0.05)
    assert "stopped" in p(["loops", "stop"])
    assert "loops: off" in p([])
    prof = _N.tracing.profiler
    assert prof.samples > 0
    # the sampler saw the main thread (this test's own frames)
    text = prof.collapsed()
    assert "MainThread;" in text


# -- per-loop lag gauges (monitors.SysMon) --------------------------------


def test_sysmon_bind_loops_sizes_and_probe_records_lag():
    class _LG:
        n = 3

    sm = SysMon()
    assert sm.loop_lags == [0.0]
    sm.bind_loops(_LG())
    assert sm.loop_lags == [0.0] * 3
    import time as _t
    sm._probe_loop(1, _t.perf_counter() - 0.25)
    assert 200.0 < sm.loop_lags[1] < 5000.0
    assert sm._probe_seq[1] == 1 and sm.loop_lags[2] == 0.0


# -- [tracing] config schema ----------------------------------------------


def test_config_tracing_section_parses():
    cfg = parse_config({"tracing": {
        "sample_rate": 0.25, "ring_size": 128, "export_keep": 500,
        "slow_subs_top": 5, "slow_subs_threshold_ms": 50,
        "profile_interval_ms": 5}})
    t = cfg.tracing
    assert t is not None and t.sample_rate == 0.25
    assert t.ring_size == 128 and t.export_keep == 500
    assert t.slow_subs_top == 5
    assert t.slow_subs_threshold_ms == 50.0  # int coerced to float
    assert parse_config({}).tracing is None  # defaults at Node


def test_config_tracing_rejects_typos_and_bad_values():
    with pytest.raises(ConfigError):
        parse_config({"tracing": {"sample_rte": 0.5}})
    with pytest.raises(ConfigError):
        parse_config({"tracing": {"sample_rate": 1.5}})
    with pytest.raises(ConfigError):
        parse_config({"tracing": {"sample_rate": True}})
    with pytest.raises(ConfigError):
        parse_config({"tracing": {"ring_size": 0}})
    with pytest.raises(ConfigError):
        parse_config({"tracing": {"slow_subs_alarm_ticks": 0}})
    with pytest.raises(ConfigError):
        parse_config({"tracing": {"profile_interval_ms": 0}})
    with pytest.raises(ConfigError):
        parse_config({"tracing": ["not", "a", "table"]})


# -- node integration: loops=2 continuity, ctl, $SYS ----------------------


async def test_trace_chain_is_continuous_across_two_loops():
    """The acceptance chain: a sampled publish through a loops=2 node
    yields one trace id whose spans cover ingress → match → dispatch
    → xloop → flush, with the flush attributed to the subscriber's
    clientid — and `ctl trace export` writes it as loadable JSON."""
    async with broker_node(
            loops=2, matcher=MatcherConfig(device_min_filters=0),
            tracing=TracingConfig(sample_rate=1.0)) as node:
        port = node_port(node)
        s1, s2, pub = (TestClient("ts1"), TestClient("ts2"),
                       TestClient("tpub"))
        for c in (s1, s2, pub):
            await c.connect(port=port)  # round-robin across 2 loops
        await s1.subscribe("tr/+", qos=1)
        await s2.subscribe("tr/t", qos=0)
        for i in range(4):
            await pub.publish("tr/t", payload=b"%d" % i, qos=1)
        for c in (s1, s2):
            for _ in range(4):
                await c.recv(timeout=5.0)
        out = node.ctl.run(["trace", "export", "/tmp/_trace_t.json"])
        assert "exported" in out
        doc = json.load(open("/tmp/_trace_t.json"))
        bytid = {}
        for e in doc["traceEvents"]:
            if e["ph"] == "X":
                bytid.setdefault(e["args"]["trace"],
                                 set()).add(e["name"])
        full = [t for t, st in bytid.items()
                if {"ingress", "match", "dispatch", "publish",
                    "flush"} <= st]
        assert full, bytid
        # the ring actually carried deliveries cross-loop, traced
        assert any("xloop" in st for st in bytid.values())
        flushed = {e["args"]["clientid"]
                   for e in doc["traceEvents"]
                   if e["ph"] == "X" and e["name"] == "flush"}
        assert {"ts1", "ts2"} <= flushed
        # slow_subs saw the same flushes, by clientid
        table = node.ctl.run(["slow_subs"])
        assert "ts1" in table and "ts2" in table
        # $SYS heartbeat carries the ranking
        sysq = Q("sysq")
        node.broker.subscribe(
            sysq, f"$SYS/brokers/{node.name}/slow_subs")
        node.sys.heartbeat()
        rows = json.loads(sysq.inbox[-1][1].payload)
        assert {"ts1", "ts2"} <= {r["clientid"] for r in rows}
        # per-loop lag gauges: one row per front-door loop
        node._update_stats(node.stats)
        all_stats = node.stats.all()
        assert "loop.0.lag_ms" in all_stats
        assert "loop.1.lag_ms" in all_stats
        for c in (s1, s2, pub):
            await c.close()


async def test_node_with_tracing_off_has_no_trace_surface():
    async with broker_node() as node:  # default: sample_rate 0
        port = node_port(node)
        c = TestClient("off1")
        await c.connect(port=port)
        await c.subscribe("o/t", qos=0)
        await c.publish("o/t", payload=b"x")
        assert (await c.recv(timeout=5.0)).payload == b"x"
        assert not node.tracing.active
        assert node.tracing._rings == []  # nothing ever recorded
        assert node.metrics.val("tracing.spans") == 0
        assert "none traced" in node.ctl.run(["slow_subs"])
        await c.close()


# -- cluster forward continuity -------------------------------------------


async def test_trace_context_survives_cluster_forward():
    """Deterministic sampling + header carriage: a message sampled on
    the publishing node arrives at the remote subscriber still
    carrying the ORIGIN node's trace context, so the remote flush
    span completes the origin's trace id."""
    from emqx_tpu.cluster import ClusterConfig

    def _fast():
        return ClusterConfig(heartbeat_interval_s=0.1,
                             suspect_after=2, down_after=5)

    n1 = Node(name="trc1@local", boot_listeners=False,
              tracing=TracingConfig(sample_rate=1.0))
    n2 = Node(name="trc2@local", boot_listeners=False,
              tracing=TracingConfig(sample_rate=1.0))
    for n in (n1, n2):
        n.enable_cluster(port=0, cookie="trace-ck", config=_fast())
    await n1.start()
    await n2.start()
    try:
        n1.cluster.join_remote("127.0.0.1",
                               n2.cluster.transport.port)

        class Rec:
            client_id = "remote-sub"

            def __init__(self):
                self.got = asyncio.Queue()

            def deliver(self, topic, msg):
                self.got.put_nowait(msg)

        r = Rec()
        n2.broker.subscribe(r, "x/+")
        deadline = asyncio.get_running_loop().time() + 20
        while not n1.router.has_dest("x/+", "trc2@local"):
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.1)
        sent = Message(topic="x/1", payload=b"hop")
        n1.broker.publish(sent)
        got = await asyncio.wait_for(r.got.get(), 20)
        ctx = got.headers.get(TRACE_HEADER)
        assert ctx is not None
        assert ctx["tid"] == sent.id and ctx["node"] == "trc1@local"
        # the remote flush completes the ORIGIN's trace id, and its
        # wall-clock latency is sane cross-node (clamped >= 0)
        n2.tracing.flush_mark(ctx, r.client_id)
        n2.tracing.drain_tick(n2.stats)
        flush = [rec for rec in n2.tracing._export
                 if rec[1] == "flush"]
        assert flush and flush[-1][0] == (sent.id,)
        assert flush[-1][3] >= 0.0
        assert flush[-1][4]["clientid"] == "remote-sub"
        # ...and the origin recorded the publish-side spans under the
        # same trace id
        n1.tracing.drain_tick(n1.stats)
        pub_tids = {tid for rec in n1.tracing._export
                    for tid in rec[0] if rec[1] == "publish"}
        assert sent.id in pub_tids
    finally:
        await n1.stop()
        await n2.stop()
