"""Topic algebra tests — ported from reference test/emqx_topic_SUITE.erl."""

import pytest

from emqx_tpu import topic as T
from emqx_tpu.topic import TopicError


def test_wildcard():
    assert T.wildcard("a/b/#")
    assert T.wildcard("a/+/#")
    assert not T.wildcard("")
    assert not T.wildcard("a/b/c")


def test_match1():
    assert T.match("a/b/c", "a/b/+")
    assert T.match("a/b/c", "a/#")
    assert T.match("abcd/ef/g", "#")
    assert T.match("abc/de/f", "abc/de/f")
    assert T.match("abc", "+")
    assert T.match("a/b/c", "a/b/c")
    assert not T.match("a/b/c", "a/c/d")
    assert not T.match("$share/x/y", "+")
    assert not T.match("$share/x/y", "+/x/y")
    assert not T.match("$share/x/y", "#")
    assert not T.match("$share/x/y", "+/+/#")
    assert not T.match("house/1/sensor/0", "house/+")
    assert not T.match("house", "house/+")


def test_match2():
    assert T.match("sport/tennis/player1", "sport/tennis/player1/#")
    assert T.match("sport/tennis/player1/ranking", "sport/tennis/player1/#")
    assert T.match("sport/tennis/player1/score/wimbledon", "sport/tennis/player1/#")
    assert T.match("sport", "sport/#")
    assert T.match("sport", "#")
    assert T.match("/sport/football/score/1", "#")
    assert T.match("Topic/C", "+/+")
    assert T.match("TopicA/B", "+/+")


def test_match3():
    assert T.match("device/60019423a83c/fw", "device/60019423a83c/#")
    assert T.match("device/60019423a83c/$fw", "device/60019423a83c/#")
    assert T.match("device/60019423a83c/$fw/fw", "device/60019423a83c/$fw/#")
    assert T.match("device/60019423a83c/fw/checksum", "device/60019423a83c/#")
    assert T.match("device/60019423a83c/dust/type", "device/60019423a83c/#")


def test_single_level_match():
    assert T.match("sport/tennis/player1", "sport/tennis/+")
    assert not T.match("sport/tennis/player1/ranking", "sport/tennis/+")
    assert not T.match("sport", "sport/+")
    assert T.match("sport/", "sport/+")
    assert T.match("/finance", "+/+")
    assert T.match("/finance", "/+")
    assert not T.match("/finance", "+")
    assert T.match("/devices/$dev1", "/devices/+")
    assert T.match("/devices/$dev1/online", "/devices/+/online")


def test_sys_match():
    assert T.match("$SYS/broker/clients/testclient", "$SYS/#")
    assert T.match("$SYS/broker", "$SYS/+")
    assert not T.match("$SYS/broker", "+/+")
    assert not T.match("$SYS/broker", "#")


def test_hash_match():
    assert T.match("a/b/c", "#")
    assert T.match("a/b/c", "+/#")
    assert not T.match("$SYS/brokers", "#")
    assert T.match("a/b/$c", "a/b/#")
    assert T.match("a/b/$c", "a/#")


def test_validate():
    assert T.validate("a/+/#")
    assert T.validate("a/b/c/d")
    assert T.validate("abc/de/f", "name")
    assert T.validate("abc/+/f", "filter")
    assert T.validate("abc/#", "filter")
    assert T.validate("x", "filter")
    assert T.validate("x//y", "name")
    assert T.validate("sport/tennis/#", "filter")
    with pytest.raises(TopicError, match="empty_topic"):
        T.validate("", "name")
    with pytest.raises(TopicError, match="topic_name_error"):
        T.validate("abc/#", "name")
    with pytest.raises(TopicError, match="topic_too_long"):
        T.validate("/".join(str(i) for i in range(10001)), "name")
    with pytest.raises(TopicError, match="topic_invalid_#"):
        T.validate("abc/#/1", "filter")
    with pytest.raises(TopicError, match="topic_invalid_char"):
        T.validate("abc/#xzy/+", "filter")
    with pytest.raises(TopicError, match="topic_invalid_char"):
        T.validate("abc/xzy/+9827", "filter")
    with pytest.raises(TopicError, match="topic_invalid_char"):
        T.validate("sport/tennis#", "filter")
    with pytest.raises(TopicError, match="topic_invalid_#"):
        T.validate("sport/tennis/#/ranking", "filter")


def test_single_level_validate():
    assert T.validate("+", "filter")
    assert T.validate("+/tennis/#", "filter")
    assert T.validate("sport/+/player1", "filter")
    with pytest.raises(TopicError, match="topic_invalid_char"):
        T.validate("sport+", "filter")


def test_prepend():
    assert T.prepend(None, "ab") == "ab"
    assert T.prepend("", "a/b") == "a/b"
    assert T.prepend("x/", "a/b") == "x/a/b"
    assert T.prepend("x/y", "a/b") == "x/y/a/b"
    assert T.prepend("+", "a/b") == "+/a/b"


def test_levels_tokens_words():
    assert T.levels("a/+/#") == 3
    assert T.levels("a/b/c/d") == 4
    assert T.tokens("a/b/+/#") == ["a", "b", "+", "#"]
    assert T.words("/a/+/#") == ["", "a", "+", "#"]
    assert T.words("/abkc/19383/+/akakdkkdkak/#") == [
        "", "abkc", "19383", "+", "akakdkkdkak", "#"]


def test_join():
    assert T.join([]) == ""
    assert T.join(["x"]) == "x"
    assert T.join(["#"]) == "#"
    assert T.join(["+", "", "#"]) == "+//#"
    assert T.join(["x", "y", "z", "+"]) == "x/y/z/+"
    assert T.join(T.words("/ab/cd/ef/")) == "/ab/cd/ef/"
    assert T.join(T.words("ab/+/#")) == "ab/+/#"


def test_systop():
    assert T.systop("xyz", node="n1@host") == "$SYS/brokers/n1@host/xyz"


def test_feed_var():
    assert T.feed_var("$c", "clientId", "$queue/client/$c") == "$queue/client/clientId"
    assert T.feed_var("%u", "test", "username/%u/client/x") == "username/test/client/x"
    assert T.feed_var("%c", "clientId", "username/test/client/%c") == \
        "username/test/client/clientId"


def test_parse():
    with pytest.raises(TopicError):
        T.parse("$queue/t", {"share": "g"})
    with pytest.raises(TopicError):
        T.parse("$share/g/t", {"share": "g"})
    with pytest.raises(TopicError):
        T.parse("$share/t")
    assert T.parse("a/b/+/#") == ("a/b/+/#", {})
    assert T.parse("$queue/a/b/+/#") == ("a/b/+/#", {"share": "$queue"})
    assert T.parse("$share/g/a/b/+/#") == ("a/b/+/#", {"share": "g"})
    with pytest.raises(TopicError):
        T.parse("$share/g+/t")
    with pytest.raises(TopicError):
        T.parse("$share/g#/t")
