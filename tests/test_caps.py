"""Zone capability checks (emqx_mqtt_caps parity)."""

from emqx_tpu import mqtt_caps
from emqx_tpu.mqtt import reason_codes as RC
from emqx_tpu.zone import Zone


def test_check_pub_within_caps():
    z = Zone()
    assert mqtt_caps.check_pub(z, 2, True, "a/b/c") is None


def test_check_pub_qos():
    z = Zone(max_qos_allowed=1)
    assert mqtt_caps.check_pub(z, 2, False, "t") == RC.QOS_NOT_SUPPORTED
    assert mqtt_caps.check_pub(z, 1, False, "t") is None


def test_check_pub_retain():
    z = Zone(retain_available=False)
    assert mqtt_caps.check_pub(z, 0, True, "t") == RC.RETAIN_NOT_SUPPORTED
    assert mqtt_caps.check_pub(z, 0, False, "t") is None


def test_check_pub_levels():
    z = Zone(max_topic_levels=2)
    assert mqtt_caps.check_pub(z, 0, False, "a/b/c") == RC.TOPIC_NAME_INVALID
    assert mqtt_caps.check_pub(z, 0, False, "a/b") is None


def test_check_sub_shared():
    z = Zone(shared_subscription=False)
    assert mqtt_caps.check_sub(z, "t", {"share": "g"}) == \
        RC.SHARED_SUBSCRIPTIONS_NOT_SUPPORTED
    assert mqtt_caps.check_sub(z, "t", {}) is None


def test_check_sub_wildcard():
    z = Zone(wildcard_subscription=False)
    assert mqtt_caps.check_sub(z, "a/+", {}) == \
        RC.WILDCARD_SUBSCRIPTIONS_NOT_SUPPORTED
    assert mqtt_caps.check_sub(z, "a/#", {}) == \
        RC.WILDCARD_SUBSCRIPTIONS_NOT_SUPPORTED
    assert mqtt_caps.check_sub(z, "a/b", {}) is None


def test_check_sub_levels():
    z = Zone(max_topic_levels=3)
    assert mqtt_caps.check_sub(z, "a/b/c/d", {}) == RC.TOPIC_FILTER_INVALID
    assert mqtt_caps.check_sub(z, "a/b/c", {}) is None


def test_get_caps():
    caps = mqtt_caps.get_caps(Zone(max_qos_allowed=1))
    assert caps["max_qos_allowed"] == 1
    assert caps["retain_available"] is True
    assert "wildcard_subscription" in caps
