"""Minimal asyncio MQTT client for integration tests — the role
emqtt plays in the reference's CT suites (rebar.config:40-45)."""

from __future__ import annotations

import asyncio
from typing import Optional

from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.frame import Parser, serialize
from emqx_tpu.mqtt.packet import (Connack, Connect, Disconnect, PubAck,
                                  Publish, Pingreq, Pingresp, Suback,
                                  Subscribe, Unsuback, Unsubscribe)


class TestClient:
    __test__ = False  # not a pytest class

    def __init__(self, client_id: str, version: int = C.MQTT_V4,
                 clean_start: bool = True, auto_ack: bool = True,
                 **connect_kw) -> None:
        self.client_id = client_id
        self.version = version
        self.clean_start = clean_start
        self.auto_ack = auto_ack  # False: flow-control tests ack by hand
        self.connect_kw = connect_kw
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.parser = Parser(version=version)
        self.inbox: asyncio.Queue = asyncio.Queue()   # inbound PUBLISHes
        self.acks: asyncio.Queue = asyncio.Queue()    # everything else
        self.connack: Optional[Connack] = None
        self._task: Optional[asyncio.Task] = None
        self._pkt_id = 0

    def next_pkt_id(self) -> int:
        self._pkt_id = (self._pkt_id % 0xFFFF) + 1
        return self._pkt_id

    async def connect(self, host="127.0.0.1", port=1883,
                      timeout=5.0, ssl=None) -> Connack:
        reader, writer = await asyncio.open_connection(
            host, port, ssl=ssl)
        return await self.connect_over(reader, writer, timeout=timeout)

    async def connect_over(self, reader, writer,
                           timeout=5.0) -> Connack:
        """CONNECT over pre-established streams (a TLS-PSK pair, a
        proxied socket, ...)."""
        self.reader, self.writer = reader, writer
        self._task = asyncio.get_event_loop().create_task(self._read_loop())
        await self.send(Connect(
            proto_ver=self.version,
            proto_name=C.PROTOCOL_NAMES[self.version],
            client_id=self.client_id, clean_start=self.clean_start,
            **self.connect_kw))
        self.connack = await asyncio.wait_for(self.acks.get(), timeout)
        assert isinstance(self.connack, Connack), self.connack
        return self.connack

    async def _read_loop(self) -> None:
        try:
            while True:
                data = await self.reader.read(65536)
                if not data:
                    return
                for pkt in self.parser.feed(data):
                    if isinstance(pkt, Publish):
                        await self.inbox.put(pkt)
                        # auto-ack inbound QoS1/2
                        if pkt.qos == 1 and self.auto_ack:
                            await self.send(PubAck(type=C.PUBACK,
                                                   packet_id=pkt.packet_id))
                        elif pkt.qos == 2 and self.auto_ack:
                            await self.send(PubAck(type=C.PUBREC,
                                                   packet_id=pkt.packet_id))
                    elif isinstance(pkt, PubAck) and pkt.type == C.PUBREL:
                        await self.send(PubAck(type=C.PUBCOMP,
                                               packet_id=pkt.packet_id))
                        await self.acks.put(pkt)
                    else:
                        await self.acks.put(pkt)
        except (ConnectionResetError, asyncio.CancelledError):
            return

    async def send(self, pkt) -> None:
        self.writer.write(serialize(pkt, self.version))
        await self.writer.drain()

    async def subscribe(self, *filters, qos=0, timeout=5.0,
                        props: Optional[dict] = None) -> Suback:
        pid = self.next_pkt_id()
        tf = [(f, {"qos": qos, "nl": 0, "rap": 0, "rh": 0})
              if isinstance(f, str) else f for f in filters]
        await self.send(Subscribe(packet_id=pid, topic_filters=tf,
                                  properties=props or {}))
        ack = await asyncio.wait_for(self.acks.get(), timeout)
        assert isinstance(ack, Suback), ack
        return ack

    async def unsubscribe(self, *filters, timeout=5.0) -> Unsuback:
        pid = self.next_pkt_id()
        await self.send(Unsubscribe(packet_id=pid,
                                    topic_filters=list(filters)))
        ack = await asyncio.wait_for(self.acks.get(), timeout)
        assert isinstance(ack, Unsuback), ack
        return ack

    async def publish(self, topic: str, payload: bytes = b"", qos: int = 0,
                      retain: bool = False, props: Optional[dict] = None,
                      timeout=5.0):
        pid = self.next_pkt_id() if qos else None
        await self.send(Publish(topic=topic, payload=payload, qos=qos,
                                retain=retain, packet_id=pid,
                                properties=props or {}))
        if qos == 1:
            ack = await asyncio.wait_for(self.acks.get(), timeout)
            assert isinstance(ack, PubAck) and ack.type == C.PUBACK, ack
            return ack
        if qos == 2:
            rec = await asyncio.wait_for(self.acks.get(), timeout)
            assert isinstance(rec, PubAck) and rec.type == C.PUBREC, rec
            await self.send(PubAck(type=C.PUBREL, packet_id=pid))
            comp = await asyncio.wait_for(self.acks.get(), timeout)
            assert isinstance(comp, PubAck) and comp.type == C.PUBCOMP, comp
            return comp
        return None

    async def recv(self, timeout=5.0) -> Publish:
        return await asyncio.wait_for(self.inbox.get(), timeout)

    async def ping(self, timeout=5.0) -> None:
        await self.send(Pingreq())
        ack = await asyncio.wait_for(self.acks.get(), timeout)
        assert isinstance(ack, Pingresp), ack

    async def disconnect(self, rc: int = 0) -> None:
        try:
            await self.send(Disconnect(reason_code=rc))
        except Exception:
            pass
        await self.close()

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
        if self.writer:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except Exception:
                pass
