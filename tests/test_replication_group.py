"""Replication groups: multi-standby fan-out, ack quorum, promotion
arbitration, FAILBACK, and the kill-anything chaos soak
(docs/DURABILITY.md "Replication groups" / "Failback"; ISSUE 13).

The acceptance properties:

  - a record acked by K standbys survives the simultaneous loss of
    any K-1 nodes (digest-verified per victim subset);
  - `ack_quorum = 0` never blocks the publish path (the PR 11 async
    contract), `ack_quorum = K` blocks bounded and degrades — never
    wedges — when the quorum is unreachable;
  - exactly ONE standby promotes (deterministic arbitration);
  - a healed primary gets its state handed back byte-exact
    (failback), with no second session-present storm, and dying
    again mid- or post-failback stays safe in both windows;
  - the seeded chaos soak (randomized kills of primaries, standbys,
    and links over a 3-node symmetric group) never loses a
    quorum-acked record and converges every plane after every heal.

Multi-node-in-one-process over real sockets, same harness shape as
tests/test_replication.py.
"""

import os
import random
import time

import pytest

from emqx_tpu import faults
from emqx_tpu.cluster import Cluster, ClusterConfig
from emqx_tpu.cluster_net import SocketTransport
from emqx_tpu.durability import DurabilityConfig
from emqx_tpu.modules.retainer import RetainerModule
from emqx_tpu.node import Node
from emqx_tpu.replication import durable_digest
from emqx_tpu.session import Session
from emqx_tpu.types import Message, SubOpts


def _fast_cfg(**kw) -> ClusterConfig:
    base = dict(heartbeat_interval_s=0.1, heartbeat_timeout_s=0.5,
                suspect_after=1, down_after=3, ok_after=1,
                anti_entropy_interval_s=1.0, call_timeout_s=5.0,
                redial_backoff_s=0.1, redial_backoff_max_s=0.5)
    base.update(kw)
    return ClusterConfig(**base)


def _wait(pred, timeout=30.0, msg="condition not met in time"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    raise AssertionError(msg)


def _wait_soft(pred, timeout=10.0) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


class _Chan:
    def __init__(self, s):
        self.session = s
        self.client_id = s.client_id


def _durable_session(node, cid, expiry=600.0):
    s = Session(cid, broker=node.broker, clean_start=False)
    node.durability.session_opened(s, expiry)
    node.cm.register_channel(cid, _Chan(s))
    return s


def _dur_cfg(tmp_path, i, names, ack_quorum, quorum_timeout_ms,
             extra=None):
    me = names[i]
    others = [x for x in names if x != me]
    kw = dict(enabled=True, dir=str(tmp_path / f"d{i}"),
              fsync=False, standbys=others, ack_quorum=ack_quorum,
              quorum_timeout_ms=quorum_timeout_ms, wal_shards=2,
              repl_ack_timeout_s=2.0)
    kw.update(extra or {})
    return DurabilityConfig(**kw)


def _boot(name, dcfg, cookie, ccfg):
    node = Node(name=name, boot_listeners=False, durability=dcfg)
    node.modules.load(RetainerModule)
    if node.durability is not None:
        node.durability.recover()
    tr = SocketTransport(name, cookie=cookie, config=ccfg)
    # scope chaos faults per transport from the start: an armed
    # net.* fault with fault_peers=None applies to EVERY peer, which
    # in a 3-node-in-one-process harness severs uninvolved links
    tr.fault_peers = set()
    tr.serve()
    cl = Cluster(node, transport=tr, config=ccfg)
    return node, tr, cl


def _mk_group(tmp_path, cookie, n=3, durable="all", ack_quorum=0,
              quorum_timeout_ms=400.0, extra_dur=None,
              cluster_kw=None):
    """n socket-clustered nodes. ``durable="all"``: every node is a
    durable primary fanning its journal to every other member (the
    symmetric quorum group); ``"first"``: only node 0 is durable,
    shipping to all the others (the directed fan-out shape)."""
    ccfg = _fast_cfg(**(cluster_kw or {}))
    names = [f"rg{i}" for i in range(n)]
    nodes, trs, cls = [], [], []
    for i, name in enumerate(names):
        dcfg = None
        if durable == "all" or i == 0:
            dcfg = _dur_cfg(tmp_path, i, names, ack_quorum,
                            quorum_timeout_ms, extra_dur)
        node, tr, cl = _boot(name, dcfg, cookie, ccfg)
        nodes.append(node)
        trs.append(tr)
        cls.append(cl)
    for i in range(1, n):
        cls[i].join_remote("127.0.0.1", trs[0].port)
    return names, nodes, trs, cls, ccfg


def _teardown(nodes, trs, cls):
    for node in nodes:
        d = getattr(node, "durability", None)
        if d is not None and d.wal is not None:
            try:
                d.wal.close()
            except Exception:
                pass
    for cl in cls:
        try:
            cl.close()
        except Exception:
            pass
    for tr in trs:
        try:
            tr.close()
        except Exception:
            pass


def _populate(n0):
    """The canonical durable workload (same as test_replication):
    a durable session with plain + shared subs and unacked QoS1
    inflight, retained set + clear."""
    s = _durable_session(n0, "dev1")
    s.subscribe("fleet/+/state", SubOpts(qos=1))
    s.subscribe("$share/g/fleet/cmd", SubOpts(qos=2))
    n0.broker.publish(Message(topic="fleet/1/state", payload=b"up",
                              qos=1, flags={"retain": True}))
    n0.broker.publish(Message(topic="fleet/2/state", payload=b"x",
                              flags={"retain": True}))
    n0.broker.publish(Message(topic="fleet/2/state", payload=b"",
                              flags={"retain": True}))  # tombstone
    n0.broker.publish(Message(topic="fleet/9/state", payload=b"q",
                              qos=1))
    n0.durability.on_batch()
    return s


def _synced(node):
    r = node.replication
    return (r.state == "replicating"
            and r.acked_seq >= r.offered_seq)


def _wait_synced(nodes, timeout=40.0,
                 msg="shippers never resynced"):
    """Wait until every node's shipper fully acked, ticking each
    node's journal flush while polling — the harness stand-in for
    the flush_interval_ms timer a started Node runs (remote retained
    applies and session closes journal outside on_batch)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        for n in nodes:
            d = getattr(n, "durability", None)
            if d is not None and d.wal is not None:
                d.on_batch()
        if all(_synced(n) for n in nodes):
            return
        time.sleep(0.1)
    raise AssertionError(msg)


def _kill(nodes, trs, cls, i):
    """kill -9 analogue: sever durability hooks (no more journaling,
    no graceful tail ship), stop the node's cluster threads, drop
    its transport so peers' detectors declare it down. The journal
    directory keeps only what was flushed — exactly a crash."""
    nodes[i].broker.durability = None
    nodes[i].cm.durability = None
    cls[i].close()
    trs[i].close()


def _restart(tmp_path, names, i, cookie, ccfg, ack_quorum,
             quorum_timeout_ms, join_port, extra_dur=None):
    """Fresh incarnation of a killed node: recover from its journal
    directory, rejoin through a survivor."""
    dcfg = _dur_cfg(tmp_path, i, names, ack_quorum,
                    quorum_timeout_ms, extra_dur)
    node, tr, cl = _boot(names[i], dcfg, cookie, ccfg)
    cl.join_remote("127.0.0.1", join_port)
    return node, tr, cl


def _cut(trs, names, a, b):
    trs[a].fault_peers = set(trs[a].fault_peers or ()) | {names[b]}
    trs[b].fault_peers = set(trs[b].fault_peers or ()) | {names[a]}
    faults.set_master(True)
    faults.arm("net.partition", times=0)


def _heal_links(trs):
    faults.disarm("net.partition")
    for tr in trs:
        tr.fault_peers = set()


# -- fan-out ---------------------------------------------------------------


def test_fanout_ships_to_all_standbys(tmp_path):
    names, nodes, trs, cls, _ = _mk_group(
        tmp_path, "grp-fan", durable="first")
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="fan-out never synced")
        for i in (1, 2):
            rep = nodes[i].replication.replicas["rg0"]
            assert "dev1" in rep.sessions
            assert "fleet/1/state" in rep.retained
            assert "fleet/2/state" in rep.tombs
            assert rep.peers == ["rg1", "rg2"]
            assert not rep.promoted
        r = nodes[0].replication
        info = r.info()
        assert set(info["standbys"]) == {"rg1", "rg2"}
        assert all(p["state"] == "replicating"
                   for p in info["standbys"].values())
        assert r.lag() == (0, 0)
        assert info["ack_quorum"] == 0
        assert info["quorum_acked_seq"] >= info["offered_seq"] - 1
    finally:
        _teardown(nodes, trs, cls)


def test_one_dead_standby_degrades_only_its_link(tmp_path):
    """A cut standby goes local-only; the healthy sibling keeps
    replicating (aggregate state 'partial'), and the cut one resyncs
    on heal."""
    names, nodes, trs, cls, _ = _mk_group(
        tmp_path, "grp-deg", durable="first")
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        _cut(trs, names, 0, 1)
        s2 = _durable_session(nodes[0], "dev2")
        s2.subscribe("late/+", SubOpts(qos=1))
        nodes[0].durability.on_batch()
        r = nodes[0].replication
        _wait(lambda: r.peers["rg2"].acked_seq >= r.offered_seq
              and r.peers["rg1"].state == "local_only",
              msg="sibling never kept shipping")
        assert r.state == "partial"
        assert "dev2" in nodes[2].replication.replicas["rg0"].sessions
        assert "dev2" not in \
            nodes[1].replication.replicas["rg0"].sessions
        _heal_links(trs)
        _wait_synced([nodes[0]], msg="cut standby never resynced")
        assert "dev2" in nodes[1].replication.replicas["rg0"].sessions
    finally:
        faults.clear()
        _teardown(nodes, trs, cls)


# -- quorum ----------------------------------------------------------------


def test_ack_quorum_zero_never_blocks(tmp_path):
    """The async pin: with every standby unreachable, ack_quorum=0
    group commits return without any quorum wait (PR 11 latency)."""
    names, nodes, trs, cls, _ = _mk_group(
        tmp_path, "grp-q0", durable="first", ack_quorum=0)
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        _cut(trs, names, 0, 1)
        _cut(trs, names, 0, 2)
        s2 = _durable_session(nodes[0], "async")
        s2.subscribe("a/+", SubOpts(qos=1))
        t0 = time.perf_counter()
        nodes[0].durability.on_batch()
        took = time.perf_counter() - t0
        assert took < 0.1, f"async commit blocked {took:.3f}s"
        r = nodes[0].replication
        assert r.counters["repl.quorum.waits"] == 0
        assert r.counters["repl.quorum.timeouts"] == 0
    finally:
        faults.clear()
        _teardown(nodes, trs, cls)


def test_quorum_wait_blocks_bounded_then_degrades(tmp_path):
    """ack_quorum=1 with every standby cut: the group commit blocks
    the bounded window, times out (counter), raises the
    repl_quorum_degraded alarm — and clears it once the quorum
    catches back up after heal."""
    names, nodes, trs, cls, _ = _mk_group(
        tmp_path, "grp-q1", durable="first", ack_quorum=1,
        quorum_timeout_ms=200.0)
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        _cut(trs, names, 0, 1)
        _cut(trs, names, 0, 2)
        s2 = _durable_session(nodes[0], "qdev")
        s2.subscribe("q/+", SubOpts(qos=1))
        t0 = time.perf_counter()
        nodes[0].durability.on_batch()
        took = time.perf_counter() - t0
        assert took >= 0.15, f"quorum commit returned in {took:.3f}s"
        assert took < 2.0, "quorum wait not bounded"
        r = nodes[0].replication
        assert r.counters["repl.quorum.timeouts"] >= 1
        nodes[0].stats.tick()
        assert any(a.name == "repl_quorum_degraded"
                   for a in nodes[0].alarms.get_alarms("activated"))
        assert r.info()["quorum_degraded"]
        _heal_links(trs)
        _wait_synced([nodes[0]], msg="never resynced after heal")
        nodes[0].stats.tick()
        assert not any(
            a.name == "repl_quorum_degraded"
            for a in nodes[0].alarms.get_alarms("activated"))
        assert r.counters["repl.quorum.waits"] >= 1
        nodes[0].stats.tick()
        assert nodes[0].metrics.val(
            "durability.repl.quorum.timeouts") >= 1
    finally:
        faults.clear()
        _teardown(nodes, trs, cls)


@pytest.mark.parametrize("victim", [0, 1, 2])
def test_quorum_acked_survives_any_single_node_loss(tmp_path,
                                                    victim):
    """The K-1 survival property at K=2: every record is acked by
    BOTH standbys before the kill, so losing any one node — the
    primary or either standby — leaves the full digest-exact state
    reachable on the survivors."""
    names, nodes, trs, cls, _ = _mk_group(
        tmp_path, f"grp-k{victim}", durable="first", ack_quorum=2)
    try:
        s = _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="sync before kill")
        r = nodes[0].replication
        assert r.quorum_acked_seq() >= r.offered_seq
        acked = r.offered_seq
        nodes[0].cm._detached["dev1"] = (s, 0, 600.0)
        want = durable_digest(nodes[0])
        del nodes[0].cm._detached["dev1"]
        _kill(nodes, trs, cls, victim)
        if victim == 0:
            # one (and only one) standby promotes — deterministic
            # arbitration: equal applied offsets, first name wins
            _wait(lambda: nodes[1].replication.replicas["rg0"]
                  .promoted, msg="no standby promoted")
            time.sleep(0.5)
            assert not nodes[2].replication.replicas["rg0"].promoted
            assert durable_digest(nodes[1]) == want
            assert nodes[1].replication.replicas["rg0"] \
                .applied_seq >= acked
        else:
            other = 2 if victim == 1 else 1
            nodes[0].cm._detached["dev1"] = (s, 0, 600.0)
            assert durable_digest(nodes[0]) == want
            del nodes[0].cm._detached["dev1"]
            rep = nodes[other].replication.replicas["rg0"]
            assert rep.applied_seq >= acked
            assert "dev1" in rep.sessions
    finally:
        faults.clear()
        _teardown(nodes, trs, cls)


def test_promotion_arbitration_highest_applied_wins(tmp_path):
    """A standby that missed the tail (lower applied offset) defers
    to the one that has it, regardless of name order."""
    names, nodes, trs, cls, _ = _mk_group(
        tmp_path, "grp-arb", durable="first")
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        # rg1 (the name-order favourite) missed the tail: wind its
        # replica back the way a dropped last batch leaves it
        rep1 = nodes[1].replication.replicas["rg0"]
        with rep1.lock:
            rep1.applied_seq -= 2
            rep1.sessions.pop("dev1", None)
        _kill(nodes, trs, cls, 0)
        _wait(lambda: nodes[2].replication.replicas["rg0"].promoted,
              msg="full replica never promoted")
        time.sleep(0.5)
        assert not nodes[1].replication.replicas["rg0"].promoted
        assert "dev1" in nodes[2].cm._detached
    finally:
        _teardown(nodes, trs, cls)


# -- failback --------------------------------------------------------------


def test_failover_failback_refailover_cycle(tmp_path):
    """The full cycle: primary dies → standby promotes; primary
    restarts from its own (stale) disk → the promoted standby ships
    the post-promotion state back, hands the sessions over without a
    session-present storm, demotes, and the pair converges
    digest-byte-exact; the primary dying AGAIN re-promotes the
    standby from the re-staged replica."""
    names, nodes, trs, cls, ccfg = _mk_group(
        tmp_path, "grp-fb", n=2, durable="all",
        cluster_kw=dict(anti_entropy_interval_s=0.5))
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        _kill(nodes, trs, cls, 0)
        _wait(lambda: nodes[1].replication.replicas["rg0"].promoted,
              msg="standby never promoted")
        assert "dev1" in nodes[1].cm._detached
        # post-promotion churn the failback must carry home: a QoS1
        # publish queues into the adopted detached session's mqueue,
        # and a retained change lands in the replicated plane
        nodes[1].broker.publish(Message(
            topic="fleet/5/state", payload=b"pp", qos=1))
        nodes[1].broker.publish(Message(
            topic="fleet/7/state", payload=b"rr", qos=1,
            flags={"retain": True}))
        want = durable_digest(nodes[1])
        fb0 = nodes[1].replication.counters["repl.failbacks"]
        node0b, tr0b, cl0b = _restart(
            tmp_path, names, 0, "grp-fb", ccfg, 0, 400.0,
            trs[1].port)
        nodes[0], trs[0], cls[0] = node0b, tr0b, cl0b
        _wait(lambda: not nodes[1].replication.replicas["rg0"]
              .promoted, timeout=40, msg="standby never demoted")
        assert nodes[1].replication.counters["repl.failbacks"] \
            == fb0 + 1
        _wait_synced([node0b],
                     msg="primary never resynced post-failback")
        # sessions handed over: home again, gone from the standby —
        # and never attached anywhere (no session-present storm)
        assert "dev1" in node0b.cm._detached
        assert "dev1" not in nodes[1].cm._detached
        assert "dev1" not in nodes[1].cm._channels
        s0 = node0b.cm._detached["dev1"][0]
        assert any(m.topic == "fleet/5/state"
                   for _p, q in s0.mqueue.snapshot() for m in q)
        # byte-exact convergence (retained rides anti-entropy)
        _wait(lambda: durable_digest(node0b) == want, timeout=40,
              msg="failback digest never converged")
        # the promoted alarm deactivated on demotion
        assert not any(a.name == "standby_promoted"
                       for a in
                       nodes[1].alarms.get_alarms("activated"))
        # …and the original dying AGAIN re-promotes from the
        # re-staged replica
        _kill(nodes, trs, cls, 0)
        _wait(lambda: nodes[1].replication.replicas["rg0"].promoted,
              timeout=40, msg="standby never re-promoted")
        assert "dev1" in nodes[1].cm._detached
    finally:
        faults.clear()
        _teardown(nodes, trs, cls)


def test_failback_aborts_on_drop_and_retries(tmp_path):
    """The repl.failback fault point: the hand-off call drops — the
    standby stays promoted and authoritative — then succeeds on the
    primary's next hello once disarmed."""
    names, nodes, trs, cls, ccfg = _mk_group(
        tmp_path, "grp-fbf", n=2, durable="all",
        cluster_kw=dict(anti_entropy_interval_s=0.5))
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        _kill(nodes, trs, cls, 0)
        _wait(lambda: nodes[1].replication.replicas["rg0"].promoted,
              msg="standby never promoted")
        faults.set_master(True)
        faults.arm("repl.failback", times=1)
        node0b, tr0b, cl0b = _restart(
            tmp_path, names, 0, "grp-fbf", ccfg, 0, 400.0,
            trs[1].port)
        nodes[0], trs[0], cls[0] = node0b, tr0b, cl0b
        r1 = nodes[1].replication
        _wait(lambda: r1.counters["repl.failback_errors"] >= 1,
              msg="failback drop never fired")
        assert r1.replicas["rg0"].promoted
        assert "dev1" in nodes[1].cm._detached
        # disarmed: the primary's hello keeps retrying and the next
        # hand-off lands
        _wait(lambda: not r1.replicas["rg0"].promoted, timeout=40,
              msg="failback never retried after the drop")
        assert "dev1" in node0b.cm._detached
    finally:
        faults.clear()
        _teardown(nodes, trs, cls)


def test_standby_crash_during_failback_double_recovery(tmp_path):
    """The standby dies between the primary's apply and its own
    finalize: both sides recover holding detached copies; the
    primary's next hello reclaims the standby's unregistered stale
    duplicates and the pair converges with the primary
    authoritative."""
    names, nodes, trs, cls, ccfg = _mk_group(
        tmp_path, "grp-fbc", n=2, durable="all",
        cluster_kw=dict(anti_entropy_interval_s=0.5))
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        _kill(nodes, trs, cls, 0)
        _wait(lambda: nodes[1].replication.replicas["rg0"].promoted,
              msg="standby never promoted")
        # freeze the standby's own hand-off so WE drive the window:
        # the primary applies, the standby never finalizes
        faults.set_master(True)
        faults.arm("repl.failback", times=0)
        node0b, tr0b, cl0b = _restart(
            tmp_path, names, 0, "grp-fbc", ccfg, 0, 400.0,
            trs[1].port)
        nodes[0], trs[0], cls[0] = node0b, tr0b, cl0b
        rep = nodes[1].replication.replicas["rg0"]
        handed = []
        for cid in sorted(rep.adopted_all):
            ent = nodes[1].cm._detached.get(cid)
            if ent is not None:
                handed.append((cid, float(ent[1]),
                               ent[0].to_wire()))
        assert handed
        reply = node0b.replication.handle_failback(
            "rg1", {"sessions": handed, "final": True,
                    "keep": [], "closed": []})
        assert reply["applied"] == len(handed)
        assert "dev1" in node0b.cm._detached
        # the standby crashes pre-finalize and recovers: its own
        # checkpoint resurrects the handed sessions a second time
        _kill(nodes, trs, cls, 1)
        faults.clear()
        node1b, tr1b, cl1b = _restart(
            tmp_path, names, 1, "grp-fbc", ccfg, 0, 400.0,
            tr0b.port)
        nodes[1], trs[1], cls[1] = node1b, tr1b, cl1b
        assert "dev1" in node1b.cm._detached  # the stale duplicate
        # the primary's hello reclaims it (registry places dev1 on
        # rg0 / nowhere): duplicate dropped, refs and all
        _wait(lambda: "dev1" not in node1b.cm._detached, timeout=40,
              msg="stale duplicate never reclaimed")
        assert "dev1" in node0b.cm._detached
        _wait_synced([node0b],
                     msg="pair never resynced after double recovery")
        _wait(lambda: cls[0].plane_digests()
              == cls[1].plane_digests(), timeout=40,
              msg="planes never converged after double recovery")
    finally:
        faults.clear()
        _teardown(nodes, trs, cls)


def test_promotion_under_load_no_crosstalk(tmp_path):
    """The standby serves its OWN live traffic while promoting: its
    live subscriber sees every one of its messages across the
    promotion (delivery parity), the warm replica never intercepts
    live traffic pre-promotion, and post-promotion the adopted
    sessions queue only their own topics (no cross-talk)."""
    names, nodes, trs, cls, _ = _mk_group(
        tmp_path, "grp-load", n=2, durable="all")
    try:
        _populate(nodes[0])
        own = _durable_session(nodes[1], "own1")
        own.subscribe("own/+", SubOpts(qos=0))
        nodes[1].durability.on_batch()
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        # pre-promotion: traffic matching the REPLICA's subs must
        # not be intercepted by the warm state
        nodes[1].broker.publish(Message(
            topic="fleet/3/state", payload=b"warm", qos=1))
        assert "dev1" not in nodes[1].cm._detached
        sent = 0
        for i in range(20):
            nodes[1].broker.publish(Message(
                topic=f"own/{i}", payload=b"x", qos=0))
            sent += 1
            if i == 9:
                _kill(nodes, trs, cls, 0)
        _wait(lambda: nodes[1].replication.replicas["rg0"].promoted,
              msg="standby never promoted")
        for i in range(20, 30):
            nodes[1].broker.publish(Message(
                topic=f"own/{i}", payload=b"x", qos=0))
            sent += 1
        got = [m.topic for _pid, m in own.drain_outbox()]
        assert len(got) == sent, (len(got), sent)
        assert all(t.startswith("own/") for t in got)
        # the adopted session queued only ITS topics — and did queue
        # the post-promotion fleet publish
        nodes[1].broker.publish(Message(
            topic="fleet/4/state", payload=b"post", qos=1))
        s0 = nodes[1].cm._detached["dev1"][0]
        qt = [m.topic for _p, q in s0.mqueue.snapshot() for m in q]
        assert "fleet/4/state" in qt
        assert not any(t.startswith("own/") for t in qt)
    finally:
        _teardown(nodes, trs, cls)


# -- config / surfaces ------------------------------------------------------


def test_config_group_knobs_and_legacy_equivalence():
    assert DurabilityConfig(enabled=True,
                            standby="a").standby_list == ("a",)
    assert DurabilityConfig(enabled=True,
                            standbys=["a"]).standby_list == ("a",)
    assert DurabilityConfig(enabled=True).standby_list == ()
    with pytest.raises(ValueError):
        DurabilityConfig(enabled=True, standby="a", standbys=["b"])
    with pytest.raises(ValueError):
        DurabilityConfig(enabled=True, standbys=["a", "a"])
    with pytest.raises(ValueError):
        DurabilityConfig(enabled=True, standbys=["a"], ack_quorum=2)
    with pytest.raises(ValueError):
        DurabilityConfig(enabled=True, ack_quorum=1)
    with pytest.raises(ValueError):
        DurabilityConfig(enabled=True, ack_quorum=-1)
    with pytest.raises(ValueError):
        DurabilityConfig(enabled=True, standbys=["a"],
                         quorum_timeout_ms=0)


def test_ctl_shows_group_topology_and_quorum(tmp_path):
    import json

    names, nodes, trs, cls, _ = _mk_group(
        tmp_path, "grp-ctl", durable="first", ack_quorum=1)
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        out = json.loads(nodes[0].ctl.run(["durability"]))
        blk = out["replication"]
        assert blk["role"] == "primary"
        assert set(blk["standbys"]) == {"rg1", "rg2"}
        for ent in blk["standbys"].values():
            assert ent["state"] == "replicating"
            assert ent["acked_seq"] == blk["offered_seq"]
        assert blk["ack_quorum"] == 1
        assert blk["quorum_acked_seq"] >= blk["offered_seq"]
        assert blk["quorum_degraded"] is False
        out1 = json.loads(nodes[1].ctl.run(["durability"]))
        rep = out1["replication"]["standby_for"]["rg0"]
        assert rep["peers"] == ["rg1", "rg2"]
        nodes[0].stats.tick()
        assert nodes[0].metrics.val("durability.repl.shipped") > 0
    finally:
        _teardown(nodes, trs, cls)


# -- the replication chaos soak --------------------------------------------


class _Soak:
    """Seeded kill-anything scheduler over a 3-node symmetric quorum
    group: every node is a durable primary fanning to the other two
    with ack_quorum=1. Each round drives quorum-acked traffic,
    disrupts (kill a node / cut a link / nothing), drives more
    traffic on the survivors, heals everything, and asserts that no
    quorum-acked record was lost and every plane converged."""

    def __init__(self, tmp_path, cookie, seed):
        self.tmp_path = tmp_path
        self.cookie = cookie
        self.rng = random.Random(seed)
        self.ccfg = _fast_cfg(anti_entropy_interval_s=0.5)
        self.names, self.nodes, self.trs, self.cls, _ = _mk_group(
            tmp_path, cookie, n=3, durable="all", ack_quorum=1,
            quorum_timeout_ms=500.0,
            cluster_kw=dict(anti_entropy_interval_s=0.5))
        self.alive = [True, True, True]
        self.oracle_sessions = {}   # cid -> home node name
        self.oracle_retained = {}   # topic -> payload
        self.seq = 0

    def live_idx(self):
        return [i for i in range(3) if self.alive[i]]

    def traffic(self, i):
        """One quorum-acked burst on node i; recorded in the oracle
        only once the quorum watermark covers it."""
        node = self.nodes[i]
        self.seq += 1
        k = self.seq
        cid = f"c{k}"
        s = _durable_session(node, cid)
        s.subscribe(f"t/{k}/+", SubOpts(qos=1))
        payload = b"v%d" % k
        node.broker.publish(Message(topic=f"r/{k}", payload=payload,
                                    qos=1, flags={"retain": True}))
        node.durability.on_batch()
        r = node.replication
        if _wait_soft(lambda: r.quorum_acked_seq() >= r.offered_seq,
                      timeout=15):
            self.oracle_sessions[cid] = self.names[i]
            self.oracle_retained[f"r/{k}"] = payload

    def kill(self, i):
        # which quorum-acked sessions does the victim hold RIGHT NOW
        # (sessions migrate through failover chains — original home
        # is not ownership)
        node = self.nodes[i]
        held = [c for c in self.oracle_sessions
                if c in node.cm._detached or c in node.cm._channels]
        _kill(self.nodes, self.trs, self.cls, i)
        self.alive[i] = False
        # survivors declare it down; if it held quorum-acked state,
        # exactly one of its standbys promotes
        survivors = self.live_idx()
        dead = self.names[i]
        _wait(lambda: all(
            dead not in self.cls[j].members for j in survivors),
            timeout=30, msg=f"{dead} never declared down")
        if held:
            _wait(lambda: any(
                self.nodes[j].replication.replicas.get(dead)
                and self.nodes[j].replication.replicas[dead].promoted
                for j in survivors),
                timeout=30, msg=f"no standby promoted for {dead}")
            promoted = [j for j in survivors
                        if self.nodes[j].replication.replicas
                        .get(dead)
                        and self.nodes[j].replication
                        .replicas[dead].promoted]
            assert len(promoted) == 1, \
                f"dual promotion for {dead}: {promoted}"
            # No per-session placement assertion HERE: mid-failover,
            # racing custody chains (spurious promotions, concurrent
            # failbacks, registry reassignment) legitimately move
            # sessions between survivors, and a session that had
            # migrated onto the victim moments before the kill may
            # exist only on its disk until the restart. The
            # acceptance invariant — every quorum-acked session
            # survives with exactly one holder — is verify()'s job
            # after every heal, which is where the RPO=0 property is
            # actually defined.

    def heal(self):
        _heal_links(self.trs)
        for i in range(3):
            if not self.alive[i]:
                join = self.trs[self.live_idx()[0]].port
                node, tr, cl = _restart(
                    self.tmp_path, self.names, i, self.cookie,
                    self.ccfg, 1, 500.0, join)
                self.nodes[i], self.trs[i], self.cls[i] = \
                    node, tr, cl
                self.alive[i] = True
        # convergence: membership, failbacks done, shippers synced,
        # plane digests byte-equal
        try:
            _wait(lambda: all(
                sorted(self.cls[i].members) == sorted(self.names)
                for i in range(3)), timeout=60,
                msg="membership never re-merged")
            _wait(lambda: all(
                not rep.promoted
                for i in range(3)
                for rep in self.nodes[i].replication.replicas
                .values()),
                timeout=60,
                msg="a promoted replica never failed back")
            _wait_synced(self.nodes, timeout=90)
            _wait(lambda: self.cls[0].plane_digests()
                  == self.cls[1].plane_digests()
                  == self.cls[2].plane_digests(),
                  timeout=60, msg="plane digests never converged")
        except AssertionError as e:
            raise AssertionError(f"{e}\n{self._dump()}") from None

    def _dump(self) -> str:
        out = []
        for i in range(3):
            r = self.nodes[i].replication
            out.append(
                f"{self.names[i]}: members="
                f"{sorted(self.cls[i].members)} "
                f"peers={{{', '.join(f'{n}:({p.state},hello={p.need_hello},acked={p.acked_seq})' for n, p in r.peers.items())}}} "
                f"offered={r.offered_seq} "
                f"flushed={r._flushed_seq} "
                f"replicas={{{', '.join(f'{n}:(prom={rep.promoted},applied={rep.applied_seq})' for n, rep in r.replicas.items())}}} "
                f"ctrs={r.counters}")
        return "\n".join(out)

    def verify(self):
        """After every heal: no quorum-acked record lost. Sessions
        legitimately MIGRATE through failover chains (a spurious
        promotion adopts them, the failback machinery and the
        registry track the chain of custody) — the invariant is
        exactly ONE live holder after convergence, with the
        converged registry pointing at it, not placement on the
        original home. Retained entries are a replicated plane:
        present on every member."""
        for cid in self.oracle_sessions:
            holders = [self.names[i] for i in range(3)
                       if cid in self.nodes[i].cm._detached
                       or cid in self.nodes[i].cm._channels]
            assert holders, f"quorum-acked session {cid} lost"
            assert len(holders) == 1, \
                f"session {cid} double-owned by {holders}"
            owner = self.cls[0]._registry.get(cid)
            if owner is not None:
                assert owner == holders[0], \
                    f"registry places {cid} on {owner}, held by " \
                    f"{holders[0]}"
        for i in range(3):
            ret = self.nodes[i].modules._loaded["retainer"]
            for topic, payload in self.oracle_retained.items():
                m = ret._store.get(topic)
                assert m is not None, \
                    f"retained {topic} lost on {self.names[i]}"
                assert bytes(m.payload) == payload

    def round(self, k):
        live = self.live_idx()
        self.traffic(self.rng.choice(live))
        # rounds 0/1 are scripted: a full failover→failback→
        # re-failover cycle on rg0; after that, kill anything
        if k in (0, 1):
            action = ("kill", 0)
        else:
            action = self.rng.choice(
                [("kill", 0), ("kill", 1), ("kill", 2),
                 ("cut", (0, 1)), ("cut", (0, 2)), ("cut", (1, 2)),
                 ("none", None)])
        if action[0] == "kill":
            self.kill(action[1])
        elif action[0] == "cut":
            a, b = action[1]
            _cut(self.trs, self.names, a, b)
            time.sleep(1.0)  # let the detectors react
        for _ in range(2):
            self.traffic(self.rng.choice(self.live_idx()))
        self.heal()
        self.verify()

    def run(self, rounds):
        try:
            for k in range(rounds):
                self.round(k)
        finally:
            faults.clear()
            _teardown(self.nodes, self.trs, self.cls)
        return {"rounds": rounds,
                "sessions": len(self.oracle_sessions),
                "retained": len(self.oracle_retained)}


def test_chaos_soak_smoke(tmp_path):
    """The CI-gated soak smoke: fixed seed, bounded rounds — the
    first two rounds alone cover a full failover→failback→
    re-failover cycle; the rest kill/cut at random."""
    seed = int(os.environ.get("SOAK_SEED", "1337"))
    rounds = int(os.environ.get("SOAK_ROUNDS", "4"))
    out = _Soak(tmp_path, f"soak-smoke-{seed}", seed).run(rounds)
    assert out["sessions"] >= rounds  # the oracle actually grew


@pytest.mark.slow
def test_chaos_soak_full(tmp_path):
    """The acceptance soak: >= 20 seeded kill/heal rounds over the
    3-node quorum group, killing primaries, standbys, and links in
    randomized order — rpo_records == 0 for quorum-acked records and
    digest-verified convergence after every heal."""
    seed = int(os.environ.get("SOAK_SEED", "1337"))
    rounds = int(os.environ.get("SOAK_ROUNDS", "20"))
    out = _Soak(tmp_path, f"soak-full-{seed}", seed).run(rounds)
    assert out["sessions"] >= rounds
