"""Chaos suite: every registered fault-injection point (faults.py)
exercised against the shedding/healing behavior it exists to trigger
(docs/ROBUSTNESS.md; ISSUE 8 acceptance).

The pinned contracts:

  - device-step failure/stall trips the circuit breaker to the exact
    host-oracle path with ZERO wrong or lost deliveries, and the
    breaker recovers through a half-open probe;
  - executor death and a crashed compaction flatten self-heal
    (respawn / alarm + backoff-retry);
  - a dead front-door loop's connections close with wills fired and
    the cross-loop join never hangs (handoff loss is bounded +
    counted, not silent);
  - a saturated ingress sheds a parked publisher after the bounded
    submit wait instead of wedging it forever;
  - faults-disabled and ``[overload] enabled = false`` keep the
    broker byte-for-byte the pre-robustness build.
"""

import asyncio
import time

import pytest

from emqx_tpu import faults
from emqx_tpu.config import ConfigError, parse_config
from emqx_tpu.mqtt import constants as C
from emqx_tpu.node import Node
from emqx_tpu.overload import (CRITICAL, OK, WARN, DeviceBreaker,
                               OverloadConfig)
from emqx_tpu.router import MatcherConfig
from emqx_tpu.session import Session
from emqx_tpu.types import Message

from helpers import broker_node, node_port
from mqtt_client import TestClient


@pytest.fixture(autouse=True)
def _clean_faults():
    """The fault registry is process-global: every test starts and
    ends with it empty (and the master switch on, its default)."""
    faults.clear()
    faults.set_master(True)
    yield
    faults.clear()
    faults.set_master(True)


class Sink:
    def __init__(self):
        self.got = []

    def deliver(self, flt, msg):
        self.got.append((flt, msg.topic, bytes(msg.payload)))


def _device_node(**over):
    kw = dict(boot_listeners=False,
              matcher=MatcherConfig(device_min_filters=0))
    kw.update(over)
    return Node(**kw)


# -- fault registry semantics ------------------------------------------------


def test_registry_validation_times_and_determinism():
    with pytest.raises(ValueError):
        faults.arm("no.such.point")
    with pytest.raises(ValueError):
        faults.arm("device.walk", action="explode")
    with pytest.raises(ValueError):
        faults.arm("device.walk", action="stall")  # needs delay_ms
    assert not faults.enabled
    # times accounting: 2 triggers then self-disarm (and the module
    # gate drops with the last arm)
    faults.arm("ingress.saturate", times=2)
    assert faults.enabled
    assert faults.fire("ingress.saturate") is True
    assert faults.fire("ingress.saturate") is True
    assert not faults.enabled
    assert faults.fire("ingress.saturate") is False
    # seeded probability is deterministic
    faults.seed(7)
    faults.arm("ingress.saturate", times=0, prob=0.5)
    seq1 = [faults.fire("ingress.saturate") for _ in range(16)]
    faults.clear()
    faults.seed(7)
    faults.arm("ingress.saturate", times=0, prob=0.5)
    seq2 = [faults.fire("ingress.saturate") for _ in range(16)]
    assert seq1 == seq2 and True in seq1 and False in seq1
    # master off keeps arms inert
    faults.clear()
    faults.arm("ingress.saturate", times=0)
    faults.set_master(False)
    assert not faults.enabled
    # context manager disarms on exit
    faults.set_master(True)
    faults.clear()
    with faults.injected("device.walk", times=0):
        assert faults.enabled
    assert not faults.enabled
    # arm-spec parsing (the TOML/ctl syntax)
    assert faults.parse_arm("device.fetch:raise:3") == \
        ("device.fetch", "raise", 3, 0.0)
    with pytest.raises(ValueError):
        faults.parse_arm("device.fetch:bogus")


def test_config_sections_closed_schema():
    with pytest.raises(ConfigError):
        parse_config({"overload": {"lag_warm_ms": 5}})  # typo'd key
    with pytest.raises(ConfigError):
        parse_config({"overload": {"lag_warn_ms": 100,
                                   "lag_critical_ms": 10}})  # order
    with pytest.raises(ConfigError):
        parse_config({"faults": {"arm": ["no.such.point"]}})
    cfg = parse_config({
        "overload": {"enabled": False},
        "faults": {"enabled": False, "seed": 3,
                   "arm": ["device.fetch:raise:2"]},
    })
    assert cfg.overload.enabled is False
    assert cfg.faults.arm == ["device.fetch:raise:2"]
    # an overload-off node builds NO monitor, breaker, or bounded
    # ingress wait — the hot paths read None (the byte-for-byte pin)
    node = Node(boot_listeners=False, overload=cfg.overload)
    assert node.overload is None
    assert node.broker.overload is None
    assert node.broker.breaker is None
    assert node.ingress.submit_wait_timeout == 0.0


# -- device-path circuit breaker ---------------------------------------------


def test_device_failure_trips_breaker_and_half_open_recovers():
    """The acceptance scenario: injected device-step failures trip
    the breaker to host-oracle matching with zero wrong/lost
    deliveries, and the breaker recovers via a half-open probe."""
    node = _device_node(overload=OverloadConfig(
        breaker_failures=2, breaker_cooldown_s=0.2))
    s = Sink()
    node.subscribe(s, "c/+")
    node.subscribe(s, "c/#")
    br = node.broker.breaker
    # two consecutive fetch failures: each batch falls back to the
    # exact host oracle (both filters still deliver), then the
    # breaker opens
    with faults.injected("device.fetch", times=2):
        for i in range(2):
            got = node.broker.publish_batch(
                [Message(topic="c/t", payload=b"f%d" % i)])
            assert got == [2]
    assert br.state == DeviceBreaker.OPEN
    assert node.metrics.val("breaker.trips") == 1
    assert node.metrics.val("breaker.failures") == 2
    assert any(a.name == "device_path_breaker"
               for a in node.alarms.get_alarms("activated"))
    # open: batches are host-matched without touching the device
    assert node.broker.publish_batch(
        [Message(topic="c/t", payload=b"open")]) == [2]
    assert node.metrics.val("breaker.fallback.batches") >= 1
    # cooldown elapses -> exactly one half-open probe rides the
    # device; success closes the breaker and clears the alarm
    time.sleep(0.25)
    assert node.broker.publish_batch(
        [Message(topic="c/t", payload=b"probe")]) == [2]
    assert br.state == DeviceBreaker.CLOSED
    assert node.metrics.val("breaker.probes") == 1
    assert not any(a.name == "device_path_breaker"
                   for a in node.alarms.get_alarms("activated"))
    # nothing was lost or duplicated across the whole episode
    assert len(s.got) == 2 * 4


def test_device_walk_failure_is_caught_too():
    node = _device_node()
    s = Sink()
    node.subscribe(s, "w/1")
    with faults.injected("device.walk", times=1):
        assert node.broker.publish_batch(
            [Message(topic="w/1", payload=b"x")]) == [1]
    assert node.metrics.val("breaker.failures") == 1
    assert len(s.got) == 1


def test_stalled_device_step_counts_as_failure():
    """A device that answers but too slowly must trip the fallback —
    breaker_slow_ms turns the stall into a recorded failure."""
    node = _device_node(overload=OverloadConfig(
        breaker_failures=1, breaker_cooldown_s=30.0))
    s = Sink()
    node.subscribe(s, "st/1")
    # warm with the latency gate off — the first fetch pays XLA
    # compiles and must not count; then arm a bound the injected
    # stall clearly exceeds but a warm fetch clearly doesn't
    node.broker.publish_batch([Message(topic="st/1", payload=b"warm")])
    assert node.broker.breaker.state == DeviceBreaker.CLOSED
    node.broker.breaker.slow_ms = 400.0
    with faults.injected("device.fetch", action="stall", times=1,
                         delay_ms=600.0):
        assert node.broker.publish_batch(
            [Message(topic="st/1", payload=b"slow")]) == [1]
    assert node.broker.breaker.state == DeviceBreaker.OPEN
    assert len(s.got) == 2


def test_breaker_off_reraises_device_failure():
    """[overload] off: no breaker — a device failure surfaces raw,
    exactly the pre-robustness behavior."""
    node = _device_node(overload=OverloadConfig(enabled=False))
    node.subscribe(Sink(), "r/1")
    with faults.injected("device.fetch", times=1):
        with pytest.raises(faults.FaultInjected):
            node.broker.publish_batch(
                [Message(topic="r/1", payload=b"x")])


# -- executor death / flatten crash supervision ------------------------------


async def test_executor_death_self_heals():
    async with broker_node(
            matcher=MatcherConfig(device_min_filters=0)) as node:
        port = node_port(node)
        sub = TestClient("exsub")
        pub = TestClient("expub")
        await sub.connect(port=port)
        await pub.connect(port=port)
        await sub.subscribe("ex/t", qos=1)
        # warm: the fetch pool is lazily created by the first batch
        await pub.publish("ex/t", payload=b"warm", qos=1)
        assert (await sub.recv()).payload == b"warm"
        with faults.injected("executor.death", times=1):
            await pub.publish("ex/t", payload=b"survives", qos=1)
        msg = await sub.recv()
        assert msg.payload == b"survives"
        assert node.metrics.val("overload.heal.executor") == 1
        await sub.close()
        await pub.close()


def test_flatten_crash_alarms_backoff_then_retries():
    node = _device_node(matcher=MatcherConfig(
        device_min_filters=0, delta_max_filters=4))
    r = node.router
    for i in range(3):
        r.add_route(f"fl/{i}")
    r.match_ids(["fl/0"])  # build the automaton (delta plane live)
    with faults.injected("compaction.flatten", times=1):
        for i in range(3, 12):
            r.add_route(f"fl/{i}")
        deadline = time.time() + 10
        while r._compact_failures == 0 and time.time() < deadline:
            time.sleep(0.01)
    assert r._compact_failures == 1
    # route ops kept landing (the delta carries them) and matching
    # still answers exactly
    assert sorted(r.host_match("fl/7")) == ["fl/7"]
    node.drain_robustness_events()
    assert any(a.name == "router_compaction_failed"
               for a in node.alarms.get_alarms("activated"))
    assert node.metrics.val("overload.heal.flatten") == 1
    # inside the backoff window nothing re-flattens; once it elapses
    # the monitor's retry hook re-kicks the compaction and it heals
    r.retry_compaction()
    assert r._compact_failures == 1
    r._compact_backoff_until = 0.0
    r.retry_compaction()
    deadline = time.time() + 10
    while (r._compacting or r._compact_failures) \
            and time.time() < deadline:
        time.sleep(0.01)
    assert r._compact_failures == 0
    node.drain_robustness_events()
    assert not any(a.name == "router_compaction_failed"
                   for a in node.alarms.get_alarms("activated"))


# -- multi-loop: dead loop, dropped handoff, stalled owner -------------------


async def test_dead_loop_heal_closes_connections_and_fires_wills():
    async with broker_node(
            loops=2,
            matcher=MatcherConfig(device_min_filters=0)) as node:
        port = node_port(node)
        obs = TestClient("obs")          # first connect -> loop 0
        await obs.connect(port=port)
        await obs.subscribe("wills/#", qos=1)
        doomed = TestClient("doomed", will_flag=True, will_qos=1,
                            will_topic="wills/loop",
                            will_payload=b"loop died")
        await doomed.connect(port=port)  # second connect -> loop 1
        lg = node.loop_group
        assert node.listeners[0].loop_connections()[1] == 1
        lg.crash(1)
        deadline = time.time() + 5
        while lg.dead_peer_indices() == [] and time.time() < deadline:
            await asyncio.sleep(0.02)
        # the monitor's heal sweep: routes around the dead loop and
        # closes its connections so the will fires
        node.overload.tick(0.0)
        msg = await obs.recv()
        assert msg.topic == "wills/loop" and msg.payload == b"loop died"
        assert node.metrics.val("overload.heal.loop") == 1
        assert 1 in lg._dead
        assert any(a.name == "frontdoor_loop_1_dead"
                   for a in node.alarms.get_alarms("activated"))
        # the node still serves: publish/deliver through loop 0
        pub = TestClient("after")
        await pub.connect(port=port)
        await pub.publish("wills/after", payload=b"alive", qos=1)
        msg = await obs.recv()
        assert msg.payload == b"alive"
        await pub.close()
        await obs.close()
        await doomed.close()


async def test_xloop_handoff_drop_is_bounded_and_counted():
    """An injected handoff loss: the batch's fold waits at most
    XLOOP_JOIN_TIMEOUT, the lost groups are counted as orphaned, and
    the next batch delivers normally — the join never hangs."""
    async with broker_node(
            loops=2,
            matcher=MatcherConfig(device_min_filters=0)) as node:
        node.broker.XLOOP_JOIN_TIMEOUT = 0.5
        port = node_port(node)
        filler = TestClient("filler")    # -> loop 0
        await filler.connect(port=port)
        sub = TestClient("xsub")         # -> loop 1 (cross-loop)
        await sub.connect(port=port)
        await sub.subscribe("xh/t", qos=1)
        pub = TestClient("xpub")         # -> loop 0
        await pub.connect(port=port)
        with faults.injected("xloop.handoff", times=1):
            t0 = time.perf_counter()
            # the PUBACK waits on the bounded join, then arrives
            await pub.publish("xh/t", payload=b"lost", qos=1,
                              timeout=5.0)
            assert time.perf_counter() - t0 < 4.0
        assert node.metrics.val("delivery.xloop.orphaned") >= 1
        # the ring works again on the next batch
        await pub.publish("xh/t", payload=b"found", qos=1)
        msg = await sub.recv()
        assert msg.payload == b"found"
        for cli in (filler, sub, pub):
            await cli.close()


async def test_takeover_timeout_on_stalled_owner_loop():
    """Satellite: the bounded cm takeover wait's timeout arm. The
    owning loop is wedged (chaos stall), so the resume-takeover
    marshal expires; the client gets a FRESH session instead of a
    hung CONNECT, and the timeout is counted."""
    async with broker_node(loops=2) as node:
        node.cm.XLOOP_CALL_TIMEOUT = 0.4
        port = node_port(node)
        filler = TestClient("filler2")   # -> loop 0
        await filler.connect(port=port)
        victim = TestClient("dup", clean_start=False)  # -> loop 1
        ack = await victim.connect(port=port)
        assert ack.reason_code == 0
        node.loop_group.stall(1, 1.5)
        await asyncio.sleep(0.05)  # let the stall land on the loop
        again = TestClient("dup", clean_start=False)   # -> loop 0
        t0 = time.perf_counter()
        ack = await again.connect(port=port, timeout=5.0)
        assert time.perf_counter() - t0 < 1.2
        assert ack.reason_code == 0
        # the wedged owner's session could not be taken over: fresh
        # session, no session_present, timeout counted
        assert not ack.session_present
        assert node.metrics.val("overload.takeover.timeout") == 1
        # the fresh session works
        await again.subscribe("tk/t", qos=1)
        pub = TestClient("tkpub")
        await pub.connect(port=port)
        await pub.publish("tk/t", payload=b"fresh", qos=1)
        msg = await again.recv()
        assert msg.payload == b"fresh"
        await asyncio.sleep(1.3)  # let the stall drain before stop
        for cli in (filler, victim, again, pub):
            await cli.close()


async def test_keepalive_survives_owner_loop_stall():
    """Satellite: a stalled owning loop must not make keepalive kill
    a live client once it unwedges — the byte-delta check sees the
    traffic that queued during the stall."""
    async with broker_node(loops=2) as node:
        port = node_port(node)
        filler = TestClient("kfill")     # -> loop 0
        await filler.connect(port=port)
        cli = TestClient("kal", keepalive=1)  # -> loop 1
        await cli.connect(port=port)
        node.loop_group.stall(1, 1.8)    # > 1.5x the interval
        # traffic sent INTO the stall: queued by the kernel, read
        # when the loop unwedges — proof of life for the check
        await cli.send(__import__("emqx_tpu.mqtt.packet",
                                  fromlist=["Pingreq"]).Pingreq())
        await asyncio.sleep(2.2)
        assert node.cm.lookup_channel("kal") is not None
        await cli.ping()                 # still serviceable
        await cli.close()
        await filler.close()


# -- socket reset, ingress saturation ----------------------------------------


async def test_socket_reset_mid_flush_closes_cleanly_fires_will():
    async with broker_node() as node:
        port = node_port(node)
        obs = TestClient("robs")
        await obs.connect(port=port)
        await obs.subscribe("wills/reset", qos=1)
        vic = TestClient("rvic", will_flag=True, will_qos=1,
                         will_topic="wills/reset",
                         will_payload=b"reset")
        await vic.connect(port=port)
        await vic.subscribe("rs/t")
        # the next flush anywhere is the victim's delivery flush
        # (server-initiated publish: no other connection writes)
        with faults.injected("socket.reset", times=1):
            node.broker.publish(Message(topic="rs/t", payload=b"x"))
            deadline = time.time() + 5
            while node.cm.lookup_channel("rvic") is not None \
                    and time.time() < deadline:
                await asyncio.sleep(0.02)
        assert node.cm.lookup_channel("rvic") is None
        msg = await obs.recv()
        assert msg.payload == b"reset"  # abnormal close -> will
        # broker unharmed: obs still serves
        node.broker.publish(Message(topic="wills/reset",
                                    payload=b"after"))
        msg = await obs.recv()
        assert msg.payload == b"after"
        await obs.close()


async def test_ingress_saturation_sheds_publisher_after_bounded_wait():
    async with broker_node() as node:
        node.ingress.submit_wait_timeout = 0.3
        port = node_port(node)
        pub = TestClient("satpub")
        await pub.connect(port=port)
        with faults.injected("ingress.saturate", times=0):
            await pub.publish("sat/t", payload=b"x", qos=0)
            deadline = time.time() + 5
            while node.cm.lookup_channel("satpub") is not None \
                    and time.time() < deadline:
                await asyncio.sleep(0.02)
        assert node.cm.lookup_channel("satpub") is None
        assert node.metrics.val("overload.shed.ingress_timeout") == 1
        assert any(a.name == "ingress_saturated"
                   for a in node.alarms.get_alarms("activated"))
        # with the saturation gone the monitor clears the alarm
        node.overload.tick(0.0)
        assert not any(a.name == "ingress_saturated"
                       for a in node.alarms.get_alarms("activated"))
        await pub.close()


# -- overload state machine + shedding ---------------------------------------


def test_overload_levels_hysteresis_and_alarm():
    node = _device_node(overload=OverloadConfig(
        lag_warn_ms=50, lag_critical_ms=500, clear_ticks=2))
    ov = node.overload
    assert ov.tick(10.0) == OK
    assert ov.tick(80.0) == WARN
    assert node.metrics.val("overload.transitions") == 1
    alarms = {a.name: a for a in node.alarms.get_alarms("activated")}
    assert alarms["overload"].details["level"] == "warn"
    assert ov.tick(900.0) == CRITICAL
    assert ov.reject_connects()
    # downgrade needs clear_ticks consecutive clean samples
    assert ov.tick(0.0) == CRITICAL
    assert ov.tick(0.0) == OK
    assert not any(a.name == "overload"
                   for a in node.alarms.get_alarms("activated"))


def test_queue_depth_drives_level_and_ingress_pressure():
    node = _device_node(overload=OverloadConfig(
        queue_warn=2.0, queue_critical=4.0, clear_ticks=1))
    ing = node.ingress
    ov = node.overload
    hw = ing.queue_hiwater
    ing._pending.extend([(None, None)] * (hw * 4))
    assert ov.tick(0.0) == CRITICAL
    # critical divides the effective high-water mark: backpressure
    # engages at a fraction of the configured mark
    del ing._pending[hw:]
    assert ing.backlogged()  # hw items >= hw//4 under pressure
    del ing._pending[hw // 8:]
    assert ing.backlogged() is (hw // 8 >= max(1, hw // 4))
    ing._pending.clear()
    assert ov.tick(0.0) == OK
    assert not ing.backlogged()


def test_warn_sheds_qos0_at_mqueue_pressure():
    node = _device_node()
    sess = Session("shed", broker=node.broker, max_mqueue_len=8,
                   mqueue_store_qos0=True)
    sess.connected = False
    for i in range(6):
        sess.enqueue(Message(topic="q/t", payload=b"%d" % i, qos=0))
    assert len(sess.mqueue) == 6
    node.overload.level = WARN
    sess.enqueue(Message(topic="q/t", payload=b"shed", qos=0))
    assert len(sess.mqueue) == 6  # dropped, not queued
    assert node.metrics.val("overload.shed.qos0") == 1
    # QoS1 still queues — the capacity shedding protects
    sess.enqueue(Message(topic="q/t", payload=b"keep", qos=1))
    assert len(sess.mqueue) == 7


async def test_critical_rejects_new_connects_server_busy():
    async with broker_node() as node:
        node.overload.level = CRITICAL
        v5 = TestClient("busy5", version=C.MQTT_V5)
        ack = await v5.connect(port=node_port(node))
        assert ack.reason_code == 0x89  # ServerBusy
        v3 = TestClient("busy3")
        ack = await v3.connect(port=node_port(node))
        assert ack.reason_code == 3     # compat: server unavailable
        assert node.metrics.val("overload.shed.connect") == 2
        node.overload.level = OK
        ok = TestClient("okc")
        ack = await ok.connect(port=node_port(node))
        assert ack.reason_code == 0
        await ok.close()
        for cli in (v5, v3):
            await cli.close()


def test_force_shutdown_policy_kills_oom_session():
    node = _device_node(overload=OverloadConfig(
        force_shutdown_queue_len=5))

    class Chan:
        def __init__(self, sess):
            self.session = sess
            self.client_id = sess.client_id
            self.kicked = False

        def kick(self, discard=False):
            self.kicked = True

    sess = Session("oom", broker=node.broker, max_mqueue_len=0,
                   mqueue_store_qos0=True)
    sess.connected = False
    for i in range(10):
        sess.enqueue(Message(topic="o/t", payload=b"%d" % i, qos=1))
    chan = Chan(sess)
    node.cm.register_channel("oom", chan)
    node.overload._sweep_force_shutdown()
    assert chan.kicked
    assert node.metrics.val("overload.force_shutdown") == 1
    assert node.cm.lookup_channel("oom") is None


def test_orphaned_counter_on_home_loop_gone_publish():
    """Satellite: the formerly-silent `return 0 # home loop gone`
    path now counts + logs the lost publish."""
    node = _device_node()

    class DeadLG:
        def on_home_thread(self):
            return False

        def post(self, idx, cb, *args):
            raise RuntimeError("loop closed")

    node.broker.loop_group = DeadLG()
    node.broker.ingress = None
    assert node.broker.publish(
        Message(topic="gone/t", payload=b"x")) == 0
    assert node.metrics.val("delivery.xloop.orphaned") == 1


# -- disabled-mode parity ----------------------------------------------------


def test_faults_disabled_sites_never_call_fire(monkeypatch):
    """The zero-cost-off pin: with nothing armed every site's guard
    is a dead branch — faults.fire is never reached."""
    def boom(point):
        raise AssertionError(f"fire({point!r}) called while disabled")

    monkeypatch.setattr(faults, "fire", boom)
    assert not faults.enabled
    node = _device_node()
    s = Sink()
    node.subscribe(s, "p/1")
    assert node.broker.publish_batch(
        [Message(topic="p/1", payload=b"x")]) == [1]
    assert len(s.got) == 1


async def _parity_workload(overload_cfg):
    """Mixed-QoS fan-out; returns (per-client wire tuples, delivery
    metric deltas) — the overload-on/off comparison payload."""
    async with broker_node(
            matcher=MatcherConfig(device_min_filters=0),
            overload=overload_cfg) as node:
        port = node_port(node)
        a = TestClient("pa")
        b = TestClient("pb", version=C.MQTT_V5)
        pub = TestClient("pp")
        for cli in (a, b, pub):
            await cli.connect(port=port)
        await a.subscribe("par/+", qos=1)
        await b.subscribe("par/t", qos=2)
        n = 0
        for i in range(3):
            await pub.publish("par/t", payload=b"m%d" % i, qos=1)
            n += 1
        await pub.publish("par/x", payload=b"x", qos=0)
        got = []
        for cli, want in ((a, n + 1), (b, n)):
            pkts = []
            for _ in range(want):
                p = await cli.recv()
                pkts.append((p.topic, bytes(p.payload), p.qos,
                             p.packet_id))
            pkts.sort(key=lambda t: t[1])
            got.append(pkts)
        metrics = {k: v for k, v in node.metrics.all().items()
                   if v and k.startswith(("messages.", "delivery.",
                                          "overload.", "breaker.",
                                          "faults."))}
        for cli in (a, b, pub):
            await cli.close()
        return got, metrics


async def test_overload_on_off_delivery_parity():
    """[overload] default-on in the OK state vs enabled=false: wire
    content and metric deltas identical — the robustness layer is
    invisible until something actually breaks."""
    on_wire, on_metrics = await _parity_workload(OverloadConfig())
    off_wire, off_metrics = await _parity_workload(
        OverloadConfig(enabled=False))
    assert on_wire == off_wire
    assert on_metrics == off_metrics  # no overload.*/breaker.* moved


# -- ctl surfaces ------------------------------------------------------------


def test_ctl_overload_and_faults_commands():
    import json

    node = _device_node()
    out = json.loads(node.ctl.run(["overload"]))
    assert out["enabled"] and out["level"] == "ok"
    assert out["breaker"]["state"] == "closed"
    assert node.ctl.run(["faults", "arm", "device.fetch:raise:2"]) \
        == "ok"
    info = json.loads(node.ctl.run(["faults"]))
    assert info["armed"]["device.fetch"]["action"] == "raise"
    assert node.ctl.run(["faults", "disarm", "device.fetch"]) == "ok"
    assert "unknown fault point" in node.ctl.run(
        ["faults", "arm", "nope"])
    assert node.ctl.run(["faults", "clear"]) == "ok"
    assert not faults.enabled


# -- device-loss recovery (devloss.py, docs/ROBUSTNESS.md) -------------------
#
# The contract: a LOST backend (every device call raises/hangs, not
# just one slow batch) is classified by the sentinel, the breaker
# enters REBUILDING, publishes ride the exact host oracle with zero
# lost or duplicated deliveries, all device-resident state rebuilds
# from host authority, the kernels re-warm off the hot path, and the
# half-open probe auto-closes the breaker — no process restart.


def _wait_for(cond, timeout=10.0, step=0.01):
    deadline = time.monotonic()
    deadline += timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return cond()


def _recovery_cfg(**over):
    kw = dict(breaker_failures=2, breaker_cooldown_s=30.0,
              rebuild_backoff_s=0.05, sentinel_timeout_s=1.0)
    kw.update(over)
    return OverloadConfig(**kw)


def test_device_lost_point_is_persistent():
    """The device.lost contract vs the times-bounded walk/fetch
    points: armed times=0, EVERY device call raises until disarmed
    (the backend is gone, not glitching)."""
    faults.arm("device.lost", times=0)
    for _ in range(5):
        with pytest.raises(faults.FaultInjected):
            faults.fire("device.lost")
    assert faults.enabled
    assert faults.disarm("device.lost")
    assert faults.fire("device.lost") is False
    # config knob validation rides along (closed schema)
    with pytest.raises(ValueError):
        OverloadConfig(rebuild_backoff_s=0.0)
    with pytest.raises(ValueError):
        OverloadConfig(sentinel_timeout_s=-1.0)


def test_device_loss_classifies_rebuilds_and_auto_closes():
    """The tentpole scenario at broker level: a lost backend trips
    the breaker, the sentinel classifies LOST (not transient), the
    breaker enters REBUILDING (cooldown_s=30 — any recovery must
    come through the rebuild, not the cooldown probe), rebuild
    attempts fail while the backend is still gone, and once it
    returns the rebuilt tables + re-warmed kernels admit the probe
    that closes the breaker. Deliveries are exact throughout."""
    node = _device_node(overload=_recovery_cfg())
    s = Sink()
    node.subscribe(s, "dl/+")
    node.subscribe(s, "dl/#")
    br = node.broker.breaker
    rec = br.recovery
    assert rec is not None
    # warm the device path so the loss is a regression, not a boot
    assert node.broker.publish_batch(
        [Message(topic="dl/t", payload=b"warm")]) == [2]
    epoch_before = node.router._rebuilds
    faults.arm("device.lost", times=0)
    try:
        # every batch during the outage host-matches exactly
        for i in range(3):
            assert node.broker.publish_batch(
                [Message(topic="dl/t", payload=b"out%d" % i)]) == [2]
        assert br.state in (DeviceBreaker.OPEN,
                            DeviceBreaker.REBUILDING)
        # classification runs off the hot path; the sentinel cannot
        # answer -> REBUILDING, device matching suspended
        assert _wait_for(lambda: br.state == DeviceBreaker.REBUILDING)
        assert rec.last_classification == "lost"
        assert node.router.device_suspended()
        assert any(a.name == "device_path_lost"
                   for a in node.alarms.get_alarms("activated"))
        # rebuild attempts fail while the backend is still gone
        assert _wait_for(lambda: rec.rebuild_failures >= 1)
        assert node.metrics.val("breaker.rebuild.failures") >= 1
        # publishes still serve, host-only, mid-rebuild
        assert node.broker.publish_batch(
            [Message(topic="dl/t", payload=b"mid")]) == [2]
    finally:
        faults.disarm("device.lost")
    # the backend is back: the next attempt rebuilds + re-warms and
    # arms the half-open window (NOT the 30s cooldown clock)
    assert _wait_for(lambda: br.state == DeviceBreaker.HALF_OPEN)
    assert rec.rebuilds == 1
    assert node.metrics.val("breaker.rebuilds") == 1
    assert rec.last_rebuild_s is not None
    assert not node.router.device_suspended()
    assert node.router._rebuilds > epoch_before  # fresh tables
    # the probe batch rides the rebuilt tables and closes the breaker
    assert node.broker.publish_batch(
        [Message(topic="dl/t", payload=b"probe")]) == [2]
    assert br.state == DeviceBreaker.CLOSED
    assert not any(a.name in ("device_path_lost",
                              "device_path_breaker")
                   for a in node.alarms.get_alarms("activated"))
    # zero lost, zero duplicated across the whole episode
    assert sorted(p for _f, _t, p in s.got) == sorted(
        2 * [b"warm", b"out0", b"out1", b"out2", b"mid", b"probe"])
    # ctl surfaces the recovery fields
    import json as _json
    out = _json.loads(node.ctl.run(["overload"]))
    assert out["breaker"]["state"] == "closed"
    assert out["breaker"]["rebuilds"] == 1
    assert out["breaker"]["classification"] == "lost"
    assert out["breaker"]["last_rebuild_s"] is not None


def test_device_loss_double_loss_mid_rebuild():
    """The device dies AGAIN mid-recovery: after the lost
    classification, the first attempts fail against the still-dead
    backend; then the rebuild itself succeeds but the warmup phase
    dies (device.fetch) — the attempt counts as failed and retries,
    and only a fully clean rebuild+warm admits the probe."""
    node = _device_node(overload=_recovery_cfg(breaker_failures=1))
    s = Sink()
    node.subscribe(s, "dd/1")
    assert node.broker.publish_batch(
        [Message(topic="dd/1", payload=b"warm")]) == [1]
    br = node.broker.breaker
    rec = br.recovery
    faults.arm("device.lost", times=0)
    try:
        assert node.broker.publish_batch(
            [Message(topic="dd/1", payload=b"out")]) == [1]
        assert _wait_for(lambda: rec.rebuild_failures >= 1)
        # the backend returns... but dies again during kernel warmup
        faults.arm("device.fetch", action="raise", times=1)
    finally:
        faults.disarm("device.lost")
    assert _wait_for(lambda: br.state == DeviceBreaker.HALF_OPEN)
    assert rec.rebuild_failures >= 2  # dead-backend + mid-warm death
    assert rec.rebuilds == 1
    assert node.broker.publish_batch(
        [Message(topic="dd/1", payload=b"probe")]) == [1]
    assert br.state == DeviceBreaker.CLOSED
    assert sorted(p for _f, _t, p in s.got) == \
        [b"out", b"probe", b"warm"]


def test_half_open_single_probe_invariant():
    """Satellite pin: concurrent batches arriving during the
    half-open window must not all ride the device — exactly ONE
    probe is admitted; and a stale pre-trip success can neither
    close an OPEN breaker nor preempt a rebuild."""
    import threading

    from emqx_tpu.metrics import Metrics
    br = DeviceBreaker(Metrics(), failures=1, cooldown_s=0.05)
    br.record_failure()
    assert br.state == DeviceBreaker.OPEN
    # a pre-trip in-flight batch completing late must NOT close it
    br.record_success()
    assert br.state == DeviceBreaker.OPEN
    time.sleep(0.06)
    results = []
    barrier = threading.Barrier(8)

    def probe():
        barrier.wait()
        results.append(br.allow_device())

    ts = [threading.Thread(target=probe) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert sum(results) == 1  # exactly one probe admitted
    assert br.state == DeviceBreaker.HALF_OPEN
    assert br.allow_device() is False  # probe still in flight
    br.record_success()
    assert br.state == DeviceBreaker.CLOSED
    # REBUILDING admits no probe even past any cooldown, ignores
    # stale successes, and only rebuild_complete re-arms the window
    br2 = DeviceBreaker(Metrics(), failures=1, cooldown_s=0.01)
    br2.record_failure()
    assert br2.enter_rebuilding()
    time.sleep(0.03)
    assert br2.allow_device() is False
    br2.record_success()
    assert br2.state == DeviceBreaker.REBUILDING
    br2.rebuild_complete()
    assert br2.state == DeviceBreaker.HALF_OPEN
    assert br2.allow_device() is True
    br2.record_success()
    assert br2.state == DeviceBreaker.CLOSED


def test_breaker_fallback_never_rides_device():
    """While the breaker is OPEN or REBUILDING the oracle fallback
    must not re-enter the device plane through any seam — with a
    truly lost backend the fallback itself would raise. Pin it by
    making every router device entry explode."""
    node = _device_node(overload=_recovery_cfg(breaker_failures=1))
    s = Sink()
    node.subscribe(s, "ho/1")
    assert node.broker.publish_batch(
        [Message(topic="ho/1", payload=b"warm")]) == [1]

    def boom(*a, **k):
        raise AssertionError("device path entered during fallback")

    faults.arm("device.lost", times=0)
    try:
        assert node.broker.publish_batch(
            [Message(topic="ho/1", payload=b"trip")]) == [1]
        assert _wait_for(
            lambda: node.broker.breaker.state
            == DeviceBreaker.REBUILDING)
        node.router.match_dispatch = boom
        node.router.match_ids = boom
        node.router._dispatch_sharded = boom
        # breaker fallback, host regime probe, retained-style lookups
        assert node.broker.publish_batch(
            [Message(topic="ho/1", payload=b"fb")]) == [1]
        assert [r.dest for r in node.router.match_routes("ho/1")] \
            == [node.broker.node]
    finally:
        # restore the seams BEFORE the backend "returns": the
        # background recovery warms through them the moment the
        # fault disarms
        for name in ("match_dispatch", "match_ids",
                     "_dispatch_sharded"):
            node.router.__dict__.pop(name, None)
        faults.disarm("device.lost")
    assert sorted(p for _f, _t, p in s.got) == \
        [b"fb", b"trip", b"warm"]


def test_rebuild_under_route_churn_parity():
    """Route ops arriving DURING the rebuild window complete and the
    rebuilt automaton matches the host oracle byte-exactly on the
    churned filter set (the PR 7 freeze protocol carries them into
    the fresh tables + next delta generation)."""
    node = _device_node(overload=_recovery_cfg(breaker_failures=1))
    sinks = {f"rc/{i}": Sink() for i in range(6)}
    for flt, s in sinks.items():
        node.subscribe(s, flt)
    assert node.broker.publish_batch(
        [Message(topic="rc/0", payload=b"warm")]) == [1]
    br = node.broker.breaker
    rec = br.recovery
    faults.arm("device.lost", times=0)
    late = Sink()
    try:
        assert node.broker.publish_batch(
            [Message(topic="rc/0", payload=b"trip")]) == [1]
        assert _wait_for(lambda: rec.rebuild_failures >= 1)
        # stretch the successful attempt's flatten so churn lands in
        # the freeze window (stall = sleep then proceed normally)
        faults.arm("compaction.flatten", action="stall", times=1,
                   delay_ms=300.0)
    finally:
        faults.disarm("device.lost")
    # churn while the rebuild flatten runs off-lock: adds, deletes,
    # and a brand-new wildcard — all must land in the fresh tables
    t0 = time.monotonic()
    node.subscribe(late, "rc/late/+")
    node.subscribe(late, "rc/0")
    node.broker.unsubscribe(sinks["rc/5"], "rc/5")
    churn_s = time.monotonic() - t0
    assert _wait_for(lambda: br.state == DeviceBreaker.HALF_OPEN,
                     timeout=15.0)
    assert churn_s < 5.0  # route ops did not ride the whole flatten
    assert node.broker.publish_batch(
        [Message(topic="rc/0", payload=b"probe")]) == [2]
    assert br.state == DeviceBreaker.CLOSED
    # parity: device match vs host oracle over the churned set
    topics = [f"rc/{i}" for i in range(6)] + ["rc/late/x", "rc/none"]
    dev = node.router.match_filters(topics)
    host = node.router.match_filters_host(topics)
    assert [sorted(r) for r in dev] == [sorted(r) for r in host]
    assert sorted(dev[0]) == ["rc/0"]
    assert dev[5] == []                     # deleted mid-rebuild
    assert dev[6] == ["rc/late/+"]          # added mid-rebuild
    # the mid-rebuild subscriber actually receives
    assert node.broker.publish_batch(
        [Message(topic="rc/late/x", payload=b"new")]) == [1]
    assert late.got[-1][2] == b"new"


def test_breaker_rebuild_off_is_legacy_open_forever():
    """[overload] breaker_rebuild = false: no recovery manager — a
    lost backend leaves the breaker cycling OPEN exactly as PR 8
    shipped it (the pre-recovery behavior, selectable)."""
    node = _device_node(overload=_recovery_cfg(
        breaker_rebuild=False, breaker_failures=1,
        breaker_cooldown_s=0.1))
    s = Sink()
    node.subscribe(s, "lg/1")
    br = node.broker.breaker
    assert br.recovery is None
    assert node.broker.publish_batch(
        [Message(topic="lg/1", payload=b"warm")]) == [1]
    faults.arm("device.lost", times=0)
    try:
        assert node.broker.publish_batch(
            [Message(topic="lg/1", payload=b"t")]) == [1]
        assert br.state == DeviceBreaker.OPEN
        time.sleep(0.12)
        # the cooldown probe re-executes against the dead backend,
        # fails, and re-opens — forever, by design with rebuild off
        assert node.broker.publish_batch(
            [Message(topic="lg/1", payload=b"p")]) == [1]
        assert br.state == DeviceBreaker.OPEN
        assert br.state != DeviceBreaker.REBUILDING
    finally:
        faults.disarm("device.lost")
    time.sleep(0.12)
    assert node.broker.publish_batch(
        [Message(topic="lg/1", payload=b"ok")]) == [1]
    assert br.state == DeviceBreaker.CLOSED
    assert len(s.got) == 4


async def test_device_loss_qos1_live_zero_lost_or_duplicated(tmp_path):
    """The acceptance scenario over real sockets: kill the device
    mid-stream under DURABLE QoS1 traffic (journal flushing from the
    very fetch seam that is failing), keep publishing through
    fallback -> rebuild -> close, and assert every payload was
    delivered exactly once — zero lost, zero duplicated, no process
    restart."""
    from emqx_tpu.durability import DurabilityConfig
    async with broker_node(
            matcher=MatcherConfig(device_min_filters=0),
            durability=DurabilityConfig(
                enabled=True, dir=str(tmp_path / "dur"), fsync=False),
            overload=_recovery_cfg(breaker_failures=1,
                                   sentinel_timeout_s=0.5)) as node:
        port = node_port(node)
        sub = TestClient("dlsub")
        pub = TestClient("dlpub")
        await sub.connect(port=port)
        await pub.connect(port=port)
        await sub.subscribe("dl/t", qos=1)
        br = node.broker.breaker
        sent = []

        async def send(i):
            payload = b"m%03d" % i
            await pub.publish("dl/t", payload=payload, qos=1)
            sent.append(payload)

        for i in range(5):          # warm device regime
            await send(i)
        faults.arm("device.lost", times=0)
        try:
            for i in range(5, 15):  # the outage window
                await send(i)
            assert _wait_for(
                lambda: br.state == DeviceBreaker.REBUILDING,
                timeout=10.0)
            for i in range(15, 20):  # mid-rebuild traffic
                await send(i)
        finally:
            faults.disarm("device.lost")
        # keep publishing until a probe closes the breaker
        i = 20
        deadline = time.monotonic() + 20.0
        while br.state != DeviceBreaker.CLOSED \
                and time.monotonic() < deadline:
            await send(i)
            i += 1
            await asyncio.sleep(0.05)
        assert br.state == DeviceBreaker.CLOSED
        for j in range(i, i + 3):   # post-recovery device traffic
            await send(j)
        got = []
        for _ in sent:
            got.append(bytes((await sub.recv(timeout=10.0)).payload))
        assert sorted(got) == sorted(sent)  # exact, no loss, no dup
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.3)     # and nothing extra
        assert node.metrics.val("breaker.rebuilds") == 1
        await sub.close()
        await pub.close()
