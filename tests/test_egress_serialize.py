"""Egress pre-serialization (ops/dispatch_plan.preserialize_plan +
Channel._wire_template + mqtt.frame.publish_template, docs/DISPATCH.md
"Egress pre-serialization"): golden-byte pid-patch fuzz against
``wire_serialize`` with the independent ``tests/indie_mqtt.py`` codec
as the second opinion, preserialize-on vs -off parity (wire bytes,
pid sequences, inflight, metric deltas) across QoS0/1/2 × v3/v4/v5 ×
retain/dup/subid/shared cases, the effective-QoS-in-key regression
for the shared wire image cache, the on-loop serialize counter, and
the ``[dispatch] preserialize`` config schema."""

import asyncio
import random

import pytest

from tests import indie_mqtt as im
from emqx_tpu.broker import Broker, DispatchConfig
from emqx_tpu.channel import Channel
from emqx_tpu.cm import ConnectionManager
from emqx_tpu.config import ConfigError, parse_config
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.frame import FrameError, publish_template
from emqx_tpu.mqtt.frame import serialize as wire_serialize
from emqx_tpu.mqtt.packet import Connect, Publish
from emqx_tpu.router import MatcherConfig, Router
from emqx_tpu.session import Session
from emqx_tpu.types import Message, SubOpts

VERSIONS = (C.MQTT_V3, C.MQTT_V4, C.MQTT_V5)

# v5 property sets a template may legally carry (per-delivery rewrites
# — Message-Expiry-Interval, Subscription-Identifier — are routed to
# the slow path by the planner and never enter a template; the codec
# itself doesn't care, so the fuzz includes an expiry case too)
PROP_SETS = (
    {},
    {"Content-Type": "application/json"},
    {"User-Property": [("a", "b"), ("c", "d")]},
    {"Payload-Format-Indicator": 1, "Response-Topic": "r/t"},
    {"Correlation-Data": b"\x00\xffcorr"},
    {"Message-Expiry-Interval": 30},
)

PIDS = (1, 0x7F, 0x80, 0xFF, 0x100, 0x1234, 0x7FFF, 0x8000, 0xFFFF)


def _indie_decode(frame: bytes, version: int):
    """Split a serialized frame with the INDEPENDENT codec's own
    primitives and decode the body — no emqx_tpu parser involved."""
    rl, boff = im.dec_varint(frame, 1)
    body = bytes(frame[boff:])
    assert len(body) == rl
    return im.decode(frame[0] >> 4, frame[0] & 0x0F, body,
                     5 if version == C.MQTT_V5 else 4)


# -- golden-byte template fuzz --------------------------------------------


def test_template_pid_patch_matches_serialize_fuzz():
    rng = random.Random(0xE5)
    alphabet = "abcdefg/μτ0"
    for _ in range(150):
        ver = rng.choice(VERSIONS)
        qos = rng.choice((1, 2))
        retain = bool(rng.randrange(2))
        dup = bool(rng.randrange(2))
        topic = "".join(rng.choice(alphabet)
                        for _ in range(rng.randint(1, 60)))
        payload = rng.randbytes(rng.randrange(0, 200))
        props = dict(rng.choice(PROP_SETS)) if ver == C.MQTT_V5 else {}
        tpl, off = publish_template(
            Publish(topic=topic, payload=payload, qos=qos,
                    retain=retain, dup=dup, packet_id=0x0B0B,
                    properties=dict(props)), ver)
        for pid in rng.sample(PIDS, 4):
            buf = bytearray(tpl)
            buf[off] = (pid >> 8) & 0xFF
            buf[off + 1] = pid & 0xFF
            patched = bytes(buf)
            assert patched == wire_serialize(
                Publish(topic=topic, payload=payload, qos=qos,
                        retain=retain, dup=dup, packet_id=pid,
                        properties=dict(props)), ver)
            # second opinion: the independent codec must read back
            # exactly what the template claims to carry
            p = _indie_decode(patched, ver)
            assert (p.ptype, p.topic, p.payload, p.qos, p.retain,
                    p.dup, p.pkt_id) == (im.PUBLISH, topic, payload,
                                         qos, retain, dup, pid)
            if ver == C.MQTT_V5:
                assert p.props == props


def test_template_alias_variant_empty_topic():
    # v5 outbound topic alias: empty topic + Topic-Alias property —
    # the pid offset derivation must hold at topic length 0
    tpl, off = publish_template(
        Publish(topic="", payload=b"x", qos=1, packet_id=0,
                properties={"Topic-Alias": 5}), C.MQTT_V5)
    buf = bytearray(tpl)
    buf[off:off + 2] = (0xBEEF).to_bytes(2, "big")
    p = _indie_decode(bytes(buf), C.MQTT_V5)
    assert p.topic == "" and p.pkt_id == 0xBEEF
    assert p.props == {"Topic-Alias": 5}


def test_template_refuses_qos0():
    with pytest.raises(FrameError):
        publish_template(Publish(topic="t", qos=0), C.MQTT_V4)


# -- preserialize_plan: what gets primed, what stays slow -----------------


def _hinted_session(broker, cid, ver=C.MQTT_V4, upgrade=False):
    s = Session(cid, broker=broker, upgrade_qos=upgrade)
    s.proto_ver = ver
    s.wire_fast_hint = True
    return s


def _device_broker(preserialize=True, **mk):
    mk.setdefault("device_min_filters", 0)
    return Broker(router=Router(MatcherConfig(**mk), node="n1"),
                  dispatch_config=DispatchConfig(
                      preserialize=preserialize))


def test_preserialize_primes_templates_and_images():
    b = _device_broker()
    s1 = _hinted_session(b, "t1")                    # qos1 template
    s0 = _hinted_session(b, "t0")                    # downgrade to 0
    s5 = _hinted_session(b, "t5", ver=C.MQTT_V5)     # v5 template
    s1.subscribe("p/t", SubOpts(qos=1))
    s0.subscribe("p/t", SubOpts(qos=0))
    s5.subscribe("p/t", SubOpts(qos=2))
    msg = Message(topic="p/t", payload=b"pay", qos=1, from_="pub")
    pb = b.publish_begin([msg])
    assert not pb.done
    b.publish_fetch(pb)
    assert pb.plan is not None
    tpl = msg.headers["_wiretpl"]
    wire = msg.headers["_wire"]
    # qos1 v4 template, qos1 v5 template (granted 2 caps at msg qos 1)
    assert set(tpl) == {(C.MQTT_V4, 1, False, False),
                        (C.MQTT_V5, 1, False, False)}
    # the downgraded-to-QoS0 copy's image keys with qos 0 — the
    # effective-QoS-in-key rule: it can never serve the QoS1 bytes
    assert set(wire) == {(C.MQTT_V4, 0, False, False)}
    data, off = tpl[(C.MQTT_V4, 1, False, False)]
    buf = bytearray(data)
    buf[off:off + 2] = (42).to_bytes(2, "big")
    assert bytes(buf) == wire_serialize(
        Publish(topic="p/t", payload=b"pay", qos=1, packet_id=42),
        C.MQTT_V4)
    assert wire[(C.MQTT_V4, 0, False, False)] == wire_serialize(
        Publish(topic="p/t", payload=b"pay", qos=0), C.MQTT_V4)
    assert wire[(C.MQTT_V4, 0, False, False)] != bytes(data)
    # finish still delivers normally
    assert b.publish_finish(pb) == [3]
    assert [pid for pid, _ in s1.outbox] == [1]
    assert [pid for pid, _ in s0.outbox] == [None]


def test_preserialize_skips_per_session_rewrites():
    b = _device_broker()
    s_subid = _hinted_session(b, "sid", ver=C.MQTT_V5)
    s_share = _hinted_session(b, "shr")
    s_nohint = Session("noh", broker=b)   # no channel hints
    s_subid.subscribe("q/t", SubOpts(qos=1, subid=9))
    s_share.subscribe("$share/g/q/t", SubOpts(qos=1))
    s_nohint.subscribe("q/t", SubOpts(qos=1))
    msg = Message(topic="q/t", qos=1, from_="pub")
    pb = b.publish_begin([msg])
    b.publish_fetch(pb)
    assert pb.plan is not None
    # nothing eligible: subid and shared are per-delivery rewrites,
    # the hintless session might need a mountpoint/alias rewrite
    assert not msg.headers.get("_wiretpl")
    assert not msg.headers.get("_wire")
    b.publish_finish(pb)


def test_preserialize_skips_expiry_messages():
    b = _device_broker()
    s = _hinted_session(b, "e1")
    s.subscribe("x/t", SubOpts(qos=1))
    msg = Message(topic="x/t", qos=1, from_="pub")
    msg.set_header("properties", {"Message-Expiry-Interval": 60})
    pb = b.publish_begin([msg])
    b.publish_fetch(pb)
    assert "_wiretpl" not in msg.headers
    b.publish_finish(pb)


# -- session-state parity: preserialize must not perturb delivery ---------


def _metric_deltas(broker):
    return {k: v for k, v in broker.metrics.all().items()
            if v and (k.startswith("messages.")
                      or k.startswith("delivery."))
            and k != "delivery.serialize.onloop"}


def test_session_state_parity_preser_on_off():
    outs = []
    for preser in (True, False):
        b = _device_broker(preserialize=preser)
        sess = [_hinted_session(b, f"s{i}") for i in range(3)]
        sess[0].subscribe("m/+", SubOpts(qos=1))
        sess[1].subscribe("m/a", SubOpts(qos=2))
        sess[2].subscribe("m/#", SubOpts(qos=0))
        for _ in range(3):
            b.publish_batch([Message(topic="m/a", qos=2, from_="p"),
                             Message(topic="m/b", qos=1, from_="p"),
                             Message(topic="m/a", qos=0, from_="p")])
        outs.append((
            [[(pid, m.topic, m.qos, m.flags.get("dup", False))
              for pid, m in s.outbox] for s in sess],
            [sorted(pid for pid, _ in s.inflight.to_list())
             for s in sess],
            _metric_deltas(b)))
    assert outs[0] == outs[1]


# -- wire-level parity through real connections ---------------------------


async def _egress_run(preserialize: bool):
    from helpers import broker_node, node_port
    from mqtt_client import TestClient

    async with broker_node(
            matcher=MatcherConfig(device_min_filters=0),
            dispatch_config=DispatchConfig(
                preserialize=preserialize)) as node:
        port = node_port(node)
        a0 = TestClient("a0")                     # v4 qos0
        a1 = TestClient("a1")                     # v4 qos1
        a2 = TestClient("a2", version=C.MQTT_V5)  # v5 qos2
        a3 = TestClient("a3", version=C.MQTT_V5)  # v5 subid slow path
        g1 = TestClient("g1")                     # shared group
        g2 = TestClient("g2")
        pub = TestClient("wp")
        pub5 = TestClient("wp5", version=C.MQTT_V5)
        clients = [a0, a1, a2, a3, g1, g2, pub, pub5]
        for cli in clients:
            await cli.connect(port=port)
        await a0.subscribe("e/+", qos=0)
        await a1.subscribe("e/#", qos=1)
        await a2.subscribe("e/t", qos=2)
        await a3.subscribe("e/+", qos=1,
                           props={"Subscription-Identifier": 7})
        await g1.subscribe("$share/g/e/t", qos=1)
        await g2.subscribe("$share/g/e/t", qos=1)
        expect = {a0: 0, a1: 0, a2: 0, a3: 0}
        for i in range(3):
            await pub.publish("e/t", payload=b"q0-%d" % i, qos=0)
            expect[a0] += 1
            expect[a1] += 1
            expect[a2] += 1
            expect[a3] += 1
        for i in range(4):
            await pub.publish("e/t", payload=b"q1-%d" % i, qos=1)
        await pub.publish("e/x", payload=b"q1-x", qos=1)
        expect[a0] += 5
        expect[a1] += 5
        expect[a2] += 4
        expect[a3] += 5
        for i in range(2):
            await pub.publish("e/t", payload=b"q2-%d" % i, qos=2)
        await pub.publish("e/t", payload=b"rt", qos=1, retain=True)
        expect[a0] += 3
        expect[a1] += 3
        expect[a2] += 3
        expect[a3] += 3
        # v5 publisher: pass-through properties + per-delivery expiry
        await pub5.publish("e/t", payload=b"v5p", qos=1,
                           props={"User-Property": [("k", "v")],
                                  "Payload-Format-Indicator": 1})
        await pub5.publish("e/t", payload=b"v5e", qos=1,
                           props={"Message-Expiry-Interval": 120})
        for cli in (a0, a1, a2, a3):
            expect[cli] += 2
        got = []
        for cli in (a0, a1, a2, a3):
            pkts = []
            for _ in range(expect[cli]):
                p = await cli.recv(timeout=5.0)
                props = {k: v for k, v in (p.properties or {}).items()
                         if k != "Message-Expiry-Interval"}
                pkts.append((p.topic, bytes(p.payload), p.qos,
                             p.retain, p.dup, p.packet_id, props))
            pkts.sort(key=lambda t: t[1])  # batch tick grouping may
            # interleave topics; per-payload identity is the contract
            got.append(pkts)
        # shared group: totals must match even if the pick rotates
        shared_total = 0
        for cli in (g1, g2):
            try:
                while True:
                    await asyncio.wait_for(cli.inbox.get(), 0.5)
                    shared_total += 1
            except asyncio.TimeoutError:
                pass
        got.append(shared_total)
        got.append({k: v for k, v in node.metrics.all().items()
                    if v and (k.startswith(("messages.", "delivery.",
                                            "packets.publish")))
                    and k != "delivery.serialize.onloop"})
        onloop = node.metrics.val("delivery.serialize.onloop")
        for cli in clients:
            await cli.close()
        return got, onloop


async def test_wire_parity_preser_on_vs_off():
    on, onloop_on = await _egress_run(True)
    off, onloop_off = await _egress_run(False)
    assert on == off
    # the A/B signal: pre-serialization moved the eligible serializes
    # off the loop; the legacy pass did every one of them on-loop
    assert onloop_on < onloop_off
    # subid subscriber saw its Subscription-Identifier (slow path)
    a3_pkts = on[3]
    assert all(p[6].get("Subscription-Identifier") == 7
               for p in a3_pkts)


async def test_onloop_counter_zero_for_eligible_qos1_fanout():
    from helpers import broker_node, node_port
    from mqtt_client import TestClient

    for preser, expect_zero in ((True, True), (False, False)):
        async with broker_node(
                matcher=MatcherConfig(device_min_filters=0),
                dispatch_config=DispatchConfig(
                    preserialize=preser)) as node:
            port = node_port(node)
            subs = [TestClient(f"k{i}") for i in range(2)]
            pub = TestClient("kp")
            for cli in subs + [pub]:
                await cli.connect(port=port)
            for cli in subs:
                await cli.subscribe("k/+", qos=1)
            for i in range(6):
                await pub.publish("k/t", payload=b"%d" % i, qos=1)
            for cli in subs:
                for _ in range(6):
                    await cli.recv(timeout=5.0)
            onloop = node.metrics.val("delivery.serialize.onloop")
            if expect_zero:
                assert onloop == 0, onloop
            else:
                assert onloop == 12, onloop  # every delivery
            for cli in subs + [pub]:
                await cli.close()


# -- effective-QoS key regression (satellite) ------------------------------


def _mk_channel(broker, cid, ver=C.MQTT_V4):
    cm = ConnectionManager()
    ch = Channel(broker, cm)
    ch.wire_fast = True
    out = ch.handle_in(Connect(client_id=cid, proto_ver=ver,
                               proto_name=C.PROTOCOL_NAMES[ver]))
    assert out and out[0].type == C.CONNACK
    return ch


def test_wire_cache_keys_by_effective_qos():
    b = Broker()  # host path is fine: the cache is channel-side
    ch = _mk_channel(b, "wc")
    ch.session.subscribe("z/t", SubOpts(qos=0))
    orig = Message(topic="z/t", payload=b"zz", qos=1, from_="p")
    orig.headers["_wire"] = {}
    # a hostile prior: a QoS1 frame somehow cached under qos byte 1
    q1_frame = wire_serialize(
        Publish(topic="z/t", payload=b"zz", qos=1, packet_id=7),
        C.MQTT_V4)
    orig.headers["_wire"][(C.MQTT_V4, 1, False, False)] = q1_frame
    # deliver: downgraded-to-QoS0 copy shares the dict but must key
    # (and build) under qos 0 — never serve the QoS1 bytes
    ch.session.deliver("z/t", orig)
    out = ch.handle_deliver()
    assert len(out) == 1 and type(out[0]) is bytes
    assert out[0] != q1_frame
    assert out[0] == wire_serialize(
        Publish(topic="z/t", payload=b"zz", qos=0), C.MQTT_V4)
    assert orig.headers["_wire"][(C.MQTT_V4, 0, False, False)] \
        == out[0]


def test_template_variant_miss_builds_on_loop_and_caches():
    b = Broker()
    ch = _mk_channel(b, "tm")
    ch.session.subscribe("y/t", SubOpts(qos=1))
    msg = Message(topic="y/t", payload=b"yy", qos=1, from_="p")
    msg.headers["_wiretpl"] = {}  # primed dict, but no variant yet
    base = b.metrics.val("delivery.serialize.onloop")
    ch.session.deliver("y/t", msg)
    out = ch.handle_deliver()
    assert len(out) == 1 and type(out[0]) is bytes
    pid = ch.session.inflight.to_list()[0][0]
    assert out[0] == wire_serialize(
        Publish(topic="y/t", payload=b"yy", qos=1, packet_id=pid),
        C.MQTT_V4)
    # the miss built (and counted) ONE on-loop serialize, then cached
    assert b.metrics.val("delivery.serialize.onloop") == base + 1
    assert (C.MQTT_V4, 1, False, False) in msg.headers["_wiretpl"]


# -- [dispatch] config schema ---------------------------------------------


def test_dispatch_preserialize_config_schema():
    cfg = parse_config({"dispatch": {"preserialize": False}})
    assert cfg.dispatch is not None
    assert cfg.dispatch.preserialize is False
    assert cfg.dispatch.planner is True
    assert DispatchConfig().preserialize is True
    with pytest.raises(ConfigError, match="unknown dispatch setting"):
        parse_config({"dispatch": {"preserialise": False}})
    with pytest.raises(ConfigError, match="must be a boolean"):
        parse_config({"dispatch": {"preserialize": 1}})
