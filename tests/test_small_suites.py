"""Dedicated suites for the small utility modules, mirroring the
reference's emqx_keepalive_SUITE / emqx_mountpoint_SUITE /
emqx_tracer_SUITE and the esockd rate-limit behavior emqx_limiter
wraps."""

import time

import pytest

from emqx_tpu.keepalive import Keepalive
from emqx_tpu.limiter import TokenBucket
from emqx_tpu.mountpoint import mount, replvar, unmount
from emqx_tpu.tracer import Tracer
from emqx_tpu.types import Message


# -- emqx_keepalive_SUITE ---------------------------------------------------

def test_keepalive_byte_delta():
    ka = Keepalive(interval=60)
    assert ka.check_interval() == 90.0  # 1.5x per the MQTT spec
    assert not ka.check(0)     # no bytes ever: dead
    assert ka.check(100)       # progress
    assert not ka.check(100)   # idle for a full interval: dead
    assert ka.check(150)


# -- emqx_mountpoint_SUITE --------------------------------------------------

def test_mountpoint_mount_unmount_roundtrip():
    mp = "tenant-a/"
    assert mount(mp, "dev/1") == "tenant-a/dev/1"
    assert unmount(mp, "tenant-a/dev/1") == "dev/1"
    assert unmount(mp, "other/dev") == "other/dev"  # foreign topic
    assert mount(None, "t") == "t"
    assert unmount(None, "t") == "t"
    assert mount("", "t") == "t"


def test_mountpoint_replvar():
    assert replvar("%c/", client_id="c1") == "c1/"
    assert replvar("u/%u/c/%c/", client_id="c1",
                   username="alice") == "u/alice/c/c1/"
    # no username: %u stays (the reference substitutes only known vars)
    assert replvar("%u/", client_id="c1") == "%u/"
    assert replvar(None, client_id="c1") is None
    assert replvar("", client_id="c1") == ""


# -- limiter (esockd_rate_limit semantics) ----------------------------------

def test_token_bucket_burst_then_pause():
    tb = TokenBucket(rate=100.0, burst=10.0)
    for _ in range(10):
        assert tb.consume(1.0) == 0.0  # burst capacity is free
    pause = tb.consume(5.0)
    assert pause > 0.0                 # exhausted: caller must pause
    assert pause == pytest.approx(5.0 / 100.0, rel=0.3)


def test_token_bucket_refills_with_time():
    tb = TokenBucket(rate=1000.0, burst=5.0)
    tb.consume(5.0)
    assert not tb.check(5.0)
    time.sleep(0.01)                   # ~10 tokens refilled, cap 5
    assert tb.check(5.0)
    assert tb.consume(5.0) == 0.0


def test_token_bucket_check_does_not_consume():
    tb = TokenBucket(rate=10.0, burst=2.0)
    assert tb.check(2.0)
    assert tb.check(2.0)               # peeking twice changes nothing
    assert tb.consume(2.0) == 0.0


# -- emqx_tracer_SUITE ------------------------------------------------------

def _msg(topic, payload=b"x", from_="c1"):
    return Message(topic=topic, payload=payload, from_=from_)


def test_tracer_topic_filter():
    t = Tracer()
    sink = t.start_trace("topic", "a/b")
    t.trace_publish(_msg("a/b"))
    t.trace_publish(_msg("other"))
    assert len(sink) == 1 and "a/b" in sink[0]
    assert t.lookup_traces() == [("topic", "a/b")]
    assert t.stop_trace("topic", "a/b")
    t.trace_publish(_msg("a/b"))
    assert len(sink) == 1              # stopped: nothing more


def test_tracer_clientid_filter_and_double_start():
    t = Tracer()
    sink = t.start_trace("clientid", "c9")
    t.trace_publish(_msg("t", from_="c9"))
    t.trace_publish(_msg("t", from_="other"))
    assert len(sink) == 1
    with pytest.raises(ValueError):
        t.start_trace("clientid", "c9")  # already_traced
    assert not t.stop_trace("clientid", "unknown")


def test_tracer_independent_instances():
    t1, t2 = Tracer(), Tracer()
    s1 = t1.start_trace("topic", "x")
    t2.trace_publish(_msg("x"))        # t2 has no traces: no-op
    assert s1 == []
    t1.trace_publish(_msg("x"))
    assert len(s1) == 1
