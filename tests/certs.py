"""Self-signed certificate fixtures for the TLS suites.

Plays the role of the reference's static ``test/certs/`` directory
(test/emqx_client_SUITE.erl:78-86 drives one- and two-way SSL with
cacert/cert/key fixtures) — generated at test time with
``cryptography`` instead of checked-in PEMs.
"""

from __future__ import annotations

import datetime
import ipaddress
import os

import pytest

# optional dependency: importing this helper from a suite without
# cryptography installed must SKIP that suite at collection, not
# error it out of the report (tier-1 hygiene — a collection error
# here masked real regressions in the importing modules)
pytest.importorskip("cryptography")

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

_NOW = datetime.datetime(2026, 1, 1, tzinfo=datetime.timezone.utc)
_EXP = _NOW + datetime.timedelta(days=3650)


def _name(cn: str) -> x509.Name:
    return x509.Name([
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, "emqx_tpu-test"),
        x509.NameAttribute(NameOID.COMMON_NAME, cn),
    ])


def _write_key(path: str, key) -> None:
    with open(path, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))


def _write_cert(path: str, cert) -> None:
    with open(path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))


def generate_cert_chain(dirpath: str) -> dict:
    """CA + server cert (SAN 127.0.0.1/localhost) + client cert.

    Returns {"cacert", "cert", "key", "client_cert", "client_key"}
    paths — the same roles as test/certs/{cacert,cert,key,
    client-cert,client-key}.pem in the reference.
    """
    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(_name("emqx-tpu-test-ca"))
        .issuer_name(_name("emqx-tpu-test-ca"))
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(_NOW).not_valid_after(_EXP)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .sign(ca_key, hashes.SHA256()))

    def issue(cn, san=None):
        key = ec.generate_private_key(ec.SECP256R1())
        b = (x509.CertificateBuilder()
             .subject_name(_name(cn))
             .issuer_name(ca_cert.subject)
             .public_key(key.public_key())
             .serial_number(x509.random_serial_number())
             .not_valid_before(_NOW).not_valid_after(_EXP))
        if san:
            b = b.add_extension(x509.SubjectAlternativeName(san),
                                critical=False)
        return key, b.sign(ca_key, hashes.SHA256())

    srv_key, srv_cert = issue("127.0.0.1", [
        x509.DNSName("localhost"),
        x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
    ])
    cli_key, cli_cert = issue("test-client")

    paths = {
        "cacert": os.path.join(dirpath, "cacert.pem"),
        "cert": os.path.join(dirpath, "cert.pem"),
        "key": os.path.join(dirpath, "key.pem"),
        "client_cert": os.path.join(dirpath, "client-cert.pem"),
        "client_key": os.path.join(dirpath, "client-key.pem"),
    }
    _write_cert(paths["cacert"], ca_cert)
    _write_cert(paths["cert"], srv_cert)
    _write_key(paths["key"], srv_key)
    _write_cert(paths["client_cert"], cli_cert)
    _write_key(paths["client_key"], cli_key)
    return paths
