"""Bitmap fan-out parity: Pallas kernel vs XLA scan vs numpy oracle.

On the CPU test mesh the Pallas kernel runs in interpret mode; the
compiled path is exercised on real TPU by bench.py BENCH_MODE=bigfan.
"""

import numpy as np
import pytest

from emqx_tpu.ops.bitmap import (BitmapTable, build_bitmaps, or_bitmaps_auto,
                                 or_bitmaps_xla, rows_for_matches, words_for)


def oracle_or(bitmaps: np.ndarray, rows: np.ndarray) -> np.ndarray:
    out = np.zeros((rows.shape[0], bitmaps.shape[1]), dtype=np.uint32)
    for b in range(rows.shape[0]):
        for r in rows[b]:
            if r >= 0:
                out[b] |= bitmaps[r]
    return out


def test_build_bitmaps_bits():
    t = build_bitmaps({3: [0, 31, 32, 95], 7: [1]}, num_filters=8,
                      n_subs=100)
    assert t.n_rows == 2
    r3 = t.big_row[3]
    assert r3 >= 0 and t.big_row[7] >= 0 and t.big_row[0] == -1
    row = t.bitmaps[r3]
    assert row[0] == (1 | (1 << 31))
    assert row[1] == 1
    assert row[2] == (1 << 31)
    # total population = 4 subscribers
    assert sum(bin(int(w)).count("1") for w in row) == 4


def test_words_padding():
    assert words_for(1, tile=1024) == 1024
    assert words_for(1024 * 32, tile=1024) == 1024
    assert words_for(1024 * 32 + 1, tile=1024) == 2048


def test_rows_for_matches_pack_and_overflow():
    import jax.numpy as jnp
    big_row = np.full((16,), -1, np.int32)
    big_row[2] = 0
    big_row[5] = 1
    big_row[9] = 2
    t = BitmapTable(bitmaps=np.zeros((4, 1024), np.uint32),
                    big_row=big_row, n_rows=3, n_subs=10)
    ids = jnp.array([[1, 2, 5, -1], [9, -1, -1, -1], [2, 5, 9, 3]])
    rows, ovf = rows_for_matches(t, ids, mb=2)
    rows = np.asarray(rows)
    assert rows[0].tolist() == [0, 1]          # small id 1 dropped
    assert rows[1].tolist() == [2, -1]
    assert not ovf[0] and not ovf[1]
    assert bool(ovf[2])                        # 3 big rows > mb=2
    assert rows[2].tolist() == [0, 1]          # first mb kept


@pytest.mark.parametrize("tile", [1024, 2048])
def test_or_parity_random(tile):
    rng = np.random.default_rng(0)
    n_subs = tile * 32 * 3 // 2  # 1.5 tiles worth of bits
    n_big = 9
    rows_dict = {
        fid: rng.choice(n_subs, size=rng.integers(1, 500), replace=False)
        for fid in rng.choice(64, size=n_big, replace=False)
    }
    t = build_bitmaps(rows_dict, num_filters=64, n_subs=n_subs, tile=tile)
    B, mb = 5, 4
    rows = np.full((B, mb), -1, np.int32)
    for b in range(B):
        k = rng.integers(0, mb + 1)
        rows[b, :k] = rng.choice(t.n_rows, size=k, replace=False)
    want = oracle_or(t.bitmaps, rows)
    got_xla = np.asarray(or_bitmaps_xla(t.bitmaps, rows))
    got_pl = np.asarray(or_bitmaps_auto(t.bitmaps, rows))
    np.testing.assert_array_equal(got_xla, want)
    np.testing.assert_array_equal(got_pl, want)


def test_or_empty_rows():
    t = build_bitmaps({0: [1]}, num_filters=4, n_subs=64, tile=1024)
    rows = np.full((3, 4), -1, np.int32)
    out = np.asarray(or_bitmaps_auto(t.bitmaps, rows))
    assert out.sum() == 0


def test_rows_for_matches_out_of_capacity_fid_drops():
    """Clamping an out-of-capacity fid would OR in the LAST filter's
    bitmap — an entire unrelated subscriber set."""
    import jax.numpy as jnp

    from emqx_tpu.ops.bitmap import build_bitmaps, rows_for_matches

    bm = build_bitmaps({3: [1, 2, 3]}, 4, 64)
    f_cap = bm.big_row.shape[0]
    ids = jnp.array([[f_cap + 1, 3, -1, -1]], dtype=jnp.int32)
    rows, ovf = rows_for_matches(bm, ids, mb=4)
    got = [int(r) for r in np.asarray(rows)[0] if r >= 0]
    assert got == [0]               # only filter 3's row
    assert not bool(np.asarray(ovf)[0])
