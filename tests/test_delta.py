"""Online delta automaton + off-lock compaction (ISSUE 7,
docs/DELTA.md): exact-match parity between delta-on and delta-off
under randomized interleaved churn (wildcards, tombstones, re-adds,
overflow topics, $share roots, the 1×1 mesh path), bounded route-op
latency while a compaction flatten is in flight with exact post-swap
parity, the ``[matcher] delta = false`` legacy pin, the runtime A/B
flip, and the new observability surfaces."""

import random
import threading
import time

import pytest

from emqx_tpu.oracle import TrieOracle
from emqx_tpu.router import MatcherConfig, Router


def _mk(**kw):
    kw.setdefault("device_min_filters", 0)
    return Router(MatcherConfig(**kw), node="node1")


def _assert_parity(r, oracle, topics, tag=""):
    got = r.match_filters(topics)
    for t, row in zip(topics, got):
        assert sorted(row) == sorted(oracle.match(t)), (tag, t)


# -- two-probe parity -------------------------------------------------------


def test_delta_pending_adds_match_immediately():
    r = _mk(match_cache=False)
    for i in range(40):
        r.add_route(f"base/{i}/x")
    r.match_filters(["base/0/x"])  # flatten → delta mode armed
    assert r._patcher is None  # delta mode keeps no main mirror
    r.add_route("fresh/topic")
    r.add_route("fresh/+/deep")
    r.add_route("wild/#")
    assert r.match_filters(["fresh/topic"]) == [["fresh/topic"]]
    assert r.match_filters(["fresh/a/deep"]) == [["fresh/+/deep"]]
    assert sorted(r.match_filters(["wild/x/y"])[0]) == ["wild/#"]
    # the main automaton was never touched
    assert r.stats()["rebuilds"] == 1
    assert r.delta_info()["pending"] == 3


def test_delta_tombstone_masks_deleted_fid():
    r = _mk(match_cache=False)
    for i in range(40):
        r.add_route(f"t/{i}/x")
    r.match_filters(["t/0/x"])
    r.delete_route("t/3/x")       # main-table fid → tombstone
    assert r.match_filters(["t/3/x"]) == [[]]
    assert r.delta_info()["tombstones"] == 1
    # re-add under a fresh fid: delta add wins over the tombstone
    r.add_route("t/3/x")
    assert r.match_filters(["t/3/x"]) == [["t/3/x"]]
    # delete of a PENDING add retracts it without a tombstone
    r.add_route("gone/soon")
    r.delete_route("gone/soon")
    assert r.match_filters(["gone/soon"]) == [[]]


@pytest.mark.parametrize("match_cache", [False, True])
def test_delta_randomized_churn_parity(match_cache):
    """Acceptance pin: exact-match parity between delta-on and
    delta-off under randomized interleaved add/delete/match churn,
    including wildcard filters, '#'-terminals, $share-rooted verbatim
    filters, re-adds of tombstoned filters, and topics past
    max_levels (overflow → host fallback)."""
    rng = random.Random(42)
    kw = dict(match_cache=match_cache, max_levels=6, active_k=4,
              delta_max_filters=10_000)  # no mid-test compaction
    r_on = _mk(delta=True, **kw)
    r_off = _mk(delta=False, **kw)
    oracle = TrieOracle()
    live = {}

    words = ["a", "b", "w1", "w2", "x"]

    def roll_filter():
        shape = rng.random()
        if shape < 0.1:
            return "$share/g1/%s/%s" % (rng.choice(words),
                                        rng.choice(words))
        depth = rng.randint(1, 5)
        ws = [rng.choice(words + ["+"]) for _ in range(depth)]
        if rng.random() < 0.2:
            ws[-1] = "#"
        return "/".join(ws)

    probe = (["a/b", "w1/w2/x", "a/a/a/a/a", "$share/g1/a/b",
              "b", "zz/unmatched", "a/b/x/w1/w2/a/b/x"]  # >6 levels
             + ["x/" + "/".join(rng.choice(words) for _ in range(3))
                for _ in range(4)])

    warm = set()
    while len(warm) < 60:
        warm.add(roll_filter())
    for f in sorted(warm):  # unique: refcounts stay mirrored
        r_on.add_route(f)
        r_off.add_route(f)
        oracle.insert(f)
        live[f] = True
    # both flattened before churn begins
    r_on.match_filters(probe[:2])
    r_off.match_filters(probe[:2])

    for step in range(150):
        if live and rng.random() < 0.45:
            f = rng.choice(list(live))
            r_on.delete_route(f)
            r_off.delete_route(f)
            oracle.delete(f)
            del live[f]
        else:
            f = roll_filter()
            if f not in live:
                r_on.add_route(f)
                r_off.add_route(f)
                oracle.insert(f)
                live[f] = True
        if step % 15 == 0:
            _assert_parity(r_on, oracle, probe, tag=f"on@{step}")
            _assert_parity(r_off, oracle, probe, tag=f"off@{step}")
            on_rows = r_on.match_filters(probe)
            off_rows = r_off.match_filters(probe)
            for t, a, b in zip(probe, on_rows, off_rows):
                assert sorted(a) == sorted(b), (step, t)
    # fold the delta and re-check: the compacted tables must agree
    r_on.rebuild()
    _assert_parity(r_on, oracle, probe, tag="post-fold")


def test_delta_on_1x1_mesh_parity():
    """The 1×1 mesh path: delta is inactive on a mesh by design
    (the collective step has no two-probe seam), so delta-on must be
    indistinguishable from delta-off there — both run per-shard
    patch-in-place."""
    from emqx_tpu.parallel.mesh import make_mesh

    oracle = TrieOracle()
    routers = [
        _mk(mesh=make_mesh(1, 1), delta=True),
        _mk(mesh=make_mesh(1, 1), delta=False),
    ]
    assert not routers[0]._delta_active
    rng = random.Random(3)
    live = {}
    probe = ["a/b", "a/x/c", "zz"]
    for step in range(40):
        if live and rng.random() < 0.4:
            f = rng.choice(list(live))
            for r in routers:
                r.delete_route(f)
            oracle.delete(f)
            del live[f]
        else:
            depth = rng.randint(1, 3)
            f = "/".join(rng.choice(["a", "b", "c", "+", "x"])
                         for _ in range(depth))
            if f not in live:
                for r in routers:
                    r.add_route(f)
                oracle.insert(f)
                live[f] = True
        if step % 10 == 0:
            for r in routers:
                _assert_parity(r, oracle, probe, tag=f"mesh@{step}")


# -- off-lock compaction ----------------------------------------------------


def test_offlock_compaction_bounded_mutation_latency():
    """Acceptance pin: a route add/delete issued while a compaction
    flatten is in flight completes in milliseconds (no full-flatten
    lock hold), and the post-swap automaton is exactly right."""
    r = _mk(match_cache=False, delta_max_filters=32)
    oracle = TrieOracle()
    for i in range(300):
        f = f"seed/{i}/leaf"
        r.add_route(f)
        oracle.insert(f)
    r.match_filters(["seed/0/leaf"])

    orig = r._flatten_main
    started = threading.Event()

    def slow_flatten(cap, nb):
        started.set()
        time.sleep(0.8)  # a 10M-sub flatten, compressed in time
        return orig(cap, nb)

    r._flatten_main = slow_flatten
    # cross delta_max_filters → background compaction kicks off
    for i in range(33):
        f = f"burst/{i}/x"
        r.add_route(f)
        oracle.insert(f)
    assert started.wait(5), "compaction never started"
    lat = []
    for i in range(40):
        t0 = time.perf_counter()
        f = f"during/{i}/y"
        r.add_route(f)
        oracle.insert(f)
        lat.append(time.perf_counter() - t0)
        if i % 2 == 0:
            g = f"during/{i}/y"
            r.delete_route(g)
            oracle.delete(g)
            lat.append(0.0)
    p99 = sorted(lat)[-1] * 1000.0
    assert p99 < 100.0, f"route op stalled {p99:.1f}ms on the flatten"
    assert r._compacting, "flatten should still be in flight"
    # matching DURING the flatten is exact (old main + live delta)
    probe = ["seed/5/leaf", "burst/3/x", "during/1/y", "during/2/y"]
    _assert_parity(r, oracle, probe, tag="during")
    # host oracle fallback during the freeze is exact too
    assert sorted(r.host_match("during/3/y")) == \
        sorted(oracle.match("during/3/y"))
    for _ in range(400):
        if not r._compacting:
            break
        time.sleep(0.02)
    assert not r._compacting
    info = r.delta_info()
    assert info["merges"] >= 1
    # post-swap exact parity: the folded tables + fresh delta agree
    _assert_parity(r, oracle, probe + ["zz/none"], tag="post-swap")
    # the lock was held for ms, not the flatten's 800ms
    assert info["rebuild_stall_ms"] < 400


def test_offlock_compaction_delete_during_flatten():
    """Deletes landing mid-flatten tombstone against the NEW tables
    (their paths were in the frozen snapshot) — the log split must
    carry them across the swap."""
    r = _mk(match_cache=False, delta_max_filters=8)
    for i in range(50):
        r.add_route(f"s/{i}/x")
    r.match_filters(["s/0/x"])
    orig = r._flatten_main
    gate = threading.Event()

    def gated(cap, nb):
        gate.wait(5)
        return orig(cap, nb)

    r._flatten_main = gated
    for i in range(9):
        r.add_route(f"b/{i}/y")   # trigger compaction (blocked)
    time.sleep(0.05)
    assert r._compacting
    # mid-flatten churn: delete a seed filter AND a burst filter
    r.delete_route("s/7/x")
    r.delete_route("b/2/y")
    r.add_route("mid/flight")
    gate.set()
    for _ in range(400):
        if not r._compacting:
            break
        time.sleep(0.02)
    assert r.match_filters(["s/7/x", "b/2/y", "mid/flight", "b/3/y"]) \
        == [[], [], ["mid/flight"], ["b/3/y"]]


# -- delta-off pin / runtime flip ------------------------------------------


def test_delta_off_restores_patch_in_place():
    """``delta = false`` restores the patch-in-place path: mutations
    go through the AutoPatcher mirror (patches counter moves, a main
    mirror exists) and no delta structures ever materialize."""
    r = _mk(delta=False, match_cache=False)
    for i in range(20):
        r.add_route(f"a/{i}")
    r.match_filters(["a/0"])
    assert r._patcher is not None
    base = r.stats()["patches"]
    r.add_route("churn/x")
    r.delete_route("a/3")
    assert r.stats()["patches"] >= base + 2
    assert r._delta is None
    assert r.delta_info()["active"] is False
    assert r.match_filters(["churn/x", "a/3"]) == [["churn/x"], []]


def test_set_delta_runtime_flip_is_equivalent():
    """The bench A/B seam: flipping delta on/off at runtime folds
    pending state via one rebuild and produces identical match
    arrays on the same router/filter set."""
    r = _mk(match_cache=False)
    for i in range(30):
        r.add_route(f"f/{i}/x")
    r.match_filters(["f/0/x"])
    r.add_route("pending/delta")     # lives in the delta
    topics = ["f/3/x", "pending/delta", "nope"]
    before = r.match_filters(topics)
    r.set_delta(False)
    assert r._patcher is not None    # legacy mirror re-armed
    assert r.match_filters(topics) == before
    r.add_route("legacy/added")
    r.set_delta(True)
    assert r._patcher is None
    assert r.match_filters(topics + ["legacy/added"]) \
        == before + [["legacy/added"]]


# -- config / observability -------------------------------------------------


def test_delta_config_validation(tmp_path):
    from emqx_tpu.config import ConfigError, load_config

    def parse(text):
        p = tmp_path / "cfg.toml"
        p.write_text(text)
        return load_config(str(p))

    with pytest.raises(ValueError):
        Router(MatcherConfig(delta_max_filters=0))
    cfg = parse("[matcher]\ndelta = false\ndelta_max_filters = 128\n")
    assert cfg.matcher.delta is False
    assert cfg.matcher.delta_max_filters == 128
    with pytest.raises(ConfigError):
        parse("[matcher]\ndelta = 1\n")


def test_delta_counters_drain_and_fold():
    from emqx_tpu.metrics import Metrics

    r = _mk(match_cache=False, delta_max_filters=8)
    for i in range(40):
        r.add_route(f"c/{i}/x")
    r.match_filters(["c/0/x"])
    r.add_route("d/new")
    r.match_filters(["d/new"])
    for i in range(9):
        r.add_route(f"e/{i}/y")  # crosses the bound → compaction
    for _ in range(400):
        if not r._compacting and r.delta_info()["merges"] >= 1:
            break
        time.sleep(0.02)
    drained = r.drain_automaton_stats()
    assert drained["delta.filters"] >= 10
    assert drained["delta.probes"] >= 1
    assert drained["delta.merges"] >= 1
    m = Metrics()
    m.fold_automaton_stats(drained)
    assert m.all()["automaton.delta.filters"] == drained["delta.filters"]
    # second drain is deltas-only
    assert r.drain_automaton_stats()["delta.merges"] == 0


def test_rebuild_stage_histogram_records_compaction():
    from emqx_tpu.telemetry import Telemetry

    tel = Telemetry()
    r = _mk(match_cache=False, delta_max_filters=4)
    r.telemetry = tel
    for i in range(20):
        r.add_route(f"h/{i}")
    r.match_filters(["h/0"])
    for i in range(5):
        r.add_route(f"hh/{i}")
    for _ in range(400):
        if not r._compacting and r.delta_info()["merges"] >= 1:
            break
        time.sleep(0.02)
    assert tel.hists["rebuild"].count >= 1
    assert "rebuild" in tel.stage_stats()
