"""MQTT-over-WebSocket transport (emqx_ws_connection parity)."""

import asyncio
import base64
import contextlib
import os

import pytest

from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.frame import Parser, serialize
from emqx_tpu.mqtt.packet import (Connack, Connect, Publish, Suback,
                                  Subscribe)
from emqx_tpu.node import Node
from emqx_tpu.ws_connection import (OP_BINARY, OP_CLOSE, OP_PING, OP_PONG,
                                    WsFrameParser, WsParseError, accept_key,
                                    encode_frame)


def mask_frame(opcode: int, payload: bytes, fin: bool = True,
               mask: bytes = b"\x01\x02\x03\x04") -> bytes:
    """Client→server frame (masked)."""
    head = bytearray([(0x80 if fin else 0) | opcode])
    n = len(payload)
    if n < 126:
        head.append(0x80 | n)
    elif n < 65536:
        head.append(0x80 | 126)
        head += n.to_bytes(2, "big")
    else:
        head.append(0x80 | 127)
        head += n.to_bytes(8, "big")
    body = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return bytes(head) + mask + body


# -- frame codec unit tests -------------------------------------------------

def test_accept_key_rfc_example():
    # the worked example from RFC 6455 §1.3
    assert accept_key("dGhlIHNhbXBsZSBub25jZQ==") == \
        "s3pPLMBiTxaQ9kYGzzhZRbK+xOo="


def test_frame_roundtrip_sizes():
    p = WsFrameParser()
    for n in (0, 1, 125, 126, 65535, 65536, 100_000):
        payload = bytes(i % 251 for i in range(n))
        out = p.feed(mask_frame(OP_BINARY, payload))
        assert out == [(OP_BINARY, payload)]


def test_frame_incremental_and_fragmented():
    p = WsFrameParser()
    data = mask_frame(OP_BINARY, b"hello", fin=False) + \
        mask_frame(0x0, b" world")  # continuation
    for i in range(0, len(data), 3):
        chunks = p.feed(data[i:i + 3])
        if chunks:
            assert chunks == [(OP_BINARY, b"hello world")]


def test_frame_rejects_unmasked():
    p = WsFrameParser()
    assert p.feed(encode_frame(OP_BINARY, b"x")) == []  # no mask
    assert p.error is not None
    with pytest.raises(WsParseError):
        p.feed(b"")  # poisoned: every later feed raises


def test_frame_rejects_bad_continuation():
    p = WsFrameParser()
    assert p.feed(mask_frame(0x0, b"orphan")) == []
    assert p.error is not None


def test_frame_rejects_oversized_control():
    p = WsFrameParser()
    assert p.feed(mask_frame(OP_PING, b"p" * 126)) == []
    assert p.error is not None
    assert "control" in str(p.error)


def test_frame_error_preserves_earlier_messages():
    # a valid message ahead of garbage must still come out
    p = WsFrameParser()
    data = mask_frame(OP_BINARY, b"keep-me") + encode_frame(OP_BINARY, b"bad")
    assert p.feed(data) == [(OP_BINARY, b"keep-me")]
    assert p.error is not None


# -- end-to-end over a real WS socket ---------------------------------------

class WsTestClient:
    """Raw WebSocket MQTT client (handshake + masked binary frames)."""

    def __init__(self, client_id: str):
        self.client_id = client_id
        self.parser = Parser()
        self.reader = None
        self.writer = None
        self.inbox = asyncio.Queue()
        self.acks = asyncio.Queue()

    async def connect(self, port: int, path: str = "/mqtt",
                      subprotocol: str = "mqtt", ssl=None):
        self.reader, self.writer = await asyncio.open_connection(
            "127.0.0.1", port, ssl=ssl)
        key = base64.b64encode(os.urandom(16)).decode()
        req = (f"GET {path} HTTP/1.1\r\nHost: 127.0.0.1:{port}\r\n"
               "Upgrade: websocket\r\nConnection: Upgrade\r\n"
               f"Sec-WebSocket-Key: {key}\r\n"
               "Sec-WebSocket-Version: 13\r\n"
               f"Sec-WebSocket-Protocol: {subprotocol}\r\n\r\n")
        self.writer.write(req.encode())
        head = await self.reader.readuntil(b"\r\n\r\n")
        status = head.split(b"\r\n")[0].decode()
        if "101" not in status:
            return status
        assert accept_key(key).encode() in head
        self._task = asyncio.get_event_loop().create_task(self._read_loop())
        await self.send_mqtt(Connect(
            proto_ver=C.MQTT_V4, proto_name=C.PROTOCOL_NAMES[C.MQTT_V4],
            client_id=self.client_id, clean_start=True))
        ack = await asyncio.wait_for(self.acks.get(), 5.0)
        assert isinstance(ack, Connack)
        return ack

    async def _read_loop(self):
        # server frames are unmasked: parse by hand
        buf = bytearray()
        try:
            while True:
                data = await self.reader.read(65536)
                if not data:
                    return
                buf += data
                while len(buf) >= 2:
                    opcode = buf[0] & 0x0F
                    n = buf[1] & 0x7F
                    pos = 2
                    if n == 126:
                        if len(buf) < 4:
                            break
                        n = int.from_bytes(buf[2:4], "big")
                        pos = 4
                    elif n == 127:
                        if len(buf) < 10:
                            break
                        n = int.from_bytes(buf[2:10], "big")
                        pos = 10
                    if len(buf) < pos + n:
                        break
                    payload = bytes(buf[pos:pos + n])
                    del buf[:pos + n]
                    if opcode == OP_PONG:
                        await self.acks.put(("pong", payload))
                    elif opcode == OP_CLOSE:
                        await self.acks.put(("close", payload))
                    elif opcode == OP_BINARY:
                        for pkt in self.parser.feed(payload):
                            if isinstance(pkt, Publish):
                                await self.inbox.put(pkt)
                            else:
                                await self.acks.put(pkt)
        except (ConnectionResetError, asyncio.CancelledError):
            pass

    async def send_mqtt(self, pkt):
        self.writer.write(
            mask_frame(OP_BINARY, serialize(pkt, C.MQTT_V4),
                       mask=os.urandom(4)))
        await self.writer.drain()

    async def send_raw(self, frame: bytes):
        self.writer.write(frame)
        await self.writer.drain()

    async def close(self):
        self.writer.close()


@contextlib.asynccontextmanager
async def ws_node():
    n = Node(boot_listeners=False)
    n.add_ws_listener(port=0)
    await n.start()
    try:
        yield n
    finally:
        await n.stop()


async def test_ws_connect_pub_sub():
    async with ws_node() as node:
        port = node.listeners[0].port
        sub, pub = WsTestClient("wsub"), WsTestClient("wpub")
        ack = await sub.connect(port)
        assert ack.reason_code == 0
        await pub.connect(port)
        await sub.send_mqtt(Subscribe(packet_id=1,
                                      topic_filters=[("t/#", {"qos": 0})]))
        sa = await asyncio.wait_for(sub.acks.get(), 5.0)
        assert isinstance(sa, Suback) and sa.reason_codes == [0]
        await pub.send_mqtt(Publish(topic="t/x", payload=b"over-ws"))
        msg = await asyncio.wait_for(sub.inbox.get(), 5.0)
        assert msg.topic == "t/x" and msg.payload == b"over-ws"
        assert node.metrics.val("client.connected") == 2
        await sub.close()
        await pub.close()


async def test_ws_ping_pong_and_close():
    async with ws_node() as node:
        port = node.listeners[0].port
        c = WsTestClient("wping")
        await c.connect(port)
        await c.send_raw(mask_frame(OP_PING, b"hi"))
        kind, payload = await asyncio.wait_for(c.acks.get(), 5.0)
        assert (kind, payload) == ("pong", b"hi")
        await c.send_raw(mask_frame(OP_CLOSE, b"\x03\xe8"))
        kind, _ = await asyncio.wait_for(c.acks.get(), 5.0)
        assert kind == "close"
        await c.close()


async def test_ws_bad_handshake_rejected():
    async with ws_node() as node:
        port = node.listeners[0].port
        # wrong path
        c = WsTestClient("wbad")
        status = await c.connect(port, path="/nope")
        assert "400" in status
        await c.close()
        # missing mqtt subprotocol
        c2 = WsTestClient("wbad2")
        status = await c2.connect(port, subprotocol="chat")
        assert "400" in status
        await c2.close()


async def test_ws_text_frame_disconnects():
    async with ws_node() as node:
        port = node.listeners[0].port
        c = WsTestClient("wtext")
        await c.connect(port)
        await c.send_raw(mask_frame(0x1, b"not-binary"))
        kind, _ = await asyncio.wait_for(c.acks.get(), 5.0)
        assert kind == "close"
        await c.close()


async def test_ws_error_after_valid_packet_still_answered():
    # regression: a malformed WS frame arriving in the same TCP read as
    # a valid MQTT packet must not swallow the valid packet's response —
    # the connection answers, drains, THEN closes (with a WS CLOSE)
    async with ws_node() as node:
        port = node.listeners[0].port
        c = WsTestClient("werr")
        ack = await c.connect(port)
        assert ack.reason_code == 0
        good = mask_frame(OP_BINARY, serialize(
            Subscribe(packet_id=7, topic_filters=[("t/err", {"qos": 0})]),
            C.MQTT_V4))
        bad = encode_frame(OP_BINARY, b"junk")  # unmasked = protocol error
        await c.send_raw(good + bad)
        got_suback = False
        got_close = False
        for _ in range(3):
            try:
                item = await asyncio.wait_for(c.acks.get(), 5.0)
            except asyncio.TimeoutError:
                break
            if isinstance(item, Suback):
                got_suback = True
            elif isinstance(item, tuple) and item[0] == "close":
                got_close = True
                break
        assert got_suback, "response to pre-error packet was dropped"
        assert got_close, "server did not send a WS CLOSE frame"
        await c.close()


# -- WS frame fuzz: corruption never crashes the server ---------------------

async def test_ws_frame_fuzz_never_crashes_listener():
    """Random garbage and truncated/flag-corrupted WS frames after a
    valid upgrade must close the socket cleanly, never wedge or kill
    the listener (mirror of the MQTT frame fuzz, applied to the
    RFC 6455 layer)."""
    import random as _r

    rng = _r.Random(99)
    n = Node(boot_listeners=False)
    lst = n.add_ws_listener(port=0)
    await n.start()
    try:
        for trial in range(30):
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", lst.port)
            writer.write(
                b"GET /mqtt HTTP/1.1\r\n"
                b"Host: x\r\nUpgrade: websocket\r\n"
                b"Connection: Upgrade\r\n"
                b"Sec-WebSocket-Key: dGhlIHNhbXBsZSBub25jZQ==\r\n"
                b"Sec-WebSocket-Version: 13\r\n"
                b"Sec-WebSocket-Protocol: mqtt\r\n\r\n")
            await writer.drain()
            await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), 5)
            # garbage after the upgrade: random bytes, or a valid
            # binary frame header with corrupted length/flags
            kind = trial % 3
            if kind == 0:
                junk = bytes(rng.randrange(256)
                             for _ in range(rng.randrange(1, 64)))
            elif kind == 1:
                junk = bytes([0x82 | rng.randrange(0x40),
                              rng.randrange(256)]) + os.urandom(8)
            else:  # unmasked client frame (protocol violation)
                junk = b"\x82\x05hello"
            writer.write(junk)
            await writer.drain()
            with contextlib.suppress(
                    asyncio.TimeoutError, ConnectionError,
                    asyncio.IncompleteReadError):
                await asyncio.wait_for(reader.read(256), 2)
            writer.close()
        # the listener survived: a normal client still works
        c = WsTestClient("post-fuzz")
        ack = await c.connect(lst.port)
        assert ack.reason_code == 0
        await c.close()
    finally:
        await n.stop()
