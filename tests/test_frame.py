"""Wire codec tests — ported from reference emqx_frame_SUITE and
prop_emqx_frame (serialize∘parse roundtrip across versions)."""

import random

import pytest

from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.frame import (
    FrameError, FrameTooLarge, Parser, serialize)
from emqx_tpu.mqtt.packet import (
    Auth, Connect, Disconnect, PubAck, Publish, Pingreq,
    Pingresp, Suback, Subscribe, Unsuback, Unsubscribe, check,
    to_message, will_msg, PacketError)


def roundtrip(pkt, version):
    data = serialize(pkt, version)
    p = Parser(version=version)
    out = p.feed(data)
    assert len(out) == 1, (pkt, out)
    return out[0]


def test_connect_roundtrip_v4():
    pkt = Connect(proto_ver=C.MQTT_V4, client_id="c1", keepalive=30,
                  clean_start=True, username="u", password=b"p")
    got = roundtrip(pkt, C.MQTT_V4)
    assert got == pkt


def test_connect_roundtrip_v5_with_will_and_props():
    pkt = Connect(
        proto_ver=C.MQTT_V5, client_id="c2", clean_start=False,
        keepalive=120,
        will_flag=True, will_qos=1, will_retain=True,
        will_topic="will/t", will_payload=b"bye",
        will_props={"Will-Delay-Interval": 5},
        properties={"Session-Expiry-Interval": 3600,
                    "Receive-Maximum": 10,
                    "User-Property": [("a", "b"), ("a", "c")]})
    got = roundtrip(pkt, C.MQTT_V5)
    assert got == pkt


def test_connect_v3():
    pkt = Connect(proto_ver=C.MQTT_V3, proto_name="MQIsdp", client_id="x")
    got = roundtrip(pkt, C.MQTT_V3)
    assert got.proto_ver == 3 and got.proto_name == "MQIsdp"


def test_bad_protocol_name():
    pkt = Connect(proto_ver=C.MQTT_V4, client_id="c")
    data = bytearray(serialize(pkt, C.MQTT_V4))
    data[4] = ord("X")  # corrupt protocol name
    with pytest.raises(FrameError):
        Parser().feed(bytes(data))


def test_publish_roundtrip_all_qos():
    for v in (C.MQTT_V3, C.MQTT_V4, C.MQTT_V5):
        for qos in (0, 1, 2):
            pkt = Publish(topic="a/b", qos=qos,
                          packet_id=None if qos == 0 else 7,
                          payload=b"\x00\xffhello", retain=qos == 1,
                          dup=qos == 2)
            if v == C.MQTT_V5 and qos:
                pkt.properties = {"Topic-Alias": 3,
                                  "Message-Expiry-Interval": 60}
            assert roundtrip(pkt, v) == pkt


def test_puback_family_roundtrip():
    for v in (C.MQTT_V4, C.MQTT_V5):
        for t in (C.PUBACK, C.PUBREC, C.PUBREL, C.PUBCOMP):
            pkt = PubAck(type=t, packet_id=99)
            if v == C.MQTT_V5:
                pkt.reason_code = 0x10
                pkt.properties = {"Reason-String": "meh"}
            assert roundtrip(pkt, v) == pkt


def test_subscribe_roundtrip():
    pkt = Subscribe(packet_id=5, topic_filters=[
        ("a/+", {"qos": 1, "nl": 0, "rap": 0, "rh": 0}),
        ("b/#", {"qos": 2, "nl": 1, "rap": 1, "rh": 2})])
    assert roundtrip(pkt, C.MQTT_V5) == pkt
    # v4 loses nl/rap/rh on the wire (they're v5 sub options)
    got = roundtrip(pkt, C.MQTT_V4)
    assert [f for f, _ in got.topic_filters] == ["a/+", "b/#"]


def test_suback_unsub_roundtrip():
    assert roundtrip(Suback(packet_id=5, reason_codes=[0, 1, 0x80]),
                     C.MQTT_V5).reason_codes == [0, 1, 0x80]
    pkt = Unsubscribe(packet_id=6, topic_filters=["a", "b/c"])
    assert roundtrip(pkt, C.MQTT_V4) == pkt
    assert roundtrip(Unsuback(packet_id=6, reason_codes=[0, 17]),
                     C.MQTT_V5).reason_codes == [0, 17]


def test_ping_disconnect_auth():
    assert isinstance(roundtrip(Pingreq(), C.MQTT_V4), Pingreq)
    assert isinstance(roundtrip(Pingresp(), C.MQTT_V4), Pingresp)
    assert roundtrip(Disconnect(), C.MQTT_V4) == Disconnect()
    d5 = Disconnect(reason_code=0x8E,
                    properties={"Reason-String": "takeover"})
    assert roundtrip(d5, C.MQTT_V5) == d5
    a = Auth(reason_code=0x18,
             properties={"Authentication-Method": "SCRAM"})
    assert roundtrip(a, C.MQTT_V5) == a


def test_incremental_feed_byte_by_byte():
    pkt = Publish(topic="x/y", qos=1, packet_id=3, payload=b"data")
    data = serialize(pkt, C.MQTT_V4)
    p = Parser()
    got = []
    for i in range(len(data)):
        got += p.feed(data[i:i + 1])
    assert got == [pkt]


def test_multiple_packets_in_one_feed():
    a = serialize(Publish(topic="a", qos=0, payload=b"1"), C.MQTT_V4)
    b = serialize(Pingreq(), C.MQTT_V4)
    got = Parser().feed(a + b)
    assert len(got) == 2 and isinstance(got[1], Pingreq)


def test_parser_version_switches_on_connect():
    p = Parser(version=C.MQTT_V4)
    con = serialize(Connect(proto_ver=C.MQTT_V5, client_id="c"), C.MQTT_V5)
    pub5 = serialize(Publish(topic="t", qos=0, payload=b"",
                             properties={"Content-Type": "x"}), C.MQTT_V5)
    out = p.feed(con + pub5)
    assert out[1].properties == {"Content-Type": "x"}


def test_frame_too_large():
    p = Parser(max_size=64)
    big = serialize(Publish(topic="t", qos=0, payload=b"x" * 1000),
                    C.MQTT_V4)
    with pytest.raises(FrameTooLarge):
        p.feed(big)


def test_bad_qos_rejected():
    data = bytes([0x30 | 0x06, 2, 0, 0])  # qos=3
    with pytest.raises(FrameError):
        Parser().feed(data)


def test_reserved_pubrel_flags_strict():
    data = bytearray(serialize(PubAck(type=C.PUBREL, packet_id=1),
                               C.MQTT_V4))
    data[0] = (C.PUBREL << 4) | 0x00  # must be 0x02
    with pytest.raises(FrameError):
        Parser().feed(bytes(data))
    Parser(strict=False).feed(bytes(data))  # lenient mode ok


def test_packet_check_and_conversion():
    with pytest.raises(PacketError):
        check(Publish(topic="a/#", qos=0))  # wildcard in name
    with pytest.raises(PacketError):
        check(Publish(topic="t", qos=1, packet_id=None))
    with pytest.raises(PacketError):
        check(Subscribe(packet_id=1, topic_filters=[]))
    msg = to_message(Publish(topic="t", qos=1, packet_id=1,
                             retain=True, payload=b"p"), "cid")
    assert msg.from_ == "cid" and msg.get_flag("retain")
    w = will_msg(Connect(client_id="c", will_flag=True, will_qos=1,
                         will_topic="w", will_payload=b"bye"))
    assert w.topic == "w" and w.qos == 1


def _rand_packet(rng):
    t = rng.choice(["pub", "sub", "unsub", "ack", "con", "disc"])
    if t == "pub":
        qos = rng.randint(0, 2)
        return Publish(
            topic="/".join("abcdef"[rng.randint(0, 5)]
                           for _ in range(rng.randint(1, 5))),
            qos=qos, packet_id=rng.randint(1, 0xFFFF) if qos else None,
            dup=bool(rng.randint(0, 1)) if qos else False,
            retain=bool(rng.randint(0, 1)),
            payload=bytes(rng.randrange(256)
                          for _ in range(rng.randint(0, 64))))
    if t == "sub":
        return Subscribe(
            packet_id=rng.randint(1, 0xFFFF),
            topic_filters=[("t/%d" % i, {"qos": rng.randint(0, 2),
                                         "nl": rng.randint(0, 1),
                                         "rap": rng.randint(0, 1),
                                         "rh": rng.randint(0, 2)})
                           for i in range(rng.randint(1, 4))])
    if t == "unsub":
        return Unsubscribe(packet_id=rng.randint(1, 0xFFFF),
                           topic_filters=["x/%d" % i
                                          for i in range(rng.randint(1, 4))])
    if t == "ack":
        return PubAck(type=rng.choice([C.PUBACK, C.PUBREC, C.PUBCOMP]),
                      packet_id=rng.randint(1, 0xFFFF))
    if t == "con":
        return Connect(proto_ver=C.MQTT_V5 if rng.random() < 0.5 else C.MQTT_V4,
                       client_id="c%d" % rng.randint(0, 99),
                       clean_start=bool(rng.randint(0, 1)),
                       keepalive=rng.randint(0, 0xFFFF))
    return Disconnect()


def test_random_roundtrip_property():
    """prop_emqx_frame analogue: serialize∘parse == id."""
    rng = random.Random(99)
    for _ in range(300):
        pkt = _rand_packet(rng)
        v = pkt.proto_ver if isinstance(pkt, Connect) else (
            C.MQTT_V5 if rng.random() < 0.5 else C.MQTT_V4)
        got = roundtrip(pkt, v)
        if isinstance(pkt, Subscribe) and v != C.MQTT_V5:
            assert [f for f, _ in got.topic_filters] == \
                [f for f, _ in pkt.topic_filters]
        else:
            assert got == pkt, (v, pkt, got)


def test_fragmented_stream_of_many_packets():
    rng = random.Random(1)
    pkts = [_rand_packet(rng) for _ in range(50)]
    pkts = [p for p in pkts if not isinstance(p, Connect)]
    stream = b"".join(serialize(p, C.MQTT_V4) for p in pkts)
    parser = Parser()
    got = []
    i = 0
    while i < len(stream):
        n = rng.randint(1, 17)
        got += parser.feed(stream[i:i + n])
        i += n
    assert len(got) == len(pkts)
