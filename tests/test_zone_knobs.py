"""Round-4 zone-knob sweep: every knob the reference consumes via
``emqx_zone:get_env`` must be consumed here too. These pin the last
four that were config surface without behavior:
use_username_as_clientid, bypass_auth_plugins, ignore_loop_deliver,
response_information (src/emqx_channel.erl:1383-1437,
src/emqx_access_control.erl:37-41)."""

from emqx_tpu.broker import Broker
from emqx_tpu.channel import Channel
from emqx_tpu.cm import ConnectionManager
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.packet import Connack, Connect, Publish, Subscribe
from emqx_tpu.zone import Zone


def _connect(zone, version=C.MQTT_V5, username=None, broker=None,
             client_id="zc", props=None):
    broker = broker or Broker()
    chan = Channel(broker, ConnectionManager(broker=broker), zone=zone)
    out = chan.handle_in(Connect(
        proto_ver=version, proto_name=C.PROTOCOL_NAMES[version],
        client_id=client_id, clean_start=True, username=username,
        properties=props or {}))
    return broker, chan, out[0]


def test_use_username_as_clientid():
    zone = Zone(name="zk-u", use_username_as_clientid=True)
    _, chan, ack = _connect(zone, username="alice")
    assert ack.reason_code == 0
    assert chan.client_id == "alice"
    # no username: the given clientid stands
    _, chan2, _ = _connect(zone, client_id="keepme")
    assert chan2.client_id == "keepme"


def test_bypass_auth_plugins_skips_hook_chain():
    broker = Broker()

    def deny_all(clientinfo, acc):
        return dict(acc, auth_result="not_authorized")

    broker.hooks.add("client.authenticate", deny_all)
    # hook denies: normal zone refuses the connect
    _, _, ack = _connect(Zone(name="zk-a1"), broker=broker)
    assert ack.reason_code != 0
    # bypass zone never runs the hook: zone default (anonymous) wins
    _, _, ack2 = _connect(Zone(name="zk-a2", bypass_auth_plugins=True),
                          broker=broker)
    assert ack2.reason_code == 0


def test_ignore_loop_deliver_v4_suppresses_self_delivery():
    zone = Zone(name="zk-nl", ignore_loop_deliver=True)
    broker, chan, ack = _connect(zone, version=C.MQTT_V4,
                                 client_id="looper")
    assert ack.reason_code == 0
    chan.handle_in(Subscribe(packet_id=1, topic_filters=[
        ("loop/t", {"qos": 0, "nl": 0, "rap": 0, "rh": 0})]))
    chan.handle_in(Publish(topic="loop/t", qos=0, payload=b"me"))
    assert chan.handle_deliver() == []  # own publish suppressed
    assert broker.metrics.val("delivery.dropped.no_local") == 1
    # a v5 client in the same zone keeps its explicit nl=0
    _, chan5, _ = _connect(zone, client_id="v5er", broker=broker)
    chan5.handle_in(Subscribe(packet_id=1, topic_filters=[
        ("loop/t", {"qos": 0, "nl": 0, "rap": 0, "rh": 0})]))
    chan5.handle_in(Publish(topic="loop/t", qos=0, payload=b"me5"))
    got = chan5.handle_deliver()
    assert any(getattr(p, "payload", b"") == b"me5" for p in got)


def test_response_information_on_request():
    zone = Zone(name="zk-ri", response_information="rsp/base")
    _, _, ack = _connect(zone, props={
        "Request-Response-Information": 1})
    assert isinstance(ack, Connack)
    assert ack.properties.get("Response-Information") == "rsp/base"
    # not requested -> not volunteered
    _, _, ack2 = _connect(zone, client_id="zc2")
    assert "Response-Information" not in ack2.properties


def test_bridge_mode_wire_roundtrip_and_rap():
    """Bridge CONNECT (proto level | 0x80, src/emqx_frame.erl:185):
    parses to is_bridge, survives serialize∘parse, and a v4 bridge's
    subscriptions keep the retain flag as published (rap=1) where a
    plain v4 client has it cleared."""
    from emqx_tpu.mqtt.frame import Parser, serialize
    from emqx_tpu.types import Message

    pkt = Connect(proto_ver=C.MQTT_V4, proto_name="MQTT",
                  is_bridge=True, client_id="bridge1")
    [back] = Parser().feed(serialize(pkt, C.MQTT_V4))
    assert back.is_bridge and back.proto_ver == C.MQTT_V4

    broker = Broker()
    chan = Channel(broker, ConnectionManager(broker=broker),
                   zone=Zone(name="zk-br"))
    ack = chan.handle_in(back)[0]
    assert ack.reason_code == 0
    chan.handle_in(Subscribe(packet_id=1, topic_filters=[
        ("br/t", {"qos": 0, "nl": 0, "rap": 0, "rh": 0})]))
    broker.publish(Message(topic="br/t", payload=b"r",
                           flags={"retain": True}))
    out = chan.handle_deliver()
    pubs = [p for p in out if isinstance(p, Publish)]
    assert pubs and pubs[0].retain, "bridge must keep retain flag"

    # control: a plain v4 client in the same broker gets retain=0
    chan2 = Channel(broker, ConnectionManager(broker=broker),
                    zone=Zone(name="zk-br2"))
    chan2.handle_in(Connect(proto_ver=C.MQTT_V4, proto_name="MQTT",
                            client_id="plain1"))
    chan2.handle_in(Subscribe(packet_id=1, topic_filters=[
        ("br/t", {"qos": 0, "nl": 0, "rap": 0, "rh": 0})]))
    broker.publish(Message(topic="br/t", payload=b"r2",
                           flags={"retain": True}))
    out2 = chan2.handle_deliver()
    pubs2 = [p for p in out2 if isinstance(p, Publish)]
    assert pubs2 and not pubs2[0].retain


def test_v5_empty_clientid_with_cs0_rejected():
    """Zero-byte clientid + clean_start=0 is invalid on EVERY proto
    version (src/emqx_packet.erl:317-320) — there is no session the
    client could resume."""
    broker = Broker()
    chan = Channel(broker, ConnectionManager(broker=broker),
                   zone=Zone(name="zk-e"))
    ack = chan.handle_in(Connect(
        proto_ver=C.MQTT_V5, proto_name="MQTT", client_id="",
        clean_start=False))[0]
    assert ack.reason_code != 0
    assert chan.close_after_send


def test_disconnect_cannot_raise_expiry_from_zero():
    """MQTT-3.14.2.2.2: a CONNECT with Session-Expiry-Interval 0
    cannot be upgraded to a persistent session at DISCONNECT — the
    server answers PROTOCOL_ERROR (src/emqx_channel.erl:639-643)."""
    from emqx_tpu.mqtt.packet import Disconnect

    zone = Zone(name="zk-se")
    _, chan, ack = _connect(zone)  # v5, no expiry property -> 0
    assert ack.reason_code == 0
    out = chan.handle_in(Disconnect(
        reason_code=0, properties={"Session-Expiry-Interval": 300}))
    assert any(isinstance(p, Disconnect) and p.reason_code == 0x82
               for p in out), out
    # and a session opened WITH expiry may lower/raise it freely
    _, chan2, _ = _connect(zone, client_id="se2", props={
        "Session-Expiry-Interval": 100})
    out2 = chan2.handle_in(Disconnect(
        reason_code=0, properties={"Session-Expiry-Interval": 900}))
    assert out2 == []
    assert chan2.expiry_interval == 900
