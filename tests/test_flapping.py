"""Flap detection → auto-ban (emqx_tpu/flapping.py; reference
src/emqx_flapping.erl): detect/ban thresholds, window reset, gc, and
the flapping→banned interaction under a reconnect-storm shape — the
live-path guard the flap-storm bench scenario
(``BENCH_MODE=flapstorm``) leans on."""

import time

from emqx_tpu.banned import Banned
from emqx_tpu.flapping import Flapping, FlappingConfig


def _mk(max_count=5, window=60.0, ban_time=300.0, banned=None):
    return Flapping(
        banned=banned if banned is not None else Banned(),
        config=FlappingConfig(max_count=max_count, window=window,
                              ban_time=ban_time))


def test_threshold_bans_client():
    fl = _mk(max_count=3)
    for _ in range(2):
        fl.disconnected("c1")
    assert fl.banned.look_up("clientid", "c1") is None
    fl.disconnected("c1")  # third strike inside the window
    rule = fl.banned.look_up("clientid", "c1")
    assert rule is not None
    assert rule.by == "flapping"
    # the track resets after the ban: counting starts over
    assert "c1" not in fl._tracks


def test_below_threshold_never_bans():
    fl = _mk(max_count=10)
    for _ in range(9):
        fl.disconnected("quiet")
    assert fl.banned.look_up("clientid", "quiet") is None


def test_window_expiry_resets_count():
    fl = _mk(max_count=3, window=60.0)
    fl.disconnected("c2")
    fl.disconnected("c2")
    # age the track past the window: the next disconnect starts a
    # fresh one instead of completing the old streak
    fl._tracks["c2"].started -= 61.0
    fl.disconnected("c2")
    assert fl.banned.look_up("clientid", "c2") is None
    assert fl._tracks["c2"].count == 1


def test_gc_drops_stale_tracks_only():
    fl = _mk(max_count=10, window=60.0)
    fl.disconnected("old")
    fl.disconnected("fresh")
    fl._tracks["old"].started -= 120.0
    fl.gc()
    assert "old" not in fl._tracks
    assert "fresh" in fl._tracks


def test_flapping_ban_never_downgrades_operator_ban():
    banned = Banned()
    banned.create("clientid", "vip-blocked", by="admin",
                  reason="operator rule", duration=None)  # permanent
    fl = _mk(max_count=2, ban_time=10.0, banned=banned)
    fl.disconnected("vip-blocked")
    fl.disconnected("vip-blocked")
    rule = banned.look_up("clientid", "vip-blocked")
    # the operator's permanent ban survives (create_unless_outlasted)
    assert rule.by == "admin"
    assert rule.until is None


def test_reconnect_storm_bans_flappers_spares_steady():
    """The storm shape the flap-storm scenario drives: a population
    reconnecting at a steady rate stays unbanned, while the hot
    flappers (many disconnects inside one window) all get caught."""
    fl = _mk(max_count=15, window=60.0, ban_time=300.0)
    flappers = [f"flap-{i}" for i in range(20)]
    steady = [f"steady-{i}" for i in range(200)]
    # steady clients: a couple of reconnects each — normal churn
    for cid in steady:
        fl.disconnected(cid)
        fl.disconnected(cid)
    # flappers: a tight crash loop
    for _ in range(15):
        for cid in flappers:
            fl.disconnected(cid)
    for cid in flappers:
        rule = fl.banned.look_up("clientid", cid)
        assert rule is not None and rule.by == "flapping", cid
        assert fl.banned.check(clientid=cid), cid
    for cid in steady:
        assert fl.banned.look_up("clientid", cid) is None, cid
    # gc after the window clears the steady tracks
    now = time.time() + 61.0
    fl.gc(now=now)
    assert not fl._tracks


def test_banned_client_rejected_then_expires():
    fl = _mk(max_count=2, ban_time=0.05)
    fl.disconnected("bounce")
    fl.disconnected("bounce")
    assert fl.banned.check(clientid="bounce")
    time.sleep(0.06)
    # the short auto-ban lapses: the client may reconnect
    assert not fl.banned.check(clientid="bounce")
