"""Multi-loop front door: cross-loop delivery parity + invariants
(docs/DISPATCH.md "Multi-loop front door").

The pinned contract: a node with ``loops = N`` delivers EXACTLY what
the single-loop node delivers — per-connection wire content (topic,
payload, qos, retain, dup, properties), per-session packet-id
sequences, delivery counts, and metric deltas — across QoS0 broadcast,
QoS1/2 per-subscriber frames, shared groups, and session takeover,
including takeover of a session owned by a *different* loop. On top of
parity, the ring's own invariants: at most one cross-loop handoff per
loop per batch, deterministic round-robin placement, and the egress
pre-serialization staying off-loop (``delivery.serialize.onloop`` 0)
across the ring.
"""

import asyncio

import pytest

from emqx_tpu.broker import DispatchConfig
from emqx_tpu.mqtt import constants as C
from emqx_tpu.router import MatcherConfig

from helpers import broker_node, node_port
from mqtt_client import TestClient

#: metric keys whose deltas are timing-dependent (wakeup coalescing,
#: handoff counts scale with how publishes landed in batch ticks) —
#: excluded from the equality dict; the xloop ones get their own
#: invariant assertions below
_TIMING_KEYS = ("delivery.wakeups", "delivery.xloop.handoffs",
                "delivery.xloop.deliveries")


async def _workload(loops: int):
    """The parity workload: mixed-QoS fan-out + shared group through
    a ``loops``-sharded node; returns (comparable, xstats)."""
    async with broker_node(
            loops=loops,
            matcher=MatcherConfig(device_min_filters=0),
            dispatch_config=DispatchConfig()) as node:
        port = node_port(node)
        a0 = TestClient("a0")                     # v4 qos0
        a1 = TestClient("a1")                     # v4 qos1
        a2 = TestClient("a2", version=C.MQTT_V5)  # v5 qos2
        a3 = TestClient("a3", version=C.MQTT_V5)  # v5 subid slow path
        a4 = TestClient("a4")                     # v4 qos1 literal
        g1 = TestClient("g1")                     # shared group
        g2 = TestClient("g2")
        pub = TestClient("wp")
        clients = [a0, a1, a2, a3, a4, g1, g2, pub]
        # sequential connects => deterministic round-robin placement
        for cli in clients:
            await cli.connect(port=port)
        await a0.subscribe("L/+", qos=0)
        await a1.subscribe("L/#", qos=1)
        await a2.subscribe("L/t", qos=2)
        await a3.subscribe("L/+", qos=1,
                           props={"Subscription-Identifier": 7})
        await a4.subscribe("L/t", qos=1)
        await g1.subscribe("$share/g/L/t", qos=1)
        await g2.subscribe("$share/g/L/t", qos=1)
        on_t = [a0, a1, a2, a3, a4]   # subscribers matching L/t
        on_x = [a0, a1, a3]           # subscribers matching L/x
        expect = {c: 0 for c in on_t}
        for i in range(3):
            await pub.publish("L/t", payload=b"q0-%d" % i, qos=0)
            for c in on_t:
                expect[c] += 1
        for i in range(4):
            await pub.publish("L/t", payload=b"q1-%d" % i, qos=1)
            for c in on_t:
                expect[c] += 1
        await pub.publish("L/x", payload=b"q1-x", qos=1)
        for c in on_x:
            expect[c] += 1
        for i in range(2):
            await pub.publish("L/t", payload=b"q2-%d" % i, qos=2)
            for c in on_t:
                expect[c] += 1
        await pub.publish("L/t", payload=b"rt", qos=1, retain=True)
        for c in on_t:
            expect[c] += 1
        got = []
        for cli in on_t:
            pkts = []
            for _ in range(expect[cli]):
                p = await cli.recv(timeout=5.0)
                pkts.append((p.topic, bytes(p.payload), p.qos,
                             p.retain, p.dup, p.packet_id,
                             dict(p.properties or {})))
            # batch-tick grouping may interleave topics; per-payload
            # identity (incl. the pid the session assigned it) is the
            # contract
            pkts.sort(key=lambda t: t[1])
            got.append(pkts)
        shared_total = 0
        for cli in (g1, g2):
            try:
                while True:
                    await asyncio.wait_for(cli.inbox.get(), 0.5)
                    shared_total += 1
            except asyncio.TimeoutError:
                pass
        got.append(shared_total)
        got.append({k: v for k, v in node.metrics.all().items()
                    if v and k.startswith(("messages.", "delivery.",
                                           "packets.publish"))
                    and k not in _TIMING_KEYS
                    and k != "delivery.serialize.onloop"})
        xstats = {
            "handoffs": node.metrics.val("delivery.xloop.handoffs"),
            "xdeliveries": node.metrics.val(
                "delivery.xloop.deliveries"),
            "onloop": node.metrics.val("delivery.serialize.onloop"),
            "flushes": node.ingress.flushes,
            "loop_conns_seen": (node.listeners[0].loop_connections()
                                if loops > 1 else []),
        }
        for cli in clients:
            await cli.close()
        return got, xstats


@pytest.mark.parametrize("loops", [2, 4])
async def test_delivery_parity_vs_single_loop(loops):
    base, base_x = await _workload(1)
    multi, multi_x = await _workload(loops)
    # wire content, pid sequences, delivery counts, metric deltas —
    # identical whatever loop each session landed on
    assert multi == base
    # single-loop control: the ring never engaged
    assert base_x["handoffs"] == 0 and base_x["xdeliveries"] == 0
    # multi-loop: the ring actually carried deliveries, with at most
    # one handoff per loop per batch, and the on-loop serialize count
    # (the workload's deliberate slow-path subscribers: subid, shared
    # redispatch state) unchanged by the sharding
    assert multi_x["xdeliveries"] > 0
    assert 0 < multi_x["handoffs"] <= multi_x["flushes"] * (loops - 1)
    assert multi_x["onloop"] == base_x["onloop"], (base_x, multi_x)


async def test_onloop_stays_zero_for_eligible_traffic_across_ring():
    """The PR 5 invariant survives the ring: eligible QoS1 fan-out
    patches pre-built templates on the OWNING loop — zero on-loop
    serializes with loops=2, exactly as with loops=1."""
    async with broker_node(
            loops=2,
            matcher=MatcherConfig(device_min_filters=0)) as node:
        port = node_port(node)
        subs = [TestClient(f"z{i}") for i in range(4)]
        pub = TestClient("zp")
        for cli in subs + [pub]:
            await cli.connect(port=port)
        for cli in subs:
            await cli.subscribe("z/+", qos=1)
        for i in range(6):
            await pub.publish("z/t", payload=b"%d" % i, qos=1)
        for cli in subs:
            for _ in range(6):
                await cli.recv(timeout=5.0)
        assert node.metrics.val("delivery.serialize.onloop") == 0
        assert node.metrics.val("delivery.xloop.deliveries") > 0
        for cli in subs + [pub]:
            await cli.close()


async def test_round_robin_placement_is_deterministic():
    async with broker_node(loops=3) as node:
        port = node_port(node)
        clients = [TestClient(f"rr{i}") for i in range(7)]
        for cli in clients:
            await cli.connect(port=port)
        # conn k lands on loop k % 3: 7 conns -> [3, 2, 2]
        assert node.listeners[0].loop_connections() == [3, 2, 2]
        for cli in clients:
            await cli.close()
        for _ in range(100):
            if node.listeners[0].loop_connections() == [0, 0, 0]:
                break
            await asyncio.sleep(0.02)
        assert node.listeners[0].loop_connections() == [0, 0, 0]


async def test_cross_loop_takeover():
    """A reconnecting client accepted by a DIFFERENT loop takes over
    the live session: the takeover marshals onto the old owning loop,
    the session resumes with its inflight/pid state, and subsequent
    deliveries route to the new owning loop."""
    async with broker_node(
            loops=2,
            matcher=MatcherConfig(device_min_filters=0)) as node:
        port = node_port(node)
        tk1 = TestClient("tk", version=C.MQTT_V5, clean_start=False,
                         properties={"Session-Expiry-Interval": 300})
        await tk1.connect(port=port)          # conn 1 -> loop 0
        await tk1.subscribe("tk/t", qos=1)
        pub = TestClient("tkp")
        await pub.connect(port=port)          # conn 2 -> loop 1
        await pub.publish("tk/t", payload=b"before", qos=1)
        p = await tk1.recv(timeout=5.0)
        assert p.payload == b"before"
        assert node.listeners[0].loop_connections() == [1, 1]
        filler = TestClient("fill")
        await filler.connect(port=port)       # conn 3 -> loop 0
        tk2 = TestClient("tk", version=C.MQTT_V5, clean_start=False,
                         properties={"Session-Expiry-Interval": 300})
        await tk2.connect(port=port)          # conn 4 -> loop 1 (!)
        assert tk2.connack.session_present
        assert node.metrics.val("session.takeovered") == 1
        # the old owner was told why, on ITS loop
        d = await asyncio.wait_for(tk1.acks.get(), 5.0)
        assert getattr(d, "reason_code", None) == 0x8E, d
        # deliveries now route to the session's NEW owning loop
        await pub.publish("tk/t", payload=b"after", qos=1)
        p2 = await tk2.recv(timeout=5.0)
        assert p2.payload == b"after"
        # pid sequence continued from the taken-over session state
        assert p2.packet_id > p.packet_id
        for cli in (tk1, tk2, pub, filler):
            await cli.close()


async def test_loops1_is_the_single_loop_build():
    """loops = 1 constructs no LoopGroup: classic asyncio server,
    lock-free metrics, no ring — byte-for-byte the pre-multi-loop
    node."""
    async with broker_node(loops=1) as node:
        assert node.loop_group is None
        assert node.broker.loop_group is None
        lst = node.listeners[0]
        assert lst._accept_task is None and lst._server is not None
        assert node.metrics._lock is None
        assert node.ingress.accepts_threadsafe() is False
        c = TestClient("one")
        await c.connect(port=node_port(node))
        await c.subscribe("o/t", qos=0)
        await c.publish("o/t", payload=b"hi")
        assert (await c.recv(timeout=5.0)).payload == b"hi"
        assert node.metrics.val("delivery.xloop.handoffs") == 0
        await c.close()


def test_loops_validation():
    from emqx_tpu.config import ConfigError, parse_config
    from emqx_tpu.node import Node

    with pytest.raises(ValueError):
        Node(boot_listeners=False, loops=0)
    with pytest.raises(ConfigError):
        parse_config({"node": {"loops": 0}})
    with pytest.raises(ConfigError):
        parse_config({"node": {"loops": True}})
    assert parse_config({"node": {"loops": 4}}).loops == 4
