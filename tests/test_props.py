"""Property-style randomized suites for the pure components.

Mirrors the reference's remaining PropEr suites (test/props/):
prop_emqx_base62, prop_emqx_reason_codes, prop_emqx_psk, plus
invariant fuzzing for the session data structures (inflight window,
priority queue, mqueue drop policy) that the reference covers with
randomized CT cases. (prop_emqx_frame's analogue lives in
test_frame_fuzz.py; prop_emqx_json is stdlib json by design;
prop_emqx_rpc's badrpc filtering is covered by the transport error
paths in test_cluster_net.py.)
"""

import random

import pytest

from emqx_tpu.inflight import Inflight
from emqx_tpu.mqtt import reason_codes as RC
from emqx_tpu.mqueue import MQueue
from emqx_tpu.pqueue import PQueue
from emqx_tpu.types import Message
from emqx_tpu.utils import base62


# -- prop_emqx_base62 -------------------------------------------------------

def test_base62_roundtrip_random_ints():
    rng = random.Random(62)
    for _ in range(2000):
        n = rng.randrange(0, 1 << rng.randint(1, 128))
        assert base62.decode(base62.encode(n)) == n


def test_base62_ordering_and_alphabet():
    # encodes use only the declared alphabet; zero encodes non-empty
    assert base62.encode(0)
    rng = random.Random(63)
    for _ in range(500):
        n = rng.randrange(0, 1 << 64)
        s = base62.encode(n)
        assert all(c in base62._ALPHABET for c in s)


# -- prop_emqx_reason_codes -------------------------------------------------

def test_reason_code_names_total_over_catalog():
    """Every exported v5 code has a stable name; unknown codes map to
    the catch-all instead of raising (prop_emqx_reason_codes)."""
    codes = [v for k, v in vars(RC).items()
             if k.isupper() and isinstance(v, int)]
    assert len(set(codes)) > 30
    for c in codes:
        n = RC.name(c)
        assert isinstance(n, str) and n
    for c in range(0x00, 0xFF):
        assert isinstance(RC.name(c), str)


def test_connack_compat_total_and_in_v3_range():
    """v5 CONNACK codes translate to a valid v3 code for every byte
    value (the v3 CONNACK return space is 0..5)."""
    for c in range(0x80, 0x100):
        v3 = RC.compat("connack", c)
        assert v3 is None or 0 <= v3 <= 5, (hex(c), v3)
    # spot-pins from the reference table (emqx_reason_codes.erl)
    assert RC.compat("connack", RC.UNSUPPORTED_PROTOCOL_VERSION) == 1
    assert RC.compat("connack", RC.CLIENT_IDENTIFIER_NOT_VALID) == 2
    assert RC.compat("connack", RC.SERVER_UNAVAILABLE) == 3
    assert RC.compat("connack", RC.BAD_USERNAME_OR_PASSWORD) == 4
    assert RC.compat("connack", RC.NOT_AUTHORIZED) == 5


# -- prop_emqx_psk ----------------------------------------------------------

def test_psk_lookup_chain_property():
    """First resolver that knows the identity wins; unknown
    identities fall through every resolver to None."""
    from emqx_tpu.hooks import Hooks
    from emqx_tpu.psk import PskAuth

    rng = random.Random(7)
    hooks = Hooks()
    stores = [
        {f"id{i}": bytes([i, j]) for i in range(rng.randint(1, 20))}
        for j in range(3)
    ]
    auths = [PskAuth(hooks, s, priority=-j)
             for j, s in enumerate(stores)]
    for _ in range(300):
        ident = f"id{rng.randint(0, 25)}"
        got = auths[0].lookup(ident)
        want = None
        for s in stores:  # priority order = registration order here
            if ident in s:
                want = s[ident]
                break
        assert got == want, (ident, got, want)


# -- inflight window invariants --------------------------------------------

def test_inflight_window_invariants_random_ops():
    rng = random.Random(11)
    inf = Inflight(max_size=16)
    model = {}
    for _ in range(3000):
        op = rng.random()
        key = rng.randint(1, 40)
        if op < 0.5:
            if key in model:
                with pytest.raises(KeyError):
                    inf.insert(key, key * 10)
            elif not inf.is_full():
                # fullness is the CALLER's check (the session gates
                # on is_full before inserting, emqx_session.erl)
                inf.insert(key, key * 10)
                model[key] = key * 10
        elif op < 0.75:
            if key in model:
                inf.delete(key)
                del model[key]
        else:
            assert inf.lookup(key) == model.get(key)
        assert len(inf) == len(model)
        assert inf.is_full() == (len(model) >= 16)
    assert sorted(inf.keys()) == sorted(model)


# -- priority queue invariants ----------------------------------------------

def test_pqueue_pops_highest_priority_fifo_within_class():
    rng = random.Random(13)
    q = PQueue()
    model = {}
    seq = 0
    for _ in range(2000):
        if rng.random() < 0.6 or not any(model.values()):
            prio = rng.choice([0, 1, 2, 5])
            q.push(("item", seq), prio)
            model.setdefault(prio, []).append(("item", seq))
            seq += 1
        else:
            ok, item = q.pop()
            best = max(p for p, xs in model.items() if xs)
            assert ok and item == model[best].pop(0)
    while True:
        ok, item = q.pop()
        if not ok:
            break
        best = max(p for p, xs in model.items() if xs)
        assert item == model[best].pop(0)
    assert not any(model.values())


# -- mqueue drop policy ------------------------------------------------------

def _msg(topic, qos=1):
    return Message(topic=topic, payload=b"", qos=qos)


def test_mqueue_drop_oldest_within_priority_class():
    rng = random.Random(17)
    q = MQueue(max_len=5, priorities={"hot": 9}, store_qos0=True)
    model = {9: [], 0: []}
    for i in range(500):
        topic = rng.choice(["hot", "cold"])
        prio = 9 if topic == "hot" else 0
        m = _msg(f"{topic}", qos=rng.randint(0, 2))
        dropped = q.push(m)
        model[prio].append(m)
        if len(model[prio]) > 5:
            oldest = model[prio].pop(0)
            assert dropped is oldest, i
        else:
            assert dropped is None
    # drains hot class first, FIFO inside each class
    out = []
    while True:
        m = q.pop()
        if m is None:
            break
        out.append(m)
    assert out == model[9] + model[0]


def test_mqueue_qos0_unstored_when_disabled():
    q = MQueue(max_len=10, store_qos0=False)
    m0 = _msg("a", qos=0)
    assert q.push(m0) is m0  # bounced straight back
    m1 = _msg("a", qos=1)
    assert q.push(m1) is None
    assert q.pop() is m1


# -- prop_emqx_sys: $SYS heartbeat content ----------------------------------

def test_sys_heartbeat_topics_and_payload_types():
    """Every heartbeat publication is a $SYS-flagged message under
    $SYS/brokers/<node>/..., with string-decimal payloads for
    stats/metrics and the catalog names intact — and $SYS traffic
    never reaches a root-wildcard subscriber (emqx_trie $SYS
    exclusion, the parity oracle's core rule)."""
    from emqx_tpu.broker import Broker
    from emqx_tpu.stats import Stats
    from emqx_tpu.sys_topics import SysTopics
    from emqx_tpu.types import Message

    b = Broker()
    got = []

    class SysSub:
        def deliver(self, topic, msg):
            got.append((msg.topic, msg.payload, msg.flags.get("sys")))

    class RootSub:
        def __init__(self):
            self.leaked = []

        def deliver(self, topic, msg):
            self.leaked.append(msg.topic)

    b.subscribe(SysSub(), "$SYS/#")
    root = RootSub()
    b.subscribe(root, "#")
    st = Stats()
    st.setstat("connections.count", 3, "connections.max")
    sys_t = SysTopics(b, node="n@h", stats=st, interval=60)
    sys_t.heartbeat()

    assert got, "heartbeat published nothing"
    prefix = "$SYS/brokers"
    by_topic = {}
    for topic, payload, sysflag in got:
        assert topic.startswith(prefix), topic
        assert sysflag, f"missing sys flag on {topic}"
        by_topic[topic] = payload
    assert by_topic["$SYS/brokers"] == b"n@h"
    assert by_topic[f"{prefix}/n@h/uptime"].isdigit()
    assert by_topic[f"{prefix}/n@h/version"]
    assert by_topic[f"{prefix}/n@h/stats/connections.count"] == b"3"
    # all stats/metrics payloads parse as integers
    for topic, payload in by_topic.items():
        if "/stats/" in topic or "/metrics/" in topic:
            int(payload)
    # $SYS exclusion: the root wildcard saw none of it
    assert root.leaked == [], root.leaked
