"""Conformance through the INDEPENDENT client (tests/indie_mqtt.py).

The server-side behavior asserted here is the same the v4/v5 suites
cover through the repo's own client — but driven by a codec with a
separate reading of the spec (the reference's emqtt role,
/root/reference/test/emqx_client_SUITE.erl:78-86). A mirrored
misreading between the repo's client and server fails HERE.

Plus wire-level golden vectors: hand-derived byte strings (and
cross-codec equality against ``emqx_tpu.mqtt``) for v5 property
round-trips — the bytes themselves are the contract.
"""

import asyncio

import pytest

from tests import indie_mqtt as im
from tests.helpers import broker_node, node_port


# -- v3.1.1 tier -----------------------------------------------------------


async def test_v4_connect_sub_pub_roundtrip():
    async with broker_node() as n:
        port = node_port(n)
        sub = im.IndieClient("i4-sub", version=4)
        ack = await sub.connect(port=port)
        assert ack.rc == 0 and not ack.session_present
        sb = await sub.subscribe(("t/+", 1), ("exact/t", 0))
        assert sb.rcs == [1, 0]  # granted qos echoes the request

        pub = im.IndieClient("i4-pub", version=4)
        await pub.connect(port=port)
        await pub.publish("t/a", b"q0")             # qos0
        rc = await pub.publish("t/b", b"q1", qos=1)
        assert rc == 0
        rc = await pub.publish("exact/t", b"q2", qos=2)
        assert rc == 0

        got = {}
        for _ in range(3):
            p = await sub.recv()
            got[p.topic] = (p.payload, p.qos)
        # subscription max qos caps delivery (3.1.1 §3.8.4)
        assert got == {"t/a": (b"q0", 0), "t/b": (b"q1", 1),
                       "exact/t": (b"q2", 0)}
        await sub.disconnect()
        await pub.disconnect()


async def test_v4_session_present_and_queueing():
    async with broker_node() as n:
        port = node_port(n)
        c = im.IndieClient("i4-sess", version=4, clean=False)
        await c.connect(port=port)
        await c.subscribe(("s/q", 1))
        c.writer.close()  # drop without DISCONNECT: session persists
        await asyncio.sleep(0.2)

        pub = im.IndieClient("i4-sess-pub", version=4)
        await pub.connect(port=port)
        await pub.publish("s/q", b"queued", qos=1)
        await pub.disconnect()

        c2 = im.IndieClient("i4-sess", version=4, clean=False)
        ack = await c2.connect(port=port)
        assert ack.session_present
        p = await c2.recv(timeout=15)
        assert (p.topic, p.payload, p.qos) == ("s/q", b"queued", 1)
        await c2.disconnect()


async def test_v4_retain_and_unsubscribe():
    async with broker_node(load_default_modules=True) as n:
        port = node_port(n)
        pub = im.IndieClient("i4-ret-pub", version=4)
        await pub.connect(port=port)
        await pub.publish("r/t", b"kept", qos=1, retain=True)

        sub = im.IndieClient("i4-ret-sub", version=4)
        await sub.connect(port=port)
        await sub.subscribe(("r/#", 0))
        p = await sub.recv()
        assert (p.topic, p.payload, p.retain) == ("r/t", b"kept", True)
        await sub.unsubscribe("r/#")
        await pub.publish("r/t", b"after-unsub")
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.5)
        # empty retained payload clears (3.1.1 §3.3.1.3)
        await pub.publish("r/t", b"", retain=True)
        sub2 = im.IndieClient("i4-ret-sub2", version=4)
        await sub2.connect(port=port)
        await sub2.subscribe(("r/#", 0))
        with pytest.raises(asyncio.TimeoutError):
            await sub2.recv(timeout=0.5)
        for c in (pub, sub, sub2):
            await c.disconnect()


async def test_v4_will_on_abnormal_disconnect():
    async with broker_node() as n:
        port = node_port(n)
        watcher = im.IndieClient("i4-will-w", version=4)
        await watcher.connect(port=port)
        await watcher.subscribe(("wills/+", 1))

        doomed = im.IndieClient(
            "i4-doomed", version=4,
            will={"topic": "wills/i4", "payload": b"gone", "qos": 1})
        await doomed.connect(port=port)
        doomed.writer.close()  # abnormal: will MUST publish
        p = await watcher.recv(timeout=15)
        assert (p.topic, p.payload) == ("wills/i4", b"gone")
        await watcher.disconnect()


async def test_v4_ping_and_qos2_server_flow():
    async with broker_node() as n:
        port = node_port(n)
        sub = im.IndieClient("i4-q2-sub", version=4)
        await sub.connect(port=port)
        await sub.ping()
        await sub.subscribe(("q2/t", 2))
        pub = im.IndieClient("i4-q2-pub", version=4)
        await pub.connect(port=port)
        await pub.publish("q2/t", b"exactly-once", qos=2)
        p = await sub.recv()
        assert p.qos == 2 and p.payload == b"exactly-once"
        # auto_ack drove PUBREC/PUBREL/PUBCOMP; the server's PUBREL
        # lands in acks
        rel = await asyncio.wait_for(sub.acks.get(), 10)
        assert rel.ptype == im.PUBREL and rel.pkt_id == p.pkt_id
        await sub.disconnect()
        await pub.disconnect()


# -- v5 tier ---------------------------------------------------------------


async def test_v5_properties_roundtrip_and_user_props():
    async with broker_node() as n:
        port = node_port(n)
        sub = im.IndieClient("i5-sub", version=5,
                             props={"Session-Expiry-Interval": 120,
                                    "Receive-Maximum": 10})
        ack = await sub.connect(port=port)
        assert ack.rc == 0
        await sub.subscribe(("p/t", 1))

        pub = im.IndieClient("i5-pub", version=5)
        await pub.connect(port=port)
        await pub.publish(
            "p/t", b"v5", qos=1,
            props={"Content-Type": "text/plain",
                   "Response-Topic": "replies/here",
                   "Correlation-Data": b"\x00\x01corr",
                   "Message-Expiry-Interval": 300,
                   "User-Property": [("k1", "v1"), ("k1", "v2")]})
        p = await sub.recv()
        assert p.props["Content-Type"] == "text/plain"
        assert p.props["Response-Topic"] == "replies/here"
        assert p.props["Correlation-Data"] == b"\x00\x01corr"
        # expiry is rewritten to remaining time, never grown (§3.3.2.3.3)
        assert 0 < p.props["Message-Expiry-Interval"] <= 300
        assert p.props["User-Property"] == [("k1", "v1"), ("k1", "v2")]
        await sub.disconnect()
        await pub.disconnect()


async def test_v5_topic_alias_inbound():
    async with broker_node() as n:
        port = node_port(n)
        sub = im.IndieClient("i5-al-sub", version=5)
        await sub.connect(port=port)
        await sub.subscribe(("al/t", 0))
        pub = im.IndieClient("i5-al-pub", version=5)
        ack = await pub.connect(port=port)
        assert ack.props.get("Topic-Alias-Maximum", 0) >= 1
        # establish alias 1 then publish by alias with empty topic
        await pub.publish("al/t", b"first",
                          props={"Topic-Alias": 1})
        await pub.publish("", b"by-alias", props={"Topic-Alias": 1})
        p1 = await sub.recv()
        p2 = await sub.recv()
        assert (p1.topic, p1.payload) == ("al/t", b"first")
        assert (p2.topic, p2.payload) == ("al/t", b"by-alias")
        await sub.disconnect()
        await pub.disconnect()


async def test_v5_subscription_options_nl_rap_rh():
    async with broker_node() as n:
        port = node_port(n)
        c = im.IndieClient("i5-opts", version=5)
        await c.connect(port=port)
        # no-local: own publishes must not come back (§3.8.3.1)
        await c.subscribe(("nl/t", 0x04))  # qos0 | nl
        await c.publish("nl/t", b"self")
        with pytest.raises(asyncio.TimeoutError):
            await c.recv(timeout=0.5)
        # retain-as-published keeps the retain flag on routed copies
        w = im.IndieClient("i5-rap", version=5)
        await w.connect(port=port)
        await w.subscribe(("rap/t", 0x08))  # qos0 | rap
        await c.publish("rap/t", b"flagged", retain=True)
        p = await w.recv()
        assert p.retain is True
        # retain-handling=2: no retained message on subscribe
        r2 = im.IndieClient("i5-rh2", version=5)
        await r2.connect(port=port)
        await r2.subscribe(("rap/t", 0x20))  # qos0 | rh=2
        with pytest.raises(asyncio.TimeoutError):
            await r2.recv(timeout=0.5)
        for x in (c, w, r2):
            await x.disconnect()


async def test_v5_subscription_identifier_delivery():
    async with broker_node() as n:
        port = node_port(n)
        c = im.IndieClient("i5-subid", version=5)
        await c.connect(port=port)
        pid = c.next_pkt_id()
        await c._send(im.build_subscribe(
            pid, [("sid/+", 1)], version=5,
            props={"Subscription-Identifier": 42}))
        sb = await c._expect(im.SUBACK)
        assert sb.rcs == [1]
        pub = im.IndieClient("i5-subid-pub", version=5)
        await pub.connect(port=port)
        await pub.publish("sid/x", b"tagged", qos=1)
        p = await c.recv()
        assert p.props.get("Subscription-Identifier") == [42]
        await c.disconnect()
        await pub.disconnect()


async def test_v5_shared_subscription_balances():
    async with broker_node() as n:
        port = node_port(n)
        members = []
        for i in range(2):
            m = im.IndieClient(f"i5-share-{i}", version=5)
            await m.connect(port=port)
            await m.subscribe(("$share/g/sh/t", 1))
            members.append(m)
        pub = im.IndieClient("i5-share-pub", version=5)
        await pub.connect(port=port)
        sent = {f"m{i}".encode() for i in range(8)}
        for i in range(8):
            await pub.publish("sh/t", f"m{i}".encode(), qos=1)
        got = []
        deadline = asyncio.get_event_loop().time() + 20
        while len(got) < 8:
            assert asyncio.get_event_loop().time() < deadline, got
            for m in members:
                try:
                    got.append((await asyncio.wait_for(
                        m.inbox.get(), 0.25)).payload)
                except asyncio.TimeoutError:
                    pass
        # exactly-once across the group, no duplicates
        assert sorted(got) == sorted(sent)
        for m in members:
            await m.disconnect()
        await pub.disconnect()


async def test_v5_unsub_reason_code_no_subscription():
    async with broker_node() as n:
        port = node_port(n)
        c = im.IndieClient("i5-unsub", version=5)
        await c.connect(port=port)
        ub = await c.unsubscribe("never/subscribed")
        assert ub.rcs == [0x11]  # No subscription existed (§3.11.3)
        await c.disconnect()


async def test_v5_server_disconnect_on_protocol_error():
    """A second CONNECT on a live connection is a protocol error —
    the server must drop the connection (v5 §3.1: may send
    DISCONNECT first)."""
    async with broker_node() as n:
        port = node_port(n)
        c = im.IndieClient("i5-dup-connect", version=5)
        await c.connect(port=port)
        await c._send(im.build_connect("i5-dup-connect", version=5))
        with pytest.raises(im.MQTTError):
            for _ in range(4):
                await c.recv(timeout=10)
        await c.close()


# -- wire-level golden vectors ---------------------------------------------


def test_golden_v5_publish_property_bytes():
    """Hand-derived golden bytes for a v5 PUBLISH with properties —
    both codecs must EMIT and ACCEPT exactly these bytes."""
    golden = bytes([
        0x32, 0x1D,              # PUBLISH qos1, remaining len 29
        0x00, 0x03, 0x61, 0x2F, 0x62,  # topic "a/b"
        0x00, 0x07,              # packet id 7
        0x13,                    # properties length 19
        0x01, 0x01,              # Payload-Format-Indicator = 1
        0x02, 0x00, 0x00, 0x00, 0x3C,  # Message-Expiry 60
        0x23, 0x00, 0x05,        # Topic-Alias = 5
        0x26, 0x00, 0x01, 0x6B, 0x00, 0x01, 0x76,  # User-Prop k:v
        0x0B, 0x2A,              # Subscription-Identifier = 42
        0x68, 0x69,              # payload "hi"
    ])
    built = im.build_publish(
        "a/b", b"hi", qos=1, pkt_id=7, version=5,
        props={"Payload-Format-Indicator": 1,
               "Message-Expiry-Interval": 60,
               "Topic-Alias": 5,
               "User-Property": [("k", "v")],
               "Subscription-Identifier": [42]})
    assert built == golden, (built.hex(), golden.hex())
    # the repo's codec parses the same bytes to the same meaning
    from emqx_tpu.mqtt.frame import Parser, serialize
    from emqx_tpu.mqtt.packet import Publish

    parser = Parser(version=5)
    pkts = parser.feed(golden)
    assert len(pkts) == 1
    pkt = pkts[0]
    assert isinstance(pkt, Publish)
    assert pkt.topic == "a/b" and pkt.payload == b"hi" \
        and pkt.qos == 1 and pkt.packet_id == 7
    props = pkt.properties
    assert props["Payload-Format-Indicator"] == 1
    assert props["Message-Expiry-Interval"] == 60
    assert props["Topic-Alias"] == 5
    assert props["User-Property"] == [("k", "v")]
    assert props["Subscription-Identifier"] in (42, [42])
    # and the repo's serializer emits byte-identical wire data
    out = serialize(pkt, version=5)
    assert bytes(out) == golden, (bytes(out).hex(), golden.hex())


def test_golden_v5_connack_session_expiry_bytes():
    """CONNACK with Session-Expiry + Assigned-Client-Identifier —
    decoded identically by both codecs from one golden byte string."""
    golden = bytes([
        0x20, 0x0F,              # CONNACK, remaining length 15
        0x01, 0x00,              # session present, rc 0
        0x0C,                    # properties length 12
        0x11, 0x00, 0x00, 0x00, 0x78,  # Session-Expiry 120
        0x12, 0x00, 0x04, 0x61, 0x62, 0x63, 0x64,  # Assigned-CID "abcd"
    ])
    p = im.decode(golden[0] >> 4, golden[0] & 0x0F, golden[2:], 5)
    assert p.session_present and p.rc == 0
    assert p.props["Session-Expiry-Interval"] == 120
    assert p.props["Assigned-Client-Identifier"] == "abcd"

    from emqx_tpu.mqtt.frame import Parser
    pkts = Parser(version=5).feed(golden)
    assert len(pkts) == 1
    pkt = pkts[0]
    assert pkt.session_present and pkt.reason_code == 0
    assert pkt.properties["Session-Expiry-Interval"] == 120
    assert pkt.properties["Assigned-Client-Identifier"] == "abcd"


def test_cross_codec_connect_subscribe_bytes():
    """The two codecs emit byte-identical CONNECT/SUBSCRIBE frames
    for the same inputs (any divergence is a spec disagreement to
    settle, not two acceptable encodings)."""
    from emqx_tpu.mqtt.frame import serialize
    from emqx_tpu.mqtt.packet import Connect, Subscribe

    indie = im.build_connect("cmp-cid", version=5, clean=True,
                             keepalive=30,
                             props={"Session-Expiry-Interval": 60})
    repo = serialize(Connect(
        proto_ver=5, proto_name="MQTT", client_id="cmp-cid",
        clean_start=True, keepalive=30,
        properties={"Session-Expiry-Interval": 60}), version=5)
    assert indie == bytes(repo), (indie.hex(), bytes(repo).hex())

    indie = im.build_subscribe(3, [("x/+", 0x01 | 0x04)], version=5)
    repo = serialize(Subscribe(
        packet_id=3,
        topic_filters=[("x/+", {"qos": 1, "nl": 1})]), version=5)
    assert indie == bytes(repo), (indie.hex(), bytes(repo).hex())