"""Replicated durability: journal shipping, warm standby, failover
(docs/DURABILITY.md "Replicated durability").

The acceptance property: a primary that flushed-and-shipped its
journal can die at any moment and its warm standby promotes with
RPO = 0 for acked records — routes, retained messages, and
persistent sessions byte-exact (digest-verified against the
primary's pre-kill state). Degradation is suspect-aware: an
unreachable standby drops the shipper to local-only (durability
itself unaffected) and the next contact resyncs.

Multi-node-in-one-process over real sockets, same harness shape as
tests/test_cluster_heal.py.
"""

import time

import pytest

from emqx_tpu import faults
from emqx_tpu.cluster import Cluster, ClusterConfig
from emqx_tpu.cluster_net import SocketTransport
from emqx_tpu.durability import DurabilityConfig
from emqx_tpu.modules.retainer import RetainerModule
from emqx_tpu.node import Node
from emqx_tpu.replication import durable_digest
from emqx_tpu.session import Session
from emqx_tpu.types import Message, SubOpts


def _fast_cfg(**kw) -> ClusterConfig:
    base = dict(heartbeat_interval_s=0.1, heartbeat_timeout_s=0.5,
                suspect_after=1, down_after=3, ok_after=1,
                anti_entropy_interval_s=30.0, call_timeout_s=2.0,
                redial_backoff_s=0.1, redial_backoff_max_s=0.5)
    base.update(kw)
    return ClusterConfig(**base)


def _wait(pred, timeout=20.0, msg="condition not met in time"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(msg)


class _Chan:
    def __init__(self, s):
        self.session = s
        self.client_id = s.client_id


def _durable_session(node, cid, expiry=300.0):
    s = Session(cid, broker=node.broker, clean_start=False)
    node.durability.session_opened(s, expiry)
    node.cm.register_channel(cid, _Chan(s))
    return s


def _mk_pair(tmp_path, cookie, dur0_kw=None, dur1=False,
             cluster_kw=None):
    """Two socket-clustered nodes; rn0 is a durable primary shipping
    to rn1. Returns (nodes, transports, clusters)."""
    cfg = _fast_cfg(**(cluster_kw or {}))
    nodes, trs, cls = [], [], []
    for i in range(2):
        dkw = None
        if i == 0:
            dkw = dict(enabled=True, dir=str(tmp_path / f"d{i}"),
                       fsync=False, standby="rn1", wal_shards=2,
                       repl_ack_timeout_s=2.0)
            dkw.update(dur0_kw or {})
        elif dur1:
            dkw = dict(enabled=True, dir=str(tmp_path / f"d{i}"),
                       fsync=False)
        node = Node(name=f"rn{i}", boot_listeners=False,
                    durability=(DurabilityConfig(**dkw)
                                if dkw else None))
        node.modules.load(RetainerModule)
        if node.durability is not None:
            node.durability.recover()
        tr = SocketTransport(f"rn{i}", cookie=cookie, config=cfg)
        tr.serve()
        cl = Cluster(node, transport=tr, config=cfg)
        nodes.append(node)
        trs.append(tr)
        cls.append(cl)
    cls[1].join_remote("127.0.0.1", trs[0].port)
    return nodes, trs, cls


def _teardown(nodes, trs, cls):
    for node in nodes:
        if node.durability is not None \
                and node.durability.wal is not None:
            node.durability.wal.close()
    for cl in cls:
        cl.close()
    for tr in trs:
        tr.close()


def _populate(n0):
    """The canonical durable workload: a durable session with plain +
    shared subs and unacked QoS1 inflight, retained set + clear."""
    s = _durable_session(n0, "dev1")
    s.subscribe("fleet/+/state", SubOpts(qos=1))
    s.subscribe("$share/g/fleet/cmd", SubOpts(qos=2))
    n0.broker.publish(Message(topic="fleet/1/state", payload=b"up",
                              qos=1, flags={"retain": True}))
    n0.broker.publish(Message(topic="fleet/2/state", payload=b"x",
                              flags={"retain": True}))
    n0.broker.publish(Message(topic="fleet/2/state", payload=b"",
                              flags={"retain": True}))  # tombstone
    n0.broker.publish(Message(topic="fleet/9/state", payload=b"q",
                              qos=1))
    n0.durability.on_batch()
    return s


def _repl(n0):
    return n0.replication


def _synced(n0):
    r = _repl(n0)
    return (r.state == "replicating"
            and r.acked_seq >= r.offered_seq)


def _kill_primary(nodes, trs):
    """kill -9 analogue for the clustered primary: drop its
    durability hooks and sever its transport so the peer's detector
    declares it down."""
    nodes[0].broker.durability = None
    nodes[0].cm.durability = None
    trs[0].close()


# -- shipping --------------------------------------------------------------


def test_ship_and_ack_reach_warm_replica(tmp_path):
    nodes, trs, cls = _mk_pair(tmp_path, "rep-ship")
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="journal never acked")
        rep = nodes[1].replication.replicas["rn0"]
        assert rep.sessions and "dev1" in rep.sessions
        assert "fleet/1/state" in rep.retained
        assert "fleet/2/state" in rep.tombs
        assert any(k[0] == "fleet/+/state" for k in rep.routes)
        assert not rep.promoted
        r = _repl(nodes[0])
        assert r.info()["role"] == "primary"
        assert r.lag() == (0, 0)
        assert r.counters["repl.resyncs"] == 1  # the initial hello
    finally:
        _teardown(nodes, trs, cls)


def test_incremental_ship_after_hello(tmp_path):
    """Records journaled after the initial snapshot ship as the
    incremental stream (no re-hello)."""
    nodes, trs, cls = _mk_pair(tmp_path, "rep-inc")
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        resyncs = _repl(nodes[0]).counters["repl.resyncs"]
        s2 = _durable_session(nodes[0], "dev2")
        s2.subscribe("late/+", SubOpts(qos=1))
        nodes[0].broker.publish(Message(
            topic="late/r", payload=b"v", flags={"retain": True}))
        nodes[0].durability.on_batch()
        _wait(lambda: _synced(nodes[0]), msg="incremental sync")
        rep = nodes[1].replication.replicas["rn0"]
        assert "dev2" in rep.sessions
        assert "late/r" in rep.retained
        assert _repl(nodes[0]).counters["repl.resyncs"] == resyncs
    finally:
        _teardown(nodes, trs, cls)


# -- failover --------------------------------------------------------------


def test_promote_on_primary_down_byte_exact_rpo_zero(tmp_path):
    """The headline property: primary dies, standby promotes —
    durable planes digest-equal to the primary's pre-kill state
    (routes remapped to the standby), RPO = 0 for acked records."""
    nodes, trs, cls = _mk_pair(tmp_path, "rep-promote")
    try:
        s = _populate(nodes[0])
        assert len(s.inflight) == 2
        _wait(lambda: _synced(nodes[0]), msg="sync before kill")
        r = _repl(nodes[0])
        acked_at_kill = r.acked_seq
        offered_at_kill = r.offered_seq
        assert acked_at_kill == offered_at_kill  # RPO = 0 premise
        # the digest compares the session DETACHED on both sides
        nodes[0].cm._detached["dev1"] = (s, 0, 300.0)
        want = durable_digest(nodes[0])
        del nodes[0].cm._detached["dev1"]
        _kill_primary(nodes, trs)
        _wait(lambda: nodes[1].replication.replicas["rn0"].promoted,
              msg="standby never promoted")
        rep = nodes[1].replication.replicas["rn0"]
        assert rep.applied_seq >= acked_at_kill  # nothing acked lost
        assert "dev1" in nodes[1].cm._detached
        got = durable_digest(nodes[1])
        assert got == want, "promoted state diverged from primary"
        # the resurrected window still carries the unacked QoS1s
        s2 = nodes[1].cm._detached["dev1"][0]
        assert len(s2.inflight) == 2
        assert nodes[1].router.route_refs(
            "fleet/+/state", nodes[1].broker.node) == 1
        lp = nodes[1].replication.last_promotion
        assert lp["primary"] == "rn0" and lp["failover_s"] < 5.0
        assert lp["sessions"] == 1
    finally:
        _teardown(nodes, trs, cls)


def test_promoted_standby_journals_and_survives_its_own_crash(
        tmp_path):
    """Double-recovery on the promoted side: a standby with its own
    durability checkpoints the adopted state, so ITS crash right
    after failover recovers the inherited sessions exactly."""
    nodes, trs, cls = _mk_pair(tmp_path, "rep-double", dur1=True)
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="sync before kill")
        _kill_primary(nodes, trs)
        _wait(lambda: nodes[1].replication.replicas["rn0"].promoted,
              msg="standby never promoted")
        want = durable_digest(nodes[1])
        # crash the promoted standby (no graceful path)…
        nodes[1].broker.durability = None
        nodes[1].cm.durability = None
        nodes[1].durability.wal.close()
        nodes[1].durability = None
        # …and recover a fresh incarnation from its directory
        n2 = Node(name="rn1", boot_listeners=False,
                  durability=DurabilityConfig(
                      enabled=True, dir=str(tmp_path / "d1"),
                      fsync=False))
        n2.modules.load(RetainerModule)
        n2.durability.recover()
        assert "dev1" in n2.cm._detached
        assert durable_digest(n2) == want
        n2.durability.wal.close()
    finally:
        _teardown(nodes, trs, cls)


# -- degradation + resync --------------------------------------------------


def test_suspect_standby_falls_back_local_only_then_resyncs(
        tmp_path):
    """An unreachable standby drops the shipper to local-only (local
    durability unaffected); when the peer heals, shipping resyncs and
    lag returns to zero."""
    nodes, trs, cls = _mk_pair(tmp_path, "rep-fallback")
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        resyncs0 = _repl(nodes[0]).counters["repl.resyncs"]
        # sever the link both ways
        trs[0].fault_peers = {"rn1"}
        trs[1].fault_peers = {"rn0"}
        faults.set_master(True)
        faults.arm("net.partition", times=0)
        s2 = _durable_session(nodes[0], "dev2")
        s2.subscribe("cut/+", SubOpts(qos=1))
        nodes[0].durability.on_batch()
        _wait(lambda: _repl(nodes[0]).state == "local_only",
              msg="shipper never degraded")
        # local durability is unaffected: the journal has the records
        assert nodes[0].durability.wal.records > 0
        assert _repl(nodes[0]).lag()[0] > 0
        # heal: detector recovers the peer, shipping resumes
        faults.disarm("net.partition")
        _wait(lambda: _synced(nodes[0]), timeout=30.0,
              msg="shipper never resynced")
        rep = nodes[1].replication.replicas["rn0"]
        assert "dev2" in rep.sessions
        assert _repl(nodes[0]).counters["repl.resyncs"] >= resyncs0
    finally:
        faults.clear()
        _teardown(nodes, trs, cls)


def test_repl_ship_fault_point_drop_and_stall(tmp_path):
    """The repl.ship fault point: drop discards the ship call (the
    shipper degrades, then resyncs when disarmed); stall only delays
    it."""
    nodes, trs, cls = _mk_pair(tmp_path, "rep-fault")
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        errors0 = _repl(nodes[0]).counters["repl.ship_errors"]
        with faults.injected("repl.ship", times=1):
            s2 = _durable_session(nodes[0], "dev2")
            s2.subscribe("drop/+", SubOpts(qos=1))
            nodes[0].durability.on_batch()
            _wait(lambda: _repl(nodes[0]).counters["repl.ship_errors"]
                  > errors0, msg="drop never fired")
        _wait(lambda: _synced(nodes[0]), msg="post-drop resync")
        assert "dev2" in \
            nodes[1].replication.replicas["rn0"].sessions
        with faults.injected("repl.ship", action="stall", times=1,
                             delay_ms=50):
            s3 = _durable_session(nodes[0], "dev3")
            s3.subscribe("slow/+", SubOpts(qos=1))
            nodes[0].durability.on_batch()
            _wait(lambda: _synced(nodes[0]), msg="stalled ship")
        assert "dev3" in \
            nodes[1].replication.replicas["rn0"].sessions
    finally:
        faults.clear()
        _teardown(nodes, trs, cls)


# -- graceful shutdown hand-off -------------------------------------------


def test_graceful_shutdown_ships_tail_and_stamps_clean(tmp_path):
    """Node.stop on a replicating primary: the journal tail ships,
    the standby acks it, the replica is stamped clean, and the final
    checkpoint carries clean_shutdown — failback never replays a
    torn tail."""
    from emqx_tpu import checkpoint

    nodes, trs, cls = _mk_pair(tmp_path, "rep-bye")
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        # tail records the shutdown must hand off (never on_batch'd)
        s2 = _durable_session(nodes[0], "tail")
        s2.subscribe("tail/+", SubOpts(qos=1))
        nodes[0].durability.shutdown()
        rep = nodes[1].replication.replicas["rn0"]
        assert rep.clean
        assert "tail" in rep.sessions
        r = _repl(nodes[0])
        assert r.acked_seq >= r.offered_seq
        m = checkpoint.read_manifest(str(tmp_path / "d0"))
        assert m["clean_shutdown"]
    finally:
        _teardown(nodes, trs, cls)


# -- observability ---------------------------------------------------------


def test_ctl_metrics_and_lag_alarm_hysteresis(tmp_path):
    import json

    nodes, trs, cls = _mk_pair(
        tmp_path, "rep-obs",
        dur0_kw=dict(repl_lag_alarm_records=3,
                     repl_lag_clear_records=0))
    try:
        _populate(nodes[0])
        _wait(lambda: _synced(nodes[0]), msg="initial sync")
        out = json.loads(nodes[0].ctl.run(["durability"]))
        blk = out["replication"]
        assert blk["role"] == "primary" and blk["standby"] == "rn1"
        assert blk["acked_seq"] == blk["offered_seq"]
        assert blk["lag_records"] == 0
        assert blk["last_ack_age_s"] is not None
        # standby side: the warm replica shows under ctl too
        out1 = json.loads(nodes[1].ctl.run(["durability"]))
        assert out1["replication"]["standby_for"]["rn0"][
            "sessions"] >= 1
        nodes[0].stats.tick()
        assert nodes[0].metrics.val("durability.repl.shipped") > 0
        assert nodes[0].metrics.val("durability.repl.acked") > 0
        assert nodes[0].stats.all()[
            "durability.repl.lag_records"] == 0
        # wedge the standby and outrun the tiny lag bound → alarm
        trs[0].fault_peers = {"rn1"}
        trs[1].fault_peers = {"rn0"}
        faults.set_master(True)
        faults.arm("net.partition", times=0)
        s2 = _durable_session(nodes[0], "lagger")
        for i in range(6):
            s2.subscribe(f"lag/{i}", SubOpts(qos=1))
        nodes[0].durability.on_batch()
        _wait(lambda: _repl(nodes[0]).state == "local_only",
              msg="never degraded")
        nodes[0].stats.tick()
        assert any(a.name == "replication_lagging"
                   for a in nodes[0].alarms.get_alarms("activated"))
        # heal → resync → lag back under the clear bound → alarm off
        faults.disarm("net.partition")
        _wait(lambda: _synced(nodes[0]), timeout=30.0,
              msg="never resynced")
        nodes[0].stats.tick()
        assert not any(
            a.name == "replication_lagging"
            for a in nodes[0].alarms.get_alarms("activated"))
    finally:
        faults.clear()
        _teardown(nodes, trs, cls)


def test_no_standby_config_builds_no_shipper(tmp_path):
    """Replication is opt-in: without [durability] standby the
    cluster attaches only the (inert) replica-hosting manager."""
    nodes, trs, cls = _mk_pair(tmp_path, "rep-off",
                               dur0_kw=dict(standby=""))
    try:
        assert _repl(nodes[0])._thread is None
        assert nodes[0].durability.repl is None
        _populate(nodes[0])
        assert nodes[1].replication.replicas == {}
        # a stray ship to a node with no replica answers resync, not
        # an error
        reply = cls[1].handle_rpc("repl_ship", "ghost", 1, [])
        assert reply["resync"]
    finally:
        _teardown(nodes, trs, cls)


def test_config_rejects_bad_repl_knobs():
    with pytest.raises(ValueError):
        DurabilityConfig(enabled=True, repl_lag_alarm_records=1,
                         repl_lag_clear_records=2)
    with pytest.raises(ValueError):
        DurabilityConfig(enabled=True, repl_queue_max_records=0)
