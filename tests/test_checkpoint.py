"""Routing-plane checkpoint/restore (SURVEY §5 "Checkpoint/resume":
device-state snapshot of the CSR automaton + route log, rebuildable
either way)."""

import pytest

from emqx_tpu import checkpoint
from emqx_tpu.broker import Broker
from emqx_tpu.router import MatcherConfig, Router
from emqx_tpu.types import Message


def _mk(**kw):
    kw.setdefault("device_min_filters", 0)
    return Router(MatcherConfig(**kw), node="n1")


FILTERS = ["a/b", "a/+", "x/#", "deep/1/2/3", "$share-less/t"]


def _fill(r):
    for f in FILTERS:
        r.add_route(f)
    r.add_route("a/+", dest=("g1", "n2"))     # shared route
    r.add_route("gone/soon")
    r.match_filters(["a/b"])                   # flatten
    r.delete_route("gone/soon")                # history: delete
    r.add_route("late/comer")                  # history: patch insert
    r.match_filters(["a/b"])                   # drain patches


def test_roundtrip_with_tables(tmp_path):
    # the table snapshot is the patch-mode mirror (delta mode keeps
    # none and saves routes-only — covered below); restoring into a
    # DELTA-mode router must still install the saved tables
    r1 = _mk(delta=False)
    _fill(r1)
    path = str(tmp_path / "ckpt.npz")
    info = checkpoint.save(r1, path)
    assert info["routes"] >= 6 and info["tables"]

    r2 = _mk()
    out = checkpoint.load(r2, path)
    assert out["tables_restored"]
    assert r2.stats()["rebuilds"] == 0  # no re-flatten happened
    for topic, want in [
        ("a/b", {"a/b", "a/+"}),
        ("a/q", {"a/+"}),
        ("x/any/depth", {"x/#"}),
        ("late/comer", {"late/comer"}),
        ("gone/soon", set()),
    ]:
        assert set(r2.match_filters([topic])[0]) == want, topic
    # shared route dest survived
    dests = {rt.dest for rt in r2.lookup_routes("a/+")}
    assert ("g1", "n2") in dests and "n1" in dests


def test_restore_supports_further_mutation(tmp_path):
    r1 = _mk()
    _fill(r1)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(r1, path)
    r2 = _mk()
    checkpoint.load(r2, path)
    # O(depth) patching continues against the restored tables
    r2.add_route("post/restore/+")
    assert set(r2.match_filters(["post/restore/x"])[0]) == \
        {"post/restore/+"}
    r2.delete_route("a/b")
    assert set(r2.match_filters(["a/b"])[0]) == {"a/+"}


def test_restore_into_used_router_rejected(tmp_path):
    r1 = _mk()
    _fill(r1)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(r1, path)
    r2 = _mk()
    r2.add_route("already/here")
    with pytest.raises(ValueError):
        checkpoint.load(r2, path)


def test_route_log_fallback_when_tables_absent(tmp_path):
    r1 = _mk()
    for f in FILTERS:
        r1.add_route(f)
    # never matched -> dirty, no patcher: snapshot is log-only
    path = str(tmp_path / "ckpt.npz")
    info = checkpoint.save(r1, path)
    assert not info["tables"]
    r2 = _mk()
    out = checkpoint.load(r2, path)
    assert not out["tables_restored"]
    assert set(r2.match_filters(["a/b"])[0]) == {"a/b", "a/+"}


async def test_ctl_checkpoint_command(tmp_path):
    from emqx_tpu.node import Node

    n = Node(boot_listeners=False)
    await n.start()
    try:
        class S:
            client_id = "c"

            def deliver(self, f, m):
                pass

        n.broker.subscribe(S(), "ck/t")
        out = n.ctl.run(["checkpoint", "save",
                         str(tmp_path / "n.npz")])
        assert "saved" in out
        assert (tmp_path / "n.npz").exists()
        out = n.ctl.run(["checkpoint", "load", str(tmp_path / "n.npz")])
        assert "error" in out  # live router refuses restore
    finally:
        await n.stop()


def test_broker_end_to_end_after_restore(tmp_path):
    b1 = Broker(config=MatcherConfig(device_min_filters=0))

    class S:
        def __init__(self, cid):
            self.client_id = cid
            self.got = []

        def deliver(self, f, m):
            self.got.append((f, m.topic))

    s = S("c1")
    b1.subscribe(s, "e2e/+")
    b1.publish(Message(topic="e2e/x"))
    path = str(tmp_path / "r.npz")
    checkpoint.save(b1.router, path)

    r2 = Router(MatcherConfig(device_min_filters=0), node="local")
    checkpoint.load(r2, path)
    b2 = Broker(router=r2)
    s2 = S("c2")
    b2.subscribe(s2, "e2e/+")  # refcount bumps on the restored route
    assert b2.publish(Message(topic="e2e/y")) == 1
    assert s2.got == [("e2e/+", "e2e/y")]


def test_restore_remaps_saved_node_name(tmp_path):
    """A snapshot restored under a DIFFERENT node name must not
    replay the saved name as a remote dest (everything would forward
    to a nonexistent peer): saved-node dests remap to the restoring
    router's own name."""
    r1 = _mk()
    _fill(r1)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(r1, path)
    r2 = Router(MatcherConfig(device_min_filters=0), node="renamed")
    checkpoint.load(r2, path)
    for rt in r2.match_routes("a/b"):
        if not isinstance(rt.dest, tuple):
            assert rt.dest == "renamed"
    # the shared route's node remaps too; its group is untouched
    dests = {rt.dest for rt in r2.lookup_routes("a/+")}
    assert ("g1", "n2") in dests and "renamed" in dests and "n1" not in dests


def test_v1_format_degrades_to_route_log(tmp_path):
    """A pre-walk-rewrite (format 1) snapshot must RESTORE via the
    route log instead of rejecting — the tables were always just an
    optimization (checkpoint.py docstring contract)."""
    import json

    import numpy as np

    r1 = _mk()
    _fill(r1)
    path = str(tmp_path / "old.npz")
    checkpoint.save(r1, path)
    # rewrite the snapshot as a format-1 file with v1-era table keys
    with np.load(path) as data:
        meta = json.loads(bytes(data["meta"]).decode())
        routes = data["routes"]
    meta["format"] = 1
    meta["has_tables"] = True
    np.savez(
        str(tmp_path / "v1.npz"),
        meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
        routes=routes,
        ht_state=np.zeros((4, 4), np.int32),  # v1 relics, ignored
        plus_child=np.zeros((4,), np.int32))
    r2 = _mk()
    out = checkpoint.load(r2, str(tmp_path / "v1.npz"))
    assert out["routes"] >= 6 and not out["tables_restored"]
    assert sorted(x.topic for x in r2.match_routes("a/b")) == \
        sorted(x.topic for x in r1.match_routes("a/b"))


def test_unknown_format_rejected(tmp_path):
    import json

    import numpy as np

    np.savez(str(tmp_path / "future.npz"),
             meta=np.frombuffer(json.dumps(
                 {"format": 99, "filter_ids": {}, "vocab": []}).encode(),
                 dtype=np.uint8),
             routes=np.frombuffer(b"[]", dtype=np.uint8))
    with pytest.raises(ValueError):
        checkpoint.load(_mk(), str(tmp_path / "future.npz"))


def test_corrupt_and_truncated_files_raise_checkpoint_error(tmp_path):
    """Satellite (ISSUE 9): a damaged snapshot must surface ONE
    clear error class, never a raw numpy/KeyError/zipfile
    traceback."""
    garbage = tmp_path / "garbage.npz"
    garbage.write_bytes(b"not a zip at all \x00\x01\x02" * 16)
    with pytest.raises(checkpoint.CheckpointError):
        checkpoint.load(_mk(), str(garbage))

    r1 = _mk()
    _fill(r1)
    good = tmp_path / "good.npz"
    checkpoint.save(r1, str(good))
    data = good.read_bytes()
    for frac in (0.25, 0.6, 0.95):
        cut = tmp_path / f"cut{frac}.npz"
        cut.write_bytes(data[:int(len(data) * frac)])
        with pytest.raises(checkpoint.CheckpointError):
            checkpoint.load(_mk(), str(cut))
    # CheckpointError subclasses ValueError: pre-durability callers
    # that caught ValueError keep working
    assert issubclass(checkpoint.CheckpointError, ValueError)


def test_has_tables_without_arrays_degrades_to_route_log(tmp_path):
    """has_tables claimed but table arrays missing (hand-damaged
    file that still unzips): the route log replays instead of a
    KeyError mid-install."""
    import json

    import numpy as np

    r1 = _mk(delta=False)
    _fill(r1)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(r1, path)
    with np.load(path) as data:
        meta = json.loads(bytes(data["meta"]).decode())
        routes = np.array(data["routes"])
    assert meta["has_tables"]
    np.savez(str(tmp_path / "damaged.npz"),
             meta=np.frombuffer(json.dumps(meta).encode(),
                                dtype=np.uint8),
             routes=routes)  # arrays stripped, claim kept
    r2 = _mk()
    out = checkpoint.load(r2, str(tmp_path / "damaged.npz"))
    assert not out["tables_restored"]
    assert set(r2.match_filters(["a/b"])[0]) == {"a/b", "a/+"}


def test_delta_onoff_roundtrip_parity(tmp_path):
    """Satellite (ISSUE 9): round-trip parity across [matcher] delta
    on/off — delta-mode saves are routes-only, and a restore (into
    either mode) re-flattens to the IDENTICAL match results as the
    patch-mode table snapshot."""
    probes = ["a/b", "a/q", "x/deep/er", "late/comer", "gone/soon",
              "deep/1/2/3", "$share-less/t", "no/match"]
    results = {}
    for save_delta in (False, True):
        r1 = _mk(delta=save_delta)
        _fill(r1)
        path = str(tmp_path / f"d{save_delta}.npz")
        info = checkpoint.save(r1, path)
        # the delta pin: delta mode keeps no mirror → routes-only
        assert info["tables"] == (not save_delta)
        for load_delta in (False, True):
            r2 = _mk(delta=load_delta)
            out = checkpoint.load(r2, path)
            assert out["tables_restored"] == (not save_delta)
            results[(save_delta, load_delta)] = [
                sorted(r2.match_filters([t])[0]) for t in probes]
    want = results[(False, False)]
    for key, got in results.items():
        assert got == want, key


def test_delta_mode_saves_routes_only_and_roundtrips(tmp_path):
    """Delta mode keeps no main-table mirror, so its snapshot is the
    route log alone — restore replays it and re-flattens on first
    match, with exact results (the v1 degradation contract)."""
    r1 = _mk()  # delta on by default
    _fill(r1)
    r1.add_route("delta/pending")  # a live pending add rides the log
    path = str(tmp_path / "ckpt.npz")
    info = checkpoint.save(r1, path)
    assert info["routes"] >= 7 and not info["tables"]

    r2 = _mk()
    out = checkpoint.load(r2, path)
    assert not out["tables_restored"]
    for topic, want in [
        ("a/b", {"a/b", "a/+"}),
        ("x/any/depth", {"x/#"}),
        ("delta/pending", {"delta/pending"}),
        ("gone/soon", set()),
    ]:
        assert set(r2.match_filters([topic])[0]) == want, topic


def test_restore_onto_lost_backend_degrades_to_route_log(tmp_path):
    """Device-loss at RESTORE time (docs/ROBUSTNESS.md "Device-loss
    recovery"): the straight-to-HBM table placement failing must not
    kill the boot — the route log just replayed is authoritative,
    matching re-flattens on first use (and at runtime the breaker +
    devloss recovery own the lost-backend story)."""
    from emqx_tpu import faults

    r1 = _mk(delta=False)
    _fill(r1)
    path = str(tmp_path / "ckpt.npz")
    assert checkpoint.save(r1, path)["tables"]

    r2 = _mk()
    with faults.injected("device.lost", times=1):
        out = checkpoint.load(r2, path)
    assert out["tables_restored"] is False   # degraded, not crashed
    assert out["routes"] >= 6                # route log replayed
    # the backend "returns": first match re-flattens and is exact
    assert set(r2.match_filters(["a/b"])[0]) == {"a/b", "a/+"}
    assert set(r2.match_filters(["x/any/depth"])[0]) == {"x/#"}
    assert r2.stats()["rebuilds"] >= 1       # the lazy re-flatten
