"""Extension-layer tests: hooks, banned, flapping, modules (delayed,
presence, rewrite, subscription, topic_metrics, acl), alarms, tracer,
stats, ctl — modeled on the corresponding reference SUITEs."""

import asyncio
import time

import pytest

from emqx_tpu.access_control import ALLOW, DENY, AccessControl, ClientInfo
from emqx_tpu.acl_cache import AclCache
from emqx_tpu.banned import Banned
from emqx_tpu.flapping import Flapping, FlappingConfig
from emqx_tpu.hooks import Hooks, STOP
from emqx_tpu.modules.acl_file import AclFileModule
from emqx_tpu.modules.delayed import DelayedModule
from emqx_tpu.modules.presence import PresenceModule
from emqx_tpu.modules.rewrite import RewriteModule
from emqx_tpu.modules.topic_metrics import TopicMetricsModule
from emqx_tpu.node import Node
from emqx_tpu.types import Message
from emqx_tpu.zone import Zone


class Q:
    def __init__(self, cid="q"):
        self.client_id = cid
        self.inbox = []

    def deliver(self, t, m):
        self.inbox.append((t, m))


# -- hooks -----------------------------------------------------------------

def test_hooks_priority_and_stop():
    h = Hooks()
    order = []
    h.add("t", lambda: order.append("lo"), priority=0)
    h.add("t", lambda: order.append("hi"), priority=10)
    h.run("t")
    assert order == ["hi", "lo"]
    h2 = Hooks()
    h2.add("t", lambda: STOP, priority=10)
    h2.add("t", lambda: order.append("never"))
    h2.run("t")
    assert "never" not in order


def test_hooks_fold_and_crash_isolation():
    h = Hooks()
    h.add("f", lambda acc: acc + 1)
    h.add("f", lambda acc: 1 / 0)      # crashes, chain continues
    h.add("f", lambda acc: (STOP, acc + 10))
    h.add("f", lambda acc: acc + 100)  # never runs after STOP
    assert h.run_fold("f", (), 0) == 11


def test_hooks_delete_and_dup():
    h = Hooks()
    fn = lambda: None  # noqa: E731
    h.add("x", fn)
    h.add("x", fn)  # dup ignored
    assert len(h.lookup("x")) == 1
    h.delete("x", fn)
    assert h.lookup("x") == []


# -- banned / flapping ------------------------------------------------------

def test_banned_check_and_expiry():
    b = Banned()
    b.create("clientid", "evil")
    b.create("peerhost", "10.0.0.1", duration=0.0)
    assert b.check(clientid="evil")
    assert not b.check(clientid="good")
    time.sleep(0.01)
    assert not b.check(peerhost="10.0.0.1")  # expired lazily
    b.delete("clientid", "evil")
    assert not b.check(clientid="evil")


def test_flapping_bans_after_threshold():
    b = Banned()
    f = Flapping(banned=b, config=FlappingConfig(max_count=3, window=10,
                                                 ban_time=100))
    for _ in range(3):
        f.disconnected("flappy", "1.2.3.4")
    assert b.check(clientid="flappy")


# -- delayed ----------------------------------------------------------------

def test_delayed_module_intercepts_and_republishes():
    n = Node(boot_listeners=False)
    n.modules.load(DelayedModule)
    dm = n.modules._loaded["delayed"]
    s = Q()
    n.broker.subscribe(s, "real/topic")
    assert n.publish(Message(topic="$delayed/1/real/topic",
                             payload=b"later")) == 0
    assert s.inbox == [] and len(dm) == 1
    assert n.metrics.val("messages.delayed") == 1
    dm.tick(now=time.time() + 2)
    assert len(s.inbox) == 1
    assert s.inbox[0][1].topic == "real/topic"


def test_delayed_bad_prefix_passes_through():
    n = Node(boot_listeners=False)
    n.modules.load(DelayedModule)
    s = Q()
    n.broker.subscribe(s, "$delayed/nope")
    assert n.publish(Message(topic="$delayed/nope")) == 1


# -- presence ---------------------------------------------------------------

def test_presence_publishes_sys_events():
    n = Node(boot_listeners=False)
    n.modules.load(PresenceModule)
    s = Q()
    n.broker.subscribe(s, f"$SYS/brokers/{n.name}/clients/#")
    n.hooks.run("client.connected",
                ({"clientid": "c1", "peerhost": "127.0.0.1"},
                 {"connected_at": time.time()}))
    n.hooks.run("client.disconnected", ({"clientid": "c1"}, "bye"))
    assert len(s.inbox) == 2
    assert s.inbox[0][1].topic.endswith("c1/connected")
    assert s.inbox[1][1].topic.endswith("c1/disconnected")


# -- rewrite ----------------------------------------------------------------

def test_rewrite_pub_and_sub():
    n = Node(boot_listeners=False)
    n.modules.load(RewriteModule, {
        "rules": [("all", "x/#", r"^x/y/(.+)$", r"z/y/$1")]})
    s = Q()
    n.broker.subscribe(s, "z/y/1")
    assert n.publish(Message(topic="x/y/1")) == 1
    tf = n.hooks.run_fold("client.subscribe", ({}, {}),
                          [("x/y/2", {"qos": 0})])
    assert tf == [("z/y/2", {"qos": 0})]


# -- topic metrics ----------------------------------------------------------

def test_topic_metrics_counts():
    n = Node(boot_listeners=False)
    n.modules.load(TopicMetricsModule, {"topics": ["m/t"]})
    tm = n.modules._loaded["topic_metrics"]
    with pytest.raises(ValueError):
        tm.register("bad/#")
    n.publish(Message(topic="m/t", qos=1))
    n.publish(Message(topic="m/t"))
    n.publish(Message(topic="other"))
    m = tm.metrics("m/t")
    assert m["messages.in"] == 2 and m["messages.qos1.in"] == 1
    assert tm.metrics("other") is None


# -- acl file ---------------------------------------------------------------

def test_acl_rules():
    n = Node(boot_listeners=False)
    n.modules.load(AclFileModule, {"rules": [
        ("allow", ("user", "dash"), "subscribe", ["$SYS/#"]),
        ("deny", "all", "subscribe", ["$SYS/#", ("eq", "#")]),
        ("deny", ("client", "bad"), "pubsub", ["#"]),
        ("allow", "all", "pubsub", ["#"]),
    ]})
    ac = AccessControl(n.hooks, Zone())
    dash = ClientInfo(clientid="d", username="dash", peerhost="9.9.9.9")
    anon = ClientInfo(clientid="a", peerhost="9.9.9.9")
    bad = ClientInfo(clientid="bad", peerhost="9.9.9.9")
    assert ac.check_acl(dash, "subscribe", "$SYS/x") == ALLOW
    assert ac.check_acl(anon, "subscribe", "$SYS/x") == DENY
    assert ac.check_acl(anon, "subscribe", "#") == DENY   # eq(#)
    assert ac.check_acl(anon, "subscribe", "a/b") == ALLOW
    assert ac.check_acl(bad, "publish", "a") == DENY
    assert ac.check_acl(anon, "publish", "a") == ALLOW


def test_acl_cache():
    c = AclCache(max_size=2, ttl=100)
    c.put("publish", "a", ALLOW)
    c.put("publish", "b", DENY)
    assert c.get("publish", "a") == ALLOW
    c.put("publish", "c", ALLOW)  # evicts LRU ("b")
    assert c.get("publish", "b") is None
    c2 = AclCache(ttl=0.0)
    c2.put("publish", "x", ALLOW)
    time.sleep(0.01)
    assert c2.get("publish", "x") == ALLOW  # ttl=0 disables expiry


# -- alarms / sys / stats / ctl --------------------------------------------

def test_alarms_publish_to_sys():
    n = Node(boot_listeners=False)
    s = Q()
    n.broker.subscribe(s, f"$SYS/brokers/{n.name}/alarms/#")
    assert n.alarms.activate("high_mem", {"usage": 0.9}, "memory high")
    assert not n.alarms.activate("high_mem")
    assert n.alarms.deactivate("high_mem")
    assert not n.alarms.deactivate("high_mem")
    kinds = [m.topic.rsplit("/", 1)[1] for _, m in s.inbox]
    assert kinds == ["alert", "clear"]
    assert len(n.alarms.get_alarms("deactivated")) == 1


def test_sys_heartbeat():
    n = Node(boot_listeners=False)
    s = Q()
    n.broker.subscribe(s, "$SYS/brokers/+/uptime")
    n.sys.heartbeat()
    assert any(m.topic.endswith("/uptime") for _, m in s.inbox)


def test_stats_tick_updates_gauges():
    n = Node(boot_listeners=False)
    s = Q()
    n.broker.subscribe(s, "a/b")
    n.stats.tick()
    assert n.stats.getstat("subscriptions.count") == 1
    assert n.stats.getstat("topics.count") == 1
    n.broker.unsubscribe(s, "a/b")
    n.stats.tick()
    assert n.stats.getstat("subscriptions.count") == 0
    assert n.stats.getstat("topics.max") == 1  # watermark


def test_tracer_topic_and_client():
    n = Node(boot_listeners=False)
    sink = n.tracer.start_trace("topic", "tr/#")
    n.publish(Message(topic="tr/x", payload=b"p", from_="c9"))
    n.publish(Message(topic="other", payload=b"q"))
    assert len(sink) == 1 and "tr/x" in sink[0]
    assert n.tracer.stop_trace("topic", "tr/#")
    sink2 = n.tracer.start_trace("clientid", "c9")
    n.publish(Message(topic="zzz", from_="c9"))
    assert len(sink2) == 1
    n.tracer.stop_trace("clientid", "c9")


def test_topic_metrics_dropped_and_out():
    n = Node(boot_listeners=False)
    n.modules.load(TopicMetricsModule, {"topics": ["d/t"]})
    tm = n.modules._loaded["topic_metrics"]
    n.publish(Message(topic="d/t"))  # no subscribers -> dropped
    assert tm.metrics("d/t")["messages.dropped"] == 1
    s = Q()
    n.broker.subscribe(s, "d/t")
    n.publish(Message(topic="d/t"))
    assert tm.metrics("d/t")["messages.out"] == 1


def test_ctl_bad_input_returns_error_text():
    n = Node(boot_listeners=False)
    out = n.ctl.run(["banned", "add", "bogus-kind", "v"])
    assert out.startswith("error:")
    out = n.ctl.run(["banned", "add", "clientid", "v", "notanum"])
    assert out.startswith("error:")
    n.ctl.run(["trace", "start", "client", "c"])
    out = n.ctl.run(["trace", "start", "client", "c"])
    assert out.startswith("error:")


def test_ctl_commands():
    n = Node(boot_listeners=False)
    s = Q()
    n.broker.subscribe(s, "ctl/t")
    out = n.ctl.run(["status"])
    assert "connections: 0" in out
    assert "ctl/t" in n.ctl.run(["topics"])
    n.ctl.run(["banned", "add", "clientid", "evil", "60"])
    assert "evil" in n.ctl.run(["banned", "list"])
    n.ctl.run(["banned", "del", "clientid", "evil"])
    assert "(none)" in n.ctl.run(["banned", "list"])
    assert "unknown command" in n.ctl.run(["bogus"])
    assert "commands:" in n.ctl.run(["help"])


def test_module_registry_load_unload():
    n = Node(boot_listeners=False)
    n.modules.load(PresenceModule)
    assert "presence" in n.modules.loaded()
    assert n.modules.unload("presence")
    assert not n.modules.unload("presence")
    # unloaded module no longer hooks
    s = Q()
    n.broker.subscribe(s, "$SYS/#")
    n.hooks.run("client.connected", ({"clientid": "x"}, {}))
    assert s.inbox == []


def test_plugins_lifecycle(tmp_path):
    from emqx_tpu.plugins import Plugin

    class P(Plugin):
        name = "demo"

        def __init__(self):
            self.loads = 0

        def load(self, node, env):
            self.loads += 1

        def unload(self, node):
            self.loads -= 1

    n = Node(boot_listeners=False)
    n.plugins.state_file = str(tmp_path / "loaded.json")
    p = P()
    n.plugins.register(p)
    assert n.plugins.load("demo")
    assert not n.plugins.load("demo")
    assert p.loads == 1
    assert n.plugins.unload("demo")
    assert p.loads == 0
    n.plugins.load("demo")
    # persisted list reloads
    n2 = Node(boot_listeners=False)
    n2.plugins.state_file = n.plugins.state_file
    p2 = P()
    n2.plugins.register(p2)
    n2.plugins.load_all()
    assert p2.loads == 1


def test_ctl_log_level():
    import logging

    n = Node(boot_listeners=False)
    root = logging.getLogger("emqx_tpu")
    saved = root.level
    try:
        out = n.ctl.run(["log", "set-level", "debug"])
        assert "DEBUG" in out
        assert root.level == logging.DEBUG
        assert "DEBUG" in n.ctl.run(["log", "show"])
        out = n.ctl.run(["log", "set-level", "bogus"])
        assert "error" in out
    finally:
        root.setLevel(saved)  # process-global: never leak a level
    # profile registration survives (regression: inserting a command
    # mid-_register_builtins once orphaned it)
    assert "profile" in n.ctl.run(["help"])


def test_acl_conf_file_parsing_reference_fixtures():
    """The acl.conf parser handles the reference's own files
    verbatim (test fixture + shipped etc/acl.conf)."""
    import os

    import pytest

    from emqx_tpu.modules.acl_file import parse_acl_file

    ref = "/root/reference/test/emqx_access_SUITE_data/acl.conf"
    if not os.path.exists(ref):
        pytest.skip("reference checkout not present")
    rules = parse_acl_file(open(ref).read())
    assert ("allow", ("user", "testuser"), "subscribe",
            ["a/b/c", "d/e/f/#"]) in rules
    assert rules[-1] == ("deny", "all", "pubsub", None)

    ours = parse_acl_file(open("etc/acl.conf").read())
    assert ("deny", "all", "subscribe",
            ["$SYS/#", ("eq", "#")]) in ours
    assert ours[-1][0] == "allow"


def test_acl_file_module_loads_from_file(tmp_path):
    from emqx_tpu.modules.acl_file import AclFileModule
    from emqx_tpu.node import Node

    path = tmp_path / "acl.conf"
    path.write_text(
        '{deny, {user, "evil"}, publish, ["secret/#"]}.\n'
        '{allow, all}.\n')
    n = Node(boot_listeners=False)
    mod = n.modules.load(AclFileModule, env={"file": str(path)})
    deny = mod.check_acl({"username": "evil", "clientid": "c",
                          "peerhost": "10.0.0.1"},
                         "publish", "secret/x", None)
    from emqx_tpu.access_control import DENY
    from emqx_tpu.hooks import STOP
    assert deny == (STOP, DENY)
    ok = mod.check_acl({"username": "good", "clientid": "c",
                        "peerhost": "10.0.0.1"},
                       "publish", "secret/x", None)
    from emqx_tpu.access_control import ALLOW
    assert ok == (STOP, ALLOW)


def test_acl_conf_escaped_quote_in_string():
    """An escaped quote inside a string must not desync the comment
    stripper — a later '%' inside the same string is content, not a
    comment (regression: advisor round-2 finding)."""
    from emqx_tpu.modules.acl_file import parse_acl_file

    rules = parse_acl_file(
        '{allow, {user, "a\\"b%c"}, publish, ["t/1"]}.\n')
    assert rules == [("allow", ("user", 'a"b%c'), "publish", ["t/1"])]
    # and %% after a closed string still comments
    rules = parse_acl_file(
        '{allow, {user, "u"}, publish, ["t/2"]}. %% tail comment\n')
    assert rules == [("allow", ("user", "u"), "publish", ["t/2"])]


def test_plugin_config_file_merged(tmp_path):
    """With a config_dir, load(name) reads <name>.toml as the
    plugin's env; explicitly passed env keys override the file's
    (emqx_plugins.erl:51-59 renders per-plugin config before load)."""
    from emqx_tpu.plugins import Plugin

    class P(Plugin):
        name = "demo"

        def load(self, node, env):
            self.env = env

        def unload(self, node):
            pass

    (tmp_path / "demo.toml").write_text(
        'answer = 42\nlabel = "from-file"\n')
    n = Node(boot_listeners=False)
    n.plugins.config_dir = str(tmp_path)
    p = P()
    n.plugins.register(p)
    n.plugins.load("demo", env={"label": "override"})
    assert p.env == {"answer": 42, "label": "override"}
    # absent file: env passes through untouched
    n.plugins.unload("demo")
    n.plugins.config_dir = str(tmp_path / "nope")
    n.plugins.load("demo", env={"k": 1})
    assert p.env == {"k": 1}


async def test_message_acked_hook_fires_on_puback_and_pubrec():
    """'message.acked' fires once per QoS1 PUBACK and QoS2 PUBREC
    with (clientinfo, message) — emqx_channel.erl:300-323."""
    from emqx_tpu.mqtt import constants as C
    from tests.mqtt_client import TestClient

    n = Node(boot_listeners=False)
    lst = n.add_listener(port=0)
    await n.start()
    acked = []
    n.hooks.add("message.acked",
                lambda ci, msg: acked.append((ci["clientid"],
                                              msg.topic, msg.qos)))
    try:
        sub = TestClient("ack-sub", version=C.MQTT_V5)
        await sub.connect(port=lst.port)
        await sub.subscribe("ack/q1", qos=1)
        await sub.subscribe("ack/q2", qos=2)
        pub = TestClient("ack-pub", version=C.MQTT_V5)
        await pub.connect(port=lst.port)
        await pub.publish("ack/q1", b"x", qos=1)
        await pub.publish("ack/q2", b"y", qos=2)
        for _ in range(2):
            await sub.recv(5)  # auto-acks (PUBACK / PUBREC+PUBCOMP)
        for _ in range(100):
            if len(acked) >= 2:
                break
            await asyncio.sleep(0.02)
        assert ("ack-sub", "ack/q1", 1) in acked
        assert ("ack-sub", "ack/q2", 2) in acked
        assert len(acked) == 2
        await pub.close()
        await sub.close()
    finally:
        await n.stop()


async def test_subscription_module_auto_subscribes_on_connect():
    """emqx_mod_subscription semantics: templated %c/%u auto-subs at
    CONNECT; unload stops them (reference
    src/emqx_mod_subscription.erl)."""
    from emqx_tpu.modules.subscription import SubscriptionModule
    from tests.helpers import broker_node, node_port
    from tests.mqtt_client import TestClient

    async with broker_node() as n:
        mod = n.modules.load(SubscriptionModule, env={
            "topics": [("client/%c/inbox", 1), ("user/%u/all", 0)]})
        c = TestClient("auto-c1", username="u9")
        await c.connect(port=node_port(n))
        import asyncio
        await asyncio.sleep(0.1)
        sess = n.cm.lookup_channel("auto-c1").session
        assert "client/auto-c1/inbox" in sess.subscriptions
        assert sess.subscriptions["client/auto-c1/inbox"].qos == 1
        assert "user/u9/all" in sess.subscriptions
        # the auto-subscription actually routes
        p = TestClient("auto-pub")
        await p.connect(port=node_port(n))
        await p.publish("client/auto-c1/inbox", b"hi", qos=1)
        pkt = await c.recv(timeout=10)
        assert pkt.payload == b"hi"
        await c.disconnect()
        n.modules.unload(mod.name)
        c2 = TestClient("auto-c2")
        await c2.connect(port=node_port(n))
        await asyncio.sleep(0.1)
        assert not n.cm.lookup_channel("auto-c2").session.subscriptions
        await c2.disconnect()
        await p.disconnect()
