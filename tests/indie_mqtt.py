"""An INDEPENDENT MQTT 3.1.1 / 5.0 client + codec for conformance.

Deliberately implemented straight from the OASIS specifications with
ZERO imports from ``emqx_tpu`` — the reference proves its wire
behavior against emqtt, a separately implemented client
(/root/reference/rebar.config:40-45, test/emqx_client_SUITE.erl:78-86);
every protocol test that drives the broker through the repo's own
``tests/mqtt_client.py`` shares one author's reading of the spec with
the server, so a mirrored misreading passes silently (round-4 verdict
item 6). This module is the second reading: its property table, flag
layouts and length rules are transcribed from the spec text
(MQTT 3.1.1 §2-§3, MQTT 5.0 §2.2.2 property tables), not from the
server code.

Keep it that way: no emqx_tpu imports, no sharing of constants.
"""

from __future__ import annotations

import asyncio
import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# -- fixed header packet types (MQTT 5.0 table 2-1) ------------------------

CONNECT, CONNACK, PUBLISH, PUBACK = 1, 2, 3, 4
PUBREC, PUBREL, PUBCOMP, SUBSCRIBE = 5, 6, 7, 8
SUBACK, UNSUBSCRIBE, UNSUBACK, PINGREQ = 9, 10, 11, 12
PINGRESP, DISCONNECT, AUTH = 13, 14, 15

# -- v5 property table (MQTT 5.0 §2.2.2.2, table 2-4) ----------------------
# id -> (name, type); types: B=byte, U2, U4, VAR=varint, S=utf8,
# BIN=binary, PAIR=utf8 string pair

PROPS = {
    0x01: ("Payload-Format-Indicator", "B"),
    0x02: ("Message-Expiry-Interval", "U4"),
    0x03: ("Content-Type", "S"),
    0x08: ("Response-Topic", "S"),
    0x09: ("Correlation-Data", "BIN"),
    0x0B: ("Subscription-Identifier", "VAR"),
    0x11: ("Session-Expiry-Interval", "U4"),
    0x12: ("Assigned-Client-Identifier", "S"),
    0x13: ("Server-Keep-Alive", "U2"),
    0x15: ("Authentication-Method", "S"),
    0x16: ("Authentication-Data", "BIN"),
    0x17: ("Request-Problem-Information", "B"),
    0x18: ("Will-Delay-Interval", "U4"),
    0x19: ("Request-Response-Information", "B"),
    0x1A: ("Response-Information", "S"),
    0x1C: ("Server-Reference", "S"),
    0x1F: ("Reason-String", "S"),
    0x21: ("Receive-Maximum", "U2"),
    0x22: ("Topic-Alias-Maximum", "U2"),
    0x23: ("Topic-Alias", "U2"),
    0x24: ("Maximum-QoS", "B"),
    0x25: ("Retain-Available", "B"),
    0x26: ("User-Property", "PAIR"),
    0x27: ("Maximum-Packet-Size", "U4"),
    0x28: ("Wildcard-Subscription-Available", "B"),
    0x29: ("Subscription-Identifier-Available", "B"),
    0x2A: ("Shared-Subscription-Available", "B"),
}
PROP_IDS = {name: (pid, typ) for pid, (name, typ) in PROPS.items()}


class MQTTError(Exception):
    pass


# -- primitive encoders (MQTT 5.0 §1.5) ------------------------------------


def enc_varint(n: int) -> bytes:
    if n < 0 or n > 268_435_455:
        raise MQTTError(f"varint out of range: {n}")
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def dec_varint(buf: bytes, off: int) -> Tuple[int, int]:
    mult, val = 1, 0
    for i in range(4):
        if off + i >= len(buf):
            raise MQTTError("truncated varint")
        b = buf[off + i]
        val += (b & 0x7F) * mult
        if not b & 0x80:
            return val, off + i + 1
        mult *= 128
    raise MQTTError("malformed varint")


def enc_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def enc_bin(b: bytes) -> bytes:
    return struct.pack(">H", len(b)) + b


def dec_str(buf: bytes, off: int) -> Tuple[str, int]:
    b, off = dec_bin(buf, off)
    return b.decode("utf-8"), off


def dec_bin(buf: bytes, off: int) -> Tuple[bytes, int]:
    if off + 2 > len(buf):
        raise MQTTError("truncated string")
    (n,) = struct.unpack_from(">H", buf, off)
    off += 2
    if off + n > len(buf):
        raise MQTTError("truncated string body")
    return buf[off:off + n], off + n


def enc_props(props: Optional[Dict[str, Any]]) -> bytes:
    """Property block: varint total length + (id, value) pairs. The
    dict value for User-Property is a list of (k, v) pairs; for
    Subscription-Identifier a list of ints (may repeat on PUBLISH)."""
    body = bytearray()
    for name, val in (props or {}).items():
        pid, typ = PROP_IDS[name]
        if typ == "PAIR":
            for kk, vv in val:
                body += bytes([pid]) + enc_str(kk) + enc_str(vv)
            continue
        if name == "Subscription-Identifier" and isinstance(val, list):
            for v in val:
                body += bytes([pid]) + enc_varint(v)
            continue
        body.append(pid)
        if typ == "B":
            body.append(val)
        elif typ == "U2":
            body += struct.pack(">H", val)
        elif typ == "U4":
            body += struct.pack(">I", val)
        elif typ == "VAR":
            body += enc_varint(val)
        elif typ == "S":
            body += enc_str(val)
        elif typ == "BIN":
            body += enc_bin(val)
    return enc_varint(len(body)) + bytes(body)


def dec_props(buf: bytes, off: int) -> Tuple[Dict[str, Any], int]:
    total, off = dec_varint(buf, off)
    end = off + total
    props: Dict[str, Any] = {}
    while off < end:
        pid, off = dec_varint(buf, off)
        if pid not in PROPS:
            raise MQTTError(f"unknown property id {pid}")
        name, typ = PROPS[pid]
        if typ == "B":
            val, off = buf[off], off + 1
        elif typ == "U2":
            (val,) = struct.unpack_from(">H", buf, off)
            off += 2
        elif typ == "U4":
            (val,) = struct.unpack_from(">I", buf, off)
            off += 4
        elif typ == "VAR":
            val, off = dec_varint(buf, off)
        elif typ == "S":
            val, off = dec_str(buf, off)
        elif typ == "BIN":
            val, off = dec_bin(buf, off)
        elif typ == "PAIR":
            kk, off = dec_str(buf, off)
            vv, off = dec_str(buf, off)
            props.setdefault(name, []).append((kk, vv))
            continue
        if name == "Subscription-Identifier":
            props.setdefault(name, []).append(val)
        else:
            if name in props:
                raise MQTTError(f"duplicate property {name}")
            props[name] = val
    if off != end:
        raise MQTTError("property length mismatch")
    return props, off


def frame(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + enc_varint(len(body)) + body


# -- packet records --------------------------------------------------------


@dataclass
class Packet:
    ptype: int
    flags: int = 0
    # common decoded fields (only the relevant ones are set per type)
    session_present: bool = False
    rc: int = 0
    rcs: List[int] = field(default_factory=list)
    pkt_id: int = 0
    topic: str = ""
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    dup: bool = False
    props: Dict[str, Any] = field(default_factory=dict)


# -- packet builders (client -> server) ------------------------------------


def build_connect(client_id: str, version: int = 4, clean: bool = True,
                  keepalive: int = 60, username: Optional[str] = None,
                  password: Optional[bytes] = None,
                  will: Optional[dict] = None,
                  props: Optional[dict] = None) -> bytes:
    """``will``: dict(topic=, payload=, qos=, retain=, props=)."""
    flags = 0x02 if clean else 0
    if will:
        flags |= 0x04 | (will.get("qos", 0) << 3)
        if will.get("retain"):
            flags |= 0x20
    if username is not None:
        flags |= 0x80
    if password is not None:
        flags |= 0x40
    body = enc_str("MQTT") + bytes([version, flags]) + \
        struct.pack(">H", keepalive)
    if version == 5:
        body += enc_props(props)
    body += enc_str(client_id)
    if will:
        if version == 5:
            body += enc_props(will.get("props"))
        body += enc_str(will["topic"]) + enc_bin(will.get("payload", b""))
    if username is not None:
        body += enc_str(username)
    if password is not None:
        body += enc_bin(password)
    return frame(CONNECT, 0, body)


def build_publish(topic: str, payload: bytes = b"", qos: int = 0,
                  retain: bool = False, dup: bool = False,
                  pkt_id: int = 0, version: int = 4,
                  props: Optional[dict] = None) -> bytes:
    flags = (0x08 if dup else 0) | (qos << 1) | (1 if retain else 0)
    body = enc_str(topic)
    if qos:
        body += struct.pack(">H", pkt_id)
    if version == 5:
        body += enc_props(props)
    return frame(PUBLISH, flags, body + payload)


def build_puback_like(ptype: int, pkt_id: int, version: int = 4,
                      rc: int = 0, props: Optional[dict] = None) -> bytes:
    flags = 0x02 if ptype == PUBREL else 0
    body = struct.pack(">H", pkt_id)
    if version == 5 and (rc or props):
        body += bytes([rc])
        if props:
            body += enc_props(props)
    return frame(ptype, flags, body)


def build_subscribe(pkt_id: int, filters, version: int = 4,
                    props: Optional[dict] = None) -> bytes:
    """``filters``: list of (filter, opts_byte) — opts per MQTT 5.0
    §3.8.3.1 (qos | nl<<2 | rap<<3 | rh<<4); 3.1.1 uses just qos."""
    body = struct.pack(">H", pkt_id)
    if version == 5:
        body += enc_props(props)
    for flt, opts in filters:
        body += enc_str(flt) + bytes([opts])
    return frame(SUBSCRIBE, 0x02, body)


def build_unsubscribe(pkt_id: int, filters, version: int = 4,
                      props: Optional[dict] = None) -> bytes:
    body = struct.pack(">H", pkt_id)
    if version == 5:
        body += enc_props(props)
    for flt in filters:
        body += enc_str(flt)
    return frame(UNSUBSCRIBE, 0x02, body)


def build_pingreq() -> bytes:
    return frame(PINGREQ, 0, b"")


def build_disconnect(version: int = 4, rc: int = 0,
                     props: Optional[dict] = None) -> bytes:
    if version == 5 and (rc or props):
        body = bytes([rc]) + (enc_props(props) if props else b"")
        return frame(DISCONNECT, 0, body)
    return frame(DISCONNECT, 0, b"")


# -- decoder (server -> client) --------------------------------------------


def decode(ptype: int, flags: int, body: bytes, version: int) -> Packet:
    p = Packet(ptype=ptype, flags=flags)
    off = 0
    if ptype == CONNACK:
        p.session_present = bool(body[0] & 0x01)
        p.rc = body[1]
        if version == 5:
            p.props, off = dec_props(body, 2)
    elif ptype == PUBLISH:
        p.dup = bool(flags & 0x08)
        p.qos = (flags >> 1) & 0x03
        p.retain = bool(flags & 0x01)
        p.topic, off = dec_str(body, 0)
        if p.qos:
            (p.pkt_id,) = struct.unpack_from(">H", body, off)
            off += 2
        if version == 5:
            p.props, off = dec_props(body, off)
        p.payload = body[off:]
    elif ptype in (PUBACK, PUBREC, PUBREL, PUBCOMP):
        (p.pkt_id,) = struct.unpack_from(">H", body, 0)
        if version == 5 and len(body) > 2:
            p.rc = body[2]
            if len(body) > 3:
                p.props, _ = dec_props(body, 3)
    elif ptype in (SUBACK, UNSUBACK):
        (p.pkt_id,) = struct.unpack_from(">H", body, 0)
        off = 2
        if version == 5:
            p.props, off = dec_props(body, off)
        p.rcs = list(body[off:])
    elif ptype in (PINGRESP, PINGREQ):
        pass
    elif ptype == DISCONNECT:
        if version == 5 and body:
            p.rc = body[0]
            if len(body) > 1:
                p.props, _ = dec_props(body, 1)
    elif ptype == AUTH:
        if body:
            p.rc = body[0]
            if len(body) > 1:
                p.props, _ = dec_props(body, 1)
    else:
        raise MQTTError(f"unexpected server packet type {ptype}")
    return p


async def read_packet(reader: asyncio.StreamReader,
                      version: int) -> Packet:
    h = await reader.readexactly(1)
    ptype, flags = h[0] >> 4, h[0] & 0x0F
    n, mult = 0, 1
    for _ in range(4):
        b = (await reader.readexactly(1))[0]
        n += (b & 0x7F) * mult
        if not b & 0x80:
            break
        mult *= 128
    else:
        raise MQTTError("malformed remaining length")
    body = await reader.readexactly(n) if n else b""
    return decode(ptype, flags, body, version)


class IndieClient:
    """Asyncio client over the independent codec."""

    def __init__(self, client_id: str, version: int = 4,
                 clean: bool = True, **connect_kw) -> None:
        self.client_id = client_id
        self.version = version
        self.clean = clean
        self.connect_kw = connect_kw
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.inbox: asyncio.Queue = asyncio.Queue()
        self.acks: asyncio.Queue = asyncio.Queue()
        self.connack: Optional[Packet] = None
        self.auto_ack = True
        self._pkt_id = 0
        self._task: Optional[asyncio.Task] = None

    def next_pkt_id(self) -> int:
        self._pkt_id = (self._pkt_id % 0xFFFF) + 1
        return self._pkt_id

    async def connect(self, host="127.0.0.1", port=1883, timeout=10.0,
                      expect_rc: Optional[int] = 0) -> Packet:
        self.reader, self.writer = await asyncio.open_connection(host, port)
        self.writer.write(build_connect(
            self.client_id, version=self.version, clean=self.clean,
            **self.connect_kw))
        await self.writer.drain()
        self.connack = await asyncio.wait_for(
            read_packet(self.reader, self.version), timeout)
        if self.connack.ptype != CONNACK:
            raise MQTTError(f"expected CONNACK, got {self.connack}")
        if expect_rc is not None and self.connack.rc != expect_rc:
            raise MQTTError(f"CONNACK rc {self.connack.rc}")
        self._task = asyncio.get_event_loop().create_task(self._read_loop())
        return self.connack

    async def _read_loop(self) -> None:
        try:
            while True:
                p = await read_packet(self.reader, self.version)
                if p.ptype == PUBLISH:
                    if self.auto_ack and p.qos == 1:
                        await self._send(build_puback_like(
                            PUBACK, p.pkt_id, self.version))
                    elif self.auto_ack and p.qos == 2:
                        await self._send(build_puback_like(
                            PUBREC, p.pkt_id, self.version))
                    await self.inbox.put(p)
                elif p.ptype == PUBREL and self.auto_ack:
                    await self._send(build_puback_like(
                        PUBCOMP, p.pkt_id, self.version))
                    await self.acks.put(p)
                else:
                    await self.acks.put(p)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            await self.inbox.put(None)   # EOF marker
            await self.acks.put(None)

    async def _send(self, data: bytes) -> None:
        self.writer.write(data)
        await self.writer.drain()

    async def _expect(self, ptype: int, timeout: float = 10.0) -> Packet:
        p = await asyncio.wait_for(self.acks.get(), timeout)
        if p is None or p.ptype != ptype:
            raise MQTTError(f"expected type {ptype}, got {p}")
        return p

    async def subscribe(self, *filters, timeout=10.0) -> Packet:
        """``filters``: str or (str, opts_byte)."""
        fl = [(f, 0) if isinstance(f, str) else f for f in filters]
        pid = self.next_pkt_id()
        await self._send(build_subscribe(pid, fl, self.version))
        p = await self._expect(SUBACK, timeout)
        if p.pkt_id != pid:
            raise MQTTError("SUBACK id mismatch")
        return p

    async def unsubscribe(self, *filters, timeout=10.0) -> Packet:
        pid = self.next_pkt_id()
        await self._send(build_unsubscribe(pid, list(filters),
                                           self.version))
        p = await self._expect(UNSUBACK, timeout)
        if p.pkt_id != pid:
            raise MQTTError("UNSUBACK id mismatch")
        return p

    async def publish(self, topic: str, payload: bytes = b"",
                      qos: int = 0, retain: bool = False,
                      props: Optional[dict] = None,
                      timeout: float = 30.0) -> Optional[int]:
        pid = self.next_pkt_id() if qos else 0
        await self._send(build_publish(
            topic, payload, qos=qos, retain=retain, pkt_id=pid,
            version=self.version, props=props))
        if qos == 1:
            p = await self._expect(PUBACK, timeout)
            if p.pkt_id != pid:
                raise MQTTError("PUBACK id mismatch")
            return p.rc
        if qos == 2:
            p = await self._expect(PUBREC, timeout)
            if p.pkt_id != pid:
                raise MQTTError("PUBREC id mismatch")
            await self._send(build_puback_like(PUBREL, pid, self.version))
            p = await self._expect(PUBCOMP, timeout)
            if p.pkt_id != pid:
                raise MQTTError("PUBCOMP id mismatch")
            return p.rc
        return None

    async def recv(self, timeout: float = 10.0) -> Packet:
        p = await asyncio.wait_for(self.inbox.get(), timeout)
        if p is None:
            raise MQTTError("connection closed")
        return p

    async def ping(self, timeout: float = 10.0) -> None:
        await self._send(build_pingreq())
        await self._expect(PINGRESP, timeout)

    async def disconnect(self, rc: int = 0) -> None:
        try:
            await self._send(build_disconnect(self.version, rc=rc))
        except (ConnectionError, OSError):
            pass
        await self.close()

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:
                pass
