"""Server-level tests for the selectable frame engine (PR 18).

``[node] frame = "native"`` routes every connection's framing through
the C++ incremental parser (native/emqx_native.cpp ``mqtt_parser_*``)
behind the same ``Parser.feed`` contract. These tests drive a real
broker through the independent client: the engine knob must be
invisible on the wire, visible only in the ``frame.*`` counters —
plus the oversize rejection path, which must answer a v5 client with
DISCONNECT 0x95 (Packet too large) before closing.
"""

import asyncio

import pytest

from tests import indie_mqtt as im
from tests.helpers import broker_node, node_port

from emqx_tpu.mqtt import reason_codes as RC
from emqx_tpu.mqtt.frame import NativeParser, make_parser, resolve_frame_mode
from emqx_tpu.ops import native as nat

needs_native = pytest.mark.skipif(
    not nat.has_frame_parser(),
    reason="native frame parser not built")


def _giant_header(claimed: int = 0x0FFFFFFF) -> bytes:
    """A PUBLISH fixed header claiming ``claimed`` bytes of body."""
    out = bytearray([0x30])
    n = claimed
    while True:
        b = n % 128
        n //= 128
        out.append(b | 0x80 if n else b)
        if not n:
            return bytes(out)


@needs_native
async def test_native_mode_roundtrip_and_counters():
    async with broker_node(frame="native") as n:
        port = node_port(n)
        sub = im.IndieClient("nf-sub")
        await sub.connect(port=port)
        await sub.subscribe(("t/#", 1))
        pub = im.IndieClient("nf-pub")
        await pub.connect(port=port)
        await pub.publish("t/a", b"zero", qos=0)
        assert await pub.publish("t/b", b"one" * 400, qos=1) == 0
        got = {}
        for _ in range(2):
            p = await sub.recv()
            got[p.topic] = p.payload
        assert got == {"t/a": b"zero", "t/b": b"one" * 400}
        m = n.broker.metrics
        assert m.val("frame.native.frames") > 0
        assert m.val("frame.fallback") == 0
        await sub.disconnect()
        await pub.disconnect()


async def test_fallback_counter_when_parser_unavailable(monkeypatch):
    """frame="native" with no usable .so must serve traffic through
    the Python parser and count the downgrade."""
    monkeypatch.setattr(
        "emqx_tpu.mqtt.frame.NativeParser.__init__",
        lambda self, **kw: (_ for _ in ()).throw(
            RuntimeError("native frame parser unavailable")))
    async with broker_node(frame="native") as n:
        port = node_port(n)
        c = im.IndieClient("nf-fb")
        await c.connect(port=port)
        await c.subscribe("t/#")
        await c.publish("t/x", b"hi")
        p = await c.recv()
        assert p.payload == b"hi"
        m = n.broker.metrics
        assert m.val("frame.fallback") >= 1
        assert m.val("frame.native.frames") == 0
        await c.disconnect()


async def test_env_var_overrides_configured_mode(monkeypatch):
    """EMQX_TPU_FRAME=py beats frame="native" at listener build; the
    node keeps the CONFIGURED value (reload diff must stay clean)."""
    monkeypatch.setenv("EMQX_TPU_FRAME", "py")
    async with broker_node(frame="native") as n:
        assert n.frame == "native"
        assert n.listeners[0].frame == "py"
        port = node_port(n)
        c = im.IndieClient("nf-env")
        await c.connect(port=port)
        await c.publish("t/x", b"ok")
        assert n.broker.metrics.val("frame.native.frames") == 0
        await c.disconnect()


def test_resolve_frame_mode_ignores_junk_env(monkeypatch):
    monkeypatch.setenv("EMQX_TPU_FRAME", "turbo")
    assert resolve_frame_mode("py") == "py"
    assert resolve_frame_mode("native") == "native"
    monkeypatch.setenv("EMQX_TPU_FRAME", "native")
    assert resolve_frame_mode("py") == "native"


def test_make_parser_falls_back_cleanly(monkeypatch):
    monkeypatch.setattr(
        "emqx_tpu.mqtt.frame.NativeParser.__init__",
        lambda self, **kw: (_ for _ in ()).throw(RuntimeError("no lib")))
    p = make_parser(mode="native")
    assert not isinstance(p, NativeParser)


@pytest.mark.parametrize("frame_mode", ["py", "native"])
async def test_oversize_header_gets_v5_disconnect_0x95(frame_mode):
    if frame_mode == "native" and not nat.has_frame_parser():
        pytest.skip("native frame parser not built")
    async with broker_node(frame=frame_mode) as n:
        port = node_port(n)
        c = im.IndieClient("nf-big", version=5)
        await c.connect(port=port)
        c.writer.write(_giant_header())
        await c.writer.drain()
        p = await asyncio.wait_for(c.acks.get(), 5)
        assert p is not None and p.ptype == im.DISCONNECT
        assert p.rc == RC.PACKET_TOO_LARGE
        # ... and the transport actually closes after the DISCONNECT
        assert await asyncio.wait_for(c.acks.get(), 5) is None
        m = n.broker.metrics
        assert m.val("frame.oversize") == 1
        assert m.val("delivery.dropped.too_large") == 1


@pytest.mark.parametrize("frame_mode", ["py", "native"])
async def test_oversize_header_v4_just_closes(frame_mode):
    """Pre-v5 there is no server DISCONNECT: the connection closes
    with nothing extra on the wire."""
    if frame_mode == "native" and not nat.has_frame_parser():
        pytest.skip("native frame parser not built")
    async with broker_node(frame=frame_mode) as n:
        port = node_port(n)
        c = im.IndieClient("nf-big4", version=4)
        await c.connect(port=port)
        c.writer.write(_giant_header())
        await c.writer.drain()
        assert await asyncio.wait_for(c.acks.get(), 5) is None  # EOF
        assert n.broker.metrics.val("frame.oversize") == 1


@needs_native
async def test_native_mode_over_websocket():
    """WsConnection shares Connection._decode, so the native engine
    must cover the WS transport with zero extra wiring."""
    from emqx_tpu.node import Node
    from emqx_tpu.mqtt.packet import Publish, Suback, Subscribe
    from tests.test_ws import WsTestClient

    n = Node(boot_listeners=False, frame="native")
    n.add_ws_listener(port=0)
    await n.start()
    try:
        port = n.listeners[0].port
        sub, pub = WsTestClient("nfw-sub"), WsTestClient("nfw-pub")
        ack = await sub.connect(port)
        assert ack.reason_code == 0
        await pub.connect(port)
        await sub.send_mqtt(Subscribe(
            packet_id=1, topic_filters=[("w/#", {"qos": 0})]))
        sa = await asyncio.wait_for(sub.acks.get(), 5.0)
        assert isinstance(sa, Suback)
        await pub.send_mqtt(Publish(topic="w/1", payload=b"via-ws"))
        msg = await asyncio.wait_for(sub.inbox.get(), 5.0)
        assert msg.payload == b"via-ws"
        assert n.metrics.val("frame.native.frames") > 0
        assert n.metrics.val("frame.fallback") == 0
        await sub.close()
        await pub.close()
    finally:
        await n.stop()
