"""Device fan-out gather tests."""

import numpy as np

from emqx_tpu.ops.fanout import build_fanout, gather_subscribers


def test_gather_basic():
    fan = build_fanout({0: [10, 11], 1: [20], 2: [30, 31, 32]}, 3)
    ids = np.array([[0, 2, -1, -1], [1, -1, -1, -1]], dtype=np.int32)
    subs, count, ovf = gather_subscribers(fan, ids, d=8)
    assert count.tolist() == [5, 1]
    assert not ovf.any()
    assert sorted(x for x in np.asarray(subs)[0] if x >= 0) == [10, 11, 30, 31, 32]
    assert [x for x in np.asarray(subs)[1] if x >= 0] == [20]


def test_gather_empty_rows_and_no_match():
    fan = build_fanout({0: [], 1: [5]}, 2)
    ids = np.array([[-1, -1], [0, 1]], dtype=np.int32)
    subs, count, ovf = gather_subscribers(fan, ids, d=4)
    assert count.tolist() == [0, 1]
    assert [x for x in np.asarray(subs)[1] if x >= 0] == [5]


def test_gather_overflow_flagged():
    fan = build_fanout({0: list(range(100))}, 1)
    ids = np.array([[0]], dtype=np.int32)
    subs, count, ovf = gather_subscribers(fan, ids, d=16)
    assert bool(np.asarray(ovf)[0])
    assert int(np.asarray(count)[0]) == 100
    got = [x for x in np.asarray(subs)[0] if x >= 0]
    assert len(got) == 16 and got == list(range(16))


def test_gather_large_random_parity():
    rng = np.random.default_rng(0)
    rows = {f: list(rng.integers(0, 10000, size=rng.integers(0, 20)))
            for f in range(200)}
    fan = build_fanout(rows, 200)
    ids = np.full((16, 32), -1, dtype=np.int32)
    for b in range(16):
        chosen = rng.choice(200, size=rng.integers(0, 30), replace=False)
        ids[b, :len(chosen)] = chosen
    subs, count, ovf = gather_subscribers(fan, ids, d=512)
    for b in range(16):
        expect = []
        for f in ids[b]:
            if f >= 0:
                expect.extend(rows[f])
        assert int(count[b]) == len(expect)
        if not ovf[b]:
            got = [x for x in np.asarray(subs)[b] if x >= 0]
            assert sorted(got) == sorted(int(x) for x in expect)


def test_pick_shared_hash_strategy():
    import jax.numpy as jnp
    import numpy as np
    from emqx_tpu.ops.fanout import build_fanout, pick_shared

    # group-membership CSR: filter 0 -> [10, 11, 12]; 1 -> [20]; 2 -> []
    fan = build_fanout({0: [10, 11, 12], 1: [20]}, num_filters=3)
    ids = jnp.array([[0, 1, -1], [2, 0, -1]], dtype=jnp.int32)
    seed = jnp.array([4, 7], dtype=jnp.int32)
    out = np.asarray(pick_shared(fan, ids, seed))
    assert out[0, 0] == 10 + (4 % 3)
    assert out[0, 1] == 20          # single member, any seed
    assert out[0, 2] == -1          # padded
    assert out[1, 0] == -1          # empty group
    assert out[1, 1] == 10 + (7 % 3)
    # deterministic per seed: same seed -> same member
    out2 = np.asarray(pick_shared(fan, ids, seed))
    assert (out == out2).all()


def test_out_of_capacity_fid_drops_not_clamps():
    """A fid at/above the table's filter capacity (a filter patched
    into the automaton after this table was built) must contribute
    nothing — clamping would alias it onto the last row."""
    import jax.numpy as jnp

    fan = build_fanout({0: [10, 11], 1: [20]}, 2)
    f_cap = fan.row_ptr.shape[0] - 1
    ids = jnp.array([[f_cap + 3, 0, -1, -1]], dtype=jnp.int32)
    subs, count, ovf = gather_subscribers(fan, ids, d=8)
    got = sorted(int(s) for s in np.asarray(subs)[0] if s >= 0)
    assert got == [10, 11]          # only filter 0's members
    assert int(np.asarray(count)[0]) == 2
    assert not bool(np.asarray(ovf)[0])


def test_pick_shared_out_of_capacity_fid_drops():
    import jax.numpy as jnp

    from emqx_tpu.ops.fanout import pick_shared

    fan = build_fanout({0: [5, 6, 7]}, 1)
    f_cap = fan.row_ptr.shape[0] - 1
    ids = jnp.array([[f_cap + 2, 0]], dtype=jnp.int32)
    seed = jnp.array([1], dtype=jnp.int32)
    picks = np.asarray(pick_shared(fan, ids, seed))[0]
    assert picks[0] == -1           # dropped, not clamped
    assert picks[1] in (5, 6, 7)


def test_expand_packed_parity_vs_dense():
    """The fused sparse expansion must produce exactly the dense
    gather's (subs, src) multiset per topic."""
    import numpy as np

    from emqx_tpu.ops.fanout import (build_fanout, expand_packed,
                                     gather_subscribers_src)
    from emqx_tpu.ops.pack import pack_matches

    rng = np.random.default_rng(7)
    F = 50
    rows = {i: list(rng.integers(0, 10_000,
                                 size=rng.integers(0, 9)))
            for i in range(F)}
    fan = build_fanout(rows, F)
    B, M = 16, 8
    ids = np.full((B, M), -1, dtype=np.int32)
    for b in range(B):
        k = rng.integers(0, M + 1)
        ids[b, :k] = rng.choice(F, size=k, replace=False)
    m_ptr, packed = pack_matches(ids, pm=256)
    f_ptr, subs, src, total = expand_packed(fan, m_ptr, packed, q=512)
    f_ptr, subs, src = map(np.asarray, (f_ptr, subs, src))
    dsubs, dsrc, _cnt, _ovf = map(
        np.asarray, gather_subscribers_src(fan, ids, d=128))
    want_total = 0
    for b in range(B):
        got = sorted(zip(subs[f_ptr[b]:f_ptr[b + 1]].tolist(),
                         src[f_ptr[b]:f_ptr[b + 1]].tolist()))
        want = sorted((int(s), int(c))
                      for s, c in zip(dsubs[b], dsrc[b]) if s >= 0)
        assert got == want, b
        want_total += len(want)
    assert int(total) == want_total


def test_expand_packed_overflow_detectable():
    import numpy as np

    from emqx_tpu.ops.fanout import build_fanout, expand_packed
    from emqx_tpu.ops.pack import pack_matches

    fan = build_fanout({0: list(range(100))}, 1)
    ids = np.zeros((4, 2), dtype=np.int32)  # every row matches f0
    ids[:, 1] = -1
    m_ptr, packed = pack_matches(ids, pm=64)
    f_ptr, subs, src, total = expand_packed(fan, m_ptr, packed, q=64)
    assert int(total) == 400 > 64  # caller re-expands bigger
