"""Publish match cache (ops/match_cache.py + router integration):
exact oracle parity through cache hits, misses, epoch invalidation
under route churn, overflow bypass, the cache-off legacy path, and
the sharded (mesh) cache on the 1×1 fast path."""

import random

import numpy as np

from emqx_tpu.broker import Broker
from emqx_tpu.oracle import TrieOracle
from emqx_tpu.router import MatcherConfig, Router
from emqx_tpu.types import Message


def _mk(**kw):
    kw.setdefault("device_min_filters", 0)
    return Router(MatcherConfig(**kw), node="node1")


class Q:
    def __init__(self, client_id="c"):
        self.client_id = client_id
        self.inbox = []

    def deliver(self, topic, msg):
        self.inbox.append((topic, msg))


# -- MatchCache unit ------------------------------------------------------


def test_cache_unit_probe_insert_merge_roundtrip():
    from emqx_tpu.ops.match_cache import MatchCache

    c = MatchCache(16, 4)
    key = ("e", 1)
    topics = ["a", "b", "c"]
    p = c.probe(topics, key)
    assert p.hit_pos == [] and p.miss_topics == topics
    rows = np.array([[1, -1, -1, -1],
                     [2, 3, -1, -1],
                     [4, 5, 6, -1]], np.int32)
    ovf = np.zeros(3, bool)
    c.insert(p, rows, ovf)
    # second probe: all hits, merged rows identical
    p2 = c.probe(["b", "a", "c", "d"], key)
    assert p2.hit_pos == [0, 1, 2] and p2.miss_topics == ["d"]
    merged, ovf2, _ = c.merge(
        8, p2, np.full((1, 4), -1, np.int32), np.zeros(1, bool))
    merged = np.asarray(merged)
    assert merged[0].tolist() == [2, 3, -1, -1]
    assert merged[1].tolist() == [1, -1, -1, -1]
    assert merged[2].tolist() == [4, 5, 6, -1]
    assert not np.asarray(ovf2)[:3].any()
    # epoch bump: everything is a (stale-counted) miss again
    p3 = c.probe(["a", "b"], ("e", 2))
    assert p3.miss_topics == ["a", "b"]
    assert c.stale == 2


def test_cache_unit_overflow_rows_store_invalid_markers():
    from emqx_tpu.ops.match_cache import MatchCache

    c = MatchCache(8, 4)
    key = 7
    p = c.probe(["t"], key)
    c.insert(p, np.array([[9, 9, 9, 9]], np.int32),
             np.array([True]))
    p2 = c.probe(["t"], key)
    assert p2.hit_pos == [0]  # found — but flagged, never served
    merged, ovf, _ = c.merge(4, p2)
    assert np.asarray(ovf)[0]            # caller must host-fallback
    assert (np.asarray(merged)[0] == -1).all()  # no truncated ids


# -- single-device router path --------------------------------------------


def _oracle_for(filters):
    t = TrieOracle()
    for f in filters:
        t.insert(f)
    return t


def _assert_parity(r, oracle, topics):
    got = r.match_filters(topics)
    for t, row in zip(topics, got):
        assert sorted(row) == sorted(oracle.match(t)), t


def test_router_cached_parity_and_hit_counters():
    r = _mk(match_cache_slots=256)
    filters = ["s/+/a", "s/1/a", "s/#", "x/y", "+/y"]
    for f in filters:
        r.add_route(f)
    oracle = _oracle_for(filters)
    topics = ["s/1/a", "s/2/a", "x/y", "nope", "s/1/a", "x/y"]
    _assert_parity(r, oracle, topics)
    c = r._match_cache_obj
    assert c is not None and c.inserts > 0
    before = c.hits
    _assert_parity(r, oracle, topics)  # identical batch: pure hits
    assert c.hits > before
    assert c.stats()["hit_rate"] > 0


def test_epoch_invalidation_on_add_and_delete():
    r = _mk(match_cache_slots=64)
    r.add_route("a/b")
    oracle = _oracle_for(["a/b"])
    _assert_parity(r, oracle, ["a/b", "a/c"])
    # a new wildcard must appear in the next match (no stale hit)
    r.add_route("a/+")
    oracle.insert("a/+")
    _assert_parity(r, oracle, ["a/b", "a/c"])
    # a delete must disappear (no ghost delivery)
    r.delete_route("a/b")
    oracle.delete("a/b")
    _assert_parity(r, oracle, ["a/b", "a/c"])
    assert r._match_cache_obj.stale > 0


def test_churn_interleaved_with_cached_matches_stays_exact():
    """The satellite churn bar: interleave add/delete with cached
    matches and assert exact oracle parity after EVERY epoch bump —
    no stale delivery, no missed delivery."""
    rng = random.Random(7)
    r = _mk(match_cache_slots=512)
    oracle = TrieOracle()
    words = ["a", "b", "c", "d"]
    live = []
    for f in ["a/#", "b/+", "a/b/c"]:
        r.add_route(f)
        oracle.insert(f)
        live.append(f)
    topics = ["/".join(rng.choice(words)
                       for _ in range(rng.randint(1, 4)))
              for _ in range(24)]
    for step in range(30):
        if live and rng.random() < 0.4:
            f = live.pop(rng.randrange(len(live)))
            r.delete_route(f)
            oracle.delete(f)
        else:
            depth = rng.randint(1, 4)
            ws = [rng.choice(words + ["+"]) for _ in range(depth)]
            if rng.random() < 0.2:
                ws.append("#")
            f = "/".join(ws)
            if f not in live:
                r.add_route(f)
                oracle.insert(f)
                live.append(f)
        batch = [rng.choice(topics) for _ in range(12)]  # hot repeats
        _assert_parity(r, oracle, batch)
    st = r._match_cache_obj.stats()
    assert st["hit"] > 0 and st["stale"] > 0


def test_overflow_topics_fall_back_exact_through_cache():
    # max_matches=2 forces m-overflow for a topic matching 3 filters
    r = _mk(match_cache_slots=64, max_matches=2, active_k=2)
    filters = ["t/#", "t/+", "t/x", "other"]
    for f in filters:
        r.add_route(f)
    oracle = _oracle_for(filters)
    for _ in range(3):  # miss, then negative-cached hits
        _assert_parity(r, oracle, ["t/x", "t/x", "other"])
    assert r._match_cache_obj.hits > 0


def test_cache_off_restores_legacy_dispatch_bytes():
    """match_cache=False must run the pre-cache dispatch
    byte-for-byte: raw (pack_ids=False) walk output, no cache
    object ever built."""
    from emqx_tpu.ops.match import depth_bucket, match_batch

    filters = ["s/+/a", "s/1/a", "s/#", "x/y"]
    topics = ["s/1/a", "x/y", "s/1/a", "zz"]
    r = _mk(match_cache=False)
    for f in filters:
        r.add_route(f)
    ids_dev, ovf_dev, id_map, epoch = r.match_dispatch(topics)
    assert r._match_cache_obj is None
    # replay the legacy dispatch by hand against the same snapshot
    auto, id_map2, epoch2 = r.automaton()
    assert epoch2 == epoch
    cfg = r.config
    bucket = cfg.min_batch
    while bucket < len(topics):
        bucket *= 2
    padded = list(topics) + ["\x00/pad"] * (bucket - len(topics))
    ids, n, sysm = r._encode(padded, cfg.max_levels)
    ids, n = depth_bucket(ids, n)
    res = match_batch(auto, ids, n, sysm, k=r.effective_k(),
                      m=cfg.max_matches, pack_ids=False,
                      **r._walk_kw(ids.shape[1]))
    assert np.array_equal(np.asarray(ids_dev), np.asarray(res.ids))
    assert np.array_equal(np.asarray(ovf_dev),
                          np.asarray(res.overflow))


def test_broker_publish_batch_hits_cache_across_batches():
    b = Broker(config=MatcherConfig(device_min_filters=0,
                                    match_cache_slots=128))
    s1, s2 = Q("c1"), Q("c2")
    b.subscribe(s1, "a/+")
    b.subscribe(s2, "a/b")
    msgs = [Message(topic=t) for t in ["a/b", "a/c", "a/b"]]
    assert b.publish_batch(msgs) == [2, 1, 2]
    c = b.router._match_cache_obj
    hits_before = c.hits
    assert b.publish_batch(msgs) == [2, 1, 2]  # all repeat topics
    assert c.hits > hits_before
    assert len(s1.inbox) == 6 and len(s2.inbox) == 4
    # churn between batches: parity must survive the epoch bump
    s3 = Q("c3")
    b.subscribe(s3, "a/#")
    assert b.publish_batch(msgs) == [3, 2, 3]


def test_drain_cache_stats_feeds_metrics():
    from emqx_tpu.metrics import Metrics

    r = _mk(match_cache_slots=64)
    r.add_route("m/1")
    r.match_filters(["m/1", "m/1"])
    r.match_filters(["m/1"])
    m = Metrics()
    drained = r.drain_cache_stats()
    assert drained["miss"] >= 1 and drained["hit"] >= 1
    m.fold_cache_stats(drained)
    assert m.val("cache.match.hit") == drained["hit"]
    assert m.val("cache.match.miss") == drained["miss"]
    assert m.val("cache.match.insert") == drained["insert"]
    # second drain: deltas only
    assert r.drain_cache_stats()["hit"] == 0
    assert r.cache_entries() >= 1


# -- sharded (mesh) cache --------------------------------------------------


def test_mesh_cached_publish_parity_1x1():
    from emqx_tpu.parallel.mesh import make_mesh

    b = Broker(router=Router(
        MatcherConfig(mesh=make_mesh(1, 1), fanout_d=8,
                      match_cache_slots=128), node="local"))
    s1, s2 = Q("c1"), Q("c2")
    b.subscribe(s1, "a/+")
    b.subscribe(s2, "a/b")
    msgs = [Message(topic="a/b"), Message(topic="a/c"),
            Message(topic="a/b")]
    assert b.publish_batch(msgs) == [2, 1, 2]
    cache = b.router._sharded_cache_obj
    assert cache is not None and cache.inserts > 0
    hits = cache.hits
    assert b.publish_batch(msgs) == [2, 1, 2]
    assert cache.hits > hits
    # epoch bump via subscribe: cached rows must not ghost-deliver
    s3 = Q("c3")
    b.subscribe(s3, "a/#")
    assert b.publish_batch(msgs) == [3, 2, 3]
    b.unsubscribe(s3, "a/#")
    assert b.publish_batch(msgs) == [2, 1, 2]


def test_mesh_big_filters_bypass_cache():
    from emqx_tpu.parallel.mesh import make_mesh

    # fanout_d=2 makes a 4-member filter "big" (bitmap path): the
    # sharded cache must refuse (a union row is unboundedly wide) and
    # the legacy collective path must stay exact
    b = Broker(router=Router(
        MatcherConfig(mesh=make_mesh(1, 1), fanout_d=2,
                      match_cache_slots=128), node="local"))
    subs = [Q(f"c{i}") for i in range(4)]
    for s in subs:
        b.subscribe(s, "big/t")
    assert b.publish(Message(topic="big/t")) == 4
    assert b.publish(Message(topic="big/t")) == 4
    cache = b.router._sharded_cache_obj
    assert cache is None or cache.hits == 0
