"""Prometheus exporter module: exposition format, the HTTP endpoint,
and the config-loaded async-module lifecycle (on_loop_start) it
motivated — the reference ships metrics scraping as the
emqx_prometheus plugin; here it reads the core metric/stat registries
(src/emqx_metrics.erl / src/emqx_stats.erl roles)."""

import asyncio

import pytest

from emqx_tpu.modules.prometheus import PrometheusModule, prom_name, render
from emqx_tpu.node import Node
from emqx_tpu.types import Message


class CollectSub:
    def __init__(self):
        self.client_id = "collect"
        self.got = []

    def deliver(self, t, m):
        self.got.append((t, m))



def test_prom_name_sanitizes():
    assert prom_name("messages.received") == "emqx_messages_received"
    assert prom_name("messages.qos1.sent") == "emqx_messages_qos1_sent"
    assert prom_name("device.match/overflow") == "emqx_device_match_overflow"


def test_render_types_and_values():
    doc = render({"messages.received": 7}, {"connections.count": 3})
    lines = doc.splitlines()
    assert "# TYPE emqx_messages_received counter" in lines
    assert "emqx_messages_received 7" in lines
    assert "# TYPE emqx_connections_count gauge" in lines
    assert "emqx_connections_count 3" in lines
    assert doc.endswith("\n")


async def _scrape(port: int, target: str = "/metrics") -> tuple:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {target} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(), timeout=5.0)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, body.decode()


async def test_scrape_endpoint_serves_live_counters():
    node = Node(name="prom@test", boot_listeners=False)
    mod = node.modules.load(PrometheusModule, env={"port": 0})
    await node.start()
    try:
        for _ in range(100):  # let the serve task bind
            if mod.port:
                break
            await asyncio.sleep(0.01)
        assert mod.port  # ephemeral port resolved
        sub = CollectSub()
        node.broker.subscribe(sub, "a/b")
        node.publish(Message(topic="a/b", payload=b"x"))
        status, body = await _scrape(mod.port)
        assert status == 200
        lines = dict(
            l.split() for l in body.splitlines() if not l.startswith("#"))
        assert int(lines["emqx_messages_received"]) >= 1
        # stats gauges ride the registered update funs via tick()
        assert int(lines["emqx_subscriptions_count"]) == 1
        status2, _ = await _scrape(mod.port, "/nope")
        assert status2 == 404
    finally:
        node.modules.unload("prometheus")
        await node.stop()


def test_sync_loaded_module_starts_on_node_start(tmp_path):
    """The boot_from_file lifecycle: modules configured in the TOML
    load BEFORE any event loop exists; node.start() must kick their
    background tasks (this was a real gap — a TOML-configured
    delayed module's timer never started). The test stays sync so
    the boot genuinely happens outside any loop."""
    from emqx_tpu.config import boot_from_file

    path = tmp_path / "n.toml"
    path.write_text("""
[node]
name = "promcfg@test"

[[listeners]]
type = "tcp"
port = 0

[modules.prometheus]
port = 0

[modules.delayed]
""")
    node = boot_from_file(str(path))  # sync context: no loop yet
    mod = node.modules._loaded["prometheus"]
    dm = node.modules._loaded["delayed"]
    assert mod._server is None and dm._task is None
    asyncio.run(_drive_config_node(node, mod, dm))


async def _drive_config_node(node, mod, dm):
    await node.start()
    try:
        for _ in range(100):
            if mod.port:
                break
            await asyncio.sleep(0.01)
        assert mod.port  # scrape endpoint actually bound
        status, body = await _scrape(mod.port)
        assert status == 200 and "emqx_messages_received" in body
        # the delayed timer loop is live: a $delayed publish fires
        sub = CollectSub()
        node.broker.subscribe(sub, "later/t")
        node.publish(Message(topic="$delayed/1/later/t", payload=b"d"))
        assert not sub.got  # intercepted, not delivered yet
        for _ in range(60):
            await asyncio.sleep(0.1)
            if sub.got:
                break
        assert [t for t, _ in sub.got] == ["later/t"]
        bound_port = mod.port
    finally:
        await node.stop()
    # stop quiesces module sockets: the real bound port must refuse
    with pytest.raises(OSError):
        await _scrape(bound_port)
    assert mod._server is None and mod.port is None


# -- publish-path telemetry exposition (ISSUE 2) ----------------------------


def test_render_histograms_and_gauge_audit():
    """Histogram families render with cumulative _bucket/_sum/_count
    lines, and audited non-monotonic names (metrics.GAUGE_METRICS —
    the retainer's live count is dec'd) say gauge, not counter."""
    from emqx_tpu.telemetry import Telemetry, TelemetryConfig

    tel = Telemetry(TelemetryConfig())
    tel.hists["dispatch"].observe(0.2)
    tel.hists["dispatch"].observe(2.0)
    doc = render({"retained.count": 4}, {}, tel.histograms())
    lines = doc.splitlines()
    assert "# TYPE emqx_retained_count gauge" in lines
    fam = "emqx_tpu_publish_stage_dispatch_ms"
    assert f"# TYPE {fam} histogram" in lines
    assert f'{fam}_bucket{{le="0.25"}} 1' in lines
    assert f'{fam}_bucket{{le="+Inf"}} 2' in lines
    assert f"{fam}_count 2" in lines


async def test_scrape_serves_publish_stage_histograms():
    """A live node's scrape carries the emqx_tpu_publish_stage_*
    families (telemetry defaults on), fed by real publish spans."""
    node = Node(name="promtel@test", boot_listeners=False)
    mod = node.modules.load(PrometheusModule, env={"port": 0})
    await node.start()
    try:
        for _ in range(100):
            if mod.port:
                break
            await asyncio.sleep(0.01)
        sub = CollectSub()
        node.broker.subscribe(sub, "h/t")
        node.publish(Message(topic="h/t"))
        status, body = await _scrape(mod.port)
        assert status == 200
        for stage in ("match", "cache_gather", "host_fallback",
                      "pack", "dispatch", "end_to_end"):
            fam = f"emqx_tpu_publish_stage_{stage}_ms"
            assert f"# TYPE {fam} histogram" in body, stage
            assert f"{fam}_count" in body
        # the host-path publish recorded real samples
        assert "emqx_tpu_publish_stage_end_to_end_ms_count 1" in body
    finally:
        node.modules.unload("prometheus")
        await node.stop()
