"""Cross-node session takeover under LIVE publish traffic
(reference: test/emqx_takeover_SUITE.erl driven across two real OS
processes — VERDICT r3 item 6).

A subscriber holds a persistent session on the subprocess node B; a
publisher streams interleaved QoS1/QoS2 messages through the parent
node A (forwarded over the socket transport); mid-stream the
subscriber reconnects on A, pulling the session across the wire.
Contract:

- QoS1: zero loss — every streamed number is delivered at least once
  (mqueue + inflight travel with the pickled session; replay covers
  the handoff window).
- QoS2: no double-publish — a payload may be retransmitted only as
  the SAME packet id (an incomplete handshake resuming); two distinct
  packet ids for one payload would mean the broker published twice.
"""

import asyncio
import contextlib

from emqx_tpu.cluster import Cluster
from emqx_tpu.cluster_net import SocketTransport
from emqx_tpu.mqtt import constants as MC
from emqx_tpu.node import Node
from tests.mqtt_client import TestClient
from tests.test_cluster_net import _read_line, _spawn_child2


def test_cross_node_takeover_under_live_traffic():
    async def main():
        proc = _spawn_child2("secret-tko")
        try:
            ready = await _read_line(proc, "READY")
            peer_cl, peer_mqtt = (int(ready.split()[1]),
                                  int(ready.split()[2]))

            a = Node(name="nodeA-tko", boot_listeners=False)
            a.add_listener(port=0)
            await a.start()
            tr = SocketTransport("nodeA-tko", cookie="secret-tko")
            tr.serve()
            cl = Cluster(a, transport=tr)
            cl.join_remote("127.0.0.1", peer_cl)
            a_port = a.listeners[0].port

            # persistent session on B, both QoS classes
            sub = TestClient("migrant", version=MC.MQTT_V5,
                             properties={"Session-Expiry-Interval": 7200})
            await sub.connect(port=peer_mqtt)
            await sub.subscribe("tko2/q1", qos=1)
            await sub.subscribe("tko2/q2", qos=2)

            pub = TestClient("streamer", version=MC.MQTT_V5)
            await pub.connect(port=a_port)

            # warm until the B-side route has replicated to A and the
            # forward path delivers (route replication is async)
            deadline = asyncio.get_running_loop().time() + 60
            while True:
                await pub.publish("tko2/q1", b"warm", qos=1, timeout=60)
                with contextlib.suppress(asyncio.TimeoutError):
                    m = await sub.recv(1.0)
                    if m.payload == b"warm":
                        break
                assert asyncio.get_running_loop().time() < deadline, \
                    "warm publish never crossed the transport"

            # record (payload, packet_id, dup) across BOTH connections
            got = []
            stop = asyncio.Event()

            async def drain(client):
                while not stop.is_set():
                    with contextlib.suppress(asyncio.TimeoutError):
                        m = await client.recv(0.2)
                        if m.payload != b"warm":
                            got.append((m.payload, m.packet_id,
                                        bool(m.dup)))

            drainers = [asyncio.create_task(drain(sub))]

            N = 30
            async def stream():
                for i in range(N):
                    await pub.publish("tko2/q1", b"1:%d" % i, qos=1,
                                      timeout=60)
                    await pub.publish("tko2/q2", b"2:%d" % i, qos=2,
                                      timeout=60)
                    await asyncio.sleep(0.02)

            stream_task = asyncio.create_task(stream())
            await asyncio.sleep(0.25)

            # MID-STREAM cross-node takeover: reconnect on A
            sub2 = TestClient("migrant", version=MC.MQTT_V5,
                              clean_start=False,
                              properties={"Session-Expiry-Interval": 7200})
            ack = await sub2.connect(port=a_port, timeout=60)
            assert ack.session_present, \
                "cross-node takeover lost the session"
            drainers.append(asyncio.create_task(drain(sub2)))
            await stream_task

            # drain until quiescent
            last = -1
            for _ in range(100):
                await asyncio.sleep(0.1)
                q1 = {p for p, _, _ in got if p.startswith(b"1:")}
                if len(q1) == N and len(got) == last:
                    break
                last = len(got)
            stop.set()
            for d in drainers:
                d.cancel()

            q1_nums = {int(p[2:]) for p, _, _ in got
                       if p.startswith(b"1:")}
            missing_q1 = set(range(N)) - q1_nums
            assert not missing_q1, \
                f"QoS1 loss across takeover: {sorted(missing_q1)}"

            q2 = {}
            for p, pid, dup in got:
                if p.startswith(b"2:"):
                    q2.setdefault(int(p[2:]), []).append((pid, dup))
            missing_q2 = set(range(N)) - set(q2)
            assert not missing_q2, \
                f"QoS2 loss across takeover: {sorted(missing_q2)}"
            for num, copies in q2.items():
                pids = {pid for pid, _ in copies}
                # a payload seen more than once must be the same
                # packet id resuming (dup retransmit) — two distinct
                # ids = the broker published twice
                assert len(pids) == 1, (
                    f"QoS2 double-publish of msg {num}: "
                    f"packet ids {sorted(pids)}")

            await sub2.close()
            await pub.close()
            proc.stdin.write(b"QUIT\n")
            proc.stdin.flush()
            proc.wait(timeout=30)
            await a.stop()
            tr.close()
        finally:
            if proc.poll() is None:
                proc.kill()

    asyncio.run(main())
