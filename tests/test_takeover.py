"""Session takeover under live traffic
(reference: test/emqx_takeover_SUITE.erl — a publisher streams while
the subscriber's clientid reconnects; no QoS1 message may be lost).
"""

import asyncio
import contextlib

from emqx_tpu.mqtt import constants as C
from tests.helpers import broker_node, node_port as _port
from tests.mqtt_client import TestClient




async def test_takeover_mid_stream_no_qos1_loss():
    N = 40
    async with broker_node() as node:
        sub = TestClient("tko", version=C.MQTT_V5, clean_start=True,
                         properties={"Session-Expiry-Interval": 300})
        await sub.connect(port=_port(node))
        await sub.subscribe("tko/t", qos=1)

        pub = TestClient("tkopub", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        # warm the compiled matcher before the timed stream
        await pub.publish("tko/t", b"warm", qos=1, timeout=120)
        assert (await sub.recv(60)).payload == b"warm"

        got = set()
        stop = asyncio.Event()

        async def drain(client):
            while not stop.is_set():
                with contextlib.suppress(asyncio.TimeoutError):
                    m = await client.recv(0.2)
                    if m.payload != b"warm":
                        got.add(int(m.payload))

        drainer = asyncio.create_task(drain(sub))

        async def stream():
            for i in range(N):
                await pub.publish("tko/t", str(i).encode(), qos=1,
                                  timeout=60)
                await asyncio.sleep(0.01)

        stream_task = asyncio.create_task(stream())
        await asyncio.sleep(0.1)
        # takeover mid-stream: same clientid, clean_start=False
        sub2 = TestClient("tko", version=C.MQTT_V5, clean_start=False,
                          properties={"Session-Expiry-Interval": 300})
        ack = await sub2.connect(port=_port(node), timeout=30)
        assert ack.session_present
        drainer2 = asyncio.create_task(drain(sub2))
        await stream_task
        # drain until nothing new arrives
        last = -1
        for _ in range(100):
            await asyncio.sleep(0.1)
            if len(got) == N:
                break
            if len(got) == last:
                continue
            last = len(got)
        stop.set()
        drainer.cancel()
        drainer2.cancel()
        missing = set(range(N)) - got
        assert not missing, f"lost QoS1 messages across takeover: {sorted(missing)}"
        await sub2.close()
        await pub.close()


async def test_takeover_replays_unacked_inflight():
    """QoS1 messages delivered but unacked on the old connection must
    be redelivered (dup=1) to the new one (emqx_session:replay)."""
    async with broker_node() as node:
        sub = TestClient("tkr", version=C.MQTT_V5, clean_start=True,
                         properties={"Session-Expiry-Interval": 300})
        await sub.connect(port=_port(node))
        await sub.subscribe("tkr/t", qos=1)
        # suppress the auto-acker: simulate a client that dies before
        # acking by tearing the socket down right after delivery
        sub._task.cancel()

        pub = TestClient("tkrpub", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("tkr/t", b"unacked", qos=1, timeout=120)
        await asyncio.sleep(0.3)  # delivered into the dead reader
        sub.writer.close()

        sub2 = TestClient("tkr", version=C.MQTT_V5, clean_start=False,
                          properties={"Session-Expiry-Interval": 300})
        ack = await sub2.connect(port=_port(node), timeout=30)
        assert ack.session_present
        m = await sub2.recv(30)
        assert m.payload == b"unacked"
        await sub2.close()
        await pub.close()


async def test_shared_sub_redispatch_on_subscriber_death():
    """A shared-group message delivered to a member that dies before
    acking is redispatched to a remaining member (reference:
    t_shared_subscriptions_client_terminates_when_qos_eq_2)."""
    async with broker_node() as node:
        a = TestClient("shA", version=C.MQTT_V5)  # clean, expiry 0
        await a.connect(port=_port(node))
        await a.subscribe("$share/gr/sh/t", qos=1)
        b = TestClient("shB", version=C.MQTT_V5)
        await b.connect(port=_port(node))
        await b.subscribe("$share/gr/sh/t", qos=1)

        # A joined first → round_robin picks A first; A never acks
        a._task.cancel()

        pub = TestClient("shpub", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("sh/t", b"must-arrive", qos=1, timeout=120)
        await asyncio.sleep(0.2)
        a.writer.close()  # A dies with the message unacked

        m = await b.recv(30)
        assert m.payload == b"must-arrive"
        await b.close()
        await pub.close()
