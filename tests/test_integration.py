"""End-to-end tests: real MQTT clients over loopback TCP against a
full broker node — the reference's emqx_client_SUITE /
mqtt_protocol_v5_SUITE tier (SURVEY §4 tier 4)."""

import asyncio

import pytest

from emqx_tpu.mqtt import constants as C
from emqx_tpu.types import Message
from tests.helpers import broker_node, node_port as _port
from tests.mqtt_client import TestClient




async def test_connect_and_ping():
    async with broker_node() as node:
        c = TestClient("c1")
        ack = await c.connect(port=_port(node))
        assert ack.reason_code == 0 and not ack.session_present
        await c.ping()
        await c.disconnect()
        assert node.metrics.val("client.connected") == 1


async def test_pub_sub_qos0():
    async with broker_node() as node:
        sub, pub = TestClient("sub"), TestClient("pub")
        await sub.connect(port=_port(node))
        await pub.connect(port=_port(node))
        ack = await sub.subscribe("t/#")
        assert ack.reason_codes == [0]
        await pub.publish("t/1", b"hello")
        msg = await sub.recv()
        assert msg.topic == "t/1" and msg.payload == b"hello" and msg.qos == 0
        await sub.disconnect()
        await pub.disconnect()


async def test_pub_sub_qos1_and_2():
    async with broker_node() as node:
        sub, pub = TestClient("sub1"), TestClient("pub1")
        await sub.connect(port=_port(node))
        await pub.connect(port=_port(node))
        await sub.subscribe("q/+", qos=2)
        await pub.publish("q/a", b"one", qos=1)
        m1 = await sub.recv()
        assert m1.qos == 1 and m1.payload == b"one"
        await pub.publish("q/b", b"two", qos=2)
        m2 = await sub.recv()
        assert m2.qos == 2 and m2.payload == b"two"
        await sub.disconnect()
        await pub.disconnect()


async def test_wildcard_and_sys_isolation():
    async with broker_node() as node:
        sub, pub = TestClient("subw"), TestClient("pubw")
        await sub.connect(port=_port(node))
        await pub.connect(port=_port(node))
        await sub.subscribe("#")
        await pub.publish("any/topic", b"x")
        assert (await sub.recv()).topic == "any/topic"
        # $-topics must not reach the '#' subscriber
        node.publish(Message(topic="$SYS/heartbeat", payload=b"no"))
        await pub.publish("plain", b"yes")
        assert (await sub.recv()).topic == "plain"
        await sub.disconnect()
        await pub.disconnect()


async def test_unsubscribe_stops_delivery():
    async with broker_node() as node:
        c, p = TestClient("cu"), TestClient("pu")
        await c.connect(port=_port(node))
        await p.connect(port=_port(node))
        await c.subscribe("u/t")
        await p.publish("u/t", b"1")
        await c.recv()
        un = await c.unsubscribe("u/t")
        assert un.packet_id > 0
        await p.publish("u/t", b"2")
        with pytest.raises(asyncio.TimeoutError):
            await c.recv(timeout=0.3)
        await c.disconnect()
        await p.disconnect()


async def test_shared_subscription_balancing():
    async with broker_node() as node:
        a, b, p = TestClient("wa"), TestClient("wb"), TestClient("wp")
        for c in (a, b, p):
            await c.connect(port=_port(node))
        await a.subscribe("$share/g/work", qos=1)
        await b.subscribe("$share/g/work", qos=1)
        for i in range(6):
            await p.publish("work", b"%d" % i, qos=1)
        await asyncio.sleep(0.2)
        got_a, got_b = a.inbox.qsize(), b.inbox.qsize()
        assert got_a + got_b == 6
        assert got_a == 3 and got_b == 3  # round_robin default
        for c in (a, b, p):
            await c.disconnect()


async def test_session_takeover():
    async with broker_node() as node:
        c1 = TestClient("same", clean_start=False)
        await c1.connect(port=_port(node))
        await c1.subscribe("keep/me", qos=1)
        c2 = TestClient("same", clean_start=False)
        ack = await c2.connect(port=_port(node))
        assert ack.session_present
        p = TestClient("tp")
        await p.connect(port=_port(node))
        await p.publish("keep/me", b"alive", qos=1)
        msg = await c2.recv()
        assert msg.payload == b"alive"
        await c2.disconnect()
        await p.disconnect()
        await c1.close()


async def test_persistent_session_offline_queue():
    async with broker_node() as node:
        c1 = TestClient("pers", clean_start=False)
        await c1.connect(port=_port(node))
        await c1.subscribe("off/line", qos=1)
        await c1.close()  # abrupt close, session kept (v3 non-clean)
        await asyncio.sleep(0.1)
        p = TestClient("pp")
        await p.connect(port=_port(node))
        await p.publish("off/line", b"queued", qos=1)
        await p.disconnect()
        c2 = TestClient("pers", clean_start=False)
        ack = await c2.connect(port=_port(node))
        assert ack.session_present
        msg = await c2.recv()
        assert msg.payload == b"queued"
        await c2.disconnect()


async def test_clean_start_discards_session():
    async with broker_node() as node:
        c1 = TestClient("cs", clean_start=False)
        await c1.connect(port=_port(node))
        await c1.subscribe("x/y", qos=1)
        await c1.close()
        c2 = TestClient("cs", clean_start=True)
        ack = await c2.connect(port=_port(node))
        assert not ack.session_present
        p = TestClient("cp")
        await p.connect(port=_port(node))
        await p.publish("x/y", b"gone", qos=1)
        with pytest.raises(asyncio.TimeoutError):
            await c2.recv(timeout=0.3)
        await c2.disconnect()
        await p.disconnect()


async def test_will_message_on_abnormal_disconnect():
    async with broker_node() as node:
        w = TestClient("willful", will_flag=True, will_qos=1,
                       will_topic="wills/t", will_payload=b"died")
        observer = TestClient("obs")
        await observer.connect(port=_port(node))
        await observer.subscribe("wills/#", qos=1)
        await w.connect(port=_port(node))
        await w.close()  # abrupt: will must fire
        msg = await observer.recv()
        assert msg.topic == "wills/t" and msg.payload == b"died"
        await observer.disconnect()


async def test_normal_disconnect_discards_will():
    async with broker_node() as node:
        w = TestClient("polite", will_flag=True, will_qos=0,
                       will_topic="wills/p", will_payload=b"no")
        observer = TestClient("obs2")
        await observer.connect(port=_port(node))
        await observer.subscribe("wills/#")
        await w.connect(port=_port(node))
        await w.disconnect()  # clean DISCONNECT: no will
        with pytest.raises(asyncio.TimeoutError):
            await observer.recv(timeout=0.3)
        await observer.disconnect()


async def test_v5_connect_and_props():
    async with broker_node() as node:
        c = TestClient("v5c", version=C.MQTT_V5)
        ack = await c.connect(port=_port(node))
        assert ack.reason_code == 0
        assert "Topic-Alias-Maximum" in ack.properties
        await c.subscribe("v5/t", qos=1)
        p = TestClient("v5p", version=C.MQTT_V5)
        await p.connect(port=_port(node))
        await p.publish("v5/t", b"x", qos=1,
                        props={"Message-Expiry-Interval": 60})
        msg = await c.recv()
        assert msg.properties.get("Message-Expiry-Interval") is not None
        await c.disconnect()
        await p.disconnect()


async def test_v5_topic_alias_inbound():
    async with broker_node() as node:
        sub = TestClient("als")
        await sub.connect(port=_port(node))
        await sub.subscribe("ali/#")
        p = TestClient("alp", version=C.MQTT_V5)
        await p.connect(port=_port(node))
        await p.publish("ali/x", b"1", props={"Topic-Alias": 4})
        await p.publish("", b"2", props={"Topic-Alias": 4})  # alias only
        m1 = await sub.recv()
        m2 = await sub.recv()
        assert m1.topic == m2.topic == "ali/x"
        await sub.disconnect()
        await p.disconnect()


async def test_assigned_clientid_v5():
    async with broker_node() as node:
        c = TestClient("", version=C.MQTT_V5)
        ack = await c.connect(port=_port(node))
        assert ack.reason_code == 0
        assert ack.properties.get(
            "Assigned-Client-Identifier", "").startswith("emqx_tpu_")
        await c.disconnect()


async def test_connect_must_be_first():
    async with broker_node() as node:
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", _port(node))
        from emqx_tpu.mqtt.frame import serialize
        from emqx_tpu.mqtt.packet import Pingreq
        writer.write(serialize(Pingreq(), C.MQTT_V4))
        data = await reader.read(100)
        assert data == b""  # server closes without response
        writer.close()


async def test_error_connack_closes_socket():
    from emqx_tpu.zone import Zone
    async with broker_node(zone=Zone(name="noauth",
                                     allow_anonymous=False)) as node:
        c = TestClient("denied")
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", _port(node))
        from emqx_tpu.mqtt.frame import Parser, serialize
        from emqx_tpu.mqtt.packet import Connect, Pingreq
        writer.write(serialize(Connect(client_id="denied"), C.MQTT_V4))
        await writer.drain()
        data = await reader.read(100)
        pkts = Parser().feed(data)
        assert pkts and pkts[0].reason_code == 5  # v3 not-authorized
        # server must close after the error CONNACK
        assert await reader.read(100) == b""
        writer.close()


async def test_mountpoint_namespacing():
    from emqx_tpu.zone import Zone
    z = Zone(name="mp", mountpoint="dev/%c/")
    async with broker_node(zone=z) as node:
        c = TestClient("cli1")
        await c.connect(port=_port(node))
        await c.subscribe("up/+", qos=1)
        await c.publish("up/x", b"ours", qos=1)
        msg = await c.recv()
        # client sees its own namespace, unprefixed
        assert msg.topic == "up/x"
        # broker-side topic is mounted
        assert node.router.has_route("dev/cli1/up/+")
        await c.disconnect()


async def test_mountpoint_queue_share_prefix():
    from emqx_tpu.zone import Zone
    z = Zone(name="mpq", mountpoint="mp/")
    async with broker_node(zone=z) as node:
        a = TestClient("qa")
        await a.connect(port=_port(node))
        await a.subscribe("$queue/t", qos=1)
        # route must be mp/t in group $queue — not a mangled filter
        assert node.router.has_route("mp/t")
        p = TestClient("qp")
        await p.connect(port=_port(node))
        await p.publish("t", b"job", qos=1)
        msg = await a.recv()
        assert msg.topic == "t" and msg.payload == b"job"
        await a.disconnect()
        await p.disconnect()


async def test_retry_does_not_double_unmount():
    from emqx_tpu.zone import Zone
    z = Zone(name="mpr", mountpoint="pre/", retry_interval=0.0)
    async with broker_node(zone=z) as node:
        # no auto-ack: the PUBACK would clear the inflight slot and
        # there would be nothing left to retry
        sub = TestClient("r1", auto_ack=False)
        await sub.connect(port=_port(node))
        await sub.subscribe("a/b", qos=1)
        chan = node.cm.lookup_channel("r1")
        p = TestClient("r2")
        await p.connect(port=_port(node))
        await p.publish("a/b", b"x", qos=1)
        m1 = await sub.recv()
        assert m1.topic == "a/b"
        # force a retry: inflight entry must still carry the mounted
        # topic, so the re-delivery unmounts to the same client topic
        out = chan.handle_timeout("retry")
        assert out and out[0].topic == "a/b" and out[0].dup
        await sub.disconnect()
        await p.disconnect()


async def test_qos_downgraded_to_sub_qos():
    async with broker_node() as node:
        sub, pub = TestClient("dq"), TestClient("dp")
        await sub.connect(port=_port(node))
        await pub.connect(port=_port(node))
        await sub.subscribe("d/t", qos=0)
        await pub.publish("d/t", b"x", qos=2)
        msg = await sub.recv()
        assert msg.qos == 0
        await sub.disconnect()
        await pub.disconnect()


async def test_near_limit_payloads_through_batched_pipeline():
    """900KB payloads ride the ingress batcher / device pipeline
    intact; a payload over the zone's max_packet_size kills the
    connection (frame-too-large) instead of being delivered."""
    from tests.helpers import broker_node, node_port

    async with broker_node() as node:
        sub = TestClient("big-sub", version=5)
        await sub.connect(port=node_port(node))
        await sub.subscribe("big/#", qos=1)
        pub = TestClient("big-pub", version=5)
        await pub.connect(port=node_port(node))
        payload = bytes(900_000)
        for i in range(3):
            await pub.publish(f"big/{i}", payload, qos=1, timeout=60)
        for _ in range(3):
            m = await asyncio.wait_for(sub.recv(), 20)
            assert len(m.payload) == 900_000
        # the oversized frame draws an explicit v5 DISCONNECT 0x95
        # (Packet too large) before the close — rejected at
        # header-decode time, never delivered
        from emqx_tpu.mqtt import reason_codes as RC
        from emqx_tpu.mqtt.packet import Disconnect, Publish
        await pub.send(Publish(topic="big/over",
                               payload=bytes(1_100_000), qos=1,
                               packet_id=99))
        d = await asyncio.wait_for(pub.acks.get(), 10)
        assert isinstance(d, Disconnect), d
        assert d.reason_code == RC.PACKET_TOO_LARGE
        await sub.disconnect()
