"""Shared-sub strategy tests — modeled on reference
test/emqx_shared_sub_SUITE.erl (random/round_robin/sticky/hash,
redispatch on failure)."""

from emqx_tpu.shared_sub import SharedSub
from emqx_tpu.types import Message


class Q:
    def __init__(self, cid, fail=False):
        self.client_id = cid
        self.inbox = []
        self.fail = fail

    def deliver(self, topic, msg):
        if self.fail:
            raise RuntimeError("conn down")
        self.inbox.append((topic, msg))


def _msg(sender="c0"):
    return Message(topic="t", from_=sender)


def test_round_robin():
    ss = SharedSub("round_robin")
    a, b = Q("a"), Q("b")
    ss.subscribe("g", "t", a)
    ss.subscribe("g", "t", b)
    for _ in range(4):
        assert ss.dispatch("g", "t", _msg()) == 1
    assert len(a.inbox) == 2 and len(b.inbox) == 2


def test_sticky():
    ss = SharedSub("sticky")
    a, b = Q("a"), Q("b")
    ss.subscribe("g", "t", a)
    ss.subscribe("g", "t", b)
    for _ in range(5):
        ss.dispatch("g", "t", _msg())
    assert (len(a.inbox), len(b.inbox)) in [(5, 0), (0, 5)]
    # sticky target leaves → re-pick the other
    target = a if a.inbox else b
    other = b if a.inbox else a
    ss.unsubscribe("g", "t", target)
    ss.dispatch("g", "t", _msg())
    assert len(other.inbox) == 1


def test_hash_is_per_sender_stable():
    ss = SharedSub("hash")
    a, b = Q("a"), Q("b")
    ss.subscribe("g", "t", a)
    ss.subscribe("g", "t", b)
    for _ in range(5):
        ss.dispatch("g", "t", _msg("client-x"))
    assert (len(a.inbox), len(b.inbox)) in [(5, 0), (0, 5)]


def test_random_delivers():
    ss = SharedSub("random")
    a, b = Q("a"), Q("b")
    ss.subscribe("g", "t", a)
    ss.subscribe("g", "t", b)
    for _ in range(20):
        assert ss.dispatch("g", "t", _msg()) == 1
    assert len(a.inbox) + len(b.inbox) == 20


def test_redispatch_on_failure():
    ss = SharedSub("round_robin")
    bad, good = Q("bad", fail=True), Q("good")
    ss.subscribe("g", "t", bad)
    ss.subscribe("g", "t", good)
    for _ in range(3):
        assert ss.dispatch("g", "t", _msg()) == 1
    assert len(good.inbox) == 3


def test_no_subscribers():
    ss = SharedSub()
    assert ss.dispatch("g", "t", _msg()) == 0


def test_all_failed():
    ss = SharedSub()
    bad = Q("bad", fail=True)
    ss.subscribe("g", "t", bad)
    assert ss.dispatch("g", "t", _msg()) == 0


def test_subscriber_down_cleans_groups():
    ss = SharedSub()
    a = Q("a")
    ss.subscribe("g1", "t1", a)
    ss.subscribe("g2", "t2", a)
    ss.subscriber_down(a)
    assert ss.subscribers("g1", "t1") == []
    assert ss.subscribers("g2", "t2") == []
