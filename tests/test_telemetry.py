"""Publish-path telemetry (emqx_tpu/telemetry.py): histogram bucket
math vs numpy, span lifecycle across real publish_batch calls (host /
device / mesh-1×1 paths, cache hit/miss tags), disabled-mode zero-
cost + byte-identical dispatch, the slow-publish log + sustained-
breach alarm, Prometheus histogram exposition, and the observability
satellites (tracer sink failure, profiler start failure, [telemetry]
config schema)."""

import logging

import numpy as np
import pytest

from emqx_tpu.alarm import AlarmManager
from emqx_tpu.broker import Broker
from emqx_tpu.metrics import GAUGE_METRICS
from emqx_tpu.modules.prometheus import render
from emqx_tpu.router import MatcherConfig, Router
from emqx_tpu.telemetry import (BUCKETS_MS, STAGES, Histogram,
                                Telemetry, TelemetryConfig)
from emqx_tpu.tracer import Tracer
from emqx_tpu.types import Message

from emqx_tpu.config import ConfigError, parse_config
from emqx_tpu.node import Node


class Q:
    def __init__(self, client_id="c"):
        self.client_id = client_id
        self.inbox = []

    def deliver(self, topic, msg):
        self.inbox.append((topic, msg))


def _wire(broker: Broker, cfg: TelemetryConfig = None,
          **tel_kw) -> Telemetry:
    """Manual Node-style wiring for standalone Broker tests."""
    tel = Telemetry(cfg or TelemetryConfig(), **tel_kw)
    broker.telemetry = tel
    broker.router.telemetry = tel
    return tel


def _device_broker(**mk) -> Broker:
    mk.setdefault("device_min_filters", 0)
    return Broker(router=Router(MatcherConfig(**mk), node="node1"))


# -- Histogram ------------------------------------------------------------


def test_histogram_percentiles_match_numpy():
    rng = np.random.default_rng(7)
    xs = rng.lognormal(mean=0.0, sigma=2.0, size=1500)
    h = Histogram(ring_size=4096)
    for x in xs:
        h.observe(float(x))
    for q in (50, 95, 99):
        got = h.percentile(q)
        lo = float(np.percentile(xs, q, method="lower"))
        hi = float(np.percentile(xs, q, method="higher"))
        assert lo <= got <= hi or got == pytest.approx(lo), (q, got)
    assert h.count == 1500
    assert h.sum == pytest.approx(float(xs.sum()), rel=1e-9)


def test_histogram_bucket_counts_are_exact_and_cumulative():
    h = Histogram(ring_size=64)
    xs = [0.005, 0.05, 0.05, 3.0, 40.0, 9999.0]  # last is > max bound
    for x in xs:
        h.observe(x)
    snap = h.snapshot()
    bounds = [b for b, _ in snap["buckets"]]
    assert bounds == list(BUCKETS_MS)
    # cumulative counts per le, computed independently
    expect = [int(sum(1 for x in xs if x <= b)) for b in bounds]
    assert [c for _, c in snap["buckets"]] == expect
    assert snap["count"] == len(xs)          # +Inf bucket == count
    assert snap["buckets"][-1][1] == 5       # 9999 only in +Inf
    # cumulative sequence never decreases
    cums = [c for _, c in snap["buckets"]]
    assert cums == sorted(cums)


def test_histogram_ring_is_bounded_but_counts_are_total():
    h = Histogram(ring_size=8)
    for i in range(100):
        h.observe(float(i))
    assert h.count == 100
    assert len(h.ring) == 8
    assert list(h.ring) == [float(i) for i in range(92, 100)]
    h.reset()
    assert h.count == 0 and not h.ring and h.sum == 0.0


# -- span lifecycle: host path --------------------------------------------


def test_host_path_span_records_match_dispatch_e2e():
    b = Broker()  # default config: few filters -> host regime
    tel = _wire(b)
    s = Q()
    b.subscribe(s, "a/+")
    assert b.publish_batch([Message(topic="a/x"),
                            Message(topic="a/y")]) == [1, 1]
    assert tel.spans_total == 1
    st = tel.stage_stats()
    for stage in ("match", "dispatch", "end_to_end"):
        assert st[stage]["count"] == 1, stage
    assert st["end_to_end"]["p50_ms"] > 0
    # device-only stages never fired on the host path
    assert st["fetch"]["count"] == 0
    assert st["cache_gather"]["count"] == 0


def test_vetoed_out_batch_still_closes_its_span():
    b = Broker()
    tel = _wire(b)
    b.hooks.add("message.publish", lambda msg: None)  # veto all
    assert b.publish_batch([Message(topic="t")]) == [0]
    assert tel.spans_total == 1
    assert tel.stage_stats()["end_to_end"]["count"] == 1


# -- span lifecycle: device path + cache tags -----------------------------


def test_device_path_span_stages_and_cache_tags():
    b = _device_broker(match_cache_slots=256)
    # threshold 0: every batch lands in the slow ring, exposing tags
    tel = _wire(b, TelemetryConfig(slow_threshold_ms=0.0,
                                   slow_alarm_after=10**9))
    s1, s2 = Q("c1"), Q("c2")
    b.subscribe(s1, "s/+/a")
    b.subscribe(s2, "s/1/a")
    msgs = [Message(topic="s/1/a"), Message(topic="s/2/a"),
            Message(topic="s/1/a")]
    assert b.publish_batch(msgs) == [2, 1, 2]
    assert b.publish_batch(msgs) == [2, 1, 2]
    assert tel.spans_total == 2
    st = tel.stage_stats()
    for stage in ("match", "cache_gather", "pack", "fetch",
                  "dispatch", "end_to_end"):
        assert st[stage]["count"] == 2, stage
    first, second = tel.slow_records()
    assert first["path"] == "device"
    assert first["n_uniq"] == 2 and first["batch"] == 3
    assert first["bucket"] >= 2
    assert first["cache_miss"] == 2 and first["cache_hit"] == 0
    # identical repeat batch: pure cache hits
    assert second["cache_hit"] == 2 and second["cache_miss"] == 0
    assert "stages_ms" in first and "match" in first["stages_ms"]


def test_mesh_1x1_span_path_tag():
    from emqx_tpu.parallel.mesh import make_mesh

    b = Broker(router=Router(
        MatcherConfig(mesh=make_mesh(1, 1), fanout_d=8,
                      match_cache_slots=128), node="local"))
    tel = _wire(b, TelemetryConfig(slow_threshold_ms=0.0,
                                   slow_alarm_after=10**9))
    s1 = Q("c1")
    b.subscribe(s1, "a/+")
    assert b.publish_batch([Message(topic="a/b")]) == [1]
    assert tel.spans_total == 1
    rec = tel.slow_records()[0]
    assert rec["path"] == "mesh"
    st = tel.stage_stats()
    assert st["match"]["count"] == 1
    assert st["fetch"]["count"] == 1


def test_chunked_finish_closes_span_once():
    b = _device_broker(match_cache=False)
    tel = _wire(b)
    s = Q()
    b.subscribe(s, "t/+")
    msgs = [Message(topic=f"t/{i}") for i in range(8)]
    pb = b.publish_begin(msgs)
    assert not pb.done
    b.publish_fetch(pb)
    # the streaming ingress form: chunked delivery tail
    for lo in range(0, len(pb.live), 3):
        b.publish_finish_chunk(pb, lo, min(lo + 3, len(pb.live)))
    pb.done = True
    assert pb.results == [1] * 8
    assert tel.spans_total == 1
    st = tel.stage_stats()
    assert st["end_to_end"]["count"] == 1
    # dispatch accumulated over 3 chunks but folded ONCE
    assert st["dispatch"]["count"] == 1


# -- disabled mode: zero samples, byte-identical dispatch -----------------


def _run_workload(broker):
    subs = [Q(f"c{i}") for i in range(3)]
    broker.subscribe(subs[0], "w/+/x")
    broker.subscribe(subs[1], "w/1/x")
    broker.subscribe(subs[2], "w/#")
    out = []
    for _ in range(3):
        out.append(broker.publish_batch(
            [Message(topic="w/1/x"), Message(topic="w/2/x"),
             Message(topic="other")]))
    return out, [[t for t, _ in s.inbox] for s in subs]


def test_disabled_mode_records_nothing_and_dispatch_is_identical():
    b_off = _device_broker(match_cache_slots=64)
    tel = _wire(b_off, TelemetryConfig(enabled=False))
    b_ref = _device_broker(match_cache_slots=64)  # telemetry = None
    got_off = _run_workload(b_off)
    got_ref = _run_workload(b_ref)
    assert got_off == got_ref  # results AND per-sub delivery streams
    assert tel.spans_total == 0 and tel.slow_total == 0
    assert all(h.count == 0 for h in tel.hists.values())
    assert tel.begin(4) is None  # the broker-facing contract
    # no span was ever attached to a batch
    pb = b_off.publish_begin([Message(topic="w/1/x")])
    assert pb.span is None
    b_off.publish_fetch(pb)
    b_off.publish_finish(pb)


def test_enabled_mode_same_dispatch_results_as_reference():
    b_on = _device_broker(match_cache_slots=64)
    _wire(b_on)
    b_ref = _device_broker(match_cache_slots=64)
    assert _run_workload(b_on) == _run_workload(b_ref)


def test_disabled_mode_ab_guard_covers_dispatch_planner():
    """The disabled-mode byte-identity guard, on BOTH delivery tails:
    planner-on (the default, its dispatch_plan stage silent) and the
    [dispatch] planner=false legacy walk."""
    from emqx_tpu.broker import DispatchConfig

    assert "dispatch_plan" in STAGES
    for planner in (True, False):
        dc = DispatchConfig(planner=planner)
        b_off = Broker(router=Router(
            MatcherConfig(device_min_filters=0, match_cache_slots=64),
            node="node1"), dispatch_config=dc)
        tel = _wire(b_off, TelemetryConfig(enabled=False))
        b_ref = Broker(router=Router(
            MatcherConfig(device_min_filters=0, match_cache_slots=64),
            node="node1"), dispatch_config=dc)
        assert _run_workload(b_off) == _run_workload(b_ref), planner
        assert tel.spans_total == 0
        assert all(h.count == 0 for h in tel.hists.values())


# -- slow-publish log + alarm ---------------------------------------------


def test_slow_publish_log_line_and_sustained_alarm(caplog):
    alarms = AlarmManager(node="t@test")
    b = Broker()
    tel = _wire(b, TelemetryConfig(slow_threshold_ms=0.0,
                                   slow_alarm_after=2),
                alarms=alarms)
    s = Q()
    b.subscribe(s, "a")
    with caplog.at_level(logging.WARNING, logger="emqx_tpu.telemetry"):
        b.publish(Message(topic="a"))
        assert not [a for a in alarms.get_alarms("activated")]
        b.publish(Message(topic="a"))  # streak hits 2 -> alarm
    assert tel.slow_total == 2
    active = alarms.get_alarms("activated")
    assert [a.name for a in active] == ["slow_publish"]
    assert active[0].details["streak"] == 2
    lines = [r.message for r in caplog.records
             if "slow publish batch" in r.message]
    assert len(lines) == 2
    assert '"end_to_end_ms"' in lines[0]
    # a fast batch clears the streak AND the alarm
    tel.config.slow_threshold_ms = 1e9
    b.publish(Message(topic="a"))
    assert not alarms.get_alarms("activated")
    assert [a.name for a in alarms.get_alarms("deactivated")] \
        == ["slow_publish"]
    # the ring keeps the slow records for ctl telemetry slow
    assert len(tel.slow_records()) == 2
    tel.reset()
    assert tel.slow_records() == [] and tel.spans_total == 0


def test_slow_record_tees_through_tracer():
    tr = Tracer()
    sink = tr.start_trace("topic", "hot/#")
    tel = Telemetry(TelemetryConfig(slow_threshold_ms=0.0),
                    tracer=tr)
    sp = tel.begin(1)
    sp.topic = "hot/t"
    tel.finish(sp)
    assert len(sink) == 1 and "SLOW PUBLISH" in sink[0]
    # a non-matching topic trace captures nothing
    tr2 = Tracer()
    sink2 = tr2.start_trace("topic", "cold/#")
    tel2 = Telemetry(TelemetryConfig(slow_threshold_ms=0.0),
                     tracer=tr2)
    sp2 = tel2.begin(1)
    sp2.topic = "hot/t"
    tel2.finish(sp2)
    assert sink2 == []


# -- Prometheus exposition ------------------------------------------------


def test_prometheus_histogram_line_format():
    tel = Telemetry(TelemetryConfig())
    tel.hists["match"].observe(0.3)
    tel.hists["match"].observe(7.0)
    tel.hists["match"].observe(99999.0)  # past the last bound
    doc = render({}, {}, tel.histograms())
    lines = doc.splitlines()
    fam = "emqx_tpu_publish_stage_match_ms"
    assert f"# TYPE {fam} histogram" in lines
    assert f'{fam}_bucket{{le="0.5"}} 1' in lines
    assert f'{fam}_bucket{{le="10"}} 2' in lines
    assert f'{fam}_bucket{{le="5000"}} 2' in lines
    assert f'{fam}_bucket{{le="+Inf"}} 3' in lines
    assert f"{fam}_count 3" in lines
    assert any(l.startswith(f"{fam}_sum ") for l in lines)
    # every stage family is present even before any traffic
    for stage in STAGES:
        assert (f"# TYPE emqx_tpu_publish_stage_{stage}_ms histogram"
                in lines), stage


def test_prometheus_gauge_audit_for_dec_counters():
    # retained.count is dec'd by the retainer (GAUGE_METRICS): the
    # exposition must say gauge, not counter — a scraper rate()s
    # counters and reads any decrease as a restart
    assert "retained.count" in GAUGE_METRICS
    doc = render({"retained.count": 5, "messages.received": 9}, {})
    lines = doc.splitlines()
    assert "# TYPE emqx_retained_count gauge" in lines
    assert "# TYPE emqx_messages_received counter" in lines
    assert "emqx_retained_count 5" in lines


# -- tracer satellites ----------------------------------------------------


class _BoomSink:
    def __init__(self):
        self.wrote = 0

    def write(self, line):
        raise OSError("closed")


def test_trace_handler_sink_failure_detaches_cleanly():
    tr = Tracer()
    tr.start_trace("topic", "a/#", sink=_BoomSink())
    ok_sink = tr.start_trace("topic", "a/b")
    # must not raise out of the logging call on the publish path
    tr.trace_publish(Message(topic="a/b", payload=b"x"))
    # broken handler detached; healthy one captured the line
    assert tr.lookup_traces() == [("topic", "a/b")]
    assert len(ok_sink) == 1
    # and the detached sink stays gone on the next publish
    tr.trace_publish(Message(topic="a/b", payload=b"y"))
    assert len(ok_sink) == 2


def test_stop_trace_flushes_file_like_sinks():
    class _FileSink:
        def __init__(self):
            self.lines = []
            self.flushed = False

        def write(self, line):
            self.lines.append(line)

        def flush(self):
            self.flushed = True

    tr = Tracer()
    fs = _FileSink()
    tr.start_trace("clientid", "c9", sink=fs)
    tr.trace_packet("RECV", "c9", "CONNECT")
    assert tr.stop_trace("clientid", "c9")
    assert fs.flushed and len(fs.lines) == 1


# -- profiling satellites -------------------------------------------------


class _Reg:
    def __init__(self):
        self.cmds = {}

    def register_command(self, name, fn, usage=""):
        self.cmds[name] = fn


def test_profile_start_failure_keeps_state_consistent(monkeypatch):
    import jax

    from emqx_tpu import profiling

    reg = _Reg()
    profiling.register_ctl(reg)

    def _boom(logdir):
        raise RuntimeError("unwritable: " + logdir)

    monkeypatch.setattr(jax.profiler, "start_trace", _boom)
    stopped = []
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: stopped.append(True))
    out = reg.cmds["profile"](["start", "/nope/dir"])
    assert "profile start failed" in out and "unwritable" in out
    assert profiling._active["dir"] is None  # no trace-running ghost
    assert stopped  # best-effort cleanup of a partial trace
    assert "off" in reg.cmds["profile"]([])


def test_kernel_timer_span_has_no_dead_block_param():
    import inspect

    from emqx_tpu.profiling import KernelTimer

    sig = inspect.signature(KernelTimer.span)
    assert "block" not in sig.parameters
    t = KernelTimer()
    with t.span("x") as done:
        done(np.zeros(2))
    assert t.stats()["x"]["count"] == 1


# -- [telemetry] config schema --------------------------------------------


def test_config_telemetry_section_parses():
    cfg = parse_config({"telemetry": {
        "enabled": False, "slow_threshold_ms": 5,
        "ring_size": 128, "slow_log_size": 8, "slow_alarm_after": 3}})
    t = cfg.telemetry
    assert t is not None and t.enabled is False
    assert t.slow_threshold_ms == 5.0 and t.ring_size == 128
    assert t.slow_log_size == 8 and t.slow_alarm_after == 3
    assert parse_config({}).telemetry is None  # defaults at Node


def test_config_telemetry_rejects_typos_and_bad_types():
    with pytest.raises(ConfigError):
        parse_config({"telemetry": {"enabld": True}})
    with pytest.raises(ConfigError):
        parse_config({"telemetry": {"enabled": "yes"}})
    with pytest.raises(ConfigError):
        parse_config({"telemetry": {"ring_size": 2.5}})
    with pytest.raises(ConfigError):
        parse_config({"telemetry": {"slow_threshold_ms": -1}})
    with pytest.raises(ConfigError):
        parse_config({"telemetry": ["not", "a", "table"]})


# -- node integration: wiring, ctl, $SYS ----------------------------------


async def test_node_wiring_ctl_and_sys_heartbeat():
    node = Node(name="tel@test", boot_listeners=False,
                batch_ingress=False)
    await node.start()
    try:
        assert node.broker.telemetry is node.telemetry
        assert node.router.telemetry is node.telemetry
        s = Q()
        node.broker.subscribe(s, "a/b")
        node.publish(Message(topic="a/b"))
        assert node.telemetry.spans_total >= 1
        out = node.ctl.run(["telemetry"])
        assert "match" in out and "end_to_end" in out
        assert "p50_ms" in out and "p99_ms" in out
        assert node.ctl.run(["telemetry", "slow"]) == "(none)"
        # $SYS heartbeat publishes the per-stage summary
        sysq = Q("sysq")
        node.broker.subscribe(
            sysq, "$SYS/brokers/tel@test/telemetry/stages")
        node.sys.heartbeat()
        assert any("end_to_end" in m.payload.decode()
                   for _, m in sysq.inbox)
        # stats gauges ride the registered update fun
        node.stats.tick()
        assert node.stats.getstat("publish.spans.count") >= 1
        assert node.ctl.run(["telemetry", "reset"]) == "ok"
        assert node.telemetry.spans_total == 0
    finally:
        await node.stop()


async def test_node_disabled_telemetry_ctl_reports_it():
    node = Node(name="teloff@test", boot_listeners=False,
                telemetry=TelemetryConfig(enabled=False))
    await node.start()
    try:
        s = Q()
        node.broker.subscribe(s, "x")
        node.publish(Message(topic="x"))
        assert node.telemetry.spans_total == 0
        assert "disabled" in node.ctl.run(["telemetry"])
    finally:
        await node.stop()


async def test_ingress_pipelined_batches_close_spans():
    """The real async ingress path: executor-thread fetch + chunked
    delivery tail must still close every span exactly once."""
    import asyncio

    node = Node(name="telin@test", boot_listeners=False,
                batch_ingress=True)
    await node.start()
    try:
        s = Q()
        node.broker.subscribe(s, "p/+")
        futs = [node.broker.ingress.submit(Message(topic=f"p/{i % 4}"))
                for i in range(32)]
        res = await asyncio.gather(*futs)
        assert res == [1] * 32
        await node.broker.ingress.drain()
        tel = node.telemetry
        assert tel.spans_total >= 1
        st = tel.stage_stats()
        assert st["end_to_end"]["count"] == tel.spans_total
        assert st["dispatch"]["count"] == tel.spans_total
    finally:
        await node.stop()
