"""Multi-chip publish step on the virtual 8-device CPU mesh:
parity of the sharded match vs the host oracle, and mesh-summed stats."""

import random

import jax
import numpy as np
import pytest

from emqx_tpu.oracle import TrieOracle
from emqx_tpu.ops.tokenize import WordTable, encode_batch
from emqx_tpu.parallel.mesh import make_mesh
from emqx_tpu.parallel.sharded import (
    build_sharded, build_sharded_fanout, place_batch, place_sharded,
    publish_step, shard_filters, shard_map_available)

# capability guard (tier-1 hygiene): a JAX build with NO shard_map
# implementation at all (neither jax.shard_map nor the experimental
# module) cannot run the multi-device mesh program — skip the suite
# instead of erroring it out of the report. The 1×1-mesh paths in
# other suites keep running (they use the plain-jit fast path).
pytestmark = pytest.mark.skipif(
    not shard_map_available(),
    reason="this JAX build has no shard_map implementation")


def _rand_filters(rng, n):
    words = ["a", "b", "c", "d", "e", "s1", "s2"]
    out = set()
    while len(out) < n:
        depth = rng.randint(1, 5)
        ws = []
        for i in range(depth):
            r = rng.random()
            if r < 0.2:
                ws.append("+")
            elif r < 0.3 and i == depth - 1:
                ws.append("#")
            else:
                ws.append(rng.choice(words))
        out.add("/".join(ws))
    return sorted(out)


@pytest.mark.parametrize("n_data,n_trie",
                         [(4, 2), (2, 4), (8, 1), (1, 1)])
def test_sharded_match_parity(n_data, n_trie):
    # (1, 1) exercises the plain-jit fast path (no shard_map): its
    # outputs must be indistinguishable from the collective program's
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rng = random.Random(0)
    filters = _rand_filters(rng, 120)
    fids = {f: i for i, f in enumerate(filters)}
    table = WordTable()
    for f in filters:
        for w in f.split("/"):
            table.intern(w)
    oracle = TrieOracle()
    for f in filters:
        oracle.insert(f)

    mesh = make_mesh(n_data, n_trie)
    shards = shard_filters(filters, n_trie)
    auto, parts = build_sharded(shards, fids, table, return_parts=True)
    rows = [{fids[f]: [fids[f] * 10, fids[f] * 10 + 1] for f in shard}
            for shard in shards]
    fan = build_sharded_fanout(rows, len(filters))

    words = ["a", "b", "c", "d", "e", "s1", "s2", "zz"]
    B = 8 * n_data
    topics = ["/".join(rng.choice(words) for _ in range(rng.randint(1, 5)))
              for _ in range(B)]
    ids_np, n_np, sys_np = encode_batch(table, topics, 8)

    auto_d = place_sharded(mesh, auto)
    fan_d = place_sharded(mesh, fan)
    b = place_batch(mesh, ids_np, n_np, sys_np)

    from emqx_tpu.ops.match import walk_params

    ids, subs, src, _bm, ovf, movf, stats = publish_step(
        mesh, auto_d, fan_d, *b, k=32, m=32, d=64,
        **walk_params(parts[0], 8))
    assert _bm is None
    assert not np.asarray(movf).any()
    ids = np.asarray(ids)
    subs = np.asarray(subs)
    src = np.asarray(src)
    inv = {v: k for k, v in fids.items()}
    total_matches = 0
    total_deliv = 0
    for i, t in enumerate(topics):
        got = sorted(inv[j] for j in ids[i] if j >= 0)
        expect = sorted(oracle.match(t))
        assert got == expect, (t, got, expect)
        total_matches += len(expect)
        exp_subs = sorted(x for f in expect for x in rows_lookup(rows, fids[f]))
        assert sorted(x for x in subs[i] if x >= 0) == exp_subs
        total_deliv += len(exp_subs)
        # src carries the matched filter id per gathered slot
        exp_pairs = sorted((fids[f], x) for f in expect
                           for x in rows_lookup(rows, fids[f]))
        got_pairs = sorted((int(s), int(x))
                           for s, x in zip(src[i], subs[i]) if x >= 0)
        assert got_pairs == exp_pairs, (t, got_pairs, exp_pairs)
    assert int(stats["matches"]) == total_matches
    assert int(stats["deliveries"]) == total_deliv
    assert int(stats["overflows"]) == 0


def rows_lookup(rows, fid):
    for shard_rows in rows:
        if fid in shard_rows:
            return shard_rows[fid]
    return []


# -- product integration: Router on a mesh (VERDICT round-1 item 7) ---------

def test_router_sharded_match_parity():
    """Router(mesh=...) matches through publish_step with exact
    oracle parity — BASELINE config 5's product path on the virtual
    8-device mesh."""
    import random

    from emqx_tpu.oracle import TrieOracle
    from emqx_tpu.parallel.mesh import default_mesh
    from emqx_tpu.router import MatcherConfig, Router

    rng = random.Random(3)
    mesh = default_mesh(8)
    r = Router(MatcherConfig(mesh=mesh), node="n1")
    oracle = TrieOracle()
    words = ["a", "b", "c", "dd", "s"]
    filters = set()
    while len(filters) < 60:
        depth = rng.randint(1, 4)
        ws = [rng.choice(words + ["+"]) for _ in range(depth)]
        if rng.random() < 0.2:
            ws[-1] = "#"
        filters.add("/".join(ws))
    for f in filters:
        r.add_route(f)
        oracle.insert(f)
    topics = ["/".join(rng.choice(words) for _ in range(rng.randint(1, 4)))
              for _ in range(40)]
    got = r.match_filters(topics)
    for t, g in zip(topics, got):
        assert sorted(g) == sorted(oracle.match(t)), t


def test_router_sharded_mutation_patches_not_rebuilds():
    """Mesh-mode route churn is O(delta): a mutation patches its
    shard's row of the stacked automaton (per-shard AutoPatcher) —
    no re-flatten (VERDICT r2 weak #5)."""
    from emqx_tpu.parallel.mesh import default_mesh
    from emqx_tpu.router import MatcherConfig, Router

    r = Router(MatcherConfig(mesh=default_mesh(8)), node="n1")
    r.add_route("a/+")
    assert [f for [f] in [r.match_filters(["a/x"])[0]]] == ["a/+"]
    base = r.stats()["rebuilds"]
    patches = r.stats()["patches"]
    r.add_route("b/#")
    assert sorted(r.match_filters(["b/z/q"])[0]) == ["b/#"]
    assert r.stats()["rebuilds"] == base  # patched, not re-flattened
    assert r.stats()["patches"] > patches
    r.delete_route("a/+")
    assert r.match_filters(["a/x"])[0] == []
    assert r.stats()["rebuilds"] == base


def test_router_sharded_churn_parity_vs_oracle():
    """Sustained mesh churn (inserts + deletes across many shards)
    keeps exact oracle parity through the per-shard patch path."""
    import random

    from emqx_tpu.oracle import TrieOracle
    from emqx_tpu.parallel.mesh import default_mesh
    from emqx_tpu.router import MatcherConfig, Router

    rng = random.Random(7)
    words = ["a", "b", "c", "d", "e"]
    r = Router(MatcherConfig(mesh=default_mesh(8)), node="n1")
    oracle = TrieOracle()
    live = set()
    while len(live) < 40:
        depth = rng.randint(1, 4)
        ws = [rng.choice(words + ["+"]) for _ in range(depth)]
        f = "/".join(ws)
        if f not in live:
            live.add(f)
            r.add_route(f)
            oracle.insert(f)
    r.match_filters(["a/b"])  # initial flatten
    base = r.stats()["rebuilds"]
    for step in range(30):
        if rng.random() < 0.5 and live:
            f = rng.choice(sorted(live))
            live.discard(f)
            r.delete_route(f)
            oracle.delete(f)
        else:
            f = "/".join(rng.choice(words + ["+"])
                         for _ in range(rng.randint(1, 4)))
            if f not in live:
                live.add(f)
                r.add_route(f)
                oracle.insert(f)
        if step % 5 == 4:
            topics = ["/".join(rng.choice(words)
                               for _ in range(rng.randint(1, 4)))
                      for _ in range(16)]
            got = r.match_filters(topics)
            for t, g in zip(topics, got):
                assert sorted(g) == sorted(oracle.match(t)), (step, t)
    assert r.stats()["rebuilds"] == base  # zero re-flattens at churn


def test_broker_on_mesh_end_to_end():
    """Full product stack on the mesh: Broker.publish fans out via
    the sharded match + the real FanoutManager tables."""
    from emqx_tpu.broker import Broker
    from emqx_tpu.parallel.mesh import default_mesh
    from emqx_tpu.router import MatcherConfig, Router
    from emqx_tpu.types import Message

    class Rec:
        def __init__(self):
            self.got = []

        def deliver(self, topic, msg):
            self.got.append((topic, msg.payload))

    mesh = default_mesh(8)
    b = Broker(router=Router(MatcherConfig(mesh=mesh), node="local"))
    subs = [Rec() for _ in range(12)]
    for i, s in enumerate(subs):
        b.subscribe(s, f"room/{i}/+")
    everyone = Rec()
    b.subscribe(everyone, "room/#")
    n = b.publish(Message(topic="room/3/temp", payload=b"hot"))
    assert n == 2  # room/3/+ and room/#
    assert subs[3].got == [("room/3/+", b"hot")]
    assert all(not s.got for j, s in enumerate(subs) if j != 3)
    assert everyone.got == [("room/#", b"hot")]


def test_mesh_use_device_false_is_honored():
    """MatcherConfig(mesh=..., use_device=False) must stay on the
    host trie walk — the debugging escape hatch wins over the mesh."""
    from emqx_tpu.parallel.mesh import default_mesh
    from emqx_tpu.router import MatcherConfig, Router

    r = Router(MatcherConfig(mesh=default_mesh(8), use_device=False),
               node="n1")
    r.add_route("esc/+")
    assert not r.use_device_now()
    assert r.match_filters(["esc/x"]) == [["esc/+"]]
    assert r.stats()["rebuilds"] == 0  # never flattened for a device


def test_distributed_init_single_process_noop():
    from emqx_tpu.parallel import distributed

    assert distributed.initialize() is False
    assert distributed.initialize(num_processes=1, process_id=0) is False
    import pytest
    with pytest.raises(ValueError):
        distributed.initialize(num_processes=2, process_id=0)


def test_distributed_global_mesh_factors():
    from emqx_tpu.parallel import distributed

    m = distributed.global_mesh()          # 8 virtual CPU devices
    assert m.shape["data"] * m.shape["trie"] == 8
    m2 = distributed.global_mesh(n_trie=4)
    assert m2.shape == {"data": 2, "trie": 4}
    m3 = distributed.global_mesh(n_data=8)
    assert m3.shape == {"data": 8, "trie": 1}


def test_broker_on_mesh_fanout_parity_with_big_filter():
    """Mesh broker delivers through the device per-shard gather with
    exact parity vs host expectations — including a filter whose
    membership exceeds the d bound (excluded from the gather,
    delivered via the host tail from sh_big)."""
    import random

    from emqx_tpu.broker import Broker
    from emqx_tpu.parallel.mesh import default_mesh
    from emqx_tpu.router import MatcherConfig, Router
    from emqx_tpu.types import Message

    class Rec:
        def __init__(self, i):
            self.i = i
            self.got = []

        def deliver(self, topic, msg):
            self.got.append((topic, msg.topic))

    rng = random.Random(11)
    mesh = default_mesh(8)
    b = Broker(router=Router(
        MatcherConfig(mesh=mesh, fanout_d=16), node="local"))
    subs = [Rec(i) for i in range(40)]
    words = ["u", "v", "w"]
    filters = set()
    while len(filters) < 25:
        depth = rng.randint(1, 3)
        ws = [rng.choice(words + ["+"]) for _ in range(depth)]
        if rng.random() < 0.2:
            ws[-1] = "#"
        filters.add("/".join(ws))
    for f in sorted(filters):
        for s in rng.sample(subs, rng.randint(1, 4)):
            b.subscribe(s, f)
    # one BIG filter: 30 members > fanout_d=16 → host-tail delivery
    for s in subs[:30]:
        b.subscribe(s, "big/#")
    from emqx_tpu.oracle import TrieOracle
    oracle = TrieOracle()
    for f in filters | {"big/#"}:
        oracle.insert(f)
    topics = ["/".join(rng.choice(words)
                       for _ in range(rng.randint(1, 3)))
              for _ in range(30)] + ["big/x", "big/y/z"]
    for t in topics:
        for s in subs:
            s.got.clear()
        n = b.publish(Message(topic=t, payload=b"p"))
        matched = oracle.match(t)
        exp_n = 0
        for f in matched:
            for s in subs:
                if f in b.subscriptions(s):
                    exp_n += 1
        assert n == exp_n, (t, n, exp_n)
        for s in subs:
            got_filters = sorted(f for f, _ in s.got)
            exp_filters = sorted(f for f in matched
                                 if f in b.subscriptions(s))
            assert got_filters == exp_filters, (t, s.i)


def test_mesh_fan_overflow_boosts_d_not_k():
    """A fan-only overflow (per-topic deliveries past the d bound,
    match within k) must grow the learned d — never k, whose
    recompile could not reduce fan-out overflow."""
    from emqx_tpu.broker import Broker
    from emqx_tpu.parallel.mesh import make_mesh
    from emqx_tpu.router import MatcherConfig, Router
    from emqx_tpu.types import Message

    class S:
        def deliver(self, flt, msg):
            pass

    mesh = make_mesh(8, 1)  # one trie shard: all fan rows sum per topic
    b = Broker(router=Router(
        MatcherConfig(mesh=mesh, fanout_d=2), node="local"))
    for f in ("m/+", "m/#", "m/a"):
        b.subscribe(S(), f)
    k0 = b.router.effective_k()
    assert b.router.effective_d() == 2
    # 3 deliveries > d=2 -> fan overflow, host fallback, d boost
    assert b.publish(Message(topic="m/a")) == 3
    assert b.router.effective_d() > 2
    assert b.router.effective_k() == k0  # k untouched
    # the grown d fits the workload: delivered via the device gather
    assert b.publish(Message(topic="m/a")) == 3


def test_sharded_shared_pick_parity():
    """shared_pick_step picks seed % group_size from each matched
    group's member row — exact host parity across shard layouts."""
    from emqx_tpu.parallel.mesh import make_mesh
    from emqx_tpu.parallel.sharded import (build_sharded,
                                           build_sharded_fanout,
                                           place_batch, place_sharded,
                                           shard_filters, shard_of,
                                           shared_pick_step)

    rng = random.Random(5)
    words = ["g1", "g2", "g3", "q"]
    filters = sorted({"/".join(rng.choice(words)
                               for _ in range(rng.randint(1, 3)))
                      for _ in range(30)})
    fids = {f: i for i, f in enumerate(filters)}
    table = WordTable()
    oracle = TrieOracle()
    for f in filters:
        oracle.insert(f)
        for w in f.split("/"):
            table.intern(w)
    from emqx_tpu.ops.match import walk_params

    for n_data, n_trie in [(4, 2), (2, 4)]:
        mesh = make_mesh(n_data, n_trie)
        shards = shard_filters(filters, n_trie)
        auto, parts = build_sharded(shards, fids, table,
                                    return_parts=True)
        wp = walk_params(parts[0], 8)
        members = {f: [fids[f] * 100 + j
                       for j in range(rng.randint(1, 5))]
                   for f in filters}
        rows = [{} for _ in range(n_trie)]
        for f in filters:
            rows[shard_of(f, n_trie)][fids[f]] = members[f]
        gfan = build_sharded_fanout(rows, len(filters))
        B = 8 * n_data
        topics = ["/".join(rng.choice(words)
                           for _ in range(rng.randint(1, 3)))
                  for _ in range(B)]
        seeds = np.arange(B, dtype=np.int32) * 7 + 3
        ids_np, n_np, sys_np = encode_batch(table, topics, 8)
        auto_d = place_sharded(mesh, auto)
        gfan_d = place_sharded(mesh, gfan)
        b = place_batch(mesh, ids_np, n_np, sys_np)
        seeds_d = jax.device_put(
            seeds, jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("data")))
        picks, mids, ovf = shared_pick_step(
            mesh, auto_d, gfan_d, *b, seeds_d, k=16, m=16, **wp)
        picks, mids = np.asarray(picks), np.asarray(mids)
        assert not np.asarray(ovf).any()
        for i, t in enumerate(topics):
            got = sorted(int(p) for p in picks[i] if p >= 0)
            expect = sorted(
                members[f][seeds[i] % len(members[f])]
                for f in oracle.match(t))
            assert got == expect, (t, got, expect)


def _pick_family(n_trie, mb, want_spread):
    """Find a topic family whose three matching filters (exact, +, #)
    spread over >1 trie shard with ≤ mb per shard (want_spread=True),
    or all collide in ONE shard with count > mb (False)."""
    from emqx_tpu.parallel.sharded import shard_of

    for i in range(1000):
        fam = f"w{i}"
        filters = [f"{fam}/x", f"{fam}/+", f"{fam}/#"]
        shards = [shard_of(f, n_trie) for f in filters]
        counts = [shards.count(t) for t in range(n_trie)]
        if want_spread:
            if max(counts) <= mb and len(set(shards)) > 1:
                return fam, filters
        else:
            if max(counts) > mb:
                return fam, [f for f, s in zip(filters, shards)
                             if s == max(range(n_trie),
                                         key=counts.__getitem__)]
    raise AssertionError("no suitable family found")


def test_sharded_bitmap_multi_big_union_across_shards():
    """Mesh bitmap path with big filters spread over BOTH trie
    shards: per-shard ORs combine over ICI into one union; the
    multi-big tail delivers each (filter, member) pair exactly."""
    from emqx_tpu.broker import Broker
    from emqx_tpu.parallel.mesh import make_mesh
    from emqx_tpu.router import MatcherConfig, Router
    from emqx_tpu.types import Message

    class S:
        def __init__(self, i):
            self.i = i
            self.got = []

        def deliver(self, flt, msg):
            self.got.append(flt)

    fam, filters = _pick_family(2, mb=2, want_spread=True)
    mesh = make_mesh(4, 2)
    b = Broker(router=Router(
        MatcherConfig(mesh=mesh, fanout_d=4, fanout_mb=2),
        node="local"))
    subs = [S(i) for i in range(30)]
    slices = [subs[:20], subs[5:25], subs[10:30]]
    big_members = dict(zip(filters, slices))
    for f, ms in big_members.items():
        for s in ms:
            b.subscribe(s, f)
    n = b.publish(Message(topic=f"{fam}/x"))
    assert n == 60  # per-subscription delivery: 20 per filter
    for i, s in enumerate(subs):
        exp = sorted(f for f, ms in big_members.items() if s in ms)
        assert sorted(s.got) == exp, (i, s.got, exp)
    assert b.metrics.val("messages.delivered") == 60
    # the device stat counts UNIQUE union members once (not once per
    # trie shard — regression: the OR-reduced union is replicated);
    # no truncation happened (≤ mb big rows per shard)
    st = b.router.drain_device_stats()
    assert st["overflows"] == 0, st
    assert st["deliveries"] == 30, st


def test_sharded_bitmap_mb_truncation_falls_back_exact():
    """More big matches than mb on ONE shard: bovf flags the row and
    the host loop delivers — exact despite the truncated union."""
    from emqx_tpu.broker import Broker
    from emqx_tpu.parallel.mesh import make_mesh
    from emqx_tpu.router import MatcherConfig, Router
    from emqx_tpu.types import Message

    class S:
        def __init__(self):
            self.got = []

        def deliver(self, flt, msg):
            self.got.append(flt)

    fam, colliding = _pick_family(2, mb=1, want_spread=False)
    assert len(colliding) >= 2
    mesh = make_mesh(4, 2)
    b = Broker(router=Router(
        MatcherConfig(mesh=mesh, fanout_d=2, fanout_mb=1),
        node="local"))
    subs = [S() for _ in range(8)]
    for f in colliding:
        for s in subs:
            b.subscribe(s, f)  # 8 > d=2: all big, same shard, > mb=1
    n = b.publish(Message(topic=f"{fam}/x"))
    assert n == 8 * len(colliding)
    for s in subs:
        assert sorted(s.got) == sorted(colliding)


def test_placed_batch_parity_with_inline_encode():
    """``encode_place_sharded`` + ``placed=`` produces the exact
    dispatch a plain ``publish_dispatch_sharded(topics, ...)`` call
    does — the pre-placed host half (used by the pipelined bench and
    any ingress that overlaps encode with in-flight device steps)
    must not change semantics."""
    import random

    import numpy as np

    from emqx_tpu.broker_helper import ShardedFanoutState
    from emqx_tpu.parallel.mesh import default_mesh
    from emqx_tpu.parallel.sharded import (build_sharded_fanout,
                                           place_sharded, shard_of)
    from emqx_tpu.router import MatcherConfig, Router

    rng = random.Random(7)
    mesh = default_mesh(4)
    n_trie = mesh.shape["trie"]
    filters = [f"a/{i}/+" for i in range(100)] + ["a/#"]
    r = Router(MatcherConfig(mesh=mesh, fanout_d=8))
    for f in filters:
        r.add_route(f)
    topics = [f"a/{rng.randrange(100)}/x" for _ in range(32)]
    r.match_ids(topics)  # flatten
    rows = [{} for _ in range(n_trie)]
    for f in filters:
        fid = r.filter_id(f)
        rows[shard_of(f, n_trie)][fid] = [fid]
    fan = place_sharded(mesh, build_sharded_fanout(
        rows, len(r._id_to_filter)))
    st = ShardedFanoutState(0, 0, fan, None, frozenset(), 8)
    provider = lambda epoch, id_map: st  # noqa: E731

    plain = r.publish_dispatch_sharded(topics, provider)
    placed = r.publish_dispatch_sharded(
        topics, provider, placed=r.encode_place_sharded(topics))
    for i in (0, 1, 2, 4):  # ids, subs, src, ovf
        a, b = np.asarray(plain[i]), np.asarray(placed[i])
        assert a.shape == b.shape and (a == b).all(), i


def test_placed_batch_stale_after_route_add_reencodes():
    """A pre-placed batch encoded BEFORE a route add must not miss
    the new filter: publish_dispatch_sharded detects the stale
    mutation revision and re-encodes from the original topics (a
    filter added after encode can intern words the old encoding
    mapped to the unknown sentinel)."""
    import numpy as np

    from emqx_tpu.broker_helper import ShardedFanoutState
    from emqx_tpu.parallel.mesh import default_mesh
    from emqx_tpu.parallel.sharded import (build_sharded_fanout,
                                           place_sharded, shard_of)
    from emqx_tpu.router import MatcherConfig, Router

    mesh = default_mesh(4)
    n_trie = mesh.shape["trie"]
    r = Router(MatcherConfig(mesh=mesh, fanout_d=8))
    r.add_route("a/+")
    topics = ["a/x", "brandnew/word"]
    r.match_ids(topics)  # flatten
    pl = r.encode_place_sharded(topics)
    # mutation AFTER encode: interns words the encoding never saw
    r.add_route("brandnew/word")
    rows = [{} for _ in range(n_trie)]
    for f in ("a/+", "brandnew/word"):
        fid = r.filter_id(f)
        rows[shard_of(f, n_trie)][fid] = [fid]
    fan = place_sharded(mesh, build_sharded_fanout(
        rows, len(r._id_to_filter)))
    st = ShardedFanoutState(0, 0, fan, None, frozenset(), 8)
    out = r.publish_dispatch_sharded(
        topics, lambda e, m: st, placed=pl)
    ids = np.asarray(out[0])[:2]
    id_map = out[6]
    matched = [sorted(id_map[i] for i in row if i >= 0
                      and id_map[i] is not None) for row in ids]
    assert matched[0] == ["a/+"]
    assert matched[1] == ["brandnew/word"], matched


def test_finalize_parts_demotes_all_shards_on_wide_guard():
    """ADVICE r5: a shard whose trie trips compress_automaton's
    wide-mode fallback guard (depth > 31) stays narrow even under
    force_mode="wide"; finalize_parts must then demote EVERY shard to
    narrow instead of stacking mismatched row widths."""
    from emqx_tpu.ops.csr import build_automaton
    from emqx_tpu.parallel.sharded import finalize_parts

    table = WordTable()

    def raw(filters):
        trie = TrieOracle()
        fids = {}
        for f in filters:
            trie.insert(f)
            fids[f] = len(fids)
            for w in f.split("/"):
                table.intern(w)
        return build_automaton(trie, fids, table, skip_hash=True)

    # shard 0: a long literal chain below depth 32 -> wants wide
    deep_ok = "/".join(f"w{i}" for i in range(10))
    # shard 1: depth 33 -> the guard forces narrow regardless
    too_deep = "/".join(f"v{i}" for i in range(33))
    parts = finalize_parts([raw([deep_ok]), raw([too_deep])])
    assert len({p.wt_slots for p in parts}) == 1
    assert all(p.wt_take == 1 for p in parts)  # demoted to narrow
