"""Multi-chip publish step on the virtual 8-device CPU mesh:
parity of the sharded match vs the host oracle, and mesh-summed stats."""

import random

import jax
import numpy as np
import pytest

from emqx_tpu.oracle import TrieOracle
from emqx_tpu.ops.tokenize import WordTable, encode_batch
from emqx_tpu.parallel.mesh import make_mesh
from emqx_tpu.parallel.sharded import (
    build_sharded, build_sharded_fanout, place_batch, place_sharded,
    publish_step, shard_filters)


def _rand_filters(rng, n):
    words = ["a", "b", "c", "d", "e", "s1", "s2"]
    out = set()
    while len(out) < n:
        depth = rng.randint(1, 5)
        ws = []
        for i in range(depth):
            r = rng.random()
            if r < 0.2:
                ws.append("+")
            elif r < 0.3 and i == depth - 1:
                ws.append("#")
            else:
                ws.append(rng.choice(words))
        out.add("/".join(ws))
    return sorted(out)


@pytest.mark.parametrize("n_data,n_trie", [(4, 2), (2, 4), (8, 1)])
def test_sharded_match_parity(n_data, n_trie):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rng = random.Random(0)
    filters = _rand_filters(rng, 120)
    fids = {f: i for i, f in enumerate(filters)}
    table = WordTable()
    for f in filters:
        for w in f.split("/"):
            table.intern(w)
    oracle = TrieOracle()
    for f in filters:
        oracle.insert(f)

    mesh = make_mesh(n_data, n_trie)
    shards = shard_filters(filters, n_trie)
    auto = build_sharded(shards, fids, table)
    rows = [{fids[f]: [fids[f] * 10, fids[f] * 10 + 1] for f in shard}
            for shard in shards]
    fan = build_sharded_fanout(rows, len(filters))

    words = ["a", "b", "c", "d", "e", "s1", "s2", "zz"]
    B = 8 * n_data
    topics = ["/".join(rng.choice(words) for _ in range(rng.randint(1, 5)))
              for _ in range(B)]
    ids_np, n_np, sys_np = encode_batch(table, topics, 8)

    auto_d = place_sharded(mesh, auto)
    fan_d = place_sharded(mesh, fan)
    b = place_batch(mesh, ids_np, n_np, sys_np)

    ids, subs, stats = publish_step(
        mesh, auto_d, fan_d, *b, k=32, m=32, d=64)
    ids = np.asarray(ids)
    subs = np.asarray(subs)
    inv = {v: k for k, v in fids.items()}
    total_matches = 0
    total_deliv = 0
    for i, t in enumerate(topics):
        got = sorted(inv[j] for j in ids[i] if j >= 0)
        expect = sorted(oracle.match(t))
        assert got == expect, (t, got, expect)
        total_matches += len(expect)
        exp_subs = sorted(x for f in expect for x in rows_lookup(rows, fids[f]))
        assert sorted(x for x in subs[i] if x >= 0) == exp_subs
        total_deliv += len(exp_subs)
    assert int(stats["matches"]) == total_matches
    assert int(stats["deliveries"]) == total_deliv
    assert int(stats["overflows"]) == 0


def rows_lookup(rows, fid):
    for shard_rows in rows:
        if fid in shard_rows:
            return shard_rows[fid]
    return []
