"""Publish-quota and msgs-in limiter wiring (VERDICT r3 item 4).

The reference's PUBLISH pipeline opens with check_quota_exceeded and
draws the quota down after each publish (src/emqx_channel.erl:458,
545-558, 1304-1310); its connection loop pauses the socket when the
conn_messages_in checker trips (src/emqx_connection.erl:633-645).
These tests pin the zone knobs `quota_conn_messages` and
`ratelimit_msg_in` to observable behavior: reason-coded acks, dropped
QoS0, and measurable wire backpressure.
"""

import time


from emqx_tpu.broker import Broker
from emqx_tpu.channel import Channel
from emqx_tpu.cm import ConnectionManager
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import reason_codes as RC
from emqx_tpu.mqtt.packet import Connack, Connect, PubAck, Publish
from emqx_tpu.zone import Zone
from tests.helpers import broker_node, node_port
from tests.mqtt_client import TestClient


def _connected_channel(zone, client_id="quota-c", version=C.MQTT_V5):
    broker = Broker()
    cm = ConnectionManager(broker=broker)
    chan = Channel(broker, cm, zone=zone)
    out = chan.handle_in(Connect(
        proto_ver=version, proto_name=C.PROTOCOL_NAMES[version],
        client_id=client_id, clean_start=True))
    assert isinstance(out[0], Connack) and out[0].reason_code == 0
    return broker, chan


def _pub(chan, pid, qos=1, topic="q/t"):
    return chan.handle_in(Publish(topic=topic, qos=qos,
                                  packet_id=pid if qos else None,
                                  payload=b"x"))


def test_quota_exceeded_qos1_puback_rc():
    # burst 3, slow refill: publishes 1-4 pass (the 4th drives the
    # bucket negative and starts the pause), the 5th is refused with
    # QUOTA_EXCEEDED on its PUBACK (v5)
    zone = Zone(name="q1", quota_conn_messages=(1.0, 3.0))
    _, chan = _connected_channel(zone)
    rcs = []
    for pid in range(1, 6):
        out = _pub(chan, pid)
        assert len(out) == 1 and isinstance(out[0], PubAck)
        rcs.append(out[0].reason_code)
    assert all(rc in (RC.SUCCESS, RC.NO_MATCHING_SUBSCRIBERS)
               for rc in rcs[:4]), rcs
    assert rcs[4] == RC.QUOTA_EXCEEDED, rcs


def test_quota_exceeded_qos2_pubrec_rc():
    # burst 1: the 2nd publish drives the bucket negative (it still
    # passes — the reference's ensure_quota draws AFTER publishing),
    # the 3rd is refused on its PUBREC
    zone = Zone(name="q2", quota_conn_messages=(1.0, 1.0))
    _, chan = _connected_channel(zone)
    for pid in (1, 2):
        out = _pub(chan, pid, qos=2)
        assert out[0].type == C.PUBREC
        assert out[0].reason_code in (RC.SUCCESS,
                                      RC.NO_MATCHING_SUBSCRIBERS)
    out = _pub(chan, 3, qos=2)
    assert out[0].type == C.PUBREC
    assert out[0].reason_code == RC.QUOTA_EXCEEDED


def test_quota_exceeded_qos0_dropped_silently():
    zone = Zone(name="q0", quota_conn_messages=(1.0, 1.0))
    broker, chan = _connected_channel(zone)
    inbox = []

    class Sub:
        client_id = "watcher"

        def deliver(self, topic, msg):
            inbox.append(msg.topic)

    broker.subscribe(Sub(), "q/t")
    assert _pub(chan, None, qos=0) == []   # 1st passes (and delivers)
    assert _pub(chan, None, qos=0) == []   # 2nd dropped by quota
    assert inbox == ["q/t"]
    assert broker.metrics.val("packets.publish.dropped") == 1


def test_quota_refills_after_pause():
    # fast refill: the pause is ~1/200s, after which publishes pass
    zone = Zone(name="qr", quota_conn_messages=(200.0, 1.0))
    _, chan = _connected_channel(zone)
    assert _pub(chan, 1)[0].reason_code != RC.QUOTA_EXCEEDED
    _pub(chan, 2)  # bucket goes negative here
    assert _pub(chan, 3)[0].reason_code == RC.QUOTA_EXCEEDED
    time.sleep(0.05)
    assert _pub(chan, 4)[0].reason_code != RC.QUOTA_EXCEEDED


def test_quota_counts_routed_deliveries():
    # each routed delivery costs one extra token: with 3 subscribers
    # a single publish (1+3 tokens) empties a burst-4 bucket
    zone = Zone(name="qd", quota_conn_messages=(0.5, 4.0))
    broker, chan = _connected_channel(zone)

    class Sub:
        def __init__(self, i):
            self.client_id = f"s{i}"

        def deliver(self, topic, msg):
            pass

    for i in range(3):
        broker.subscribe(Sub(i), "q/t")
    assert _pub(chan, 1)[0].reason_code == RC.SUCCESS  # 4 tokens -> 0
    assert _pub(chan, 2)[0].reason_code == RC.SUCCESS  # -> -4, pause
    # 3 publishes at 1 token each would not have emptied a burst-4
    # bucket: refusal here proves routed deliveries are counted
    assert _pub(chan, 3)[0].reason_code == RC.QUOTA_EXCEEDED


def test_v4_quota_ack_has_no_reason_code():
    # v3.1.1 has no reason codes: the refused publish still gets its
    # PUBACK (the reference's handle_out compat path), rc byte 0
    zone = Zone(name="q4", quota_conn_messages=(1.0, 1.0))
    _, chan = _connected_channel(zone, version=C.MQTT_V4)
    _pub(chan, 1)
    _pub(chan, 2)  # drives the bucket negative
    out = _pub(chan, 3)
    assert isinstance(out[0], PubAck) and out[0].reason_code == 0


async def test_msgs_in_limiter_paces_the_wire():
    # burst 2 @ 20 msg/s: 8 sequential QoS1 publishes must take at
    # least ~(8-2)/20 = 0.3s; without the limiter they take ~ms.
    zone = Zone(name="ml", ratelimit_msg_in=(20.0, 2.0))
    async with broker_node(zone=zone, batch_ingress=False) as node:
        cli = TestClient("paced")
        await cli.connect(port=node_port(node))
        t0 = time.monotonic()
        for _ in range(8):
            await cli.publish("pace/t", b"x", qos=1)
        elapsed = time.monotonic() - t0
        await cli.close()
        assert elapsed >= 0.25, elapsed


async def test_throttled_client_survives_short_keepalive():
    # a limiter pause longer than the keepalive window must NOT get
    # the client killed: while the read loop is paused the client is
    # unobservable, not dead (code-review r4 finding — the reference's
    # `blocked` sockstate defers idle shutdown the same way)
    zone = Zone(name="mlka", ratelimit_msg_in=(2.0, 1.0))
    async with broker_node(zone=zone, batch_ingress=False) as node:
        cli = TestClient("throttled", keepalive=1)
        await cli.connect(port=node_port(node))
        t0 = time.monotonic()
        # 5 publishes at burst 1 / 2 msg/s: ~2s of pause, spanning
        # several 1s-keepalive check windows
        for _ in range(5):
            await cli.publish("ka/t", b"x", qos=1)
        assert time.monotonic() - t0 >= 1.2
        await cli.ping()  # still connected
        await cli.close()


async def test_no_msgs_in_limiter_is_fast():
    async with broker_node(batch_ingress=False) as node:
        cli = TestClient("unpaced")
        await cli.connect(port=node_port(node))
        t0 = time.monotonic()
        for _ in range(8):
            await cli.publish("pace/t", b"x", qos=1)
        elapsed = time.monotonic() - t0
        await cli.close()
        assert elapsed < 1.0, elapsed
