"""Shared live-broker fixtures for the integration-tier suites."""

import contextlib

from emqx_tpu.node import Node


@contextlib.asynccontextmanager
async def broker_node(**kw):
    n = Node(**kw)
    n.add_listener(port=0)  # ephemeral port
    await n.start()
    try:
        yield n
    finally:
        await n.stop()


def node_port(node):
    return node.listeners[0].port
