"""Config-file layer: TOML → zones/listeners/node (the reference's
etc/emqx.conf + cuttlefish pipeline, src/emqx_zone.erl:89-95)."""

import asyncio

import pytest

from emqx_tpu.config import (ConfigError, boot_from_file, build_node,
                             load_config, parse_config)
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.packet import Connack

from mqtt_client import TestClient


def _write(tmp_path, text):
    p = tmp_path / "emqx_tpu.toml"
    p.write_text(text)
    return str(p)


def test_parse_zones_listeners(tmp_path):
    cfg = load_config(_write(tmp_path, """
[node]
name = "n1@local"
sys_interval = 7.5

[zones.default]
max_packet_size = 2048
idle_timeout = 3.0

[zones.edge]
max_inflight = 4
ratelimit_bytes_in = [1000, 2000]

[[listeners]]
type = "tcp"
port = 0
zone = "edge"

[[listeners]]
type = "ws"
port = 0
path = "/mq"
"""))
    assert cfg.name == "n1@local"
    assert cfg.sys_interval == 7.5
    assert cfg.zones["default"].max_packet_size == 2048
    assert cfg.zones["edge"].max_inflight == 4
    assert cfg.zones["edge"].ratelimit_bytes_in == (1000, 2000)
    assert [l.type for l in cfg.listeners] == ["tcp", "ws"]
    assert cfg.listeners[0].zone == "edge"
    assert cfg.listeners[1].path == "/mq"


def test_unknown_keys_rejected():
    with pytest.raises(ConfigError, match="zones.default.max_paket"):
        parse_config({"zones": {"default": {"max_paket_size": 1}}})
    with pytest.raises(ConfigError, match="node.naem"):
        parse_config({"node": {"naem": "x"}})
    with pytest.raises(ConfigError, match="type"):
        parse_config({"listeners": [{"type": "udp", "port": 1}]})
    with pytest.raises(ConfigError, match="certfile"):
        parse_config({"listeners": [{"type": "ssl", "port": 1}]})
    with pytest.raises(ConfigError, match="listeners\\[0\\].prot"):
        parse_config({"listeners": [{"type": "tcp", "port": 1,
                                     "prot": 2}]})


def test_example_config_parses():
    cfg = load_config("etc/emqx_tpu.toml")
    assert cfg.zones["external"].max_packet_size == 65536
    assert len(cfg.listeners) == 2


def test_boot_node_from_file(tmp_path):
    """Integration: node boots from a config file; the listener's
    zone settings bite (max_packet_size rejects an oversized
    publish); a TLS listener comes up from file settings."""
    # cert generation needs the optional cryptography package; only
    # this test skips without it — the rest of the config suite runs
    from certs import generate_cert_chain

    certs = generate_cert_chain(str(tmp_path))
    path = _write(tmp_path, f"""
[node]
name = "cfg@test"

[zones.default]
max_packet_size = 512

[zones.tiny]
max_packet_size = 128

[[listeners]]
type = "tcp"
port = 0
zone = "tiny"

[[listeners]]
type = "ssl"
port = 0
certfile = "{certs['cert']}"
keyfile = "{certs['key']}"
""")

    async def main():
        node = boot_from_file(path)
        assert node.name == "cfg@test"
        await node.start()
        try:
            tcp, tls = node.listeners
            assert tcp.zone.name == "tiny"
            c = TestClient("cfg-c1", version=C.MQTT_V4)
            ack = await c.connect(port=tcp.port)
            assert isinstance(ack, Connack) and ack.reason_code == 0
            await c.subscribe("t/1")
            # an oversized publish violates the zone cap: the broker
            # drops the connection (frame_too_large)
            import contextlib
            with contextlib.suppress(ConnectionError, asyncio.TimeoutError):
                await c.publish("t/1", b"x" * 4096, qos=1, timeout=2.0)
            await asyncio.sleep(0.2)
            # small publish from a fresh client on the TLS listener
            from emqx_tpu.tls import make_client_context
            ctx = make_client_context(cacertfile=certs["cacert"])
            s = TestClient("cfg-tls")
            await s.connect(port=tls.port, ssl=ctx)
            await s.subscribe("t/2")
            await s.publish("t/2", b"ok", qos=0)
            msg = await asyncio.wait_for(s.inbox.get(), 5)
            assert msg.payload == b"ok"
            await s.disconnect()
        finally:
            await node.stop()

    asyncio.get_event_loop_policy().new_event_loop() \
        .run_until_complete(main())


def test_listener_zone_typo_rejected():
    with pytest.raises(ConfigError, match="exernal"):
        parse_config({
            "zones": {"external": {"idle_timeout": 1.0}},
            "listeners": [{"type": "tcp", "port": 1, "zone": "exernal"}],
        })


def test_tls_keys_on_plain_listener_rejected():
    with pytest.raises(ConfigError, match="ssl"):
        parse_config({"listeners": [
            {"type": "tcp", "port": 1, "certfile": "x.pem"}]})


def test_cluster_from_config(tmp_path):
    """Two nodes booted purely from TOML files cluster over the
    configured socket transport."""
    def write(name, fname):
        p = tmp_path / fname
        p.write_text(f"""
[node]
name = "{name}"
cookie = "toml-cookie"
cluster_port = 0

[[listeners]]
type = "tcp"
port = 0
""")
        return str(p)

    async def main():
        n1 = boot_from_file(write("cfg1@local", "a.toml"))
        n2 = boot_from_file(write("cfg2@local", "b.toml"))
        await n1.start()
        await n2.start()
        try:
            assert n1.cluster is not None and n2.cluster is not None
            port2 = n2.cluster.transport.port
            n1.cluster.join_remote("127.0.0.1", port2)
            assert sorted(n1.cluster.members) == \
                ["cfg1@local", "cfg2@local"]
            assert sorted(n2.cluster.members) == \
                ["cfg1@local", "cfg2@local"]

            class Rec:
                def __init__(self):
                    self.got = asyncio.Queue()

                def deliver(self, topic, msg):
                    self.got.put_nowait(msg.payload)

            from emqx_tpu.types import Message
            r = Rec()
            n2.broker.subscribe(r, "cfg/+")
            # route_add replication is an async cast: poll for it
            deadline = asyncio.get_running_loop().time() + 20
            while not n1.router.has_dest("cfg/+", "cfg2@local"):
                assert asyncio.get_running_loop().time() < deadline, \
                    "route never replicated"
                await asyncio.sleep(0.2)
            n1.broker.publish(Message(topic="cfg/x", payload=b"via-toml"))
            got = await asyncio.wait_for(r.got.get(), 20)
            assert got == b"via-toml"
        finally:
            await n1.stop()
            await n2.stop()

    asyncio.get_event_loop_policy().new_event_loop() \
        .run_until_complete(main())


def test_ctl_cluster_commands(tmp_path):
    """emqx_ctl-style cluster join/status/leave through the CLI
    against two config-booted nodes."""
    def write(name, fname):
        p = tmp_path / fname
        p.write_text(f"""
[node]
name = "{name}"
cookie = "ctl-c"
cluster_port = 0

[[listeners]]
type = "tcp"
port = 0
""")
        return str(p)

    async def main():
        n1 = boot_from_file(write("ctl1@x", "a.toml"))
        n2 = boot_from_file(write("ctl2@x", "b.toml"))
        await n1.start()
        await n2.start()
        try:
            out = n1.ctl.run(["cluster", "status"])
            assert '"ctl1@x"' in out
            port2 = n2.cluster.transport.port
            out = n1.ctl.run(["cluster", "join", f"127.0.0.1:{port2}"])
            # on a running loop the join goes to a worker thread so
            # the serving loop never blocks on the network
            assert "background" in out
            deadline = asyncio.get_running_loop().time() + 20
            while sorted(n2.cluster.members) != ["ctl1@x", "ctl2@x"]:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.2)
            out = n1.ctl.run(["cluster", "leave"])
            assert "left" in out
            assert n1.cluster.members == ["ctl1@x"]
            deadline = asyncio.get_running_loop().time() + 10
            while "ctl1@x" in n2.cluster.members:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.2)
        finally:
            await n1.stop()
            await n2.stop()

    asyncio.get_event_loop_policy().new_event_loop() \
        .run_until_complete(main())


def test_modules_section_loads_and_validates():
    from emqx_tpu.config import ConfigError, build_node, parse_config

    cfg = parse_config({"modules": {
        "retainer": {"max_retained": 7},
        "delayed": {},
    }})
    n = build_node(cfg)
    assert sorted(n.modules.loaded()) == ["delayed", "retainer"]
    assert n.broker.delayed is n.modules._loaded["delayed"]
    assert n.modules._loaded["retainer"].max_retained == 7
    import pytest
    with pytest.raises(ConfigError):
        parse_config({"modules": {"no_such_module": {}}})
    with pytest.raises(ConfigError):
        parse_config({"modules": {"retainer": 3}})


def test_example_config_file_boots_modules(tmp_path):
    from emqx_tpu.config import boot_from_file

    node = boot_from_file("etc/emqx_tpu.toml")
    assert "retainer" in node.modules.loaded()
    assert "delayed" in node.modules.loaded()


async def test_python_m_emqx_tpu_boot_and_sigterm(tmp_path):
    """`python -m emqx_tpu` boots a real broker process from a config
    file, serves MQTT, and shuts down cleanly on SIGTERM."""
    import asyncio
    import os
    import signal
    import sys

    cfg = tmp_path / "n.toml"
    cfg.write_text(
        '[node]\nname = "main-test@127.0.0.1"\n\n'
        '[[listeners]]\ntype = "tcp"\nport = 0\n')
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "emqx_tpu", "--config", str(cfg),
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT, env=env, cwd="/root/repo")
    try:
        port = None
        while port is None:
            line = await asyncio.wait_for(proc.stdout.readline(), 60)
            assert line, "process exited before listening"
            if b"listening:" in line:
                port = int(line.rsplit(b":", 1)[1])
        from tests.mqtt_client import TestClient
        c = TestClient("m-boot")
        await c.connect(port=port)
        await c.subscribe("m/t")
        await c.publish("m/t", b"via-module", qos=1)
        m = await c.recv(10)
        assert m.payload == b"via-module"
        c.writer.close()
        proc.send_signal(signal.SIGTERM)
        rc = await asyncio.wait_for(proc.wait(), 20)
        assert rc == 0
    finally:
        if proc.returncode is None:
            proc.kill()
            await proc.wait()


async def test_runtime_zone_reload_rebinds_listeners(tmp_path):
    """`ctl reload <file>` republishes zones AND rebinds running
    listeners: connections accepted after the reload get the new
    limits; existing connections keep their snapshot (the reference's
    emqx_zone:force_reload semantics)."""
    from emqx_tpu.config import build_node, load_config
    from emqx_tpu.zone import get_zone

    cfg = tmp_path / "z.toml"
    cfg.write_text(
        '[zones.hot]\nmax_packet_size = 1024\n\n'
        '[[listeners]]\ntype = "tcp"\nport = 0\nzone = "hot"\n')
    node = build_node(load_config(str(cfg)))
    await node.start()
    try:
        lst = node.listeners[0]
        assert lst.zone.max_packet_size == 1024
        from tests.mqtt_client import TestClient
        old_conn = TestClient("old")
        await old_conn.connect(port=lst.port)

        cfg.write_text(
            '[zones.hot]\nmax_packet_size = 2048\n\n'
            '[[listeners]]\ntype = "tcp"\nport = 0\nzone = "hot"\n')
        out = node.ctl.run(["reload", str(cfg)])
        assert "hot" in out and "rebound" in out
        assert lst.zone.max_packet_size == 2048
        assert get_zone("hot").max_packet_size == 2048
        # a NEW connection is built against the new zone
        new_conn = TestClient("new")
        await new_conn.connect(port=lst.port)
        assert new_conn.connack.reason_code == 0
        # the old connection kept its original snapshot
        assert old_conn.connack is not None
        # a broken file is rejected whole, zones untouched
        cfg.write_text('[zones.hot]\nno_such_setting = 1\n')
        out = node.ctl.run(["reload", str(cfg)])
        assert "error" in out.lower()
        assert get_zone("hot").max_packet_size == 2048
        # a zone removed from the file is reported stale
        cfg.write_text(
            '[zones.other]\nmax_inflight = 5\n\n'
            '[[listeners]]\ntype = "tcp"\nport = 0\nzone = "other"\n')
        out = node.ctl.run(["reload", str(cfg)])
        assert "stale" in out and "hot" in out
        old_conn.writer.close()
        new_conn.writer.close()
    finally:
        await node.stop()
