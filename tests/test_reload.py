"""Live config reload (docs/OPERATIONS.md, emqx_tpu/reload.py).

The acceptance properties: ``ctl reload <toml>`` applies a
reloadable-knob change without dropping a single connection; a
boot-only edit rejects the WHOLE reload (nothing applied, zones
included) with an explicit per-knob report; the zones-only output
shape of the legacy reload is preserved; and the reloadable/boot_only
classification covers every closed-schema knob and matches the
docs/OPERATIONS.md table.
"""

import dataclasses

from emqx_tpu.config import build_node, load_config
from emqx_tpu.node import Node
from emqx_tpu.reload import apply_reload, classification, diff_config

from tests.mqtt_client import TestClient


def _write(cfg_path, body: str) -> str:
    cfg_path.write_text(body)
    return str(cfg_path)


BASE = (
    '[zones.hot]\nmax_packet_size = 1024\n\n'
    '[[listeners]]\ntype = "tcp"\nport = 0\nzone = "hot"\n\n'
    '[overload]\nlag_warn_ms = 200.0\n\n'
    '[telemetry]\nslow_threshold_ms = 100.0\n'
)


async def test_reload_applies_reloadable_without_drop(tmp_path):
    """The headline property: a reloadable-knob change applies
    atomically while a connected client never notices — and the
    applied values reach the LIVE objects (monitor thresholds, the
    breaker, the ingress wait bound), not just the config dataclass."""
    p = _write(tmp_path / "n.toml", BASE)
    node = build_node(load_config(p))
    await node.start()
    try:
        c = TestClient("rl-live")
        await c.connect(port=node.listeners[0].port)
        _write(tmp_path / "n.toml", (
            '[zones.hot]\nmax_packet_size = 2048\n\n'
            '[[listeners]]\ntype = "tcp"\nport = 0\nzone = "hot"\n\n'
            '[overload]\nlag_warn_ms = 500.0\n'
            'breaker_failures = 7\nbreaker_cooldown_s = 9.0\n'
            'ingress_wait_timeout_s = 11.0\n\n'
            '[telemetry]\nslow_threshold_ms = 250.0\n\n'
            '[dispatch]\npreserialize = false\n\n'
            '[drain]\nwave_size = 5\n'
        ))
        out = node.ctl.run(["reload", p])
        assert "zones reloaded: hot" in out
        assert "rebound" in out
        assert "applied: overload.lag_warn_ms 200.0 -> 500.0" in out
        # the values landed in the RUNNING objects
        assert node.overload.cfg.lag_warn_ms == 500.0
        assert node.broker.breaker.threshold == 7
        assert node.broker.breaker.cooldown_s == 9.0
        assert node.ingress.submit_wait_timeout == 11.0
        assert node.telemetry.config.slow_threshold_ms == 250.0
        assert node.broker.dispatch_config.preserialize is False
        assert node.drain.cfg.wave_size == 5
        assert node.metrics.val("config.reload.applied") >= 5
        # the client never dropped: round-trips still work
        await c.ping()
        await c.publish("rl/t", b"x", qos=1)
        await c.close()
    finally:
        await node.stop()


async def test_reload_rejects_boot_only_atomic(tmp_path):
    """Any boot_only edit rejects the WHOLE reload with a per-knob
    report — nothing applies, zones included."""
    p = _write(tmp_path / "n.toml", BASE)
    node = build_node(load_config(p))
    await node.start()
    try:
        _write(tmp_path / "n.toml", (
            '[zones.hot]\nmax_packet_size = 4096\n\n'
            '[[listeners]]\ntype = "tcp"\nport = 0\nzone = "hot"\n\n'
            '[node]\nloops = 4\n\n'
            '[overload]\nlag_warn_ms = 900.0\n\n'
            '[matcher]\nmax_levels = 8\n'
        ))
        out = node.ctl.run(["reload", p])
        assert "reload rejected" in out
        assert "node.loops" in out and "matcher.max_levels" in out
        # NOTHING applied: zone, reloadable knob, all untouched
        from emqx_tpu.zone import get_zone
        assert get_zone("hot").max_packet_size == 1024
        assert node.overload.cfg.lag_warn_ms == 200.0
        assert node.router.config.max_levels == 16
        assert node.metrics.val("config.reload.rejected") >= 2
        assert node.metrics.val("config.reload.applied") == 0
    finally:
        await node.stop()


async def test_reload_inactive_sections_are_boot_only(tmp_path):
    """Enabling a subsystem that was never built (durability on a
    volatile node, cluster without a transport) is boot_only by
    definition; listener topology diffs are boot_only too."""
    p = _write(tmp_path / "n.toml", BASE)
    node = build_node(load_config(p))
    await node.start()
    try:
        _write(tmp_path / "n.toml", (
            '[zones.hot]\nmax_packet_size = 1024\n\n'
            '[[listeners]]\ntype = "tcp"\nport = 0\nzone = "hot"\n\n'
            '[[listeners]]\ntype = "tcp"\nport = 1884\nzone = "hot"\n\n'
            '[overload]\nlag_warn_ms = 200.0\n\n'
            '[telemetry]\nslow_threshold_ms = 100.0\n\n'
            '[durability]\nenabled = true\n'
        ))
        out = node.ctl.run(["reload", p])
        assert "reload rejected" in out
        assert "durability.enabled" in out
        assert "listeners.*" in out
        assert node.durability is None
    finally:
        await node.stop()


async def test_reload_absent_sections_untouched(tmp_path):
    """A section absent from the file means "not configured here" —
    the running values survive (never a reset-to-defaults)."""
    p = _write(tmp_path / "n.toml", BASE)
    node = build_node(load_config(p))
    await node.start()
    try:
        # file WITHOUT [overload]/[telemetry]: no diff for them
        _write(tmp_path / "n.toml", (
            '[zones.hot]\nmax_packet_size = 1024\n\n'
            '[[listeners]]\ntype = "tcp"\nport = 0\nzone = "hot"\n'
        ))
        out = node.ctl.run(["reload", p])
        assert "rejected" not in out
        assert node.overload.cfg.lag_warn_ms == 200.0
    finally:
        await node.stop()


async def test_reload_zone_only_output_shape(tmp_path):
    """The legacy zones-only reload keeps its exact output shape
    (zones reloaded / listeners rebound / stale), and a broken file
    still rejects whole with zones untouched."""
    p = _write(tmp_path / "n.toml", BASE)
    node = build_node(load_config(p))
    await node.start()
    try:
        _write(tmp_path / "n.toml", BASE.replace("1024", "2048"))
        out = node.ctl.run(["reload", p])
        assert out.startswith("zones reloaded: hot")
        assert "listeners rebound: tcp:0" in out
        # stale zone reporting preserved
        _write(tmp_path / "n.toml", (
            '[zones.other]\nmax_inflight = 5\n\n'
            '[[listeners]]\ntype = "tcp"\nport = 0\nzone = "other"\n'
        ))
        out = node.ctl.run(["reload", p])
        assert "stale" in out and "hot" in out
        # broken file: error text, nothing changes
        _write(tmp_path / "n.toml", '[zones.hot]\nno_such = 1\n')
        out = node.ctl.run(["reload", p])
        assert "error" in out.lower()
        # usage string describes the diff-based behavior now
        assert "diff" in node.ctl.usage()
    finally:
        await node.stop()


async def test_reload_matcher_delta_flip_applies(tmp_path):
    """matcher.delta is reloadable through Router.set_delta (the
    runtime flip PR 7 built) — the router actually changes mode."""
    p = _write(tmp_path / "n.toml", BASE)
    node = build_node(load_config(p))
    await node.start()
    try:
        assert node.router.config.delta
        _write(tmp_path / "n.toml",
               BASE + '\n[matcher]\ndelta = false\n')
        out = node.ctl.run(["reload", p])
        assert "applied: matcher.delta" in out
        assert not node.router.config.delta
        # the flip went through set_delta: no delta automaton is
        # published anymore
        assert node.router.delta_info().get("enabled") in (False,
                                                          None) \
            or not node.router.config.delta
    finally:
        await node.stop()


# -- classification integrity --------------------------------------------

def test_classification_covers_every_knob():
    """Every closed-schema dataclass field is classified, RELOADABLE
    names only real fields, and the [node] pseudo-section matches
    config.parse_config's key tuple."""
    table = classification()
    from emqx_tpu.reload import _sections
    for section, cls in _sections().items():
        fields = {f.name for f in dataclasses.fields(cls)} - {"mesh"}
        assert set(table[section]) == fields, section
        reloadable = getattr(cls, "RELOADABLE", frozenset())
        assert reloadable <= fields, (
            f"[{section}] RELOADABLE names unknown knobs: "
            f"{reloadable - fields}")
    assert set(table["node"]) == {
        "name", "sys_interval", "cookie", "cluster_port",
        "load_default_modules", "loops", "frame"}


def test_classification_matches_operations_doc():
    """The docs/OPERATIONS.md knob table is generated from
    classification() — regenerate and require every row verbatim
    (the lint-checked-docs satellite)."""
    doc = open("docs/OPERATIONS.md").read()
    for section, knobs in classification().items():
        r = ", ".join(f"`{k}`" for k, v in sorted(knobs.items())
                      if v == "reloadable") or "—"
        b = ", ".join(f"`{k}`" for k, v in sorted(knobs.items())
                      if v == "boot_only") or "—"
        row = f"| `[{section}]` | {r} | {b} |"
        assert row in doc, (
            f"docs/OPERATIONS.md knob table out of date for "
            f"[{section}]: expected row\n{row}")


def test_diff_config_programmatic_node(tmp_path):
    """diff_config works against a node never booted from a file
    (boot_config None): sections diff against live objects, listener
    topology silently skips (nothing to compare against)."""
    from emqx_tpu.config import parse_config
    node = Node(boot_listeners=False)
    cfg = parse_config({"overload": {"lag_warn_ms": 777.0},
                        "listeners": [{"type": "tcp", "port": 1883}]})
    changes = diff_config(node, cfg)
    knobs = {c.knob: c.kind for c in changes}
    assert knobs.get("overload.lag_warn_ms") == "reloadable"
    assert "listeners.*" not in knobs
    report = apply_reload(node, cfg)
    assert [a["knob"] for a in report["applied"]] \
        == ["overload.lag_warn_ms"]
    assert node.overload_config.lag_warn_ms == 777.0
