"""Partitioned match-cache epochs (ISSUE 4, docs/MATCH_CACHE.md
"Partitioned epochs"): invalidation-scope unit mapping, disjoint-
prefix churn keeping entries valid, conservative global bumps for
root wildcards / share prefixes, randomized interleaved churn parity
against the host oracle on both the single-chip and mesh paths, the
``cache_partitions = 1`` legacy whole-epoch A/B pin, and the new
observability surfaces (bump counters, gauges, `ctl cache`, the
fid-quarantine alarm)."""

import random

import numpy as np
import pytest

from emqx_tpu import topic as T
from emqx_tpu.broker import Broker
from emqx_tpu.oracle import TrieOracle
from emqx_tpu.router import (MatcherConfig, Router, filter_partitions,
                             topic_partition)
from emqx_tpu.types import Message


def _mk(**kw):
    kw.setdefault("device_min_filters", 0)
    return Router(MatcherConfig(**kw), node="node1")


class Q:
    def __init__(self, client_id="c"):
        self.client_id = client_id
        self.inbox = []

    def deliver(self, topic, msg):
        self.inbox.append((topic, msg))


def _oracle_for(filters):
    t = TrieOracle()
    for f in filters:
        t.insert(f)
    return t


def _assert_parity(r, oracle, topics):
    got = r.match_filters(topics)
    for t, row in zip(topics, got):
        assert sorted(row) == sorted(oracle.match(t)), t


# -- invalidation-scope unit ------------------------------------------------


def test_filter_partitions_mapping():
    P = 64
    # literal root: exactly its own partition, == the topic's
    assert filter_partitions("a/+/c", P) == (topic_partition("a/x/c", P),)
    assert filter_partitions("a/#", P) == filter_partitions("a/b", P)
    # empty first level ("/x") is a literal too
    assert filter_partitions("/x", P) == (topic_partition("/y/z", P),)
    # root wildcards can match any topic root: global only
    assert filter_partitions("+/x", P) is None
    assert filter_partitions("#", P) is None
    assert filter_partitions("+", P) is None
    # share prefixes partition on the post-prefix root AND the raw
    # '$share' root (covers a trie handed the string verbatim)
    ps = filter_partitions("$share/g/a/b", P)
    assert topic_partition("a/zz", P) in ps
    assert topic_partition("$share/anything", P) in ps
    pq = filter_partitions("$queue/a/b", P)
    assert topic_partition("a/zz", P) in pq
    # wildcard-rooted inner filter / malformed prefix: global
    assert filter_partitions("$share/g/+/b", P) is None
    assert filter_partitions("$share/nofilter", P) is None
    # partitions stay inside [0, P)
    for f in ("a/b", "$share/g/deep/x", "w0_1/w1_2"):
        for p in filter_partitions(f, P):
            assert 0 <= p < P


def test_disjoint_literal_churn_keeps_entries_valid():
    r = _mk(match_cache_slots=256, cache_partitions=64)
    filters = ["a/+", "a/b", "b/#"]
    for f in filters:
        r.add_route(f)
    oracle = _oracle_for(filters)
    topics = ["a/b", "a/c", "b/x"]
    _assert_parity(r, oracle, topics)  # fill
    c = r._match_cache_obj
    # warm one full churn round first: the early adds can overflow
    # the tiny automaton's capacity and force a growth rebuild — a
    # legitimate GLOBAL bump the measured round must not see
    for i in range(8):
        r.add_route(f"churn{i}/x/leaf")
        r.delete_route(f"churn{i}/x/leaf")
    _assert_parity(r, oracle, topics)  # re-fill post-rebuild
    hits0, stale0, rebuilds0 = c.hits, c.stale, r._rebuilds
    # literal-rooted churn in a DISJOINT partition: cached entries
    # for a/* and b/* must stay valid (pure hits, no stale)
    for i in range(8):
        r.add_route(f"churn{i}/x/leaf")
        oracle.insert(f"churn{i}/x/leaf")
        _assert_parity(r, oracle, topics)
        r.delete_route(f"churn{i}/x/leaf")
        oracle.delete(f"churn{i}/x/leaf")
    if r._rebuilds == rebuilds0:  # no capacity rebuild interfered
        assert c.hits - hits0 == 8 * len(topics)
        assert c.stale == stale0
    assert r.cache_bump_totals()["partition"] >= 16
    # ...and a literal mutation in a HOT partition invalidates only it
    r.add_route("a/new")
    oracle.insert("a/new")
    _assert_parity(r, oracle, topics)  # a/* stale-missed, b/* hit
    assert c.stale > stale0


def test_root_wildcard_mutations_bump_globally():
    r = _mk(match_cache_slots=128, cache_partitions=16)
    r.add_route("a/b")
    oracle = _oracle_for(["a/b"])
    _assert_parity(r, oracle, ["a/b", "z/z"])
    g0 = r.cache_bump_totals()["global"]
    # root '+' and root '#' filters may match ANY cached topic — the
    # partitioned code must fall back to the global bump and the next
    # match must see them (no stale hit)
    for f in ("+/b", "#"):
        r.add_route(f)
        oracle.insert(f)
        _assert_parity(r, oracle, ["a/b", "z/z"])
        r.delete_route(f)
        oracle.delete(f)
        _assert_parity(r, oracle, ["a/b", "z/z"])
    assert r.cache_bump_totals()["global"] - g0 == 4
    assert r._match_cache_obj.stale > 0


def test_share_filter_bumps_post_prefix_partition():
    r = _mk(match_cache_slots=128, cache_partitions=64)
    for f in ("a/+", "b/x"):
        r.add_route(f)
    oracle = _oracle_for(["a/+", "b/x"])
    _assert_parity(r, oracle, ["a/1", "b/x"])
    c = r._match_cache_obj
    stale0, hits0 = c.stale, c.hits
    # a $share filter handed to the router verbatim (the broker
    # normally strips the prefix) invalidates the POST-prefix
    # partition: cached topics rooted 'a' must re-walk...
    r.add_route("$share/g/a/leaf")
    oracle.insert("$share/g/a/leaf")
    _assert_parity(r, oracle, ["a/1", "b/x"])
    assert c.stale > stale0  # 'a' partition re-walked
    assert c.hits > hits0    # 'b' partition still served
    # ...and the literal interpretation stays exact too: a topic
    # rooted '$share' matches the verbatim filter through the cache
    _assert_parity(r, oracle, ["$share/g/a/leaf", "a/1"])
    r.delete_route("$share/g/a/leaf")
    oracle.delete("$share/g/a/leaf")
    _assert_parity(r, oracle, ["$share/g/a/leaf", "a/1", "b/x"])


def test_partitions_one_is_legacy_whole_epoch():
    """``cache_partitions = 1`` must reproduce the PR-1 whole-epoch
    behavior exactly: every mutation bumps the global revision, probe
    keys carry no partition component, and every cached entry goes
    stale on any filter-set change."""
    r1 = _mk(match_cache_slots=64, cache_partitions=1)
    rev0 = r1._cache_rev
    r1.add_route("a/b")
    assert r1._cache_rev == rev0 + 1  # disjoint literal still global
    assert r1._part_revs == [0]
    oracle = _oracle_for(["a/b"])
    _assert_parity(r1, oracle, ["a/b", "zz/q"])
    # stored keys are the legacy 3-tuple (epoch, rev, k_boost)
    keys = [k for k in r1._match_cache_obj._slot_key if k is not None]
    assert keys and all(len(k) == 3 for k in keys)
    c = r1._match_cache_obj
    stale0 = c.stale
    r1.add_route("disjoint/leaf")  # whole-epoch: stales EVERYTHING
    oracle.insert("disjoint/leaf")
    _assert_parity(r1, oracle, ["a/b", "zz/q"])
    assert c.stale > stale0
    assert r1.cache_bump_totals()["partition"] == 0
    # and the partitioned router computes identical match rows on the
    # same sequence (parity of results, not just counters)
    r64 = _mk(match_cache_slots=64, cache_partitions=64)
    for f in ("a/b", "disjoint/leaf"):
        r64.add_route(f)
    topics = ["a/b", "zz/q", "disjoint/leaf"]
    ids1, ovf1 = r1.match_dispatch(topics)[:2]
    ids64, ovf64 = r64.match_dispatch(topics)[:2]
    assert np.array_equal(np.asarray(ids1), np.asarray(ids64))
    assert np.array_equal(np.asarray(ovf1), np.asarray(ovf64))


# -- randomized interleaved churn parity ------------------------------------


def _random_filter(rng, words):
    """A filter from the full class mix: literal, root-'+', root-'#',
    deep wildcard, or a verbatim $share prefix."""
    kind = rng.random()
    depth = rng.randint(1, 4)
    ws = [rng.choice(words) for _ in range(depth)]
    if kind < 0.15:
        ws[0] = "+"
    elif kind < 0.25:
        return "#"
    elif kind < 0.45 and depth > 1:
        ws[rng.randrange(1, depth)] = "+"
    elif kind < 0.55:
        return "$share/grp/" + "/".join(ws)
    if rng.random() < 0.2:
        ws = ws[:max(1, depth - 1)] + ["#"]
    return "/".join(ws)


def test_randomized_churn_parity_single_chip():
    """The satellite bar: interleaved add/delete/match with literal,
    root-wildcard, $share, and overflow-marker topics — exact oracle
    parity after EVERY mutation, partition and global bumps both
    exercised."""
    rng = random.Random(11)
    # small max_matches/active_k force m-overflow markers for hot
    # topics matching many filters (host-fallback path through cache)
    r = _mk(match_cache_slots=512, cache_partitions=16,
            max_matches=4, active_k=4)
    oracle = TrieOracle()
    words = ["a", "b", "c", "d"]
    live = []
    topics = ["/".join(rng.choice(words)
                       for _ in range(rng.randint(1, 4)))
              for _ in range(20)] + ["$share/grp/a/b", "$sys-ish/x"]
    for step in range(40):
        if live and rng.random() < 0.45:
            f = live.pop(rng.randrange(len(live)))
            r.delete_route(f)
            oracle.delete(f)
        else:
            f = _random_filter(rng, words)
            if f not in live:
                r.add_route(f)
                oracle.insert(f)
                live.append(f)
        batch = [rng.choice(topics) for _ in range(10)]
        _assert_parity(r, oracle, batch)
    st = r._match_cache_obj.stats()
    bumps = r.cache_bump_totals()
    assert st["hit"] > 0 and st["stale"] > 0
    assert bumps["global"] > 0 and bumps["partition"] > 0


def test_randomized_churn_parity_mesh():
    """Same interleaved-churn bar through the full broker on a 1×1
    mesh (the sharded cache path): delivery counts must equal the
    host-computed expectation after every subscribe/unsubscribe."""
    from emqx_tpu.parallel.mesh import make_mesh

    rng = random.Random(5)
    b = Broker(router=Router(
        MatcherConfig(mesh=make_mesh(1, 1), fanout_d=8,
                      match_cache_slots=128, cache_partitions=16),
        node="local"))
    words = ["a", "b", "c"]
    subs = {}  # filter (as subscribed, incl $share) -> Q
    topics = ["a/b", "a/c", "b/x", "c/c/c", "a/b"]

    def expected(topic):
        n = 0
        for full in subs:
            flt, opts = T.parse(full)
            if T.match(topic, flt):
                n += 1  # one member per share group -> 1 delivery
        return n

    for step in range(12):
        if subs and rng.random() < 0.4:
            full = rng.choice(list(subs))
            q = subs.pop(full)
            b.unsubscribe(q, full)
        else:
            depth = rng.randint(1, 3)
            ws = [rng.choice(words) for _ in range(depth)]
            if rng.random() < 0.2:
                ws[rng.randrange(depth)] = "+"
            full = "/".join(ws)
            if rng.random() < 0.3:
                full = f"$share/g{step}/{full}"
            if full not in subs:
                q = Q(f"c{step}")
                subs[full] = q
                b.subscribe(q, full)
        msgs = [Message(topic=t) for t in topics]
        got = b.publish_batch(msgs)
        want = [expected(t) for t in topics]
        assert got == want, (step, sorted(subs))
    cache = b.router._sharded_cache_obj
    assert cache is not None and cache.hits > 0


def test_overflow_markers_respect_partition_epochs():
    """Overflow markers (never-served slots) live under the same
    partitioned keys: a disjoint literal add must NOT un-pin an
    overflowed topic (still host fallback, still exact), while a
    same-partition mutation re-keys it."""
    r = _mk(match_cache_slots=64, cache_partitions=64,
            max_matches=2, active_k=2)
    filters = ["t/#", "t/+", "t/x", "other/y"]
    for f in filters:
        r.add_route(f)
    oracle = _oracle_for(filters)
    for _ in range(2):
        _assert_parity(r, oracle, ["t/x", "other/y"])
    c = r._match_cache_obj
    hits0 = c.hits
    r.add_route("disjoint/leaf")  # other partition
    oracle.insert("disjoint/leaf")
    _assert_parity(r, oracle, ["t/x", "other/y"])  # marker hit again
    assert c.hits > hits0
    r.add_route("t/y")  # t partition: marker re-keys, still exact
    oracle.insert("t/y")
    _assert_parity(r, oracle, ["t/x", "t/y", "other/y"])


# -- observability surfaces -------------------------------------------------


def test_bump_counters_drain_and_fold():
    from emqx_tpu.metrics import Metrics

    r = _mk(match_cache_slots=64, cache_partitions=16)
    r.add_route("lit/x")     # partition bump
    r.add_route("+/w")       # global bump
    r.match_filters(["lit/x"])
    drained = r.drain_cache_stats()
    assert drained["bump.partition"] >= 1
    assert drained["bump.global"] >= 1
    m = Metrics()
    m.fold_cache_stats(drained)
    assert m.val("cache.match.bump.partition") == drained["bump.partition"]
    assert m.val("cache.match.bump.global") == drained["bump.global"]
    # second drain: deltas only
    again = r.drain_cache_stats()
    assert again["bump.partition"] == 0 and again["bump.global"] == 0
    # cache off: no bump keys leak into the fold
    r_off = _mk(match_cache=False)
    r_off.add_route("a/b")
    assert "bump.global" not in r_off.drain_cache_stats()


def test_node_gauges_ctl_cache_and_quarantine_alarm():
    from emqx_tpu.node import Node

    n = Node(boot_listeners=False,
             matcher=MatcherConfig(device_min_filters=0,
                                   cache_partitions=16))
    q = Q("c1")
    n.subscribe(q, "g/t")
    n.broker.publish(Message(topic="g/t"))
    n.stats.tick()
    assert n.stats.getstat("match.cache.partition.live") == 16
    assert n.stats.getstat("router.ids.quarantined.count") == 0
    out = n.ctl.run(["cache"])
    assert '"partitions": 16' in out
    assert "bumps" in out and "quarantined_ids" in out
    # sustained quarantine growth (above the reclaim bound) raises
    # the alarm on the 3rd growing tick; a flat tick clears it
    bound = n.router.config.host_reclaim_pending
    n.router._pending_free = list(range(bound + 1))
    for i in range(Node.QUARANTINE_ALARM_TICKS):
        n.router._pending_free.append(i)
        n.stats.tick()
    active = [a.name for a in n.alarms.get_alarms("activated")]
    assert "router_ids_quarantined" in active
    assert n.stats.getstat("router.ids.quarantined.count") > bound
    n.stats.tick()  # no growth: clears
    active = [a.name for a in n.alarms.get_alarms("activated")]
    assert "router_ids_quarantined" not in active


def test_matcher_toml_cache_partitions():
    from emqx_tpu.config import ConfigError, _build_matcher

    assert _build_matcher({"cache_partitions": 16}).cache_partitions == 16
    assert _build_matcher({"cache_partitions": 1}).cache_partitions == 1
    with pytest.raises(ConfigError):
        _build_matcher({"cache_partitions": 24})
    with pytest.raises(ConfigError):
        _build_matcher({"cache_partitions": 0})
    with pytest.raises(ValueError):
        Router(MatcherConfig(cache_partitions=12))
