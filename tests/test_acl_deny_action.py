"""Zone knob ``acl_deny_action`` (etc/emqx.conf:617): "ignore"
answers a denied PUBLISH/SUBSCRIBE with the reason code, "disconnect"
drops the client (src/emqx_channel.erl:372-377, 470-478)."""

from emqx_tpu.access_control import DENY
from emqx_tpu.broker import Broker
from emqx_tpu.channel import Channel
from emqx_tpu.cm import ConnectionManager
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import reason_codes as RC
from emqx_tpu.mqtt.packet import (Connack, Connect, Disconnect, PubAck,
                                  Publish, Suback, Subscribe)
from emqx_tpu.zone import Zone


def _chan(action, deny_topic="secret/t"):
    broker = Broker()

    def acl(clientinfo, pubsub, topic, acc):
        return DENY if topic.startswith("secret/") else acc

    broker.hooks.add("client.check_acl", acl)
    zone = Zone(name=f"acl-{action}", acl_deny_action=action)
    chan = Channel(broker, ConnectionManager(broker=broker), zone=zone)
    out = chan.handle_in(Connect(
        proto_ver=C.MQTT_V5, proto_name="MQTT", client_id="aclc",
        clean_start=True))
    assert isinstance(out[0], Connack) and out[0].reason_code == 0
    return chan


def test_publish_deny_ignore_acks_not_authorized():
    chan = _chan("ignore")
    out = chan.handle_in(Publish(topic="secret/t", qos=1, packet_id=1))
    assert isinstance(out[0], PubAck)
    assert out[0].reason_code == RC.NOT_AUTHORIZED
    assert not chan.closed


def test_publish_deny_disconnect_drops_client():
    chan = _chan("disconnect")
    out = chan.handle_in(Publish(topic="secret/t", qos=1, packet_id=1))
    assert any(isinstance(p, Disconnect) and
               p.reason_code == RC.NOT_AUTHORIZED for p in out), out
    assert chan.close_after_send


def test_subscribe_deny_ignore_suback_rc():
    chan = _chan("ignore")
    out = chan.handle_in(Subscribe(packet_id=1, topic_filters=[
        ("secret/t", {"qos": 1, "nl": 0, "rap": 0, "rh": 0}),
        ("open/t", {"qos": 1, "nl": 0, "rap": 0, "rh": 0})]))
    assert isinstance(out[0], Suback)
    assert out[0].reason_codes[0] == RC.NOT_AUTHORIZED
    assert out[0].reason_codes[1] in (0, 1)
    assert not chan.closed


def test_subscribe_deny_disconnect_on_any_denied_filter():
    chan = _chan("disconnect")
    out = chan.handle_in(Subscribe(packet_id=1, topic_filters=[
        ("open/t", {"qos": 1, "nl": 0, "rap": 0, "rh": 0}),
        ("secret/t", {"qos": 1, "nl": 0, "rap": 0, "rh": 0})]))
    assert any(isinstance(p, Disconnect) and
               p.reason_code == RC.NOT_AUTHORIZED for p in out), out
    assert chan.close_after_send
