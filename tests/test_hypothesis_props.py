"""Hypothesis property tests — the reference's PropEr tier
(test/props/), generator-driven instead of hand-rolled randomness:

  - frame codec: serialize∘parse identity over generated packets ×
    protocol versions (prop_emqx_frame.erl:26-55);
  - topic algebra: match/words/join laws over generated topics;
  - matcher parity: device automaton ≡ host oracle over generated
    filter sets and topics (the emqx_trie_SUITE semantics, fuzzed);
  - base62: roundtrip over arbitrary ints (prop_emqx_base62).
"""

import pytest

# optional dependency: skip the property tier cleanly where
# hypothesis isn't installed (tier-1 hygiene)
pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from emqx_tpu import topic as T
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.frame import Parser, serialize
from emqx_tpu.mqtt.packet import Publish, Subscribe

# -- strategies -------------------------------------------------------------

word = st.text(alphabet="abcdefg01", min_size=1, max_size=4)
topic_name = st.lists(word, min_size=1, max_size=6).map("/".join)


@st.composite
def topic_filter(draw):
    words = draw(st.lists(
        st.one_of(word, st.just("+")), min_size=1, max_size=6))
    if draw(st.booleans()):
        words = words[: draw(st.integers(1, len(words)))] + ["#"]
    return "/".join(words)


@st.composite
def publish_packet(draw):
    qos = draw(st.integers(0, 2))
    props = {}
    if draw(st.booleans()):
        props["Message-Expiry-Interval"] = draw(st.integers(1, 2**31 - 1))
    if draw(st.booleans()):
        props["User-Property"] = [
            (draw(st.text(max_size=8)), draw(st.text(max_size=8)))]
    return Publish(
        topic=draw(topic_name),
        payload=draw(st.binary(max_size=64)),
        qos=qos,
        retain=draw(st.booleans()),
        dup=draw(st.booleans()) if qos else False,
        packet_id=draw(st.integers(1, 0xFFFF)) if qos else None,
        properties=props,
    )


# -- frame codec ------------------------------------------------------------

@settings(max_examples=200, deadline=None)
@given(pkt=publish_packet(),
       ver=st.sampled_from([C.MQTT_V3, C.MQTT_V4, C.MQTT_V5]))
def test_publish_serialize_parse_identity(pkt, ver):
    if ver != C.MQTT_V5:
        pkt.properties = {}
    data = serialize(pkt, ver)
    [out] = Parser(version=ver).feed(data)
    assert isinstance(out, Publish)
    assert (out.topic, out.payload, out.qos, out.retain, out.dup) == \
        (pkt.topic, pkt.payload, pkt.qos, pkt.retain, pkt.dup)
    if pkt.qos:
        assert out.packet_id == pkt.packet_id
    if ver == C.MQTT_V5:
        for k, v in pkt.properties.items():
            assert out.properties.get(k) == v


@settings(max_examples=100, deadline=None)
@given(pkt=publish_packet(), cut=st.integers(1, 8),
       ver=st.sampled_from([C.MQTT_V4, C.MQTT_V5]))
def test_parser_incremental_feed_identity(pkt, cut, ver):
    """Byte-at-a-time / chunked feeding yields the same packet."""
    if ver != C.MQTT_V5:
        pkt.properties = {}
    data = serialize(pkt, ver)
    p = Parser(version=ver)
    outs = []
    for i in range(0, len(data), cut):
        outs.extend(p.feed(data[i:i + cut]))
    assert len(outs) == 1
    out = outs[0]
    assert (out.topic, out.payload, out.qos, out.retain, out.dup) == \
        (pkt.topic, pkt.payload, pkt.qos, pkt.retain, pkt.dup)
    if pkt.qos:
        assert out.packet_id == pkt.packet_id
    if ver == C.MQTT_V5:
        for k, v in pkt.properties.items():
            assert out.properties.get(k) == v


@settings(max_examples=100, deadline=None)
@given(filters=st.lists(topic_filter(), min_size=1, max_size=5))
def test_subscribe_roundtrip(filters):
    pkt = Subscribe(packet_id=7, topic_filters=[
        (f, {"qos": 1, "nl": 0, "rap": 0, "rh": 0}) for f in filters])
    [out] = Parser(version=C.MQTT_V5).feed(serialize(pkt, C.MQTT_V5))
    assert [f for f, _ in out.topic_filters] == filters


# -- topic algebra ----------------------------------------------------------

@settings(max_examples=300, deadline=None)
@given(t=topic_name)
def test_topic_matches_itself_and_hash(t):
    assert T.match(t, t)
    assert T.match(t, "#")
    assert T.match(t, "/".join(["+"] * len(T.words(t))))


@settings(max_examples=300, deadline=None)
@given(t=topic_name, f=topic_filter())
def test_match_agrees_with_word_semantics(t, f):
    """T.match ≡ the word-by-word reference semantics."""
    def ref_match(tw, fw):
        i = 0
        for w in fw:
            if w == "#":
                return True
            if i >= len(tw):
                return False
            if w != "+" and w != tw[i]:
                return False
            i += 1
        return i == len(tw)

    assert T.match(t, f) == ref_match(T.words(t), T.words(f))


@settings(max_examples=200, deadline=None)
@given(t=topic_name)
def test_sys_topics_never_match_root_wildcards(t):
    sys_t = "$SYS/" + t
    assert not T.match(sys_t, "#")
    assert not T.match(sys_t, "+/" + t)


# -- matcher parity: device automaton ≡ oracle ------------------------------

@settings(max_examples=30, deadline=None)
@given(filters=st.lists(topic_filter(), min_size=1, max_size=40,
                        unique=True),
       topics=st.lists(topic_name, min_size=1, max_size=20))
def test_router_device_matches_oracle(filters, topics):
    from emqx_tpu.oracle import TrieOracle
    from emqx_tpu.router import MatcherConfig, Router

    r = Router(MatcherConfig(device_min_filters=0, use_native=False),
               node="prop")
    oracle = TrieOracle()
    for f in filters:
        r.add_route(f)
        oracle.insert(f)
    got = r.match_filters(topics)
    assert len(got) == len(topics)
    for t, g in zip(topics, got):
        assert sorted(g) == sorted(oracle.match(t)), t


# -- base62 -----------------------------------------------------------------

@settings(max_examples=300, deadline=None)
@given(n=st.integers(0, 2**128))
def test_base62_roundtrip(n):
    from emqx_tpu.utils.base62 import decode, encode

    assert decode(encode(n)) == n


@given(filters=st.lists(topic_filter(), min_size=1, max_size=30,
                        unique=True),
       topics=st.lists(topic_name, min_size=1, max_size=16),
       mode=st.sampled_from(["narrow", "wide"]))
@settings(max_examples=40, deadline=None)
def test_compressed_walk_matches_oracle(filters, topics, mode):
    """Both kernel layouts (forced) hold exact oracle parity on
    arbitrary filter sets — the chain-compression invariant. Reuses
    the parity harness (incl. its res.count cross-check)."""
    from tests.test_match_parity import _check_parity

    _check_parity(filters, topics, k=32, mode=mode)


@given(data=st.recursive(
    st.none() | st.booleans()
    | st.integers(-(1 << 70), 1 << 70)
    | st.floats(allow_nan=False) | st.text(max_size=30)
    | st.binary(max_size=30),
    lambda children: st.lists(children, max_size=4)
    | st.tuples(children, children)
    | st.dictionaries(st.text(max_size=8), children, max_size=4),
    max_leaves=20))
@settings(max_examples=150, deadline=None)
def test_wire_codec_roundtrip_property(data):
    """The cluster wire codec is total over its vocabulary: encode
    then decode is the identity (types included, recursively —
    Python equality conflates bool/int/float, so == alone would
    accept True→1 corruption inside containers)."""
    from emqx_tpu import wire

    def same(a, b):
        if type(a) is not type(b):
            return False
        if isinstance(a, (list, tuple)):
            return len(a) == len(b) and all(
                same(x, y) for x, y in zip(a, b))
        if isinstance(a, dict):
            return (len(a) == len(b)
                    and all(k in b and same(v, b[k])
                            for k, v in a.items()))
        return a == b or (a != a and b != b)  # NaN-safe

    got = wire.loads(wire.dumps(data))
    assert same(got, data), (got, data)
