"""ops/pack.py — device-side result compaction.

Parity model: packing dense -1-padded kernel outputs into CSR buffers
must preserve exactly the valid elements in row order; overflow is
detected from the row-pointer totals. The dense inputs here are random
in the same shapes the broker's publish path produces.
"""

import numpy as np
import pytest

from emqx_tpu.ops.pack import (budget_for, pack_fanout, pack_matches,
                               pack_union_rows)


def _random_padded(rng, B, M, density, lo=0, hi=500):
    """Dense [B, M] int32 with -1 padding; valid entries front-packed
    (as the match/gather kernels emit) in half the rows, scattered in
    the rest — the pack must not depend on packing discipline."""
    out = np.full((B, M), -1, dtype=np.int32)
    for b in range(B):
        n = rng.binomial(M, density)
        vals = rng.integers(lo, hi, size=n).astype(np.int32)
        if b % 2:
            out[b, :n] = vals
        else:
            cols = rng.choice(M, size=n, replace=False)
            out[b, cols] = vals
    return out


def _rows(dense):
    return [[int(v) for v in row if v >= 0] for row in dense]


def test_budget_for_pow2():
    assert budget_for(8, 8) == 64
    assert budget_for(256, 8) == 2048
    assert budget_for(100, 3, floor=64) == 512
    assert budget_for(1, 1) == 64


def test_pack_matches_parity():
    rng = np.random.default_rng(0)
    ids = _random_padded(rng, 32, 16, 0.3)
    pm = budget_for(32, 16)
    m_ptr, packed = map(np.asarray, pack_matches(ids, pm=pm))
    total = int(m_ptr[-1])
    assert total == sum(len(r) for r in _rows(ids))
    got = [sorted(packed[m_ptr[b]:m_ptr[b + 1]].tolist())
           for b in range(32)]
    want = [sorted(r) for r in _rows(ids)]
    assert got == want
    # budget tail stays -1
    assert (packed[total:] == -1).all()


def test_pack_matches_row_order_front_packed():
    """Front-packed rows (the kernels' actual discipline) keep their
    in-row order after packing."""
    ids = np.full((4, 8), -1, dtype=np.int32)
    ids[0, :3] = [7, 3, 9]
    ids[2, :2] = [1, 2]
    m_ptr, packed = map(np.asarray, pack_matches(ids, pm=64))
    assert packed[m_ptr[0]:m_ptr[1]].tolist() == [7, 3, 9]
    assert m_ptr[1] == m_ptr[2]  # empty row
    assert packed[m_ptr[2]:m_ptr[3]].tolist() == [1, 2]


def test_pack_matches_overflow_detectable():
    ids = np.zeros((8, 16), dtype=np.int32)  # 128 valid entries
    m_ptr, packed = map(np.asarray, pack_matches(ids, pm=64))
    assert int(m_ptr[-1]) == 128 > 64  # caller re-packs bigger
    # the budget's worth that did land is correct
    assert (packed == 0).all()


def test_pack_fanout_parity():
    rng = np.random.default_rng(1)
    subs = _random_padded(rng, 16, 64, 0.2, hi=10_000)
    src = np.where(subs >= 0,
                   rng.integers(0, 100, size=subs.shape).astype(np.int32),
                   -1)
    pq = budget_for(16, 64)
    f_ptr, psubs, psrc = map(np.asarray, pack_fanout(subs, src, pq=pq))
    assert int(f_ptr[-1]) == int((subs >= 0).sum())
    for b in range(16):
        lo, hi = int(f_ptr[b]), int(f_ptr[b + 1])
        pairs = sorted(zip(psubs[lo:hi].tolist(), psrc[lo:hi].tolist()))
        want = sorted((int(s), int(c))
                      for s, c in zip(subs[b], src[b]) if s >= 0)
        assert pairs == want
    assert (psubs[int(f_ptr[-1]):] == -1).all()


def test_pack_union_rows():
    rng = np.random.default_rng(2)
    B, W = 12, 128
    union = rng.integers(0, 2**32, size=(B, W), dtype=np.uint32)
    has_big = np.zeros((B,), dtype=bool)
    has_big[[1, 4, 9]] = True
    sel, rows, total = pack_union_rows(union, has_big, pr=8)
    sel, rows = np.asarray(sel), np.asarray(rows)
    assert int(total) == 3
    assert sel[1] == 0 and sel[4] == 1 and sel[9] == 2
    assert (sel[~has_big] == -1).all()
    for b in (1, 4, 9):
        assert (rows[sel[b]] == union[b]).all()
    # untouched budget rows are zero
    assert (rows[3:] == 0).all()


def test_pack_union_rows_overflow():
    union = np.ones((8, 128), dtype=np.uint32)
    has_big = np.ones((8,), dtype=bool)
    sel, rows, total = pack_union_rows(union, has_big, pr=4)
    assert int(total) == 8 > 4


@pytest.mark.parametrize("B,M", [(1, 1), (8, 128), (64, 4)])
def test_pack_matches_shapes(B, M):
    rng = np.random.default_rng(B * 100 + M)
    ids = _random_padded(rng, B, M, 0.5)
    pm = budget_for(B, M)
    m_ptr, packed = map(np.asarray, pack_matches(ids, pm=pm))
    assert m_ptr.shape == (B + 1,) and packed.shape == (pm,)
    assert int(m_ptr[-1]) == int((ids >= 0).sum())


def test_mask_pad_rows():
    from emqx_tpu.ops.pack import mask_pad_rows

    ids = np.arange(32, dtype=np.int32).reshape(8, 4)
    out = np.asarray(mask_pad_rows(ids, np.int32(3)))
    assert (out[:3] == ids[:3]).all()
    assert (out[3:] == -1).all()
