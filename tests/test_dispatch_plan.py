"""Batch dispatch planner (emqx_tpu/ops/dispatch_plan.py +
Broker.publish_finish_planned, docs/DISPATCH.md): plan grouping
invariants, planner-on vs legacy-tail parity (delivery counts,
per-subscriber streams, session outboxes, per-connection wire
packets, metric deltas) across QoS0 broadcast / QoS1-2 inflight /
no-local / shared-sub / mountpoint / bitmap big-fan, the ≤1
notify-wakeup-per-connection-per-batch contract, overflow fallback to
the legacy walk, and the [dispatch] config schema."""

import numpy as np
import pytest

from emqx_tpu.broker import Broker, DispatchConfig
from emqx_tpu.config import ConfigError, parse_config
from emqx_tpu.ops.dispatch_plan import DispatchPlan, build_plan
from emqx_tpu.router import MatcherConfig, Router
from emqx_tpu.session import Session
from emqx_tpu.telemetry import Telemetry, TelemetryConfig
from emqx_tpu.types import Message, SubOpts


class Q:
    def __init__(self, client_id="c"):
        self.client_id = client_id
        self.inbox = []

    def deliver(self, topic, msg):
        self.inbox.append((topic, msg))


def _broker(planner: bool, **mk) -> Broker:
    mk.setdefault("device_min_filters", 0)
    return Broker(router=Router(MatcherConfig(**mk), node="node1"),
                  dispatch_config=DispatchConfig(planner=planner))


def _metric_deltas(broker):
    return {k: v for k, v in broker.metrics.all().items()
            if v and (k.startswith("messages.")
                      or k.startswith("delivery."))}


# -- plan grouping invariants ---------------------------------------------


def test_build_plan_groups_by_subscriber_in_walk_order():
    # two live rows over two unique topics; CSR pack:
    #   urow0 -> (sub 7, fid 1), (sub 3, fid 1)
    #   urow1 -> (sub 3, fid 2)
    f_ptr = np.array([0, 2, 3])
    subs = np.array([7, 3, 3])
    src = np.array([1, 1, 2])
    ovf = np.zeros(2, bool)
    plan = build_plan([0, 1], 2, ovf, None, f_ptr, subs, src, {})
    assert plan is not None and plan.n_groups == 2
    # groups sorted by sid; within a group, legacy walk order (row-
    # major, packed-slot order)
    assert plan.g_sids == [3, 7]
    g0 = slice(plan.g_ptr[0], plan.g_ptr[1])
    assert plan.rows[g0] == [0, 1]
    assert plan.fids[g0] == [1, 2]
    g1 = slice(plan.g_ptr[1], plan.g_ptr[2])
    assert plan.rows[g1] == [1 - 1]  # row 0
    assert plan.fids[g1] == [1]


def test_build_plan_expands_duplicate_topics_via_inverse_index():
    # three live rows, rows 0 and 2 share unique topic 0
    f_ptr = np.array([0, 1, 2])
    subs = np.array([5, 9])
    src = np.array([4, 6])
    plan = build_plan([0, 1, 0], 2, np.zeros(2, bool), None,
                      f_ptr, subs, src, {})
    assert plan.n_deliveries == 3
    g0 = slice(plan.g_ptr[0], plan.g_ptr[1])
    assert plan.g_sids == [5, 9]
    assert plan.rows[g0] == [0, 2]  # both copies, row order


def test_build_plan_merges_bitmap_rows_after_csr_within_a_row():
    f_ptr = np.array([0, 1])
    subs = np.array([2])
    src = np.array([0])
    big = {0: [(8, np.array([1, 2], np.int64))]}
    plan = build_plan([0], 1, np.zeros(1, bool),
                      np.zeros(1, bool), f_ptr, subs, src, big)
    assert plan.n_deliveries == 3
    # sub 2's CSR slot and the bitmap bits, grouped by sid
    assert plan.g_sids == [1, 2]
    g2 = slice(plan.g_ptr[1], plan.g_ptr[2])
    assert sorted(plan.fids[g2]) == [0, 8]
    # within sub 2's group: CSR (fid 0) precedes bitmap (fid 8) —
    # the legacy within-row walk order
    assert plan.fids[g2] == [0, 8]


def test_build_plan_refuses_overflow_batches():
    f_ptr = np.array([0, 1])
    subs = np.array([2])
    src = np.array([0])
    ovf = np.array([True])
    assert build_plan([0], 1, ovf, None, f_ptr, subs, src, {}) is None
    bovf = np.array([True])
    assert build_plan([0], 1, np.zeros(1, bool), bovf,
                      f_ptr, subs, src, {}) is None


def test_empty_plan_has_zero_groups():
    plan = DispatchPlan(np.empty(0, np.int64), np.empty(0, np.int64),
                        np.empty(0, np.int64))
    assert plan.n_groups == 0 and plan.n_deliveries == 0


# -- planner vs legacy parity (device path) -------------------------------


def _qos0_broadcast(b):
    subs = [Q(f"c{i}") for i in range(4)]
    b.subscribe(subs[0], "w/+/x")
    b.subscribe(subs[1], "w/1/x")
    b.subscribe(subs[2], "w/#")
    b.subscribe(subs[3], "other")
    res = []
    for _ in range(3):
        res.append(b.publish_batch(
            [Message(topic="w/1/x"), Message(topic="w/2/x"),
             Message(topic="nomatch"), Message(topic="w/1/x")]))
    return res, [[(t, m.topic, m.qos) for t, m in s.inbox]
                 for s in subs]


def _no_local(b):
    pub = Q("pub")
    other = Q("other")
    b.subscribe(pub, "t/+", SubOpts(nl=1))
    b.subscribe(other, "t/+", SubOpts(nl=1))
    res = [b.publish_batch([Message(topic="t/1", from_="pub"),
                            Message(topic="t/2", from_="other")])]
    return res, [[(t, m.topic) for t, m in s.inbox]
                 for s in (pub, other)]


def _sessions_qos12(b):
    sess = [Session(f"s{i}", broker=b) for i in range(3)]
    sess[0].subscribe("q/+", SubOpts(qos=1))
    sess[1].subscribe("q/a", SubOpts(qos=2))
    sess[2].subscribe("q/#", SubOpts(qos=0))
    res = []
    for k in range(2):
        res.append(b.publish_batch(
            [Message(topic="q/a", qos=2, from_="p"),
             Message(topic="q/b", qos=1, from_="p"),
             Message(topic="q/a", qos=0, from_="p")]))
    outs = [[(pid, m.topic, m.qos, m.flags.get("dup", False))
             for pid, m in s.outbox] for s in sess]
    infl = [sorted(pid for pid, _ in s.inflight.to_list())
            for s in sess]
    return res, outs, infl


def _shared_sub(b):
    m1, m2, plain = Q("m1"), Q("m2"), Q("plain")
    b.subscribe(m1, "$share/g/s/t")
    b.subscribe(m2, "$share/g/s/t")
    b.subscribe(plain, "s/t")
    res = [b.publish_batch([Message(topic="s/t")]) for _ in range(4)]
    # shared picks one member per publish; totals must match even if
    # the pick rotates
    return res, len(m1.inbox) + len(m2.inbox), \
        [(t, m.topic) for t, m in plain.inbox]


def _bitmap_bigfan(b):
    # fanout_threshold=2 puts these filters on the bitmap path
    subs = [Q(f"b{i}") for i in range(5)]
    for s in subs[:4]:
        b.subscribe(s, "big/t")
    for s in subs[1:]:
        b.subscribe(s, "big/+")      # second big filter: multi-fid union
    b.subscribe(subs[0], "small/x")  # CSR path in the same batch
    res = []
    for _ in range(2):
        res.append(b.publish_batch(
            [Message(topic="big/t"), Message(topic="small/x"),
             Message(topic="big/t")]))
    return res, [[(t, m.topic) for t, m in s.inbox] for s in subs]


@pytest.mark.parametrize("scenario,mk", [
    (_qos0_broadcast, {}),
    (_no_local, {}),
    (_sessions_qos12, {}),
    (_shared_sub, {}),
    (_bitmap_bigfan, {"fanout_threshold": 2}),
])
def test_planner_parity_with_legacy_tail(scenario, mk):
    b_on = _broker(True, **mk)
    b_off = _broker(False, **mk)
    got_on = scenario(b_on)
    got_off = scenario(b_off)
    assert got_on == got_off
    assert _metric_deltas(b_on) == _metric_deltas(b_off)


def test_planner_parity_on_mesh_1x1():
    from emqx_tpu.parallel.mesh import make_mesh

    outs = []
    for planner in (True, False):
        b = Broker(router=Router(
            MatcherConfig(mesh=make_mesh(1, 1), fanout_d=8), node="n"),
            dispatch_config=DispatchConfig(planner=planner))
        outs.append(_qos0_broadcast(b) + (_metric_deltas(b),))
    assert outs[0] == outs[1]


def test_match_overflow_batch_falls_back_to_legacy_walk():
    # max_matches=1 with 2 matching filters per topic overflows the
    # match output -> the batch must refuse to plan and still deliver
    # exactly like the legacy walk (host re-match per overflow row)
    outs = []
    for planner in (True, False):
        b = _broker(planner, max_matches=1)
        s1, s2 = Q("c1"), Q("c2")
        b.subscribe(s1, "o/+")
        b.subscribe(s2, "o/1")
        pb = b.publish_begin([Message(topic="o/1")])
        assert not pb.done
        b.publish_fetch(pb)
        if planner:
            assert pb.plan is None  # overflow row -> not plannable
        res = b.publish_finish(pb)
        outs.append((res, [(t, m.topic) for t, m in s1.inbox],
                     [(t, m.topic) for t, m in s2.inbox],
                     _metric_deltas(b)))
    assert outs[0] == outs[1]
    assert outs[0][0] == [2]


def test_unsubscribed_since_fetch_is_skipped():
    b = _broker(True)
    s1, s2 = Q("c1"), Q("c2")
    b.subscribe(s1, "u/t")
    b.subscribe(s2, "u/t")
    pb = b.publish_begin([Message(topic="u/t")])
    b.publish_fetch(pb)
    assert pb.plan is not None
    b.unsubscribe(s2, "u/t")  # between fetch and finish
    assert b.publish_finish(pb) == [1]
    assert len(s1.inbox) == 1 and not s2.inbox
    assert b.metrics.val("messages.delivered") == 1


# -- wakeup coalescing: ≤1 notify per connection per batch ----------------


def test_one_notify_per_session_per_batch():
    b = _broker(True)
    counts = {}
    sess = []
    for i in range(3):
        s = Session(f"n{i}", broker=b)
        counts[s.client_id] = 0

        def notify(cid=s.client_id):
            counts[cid] += 1

        s.notify = notify
        s.subscribe("hot/#")
        sess.append(s)
    msgs = [Message(topic=f"hot/{i % 4}") for i in range(16)]
    assert b.publish_batch(msgs) == [3] * 16
    # 16 deliveries each, ONE wakeup each (the legacy tail fires 16)
    assert counts == {s.client_id: 1 for s in sess}
    b.publish_batch(msgs)
    assert all(v == 2 for v in counts.values())


def test_legacy_tail_fires_per_delivery_wakeups():
    b = _broker(False)
    s = Session("leg", broker=b)
    n = [0]
    s.notify = lambda: n.__setitem__(0, n[0] + 1)
    s.subscribe("hot/#")
    b.publish_batch([Message(topic=f"hot/{i}") for i in range(8)])
    assert n[0] == 8  # the contrast the planner removes


# -- wire-level parity through real connections ---------------------------


async def _wire_run(planner: bool):
    from helpers import broker_node, node_port
    from mqtt_client import TestClient
    from emqx_tpu.zone import Zone

    zone = Zone(name="default", mountpoint="mp/")
    async with broker_node(zone=zone,
                           matcher=MatcherConfig(device_min_filters=0),
                           dispatch_config=DispatchConfig(
                               planner=planner)) as node:
        port = node_port(node)
        s0 = TestClient("w0")
        s1 = TestClient("w1")
        pub = TestClient("wp")
        for c in (s0, s1, pub):
            await c.connect(port=port)
        await s0.subscribe("x/+", qos=0)
        await s1.subscribe("x/#", qos=1)
        for i in range(12):
            await pub.publish("x/t", payload=b"p%d" % i, qos=0)
        await pub.publish("x/end", payload=b"end", qos=1)
        got = []
        for cli in (s0, s1):
            pkts = []
            for _ in range(13):
                p = await cli.recv(timeout=5.0)
                pkts.append((p.topic, bytes(p.payload), p.qos,
                             p.retain, getattr(p, "dup", False)))
            got.append(pkts)
        for c in (s0, s1, pub):
            await c.close()
        return got


async def test_wire_parity_planner_vs_legacy_with_mountpoint():
    on = await _wire_run(True)
    off = await _wire_run(False)
    assert on == off
    # sanity: the mountpoint round-tripped (subscriber sees bare topic)
    assert on[0][0][0] == "x/t"


# -- telemetry stage ------------------------------------------------------


def test_dispatch_plan_stage_records_only_when_planning():
    for planner, expect in ((True, 1), (False, 0)):
        b = _broker(planner)
        tel = Telemetry(TelemetryConfig())
        b.telemetry = tel
        b.router.telemetry = tel
        s = Q()
        b.subscribe(s, "t/+")
        assert b.publish_batch([Message(topic="t/1")]) == [1]
        assert tel.hists["dispatch_plan"].count == expect, planner
        assert tel.hists["dispatch"].count == 1


def test_host_path_never_records_dispatch_plan():
    b = Broker()  # default: host regime
    tel = Telemetry(TelemetryConfig())
    b.telemetry = tel
    b.router.telemetry = tel
    s = Q()
    b.subscribe(s, "h/+")
    assert b.publish_batch([Message(topic="h/1")]) == [1]
    assert tel.hists["dispatch_plan"].count == 0


# -- [dispatch] config schema ---------------------------------------------


def test_dispatch_config_section_parses_and_rejects_typos():
    cfg = parse_config({"dispatch": {"planner": False}})
    assert cfg.dispatch is not None and cfg.dispatch.planner is False
    assert parse_config({}).dispatch is None
    with pytest.raises(ConfigError, match="unknown dispatch setting"):
        parse_config({"dispatch": {"plannner": False}})
    with pytest.raises(ConfigError, match="must be a boolean"):
        parse_config({"dispatch": {"planner": "yes"}})
    with pytest.raises(ConfigError, match="must be a table"):
        parse_config({"dispatch": True})
