"""MQTT v5 spec-conformance over live loopback TCP.

Mirrors the reference's ``test/mqtt_protocol_v5_SUITE.erl`` (756 LoC)
case by case where the behaviour is observable through a real client:
session expiry, will delay, topic aliases, RAP/no-local subscription
options, batch subscribe reason codes, wildcard-publish rejection,
duplicate clientid takeover, overlapping subscriptions.
"""

import asyncio
import contextlib
import time

from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.packet import Disconnect, Publish, Subscribe
from emqx_tpu.node import Node
from tests.helpers import broker_node, node_port as _port
from tests.mqtt_client import TestClient




# -- session expiry (t_connect_session_expiry_interval) ---------------------

async def test_session_expiry_interval_queues_offline():
    async with broker_node() as node:
        c1 = TestClient("sei1", version=C.MQTT_V5,
                        properties={"Session-Expiry-Interval": 7200})
        await c1.connect(port=_port(node))
        await c1.subscribe("sei/t", qos=2)
        await c1.disconnect()  # normal disconnect, session kept

        pub = TestClient("seipub", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("sei/t", b"while-away", qos=2, timeout=60)

        c2 = TestClient("sei1", version=C.MQTT_V5, clean_start=False,
                        properties={"Session-Expiry-Interval": 7200})
        ack = await c2.connect(port=_port(node))
        assert ack.session_present
        m = await c2.recv(10)
        assert m.payload == b"while-away" and m.qos == 2
        await c2.close()
        await pub.close()


async def test_disconnect_with_zero_expiry_drops_session():
    async with broker_node() as node:
        c1 = TestClient("sei0", version=C.MQTT_V5,
                        properties={"Session-Expiry-Interval": 7200})
        await c1.connect(port=_port(node))
        await c1.subscribe("sei0/t", qos=1)
        # DISCONNECT overriding expiry to 0 → session dropped now
        await c1.send(Disconnect(
            reason_code=0, properties={"Session-Expiry-Interval": 0}))
        await c1.close()
        await asyncio.sleep(0.1)

        c2 = TestClient("sei0", version=C.MQTT_V5, clean_start=False)
        ack = await c2.connect(port=_port(node))
        assert not ack.session_present
        await c2.close()


# -- will delay (t_connect_will_delay_interval) -----------------------------

async def test_will_delay_interval():
    async with broker_node() as node:
        watcher = TestClient("wdwatch", version=C.MQTT_V5)
        await watcher.connect(port=_port(node))
        await watcher.subscribe("wd/t")

        c = TestClient("wdc", version=C.MQTT_V5,
                       will_flag=True, will_topic="wd/t",
                       will_payload=b"gone",
                       will_props={"Will-Delay-Interval": 1},
                       properties={"Session-Expiry-Interval": 60})
        await c.connect(port=_port(node))
        c.writer.close()  # abnormal loss, no DISCONNECT
        t0 = time.time()
        with contextlib.suppress(asyncio.TimeoutError):
            await watcher.recv(0.4)
            raise AssertionError("will published before the delay")
        m = await watcher.recv(15)
        assert m.payload == b"gone"
        assert time.time() - t0 >= 0.8
        await watcher.close()


async def test_will_delay_cancelled_by_reconnect():
    async with broker_node() as node:
        watcher = TestClient("wdw2", version=C.MQTT_V5)
        await watcher.connect(port=_port(node))
        await watcher.subscribe("wd2/t")

        c = TestClient("wdc2", version=C.MQTT_V5,
                       will_flag=True, will_topic="wd2/t",
                       will_payload=b"gone",
                       will_props={"Will-Delay-Interval": 2},
                       properties={"Session-Expiry-Interval": 60})
        await c.connect(port=_port(node))
        c.writer.close()
        await asyncio.sleep(0.2)
        # reconnect before the delay elapses → will must not fire
        c2 = TestClient("wdc2", version=C.MQTT_V5, clean_start=False,
                        properties={"Session-Expiry-Interval": 60})
        await c2.connect(port=_port(node))
        with contextlib.suppress(asyncio.TimeoutError):
            m = await watcher.recv(3.0)
            raise AssertionError(f"will fired despite reconnect: {m}")
        await c2.close()
        await watcher.close()


# -- topic alias (t_publish_topic_alias) ------------------------------------

async def test_topic_alias_zero_is_protocol_error():
    async with broker_node() as node:
        c = TestClient("alias0", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.send(Publish(topic="t", payload=b"x", qos=0,
                             properties={"Topic-Alias": 0}))
        # server must DISCONNECT (0x94 topic alias invalid) and close
        pkt = await asyncio.wait_for(c.acks.get(), 5)
        assert isinstance(pkt, Disconnect)
        assert pkt.reason_code == 0x94
        await c.close()


async def test_topic_alias_reuse_across_publishes():
    async with broker_node() as node:
        sub = TestClient("aliassub", version=C.MQTT_V5)
        await sub.connect(port=_port(node))
        await sub.subscribe("al/t", qos=0)
        c = TestClient("aliasc", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.send(Publish(topic="al/t", payload=b"first", qos=0,
                             properties={"Topic-Alias": 3}))
        # empty topic + alias resolves to the registered one
        await c.send(Publish(topic="", payload=b"second", qos=0,
                             properties={"Topic-Alias": 3}))
        m1 = await sub.recv(60)
        m2 = await sub.recv(10)
        assert (m1.payload, m2.payload) == (b"first", b"second")
        assert m1.topic == m2.topic == "al/t"
        await c.close()
        await sub.close()


# -- subscription options (t_publish_rap, t_subscribe_no_local) -------------

async def test_retain_as_published():
    async with broker_node() as node:
        rap1 = TestClient("rap1", version=C.MQTT_V5)
        await rap1.connect(port=_port(node))
        await rap1.subscribe(("rap/t", {"qos": 0, "nl": 0, "rap": 1,
                                        "rh": 0}))
        rap0 = TestClient("rap0", version=C.MQTT_V5)
        await rap0.connect(port=_port(node))
        await rap0.subscribe("rap/t")
        pub = TestClient("rappub", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("rap/t", b"r", retain=True)
        m1 = await rap1.recv(60)
        m0 = await rap0.recv(10)
        assert m1.retain is True      # rap=1 keeps the flag
        assert m0.retain is False     # rap=0 clears it on routed pubs
        for c in (rap1, rap0, pub):
            await c.close()


async def test_no_local_over_wire():
    async with broker_node() as node:
        c = TestClient("nloc", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.subscribe(("nl/t", {"qos": 0, "nl": 1, "rap": 0, "rh": 0}))
        await c.publish("nl/t", b"self", timeout=60)
        other = TestClient("nloc2", version=C.MQTT_V5)
        await other.connect(port=_port(node))
        await other.publish("nl/t", b"peer", timeout=60)
        m = await c.recv(10)
        assert m.payload == b"peer"
        assert c.inbox.empty()
        await c.close()
        await other.close()


# -- batch subscribe (t_batch_subscribe) ------------------------------------

async def test_batch_subscribe_mixed_reason_codes():
    async with broker_node() as node:
        c = TestClient("batch", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        pid = c.next_pkt_id()
        await c.send(Subscribe(packet_id=pid, topic_filters=[
            ("ok/a", {"qos": 2, "nl": 0, "rap": 0, "rh": 0}),
            ("bad/#/mid", {"qos": 1, "nl": 0, "rap": 0, "rh": 0}),
            ("ok/b", {"qos": 1, "nl": 0, "rap": 0, "rh": 0}),
        ]))
        ack = await asyncio.wait_for(c.acks.get(), 5)
        assert ack.reason_codes == [2, 0x8F, 1]  # granted, invalid, granted
        await c.close()


# -- wildcard publish (t_publish_wildtopic) ---------------------------------

async def test_publish_to_wildcard_topic_rejected():
    async with broker_node() as node:
        c = TestClient("wildpub", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.send(Publish(topic="oops/#", payload=b"x", qos=0))
        pkt = await asyncio.wait_for(c.acks.get(), 5)
        assert isinstance(pkt, Disconnect)
        data = await asyncio.wait_for(c.reader.read(64), 5)
        assert data == b""  # server closed the socket
        await c.close()


# -- duplicate clientid (t_connect_duplicate_clientid) ----------------------

async def test_duplicate_clientid_kicks_old_connection():
    async with broker_node() as node:
        a = TestClient("dup", version=C.MQTT_V5)
        await a.connect(port=_port(node))
        b = TestClient("dup", version=C.MQTT_V5)
        await b.connect(port=_port(node))
        # old connection receives DISCONNECT 0x8E (session taken over)
        pkt = await asyncio.wait_for(a.acks.get(), 5)
        assert isinstance(pkt, Disconnect)
        assert pkt.reason_code == 0x8E
        assert await b.ping() is None
        await a.close()
        await b.close()


# -- overlapping subscriptions (t_publish_overlapping_subscriptions) --------

async def test_overlapping_subscriptions_deliver_per_subscription():
    async with broker_node() as node:
        c = TestClient("overlap", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.subscribe(("ov/+", {"qos": 2, "nl": 0, "rap": 0, "rh": 0}))
        await c.subscribe(("ov/#", {"qos": 1, "nl": 0, "rap": 0, "rh": 0}))
        pub = TestClient("ovpub", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("ov/x", b"m", qos=0)
        m1 = await c.recv(60)
        m2 = await c.recv(10)
        assert {m1.payload, m2.payload} == {b"m"}
        await c.close()
        await pub.close()
