"""MQTT v5 spec-conformance over live loopback TCP.

Mirrors the reference's ``test/mqtt_protocol_v5_SUITE.erl`` (756 LoC)
case by case where the behaviour is observable through a real client:
session expiry, will delay, topic aliases, RAP/no-local subscription
options, batch subscribe reason codes, wildcard-publish rejection,
duplicate clientid takeover, overlapping subscriptions.
"""

import asyncio
import contextlib
import time

from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.packet import Disconnect, Publish, Subscribe
from tests.helpers import broker_node, node_port as _port
from tests.mqtt_client import TestClient




# -- session expiry (t_connect_session_expiry_interval) ---------------------

async def test_session_expiry_interval_queues_offline():
    async with broker_node() as node:
        c1 = TestClient("sei1", version=C.MQTT_V5,
                        properties={"Session-Expiry-Interval": 7200})
        await c1.connect(port=_port(node))
        await c1.subscribe("sei/t", qos=2)
        await c1.disconnect()  # normal disconnect, session kept

        pub = TestClient("seipub", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("sei/t", b"while-away", qos=2, timeout=60)

        c2 = TestClient("sei1", version=C.MQTT_V5, clean_start=False,
                        properties={"Session-Expiry-Interval": 7200})
        ack = await c2.connect(port=_port(node))
        assert ack.session_present
        m = await c2.recv(10)
        assert m.payload == b"while-away" and m.qos == 2
        await c2.close()
        await pub.close()


async def test_disconnect_with_zero_expiry_drops_session():
    async with broker_node() as node:
        c1 = TestClient("sei0", version=C.MQTT_V5,
                        properties={"Session-Expiry-Interval": 7200})
        await c1.connect(port=_port(node))
        await c1.subscribe("sei0/t", qos=1)
        # DISCONNECT overriding expiry to 0 → session dropped now
        await c1.send(Disconnect(
            reason_code=0, properties={"Session-Expiry-Interval": 0}))
        await c1.close()
        await asyncio.sleep(0.1)

        c2 = TestClient("sei0", version=C.MQTT_V5, clean_start=False)
        ack = await c2.connect(port=_port(node))
        assert not ack.session_present
        await c2.close()


# -- will delay (t_connect_will_delay_interval) -----------------------------

async def test_will_delay_interval():
    async with broker_node() as node:
        watcher = TestClient("wdwatch", version=C.MQTT_V5)
        await watcher.connect(port=_port(node))
        await watcher.subscribe("wd/t")

        c = TestClient("wdc", version=C.MQTT_V5,
                       will_flag=True, will_topic="wd/t",
                       will_payload=b"gone",
                       will_props={"Will-Delay-Interval": 1},
                       properties={"Session-Expiry-Interval": 60})
        await c.connect(port=_port(node))
        c.writer.close()  # abnormal loss, no DISCONNECT
        t0 = time.time()
        with contextlib.suppress(asyncio.TimeoutError):
            await watcher.recv(0.4)
            raise AssertionError("will published before the delay")
        m = await watcher.recv(15)
        assert m.payload == b"gone"
        assert time.time() - t0 >= 0.8
        await watcher.close()


async def test_will_delay_cancelled_by_reconnect():
    async with broker_node() as node:
        watcher = TestClient("wdw2", version=C.MQTT_V5)
        await watcher.connect(port=_port(node))
        await watcher.subscribe("wd2/t")

        c = TestClient("wdc2", version=C.MQTT_V5,
                       will_flag=True, will_topic="wd2/t",
                       will_payload=b"gone",
                       will_props={"Will-Delay-Interval": 2},
                       properties={"Session-Expiry-Interval": 60})
        await c.connect(port=_port(node))
        c.writer.close()
        await asyncio.sleep(0.2)
        # reconnect before the delay elapses → will must not fire
        c2 = TestClient("wdc2", version=C.MQTT_V5, clean_start=False,
                        properties={"Session-Expiry-Interval": 60})
        await c2.connect(port=_port(node))
        with contextlib.suppress(asyncio.TimeoutError):
            m = await watcher.recv(3.0)
            raise AssertionError(f"will fired despite reconnect: {m}")
        await c2.close()
        await watcher.close()


# -- topic alias (t_publish_topic_alias) ------------------------------------

async def test_topic_alias_zero_is_protocol_error():
    async with broker_node() as node:
        c = TestClient("alias0", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.send(Publish(topic="t", payload=b"x", qos=0,
                             properties={"Topic-Alias": 0}))
        # server must DISCONNECT (0x94 topic alias invalid) and close
        pkt = await asyncio.wait_for(c.acks.get(), 5)
        assert isinstance(pkt, Disconnect)
        assert pkt.reason_code == 0x94
        await c.close()


async def test_topic_alias_reuse_across_publishes():
    async with broker_node() as node:
        sub = TestClient("aliassub", version=C.MQTT_V5)
        await sub.connect(port=_port(node))
        await sub.subscribe("al/t", qos=0)
        c = TestClient("aliasc", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.send(Publish(topic="al/t", payload=b"first", qos=0,
                             properties={"Topic-Alias": 3}))
        # empty topic + alias resolves to the registered one
        await c.send(Publish(topic="", payload=b"second", qos=0,
                             properties={"Topic-Alias": 3}))
        m1 = await sub.recv(60)
        m2 = await sub.recv(10)
        assert (m1.payload, m2.payload) == (b"first", b"second")
        assert m1.topic == m2.topic == "al/t"
        # MQTT-3.3.2-6: the PUBLISHER's alias is a per-connection
        # input artifact — a subscriber that advertised NO alias
        # support (Topic-Alias-Maximum absent -> 0) must never see a
        # Topic-Alias property (regression: the shared broadcast
        # frame once carried it through)
        assert "Topic-Alias" not in (m1.properties or {})
        assert "Topic-Alias" not in (m2.properties or {})
        await c.close()
        await sub.close()


# -- subscription options (t_publish_rap, t_subscribe_no_local) -------------

async def test_retain_as_published():
    async with broker_node() as node:
        rap1 = TestClient("rap1", version=C.MQTT_V5)
        await rap1.connect(port=_port(node))
        await rap1.subscribe(("rap/t", {"qos": 0, "nl": 0, "rap": 1,
                                        "rh": 0}))
        rap0 = TestClient("rap0", version=C.MQTT_V5)
        await rap0.connect(port=_port(node))
        await rap0.subscribe("rap/t")
        pub = TestClient("rappub", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("rap/t", b"r", retain=True)
        m1 = await rap1.recv(60)
        m0 = await rap0.recv(10)
        assert m1.retain is True      # rap=1 keeps the flag
        assert m0.retain is False     # rap=0 clears it on routed pubs
        for c in (rap1, rap0, pub):
            await c.close()


async def test_no_local_over_wire():
    async with broker_node() as node:
        c = TestClient("nloc", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.subscribe(("nl/t", {"qos": 0, "nl": 1, "rap": 0, "rh": 0}))
        await c.publish("nl/t", b"self", timeout=60)
        other = TestClient("nloc2", version=C.MQTT_V5)
        await other.connect(port=_port(node))
        await other.publish("nl/t", b"peer", timeout=60)
        m = await c.recv(10)
        assert m.payload == b"peer"
        assert c.inbox.empty()
        await c.close()
        await other.close()


# -- batch subscribe (t_batch_subscribe) ------------------------------------

async def test_batch_subscribe_mixed_reason_codes():
    async with broker_node() as node:
        c = TestClient("batch", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        pid = c.next_pkt_id()
        await c.send(Subscribe(packet_id=pid, topic_filters=[
            ("ok/a", {"qos": 2, "nl": 0, "rap": 0, "rh": 0}),
            ("bad/#/mid", {"qos": 1, "nl": 0, "rap": 0, "rh": 0}),
            ("ok/b", {"qos": 1, "nl": 0, "rap": 0, "rh": 0}),
        ]))
        ack = await asyncio.wait_for(c.acks.get(), 5)
        assert ack.reason_codes == [2, 0x8F, 1]  # granted, invalid, granted
        await c.close()


# -- wildcard publish (t_publish_wildtopic) ---------------------------------

async def test_publish_to_wildcard_topic_rejected():
    async with broker_node() as node:
        c = TestClient("wildpub", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.send(Publish(topic="oops/#", payload=b"x", qos=0))
        pkt = await asyncio.wait_for(c.acks.get(), 5)
        assert isinstance(pkt, Disconnect)
        data = await asyncio.wait_for(c.reader.read(64), 5)
        assert data == b""  # server closed the socket
        await c.close()


# -- duplicate clientid (t_connect_duplicate_clientid) ----------------------

async def test_duplicate_clientid_kicks_old_connection():
    async with broker_node() as node:
        a = TestClient("dup", version=C.MQTT_V5)
        await a.connect(port=_port(node))
        b = TestClient("dup", version=C.MQTT_V5)
        await b.connect(port=_port(node))
        # old connection receives DISCONNECT 0x8E (session taken over)
        pkt = await asyncio.wait_for(a.acks.get(), 5)
        assert isinstance(pkt, Disconnect)
        assert pkt.reason_code == 0x8E
        assert await b.ping() is None
        await a.close()
        await b.close()


# -- overlapping subscriptions (t_publish_overlapping_subscriptions) --------

async def test_overlapping_subscriptions_deliver_per_subscription():
    async with broker_node() as node:
        c = TestClient("overlap", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.subscribe(("ov/+", {"qos": 2, "nl": 0, "rap": 0, "rh": 0}))
        await c.subscribe(("ov/#", {"qos": 1, "nl": 0, "rap": 0, "rh": 0}))
        pub = TestClient("ovpub", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("ov/x", b"m", qos=0)
        m1 = await c.recv(60)
        m2 = await c.recv(10)
        assert {m1.payload, m2.payload} == {b"m"}
        await c.close()
        await pub.close()


# -- request/response (t_request_response) ----------------------------------

async def test_request_response_pattern():
    """Response-Topic + Correlation-Data flow end-to-end: the
    responder replies to the request's Response-Topic echoing its
    Correlation-Data (reference t_request_response via
    emqx_request_sender/handler)."""
    async with broker_node() as node:
        responder = TestClient("rr-resp", version=C.MQTT_V5)
        await responder.connect(port=_port(node))
        await responder.subscribe("svc/echo", qos=1)
        requester = TestClient("rr-req", version=C.MQTT_V5)
        await requester.connect(port=_port(node))
        await requester.subscribe("svc/replies/rr-req", qos=1)

        await requester.publish(
            "svc/echo", b"what-time", qos=1,
            props={"Response-Topic": "svc/replies/rr-req",
                   "Correlation-Data": b"req-42"})
        req = await responder.recv(10)
        assert req.properties["Response-Topic"] == "svc/replies/rr-req"
        assert req.properties["Correlation-Data"] == b"req-42"
        await responder.publish(
            req.properties["Response-Topic"], b"noon", qos=1,
            props={"Correlation-Data":
                   req.properties["Correlation-Data"]})
        resp = await requester.recv(10)
        assert resp.payload == b"noon"
        assert resp.properties["Correlation-Data"] == b"req-42"
        await responder.close()
        await requester.close()


# -- subscription identifiers (t_subscribe_subid) ---------------------------

async def test_subscription_identifier_delivered():
    async with broker_node() as node:
        c = TestClient("subid1", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.subscribe("sid/a", qos=1,
                          props={"Subscription-Identifier": 7})
        pub = TestClient("subidp", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("sid/a", b"x", qos=1)
        m = await c.recv(10)
        assert m.properties.get("Subscription-Identifier") == 7
        await c.close()
        await pub.close()


async def test_subscription_identifier_per_overlapping_sub():
    """Overlapping subscriptions deliver one PUBLISH per subscription,
    each carrying ITS subid."""
    async with broker_node() as node:
        c = TestClient("subid2", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.subscribe("sid/b/+", qos=0,
                          props={"Subscription-Identifier": 1})
        await c.subscribe("sid/b/#", qos=0,
                          props={"Subscription-Identifier": 2})
        pub = TestClient("subid2p", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("sid/b/x", b"y", qos=0)
        got = sorted([
            (await c.recv(10)).properties["Subscription-Identifier"],
            (await c.recv(10)).properties["Subscription-Identifier"]])
        assert got == [1, 2]
        await c.close()
        await pub.close()


async def test_subscription_identifier_on_shared_sub():
    async with broker_node() as node:
        c = TestClient("subid3", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.subscribe("$share/g1/sid/c", qos=1,
                          props={"Subscription-Identifier": 9})
        pub = TestClient("subid3p", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("sid/c", b"s", qos=1)
        m = await c.recv(10)
        assert m.properties.get("Subscription-Identifier") == 9
        await c.close()
        await pub.close()


# -- flow control (t_connect_limit_timeout / receive maximum) ---------------

async def test_receive_maximum_limits_inflight():
    """Receive-Maximum=2 on CONNECT: the server holds at most two
    unacked QoS1 deliveries in flight; acking releases the next."""
    async with broker_node() as node:
        c = TestClient("rm1", version=C.MQTT_V5, auto_ack=False,
                       properties={"Receive-Maximum": 2})
        await c.connect(port=_port(node))
        await c.subscribe("rm/t", qos=1)
        pub = TestClient("rmp", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        for i in range(5):
            await pub.publish("rm/t", b"%d" % i, qos=1)
        first = await c.recv(10)
        second = await c.recv(10)
        with contextlib.suppress(asyncio.TimeoutError):
            extra = await c.recv(0.7)
            raise AssertionError(f"third in-flight delivery: {extra}")
        # ack one → exactly one more arrives
        from emqx_tpu.mqtt.packet import PubAck
        await c.send(PubAck(type=C.PUBACK, packet_id=first.packet_id))
        third = await c.recv(10)
        assert third.payload == b"2"
        with contextlib.suppress(asyncio.TimeoutError):
            await c.recv(0.7)
            raise AssertionError("window exceeded after one ack")
        await c.close()
        await pub.close()


# -- message expiry on delivery (t_publish_message_expiry) ------------------

async def test_message_expiry_drops_queued_message():
    async with broker_node() as node:
        c1 = TestClient("mx1", version=C.MQTT_V5,
                        properties={"Session-Expiry-Interval": 7200})
        await c1.connect(port=_port(node))
        await c1.subscribe("mx/t", qos=1)
        await c1.disconnect()
        pub = TestClient("mxp", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("mx/t", b"fleeting", qos=1,
                          props={"Message-Expiry-Interval": 1})
        await pub.publish("mx/t", b"durable", qos=1,
                          props={"Message-Expiry-Interval": 3600})
        await asyncio.sleep(1.5)  # first expires in the queue
        c2 = TestClient("mx1", version=C.MQTT_V5, clean_start=False,
                        properties={"Session-Expiry-Interval": 7200})
        await c2.connect(port=_port(node))
        m = await c2.recv(10)
        assert m.payload == b"durable"
        # the survivor's expiry interval shrank while queued
        assert m.properties["Message-Expiry-Interval"] < 3600
        with contextlib.suppress(asyncio.TimeoutError):
            extra = await c2.recv(0.7)
            raise AssertionError(f"expired message delivered: {extra}")
        await c2.close()
        await pub.close()


# -- server-side topic alias out (t_publish_topic_alias) --------------------

async def test_server_assigns_outbound_topic_alias():
    """Client advertises Topic-Alias-Maximum: the server's first
    delivery carries topic + alias, repeats carry ONLY the alias
    (empty topic)."""
    async with broker_node() as node:
        c = TestClient("ta-out", version=C.MQTT_V5,
                       properties={"Topic-Alias-Maximum": 4})
        await c.connect(port=_port(node))
        await c.subscribe("ta/hot", qos=0)
        pub = TestClient("ta-outp", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("ta/hot", b"m1", qos=0)
        first = await c.recv(10)
        assert first.topic == "ta/hot"
        alias = first.properties.get("Topic-Alias")
        assert alias is not None
        await pub.publish("ta/hot", b"m2", qos=0)
        second = await c.recv(10)
        assert second.topic == ""                 # alias only
        assert second.properties["Topic-Alias"] == alias
        await c.close()
        await pub.close()


async def test_no_outbound_alias_without_client_maximum():
    async with broker_node() as node:
        c = TestClient("ta-none", version=C.MQTT_V5)  # no alias max
        await c.connect(port=_port(node))
        await c.subscribe("ta/cold", qos=0)
        pub = TestClient("ta-nonep", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("ta/cold", b"m", qos=0)
        await pub.publish("ta/cold", b"m2", qos=0)
        for _ in range(2):
            m = await c.recv(10)
            assert m.topic == "ta/cold"
            assert "Topic-Alias" not in m.properties
        await c.close()
        await pub.close()


# -- maximum packet size out (t_connack_max_packet_size) --------------------

async def test_client_maximum_packet_size_drops_oversized_delivery():
    async with broker_node() as node:
        c = TestClient("mps1", version=C.MQTT_V5,
                       properties={"Maximum-Packet-Size": 256})
        await c.connect(port=_port(node))
        await c.subscribe("mps/t", qos=0)
        pub = TestClient("mpsp", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("mps/t", b"x" * 1024, qos=0)   # too big: drop
        await pub.publish("mps/t", b"small", qos=0)
        m = await c.recv(10)
        assert m.payload == b"small"
        assert node.metrics.val("delivery.dropped.too_large") >= 1
        await c.close()
        await pub.close()


async def test_oversized_qos1_releases_inflight_window():
    """A size-dropped QoS1 delivery is 'discarded but acknowledged'
    (MQTT-3.1.2-24): its inflight slot frees, so later small
    messages still flow — the slot must not leak into a permanently
    wedged Receive-Maximum window."""
    async with broker_node() as node:
        c = TestClient("mps2", version=C.MQTT_V5,
                       properties={"Maximum-Packet-Size": 256,
                                   "Receive-Maximum": 2})
        await c.connect(port=_port(node))
        await c.subscribe("mps2/t", qos=1)
        pub = TestClient("mps2p", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        # fill the 2-slot window with oversized messages, twice over
        for _ in range(4):
            await pub.publish("mps2/t", b"x" * 1024, qos=1)
        for i in range(3):
            await pub.publish("mps2/t", b"ok%d" % i, qos=1)
        got = [await c.recv(10) for _ in range(3)]
        assert [m.payload for m in got] == [b"ok0", b"ok1", b"ok2"]
        assert node.metrics.val("delivery.dropped.too_large") == 4
        await c.close()
        await pub.close()


async def test_alias_overhead_falls_back_to_plain_topic():
    """A packet that fits the client's Maximum-Packet-Size only
    WITHOUT the Topic-Alias property is delivered plain; the alias
    assignment rolls back (the client must never later receive an
    alias whose defining packet was dropped)."""
    from emqx_tpu.mqtt.frame import serialize as ser
    from emqx_tpu.mqtt.packet import Publish as P

    topic, payload = "alb/t", b"p" * 64
    cap = len(ser(P(topic=topic, payload=payload, qos=0,
                    properties={}), C.MQTT_V5))
    async with broker_node() as node:
        c = TestClient("alb", version=C.MQTT_V5,
                       properties={"Topic-Alias-Maximum": 4,
                                   "Maximum-Packet-Size": cap})
        await c.connect(port=_port(node))
        await c.subscribe(topic, qos=0)
        pub = TestClient("albp", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        # exactly at cap without alias -> sent plain, no alias burned
        await pub.publish(topic, payload, qos=0)
        m1 = await c.recv(10)
        assert m1.topic == topic and "Topic-Alias" not in m1.properties
        # smaller payload fits WITH an alias -> alias established
        await pub.publish(topic, b"small", qos=0)
        m2 = await c.recv(10)
        assert m2.properties.get("Topic-Alias") is not None
        assert m2.topic == topic  # defining packet carries the name
        await pub.publish(topic, b"small2", qos=0)
        m3 = await c.recv(10)
        assert m3.topic == "" and "Topic-Alias" in m3.properties
        assert node.metrics.val("delivery.dropped.too_large") == 0
        await c.close()
        await pub.close()


# -- CONNACK capability properties (t_connack_max_qos_allowed) --------------

async def test_connack_maximum_qos_and_violation_disconnects():
    """Zone caps QoS at 1: CONNACK carries Maximum-QoS=1 and a QoS2
    PUBLISH is a protocol violation (MQTT-3.2.2-11: DISCONNECT 0x9B
    or close)."""
    from emqx_tpu.zone import Zone

    async with broker_node(zone=Zone(max_qos_allowed=1)) as node:
        c = TestClient("maxq", version=C.MQTT_V5)
        ack = await c.connect(port=_port(node))
        assert ack.properties.get("Maximum-QoS") == 1
        await c.publish("mq/ok", b"x", qos=1)  # allowed
        await c.send(Publish(topic="mq/bad", payload=b"x", qos=2,
                             packet_id=9))
        # server must refuse: either DISCONNECT 0x9B or socket close
        got = None
        with contextlib.suppress(asyncio.TimeoutError):
            got = await asyncio.wait_for(c.acks.get(), 3)
        if got is not None and isinstance(got, Disconnect):
            assert got.reason_code == 0x9B
        else:
            # socket close: the client read loop exits on EOF
            await asyncio.wait_for(c._task, 3)
        await c.close()


async def test_connack_server_keepalive_override():
    from emqx_tpu.zone import Zone

    async with broker_node(zone=Zone(server_keepalive=5)) as node:
        c = TestClient("ska", version=C.MQTT_V5, keepalive=300)
        ack = await c.connect(port=_port(node))
        assert ack.properties.get("Server-Keep-Alive") == 5
        await c.close()


# -- publish properties passthrough (t_publish_properties / _payload_ -------
# format_indicator / _response_topic)

async def test_publish_properties_passthrough():
    """v5 application properties travel intact broker→subscriber:
    payload format, content type, user properties, response topic,
    correlation data (MQTT-3.3.2)."""
    async with broker_node() as node:
        sub = TestClient("pp-sub", version=C.MQTT_V5)
        await sub.connect(port=_port(node))
        await sub.subscribe("pp/t", qos=1)
        pub = TestClient("pp-pub", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        props = {
            "Payload-Format-Indicator": 1,
            "Content-Type": "application/json",
            "Response-Topic": "pp/replies",
            "Correlation-Data": b"\x01\x02",
            "User-Property": [("k1", "v1"), ("k2", "v2")],
        }
        await pub.publish("pp/t", b'{"a":1}', qos=1, props=props,
                          timeout=60)
        m = await sub.recv(10)
        assert m.properties.get("Payload-Format-Indicator") == 1
        assert m.properties.get("Content-Type") == "application/json"
        assert m.properties.get("Response-Topic") == "pp/replies"
        assert m.properties.get("Correlation-Data") == b"\x01\x02"
        assert m.properties.get("User-Property") == [("k1", "v1"),
                                                     ("k2", "v2")]
        await sub.close()
        await pub.close()


# -- will flags + properties (t_connect_will_message / _will_retain) --------

async def test_will_message_flags_and_properties():
    async with broker_node() as node:
        watcher = TestClient("will-w", version=C.MQTT_V5)
        await watcher.connect(port=_port(node))
        # RAP so the will's retain flag is observable (MQTT-3.3.1-12)
        await watcher.subscribe(("wl/t", {"qos": 1, "rap": 1,
                                          "nl": 0, "rh": 0}), qos=1)
        dying = TestClient(
            "will-d", version=C.MQTT_V5,
            will_flag=True, will_topic="wl/t", will_payload=b"gone",
            will_qos=1, will_retain=True,
            will_props={"Content-Type": "text/plain"})
        await dying.connect(port=_port(node))
        dying.writer.close()  # abnormal close → will fires
        m = await watcher.recv(10)
        assert m.topic == "wl/t" and m.payload == b"gone"
        assert m.retain  # will retain flag preserved (RAP)
        assert m.properties.get("Content-Type") == "text/plain"
        await watcher.close()


# -- subscription option updates (t_subscribe_actions) ----------------------

async def test_resubscribe_updates_subscription_options():
    async with broker_node() as node:
        sub = TestClient("resub", version=C.MQTT_V5)
        await sub.connect(port=_port(node))
        ack = await sub.subscribe("ra/t", qos=2)
        assert ack.reason_codes == [2]
        pub = TestClient("resub-p", version=C.MQTT_V5)
        await pub.connect(port=_port(node))
        await pub.publish("ra/t", b"1", qos=2, timeout=60)
        m = await sub.recv(10)
        assert m.qos == 2
        # drain the inbound-QoS2 PUBREL the auto-ack flow queued
        await asyncio.sleep(0.2)
        while not sub.acks.empty():
            sub.acks.get_nowait()
        # resubscribe same filter at qos0: options replaced, not added
        ack = await sub.subscribe("ra/t", qos=0)
        assert ack.reason_codes == [0]
        await pub.publish("ra/t", b"2", qos=2)
        m = await sub.recv(10)
        assert m.qos == 0  # delivered at the NEW max qos
        # still exactly one subscription: one delivery per publish
        with contextlib.suppress(asyncio.TimeoutError):
            extra = await sub.recv(0.3)
            raise AssertionError(f"duplicate delivery {extra!r}")
        await sub.close()
        await pub.close()


async def test_unsubscribe_reason_codes():
    """UNSUBACK per-filter codes: 0x00 success, 0x11 no subscription
    existed (MQTT-3.11.3)."""
    async with broker_node() as node:
        c = TestClient("unsub", version=C.MQTT_V5)
        await c.connect(port=_port(node))
        await c.subscribe("un/t", qos=0)
        ack = await c.unsubscribe("un/t", "never/was")
        assert ack.reason_codes == [0x00, 0x11]
        await c.close()


# -- keepalive enforcement (t_connect_keepalive_timeout) --------------------

async def test_keepalive_timeout_closes_connection():
    """No control packets for 1.5× keepalive → server closes the
    network connection (MQTT-3.1.2-22)."""
    async with broker_node() as node:
        c = TestClient("ka1", version=C.MQTT_V5, keepalive=1)
        await c.connect(port=_port(node))
        t0 = time.monotonic()
        # the client read loop exits when the server closes on us
        await asyncio.wait_for(c._task, 10)
        elapsed = time.monotonic() - t0
        assert 0.9 <= elapsed <= 6.0
        # a PINGing client at the same keepalive stays up
        c2 = TestClient("ka2", version=C.MQTT_V5, keepalive=1)
        await c2.connect(port=_port(node))
        from emqx_tpu.mqtt.packet import Pingreq
        for _ in range(4):
            await asyncio.sleep(0.5)
            await c2.send(Pingreq())
        assert not c2.writer.is_closing()
        await c2.close()
