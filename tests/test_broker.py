"""Broker tests — modeled on reference test/emqx_broker_SUITE.erl:
subscribe/unsubscribe bookkeeping, publish/dispatch, hook veto,
shared-group dispatch, subscriber_down cleanup.
"""

from emqx_tpu.broker import Broker
from emqx_tpu.hooks import STOP
from emqx_tpu.types import Message, SubOpts


class Q:
    """Queue subscriber test double (the conn process stand-in)."""

    def __init__(self, client_id="c"):
        self.client_id = client_id
        self.inbox = []

    def deliver(self, topic, msg):
        self.inbox.append((topic, msg))


def test_subscribe_unsubscribe():
    b = Broker()
    s = Q()
    b.subscribe(s, "topic/a")
    b.subscribe(s, "topic/+")
    assert sorted(b.subscriptions(s)) == ["topic/+", "topic/a"]
    assert b.subscribers("topic/a") == [s]
    b.unsubscribe(s, "topic/a")
    assert sorted(b.subscriptions(s)) == ["topic/+"]
    b.unsubscribe(s, "topic/+")
    assert b.subscriptions(s) == {}
    assert not b.router.has_route("topic/a")


def test_publish_dispatch():
    b = Broker()
    s1, s2, s3 = Q("c1"), Q("c2"), Q("c3")
    b.subscribe(s1, "a/b/c")
    b.subscribe(s2, "a/+/c")
    b.subscribe(s3, "zzz")
    n = b.publish(Message(topic="a/b/c", payload=b"hi"))
    assert n == 2
    assert s1.inbox[0][0] == "a/b/c"
    assert s2.inbox[0][0] == "a/+/c"  # deliver carries the filter
    assert s2.inbox[0][1].topic == "a/b/c"
    assert s3.inbox == []


def test_publish_no_subscribers_counts_dropped():
    b = Broker()
    assert b.publish(Message(topic="lonely")) == 0
    assert b.metrics.val("messages.dropped.no_subscribers") == 1


def test_hook_veto_stops_publish():
    b = Broker()
    s = Q()
    b.subscribe(s, "t")

    def veto(msg):
        msg.set_header("allow_publish", False)
        return (STOP, msg)

    b.hooks.add("message.publish", veto)
    assert b.publish(Message(topic="t")) == 0
    assert s.inbox == []
    assert b.metrics.val("messages.dropped") == 1


def test_hook_rewrite_topic():
    b = Broker()
    s = Q()
    b.subscribe(s, "rewritten")

    def rw(msg):
        msg.topic = "rewritten"
        return msg

    b.hooks.add("message.publish", rw)
    assert b.publish(Message(topic="original")) == 1


def test_shared_dispatch_round_robin():
    b = Broker()
    s1, s2 = Q("c1"), Q("c2")
    b.subscribe(s1, "$share/g/t")
    b.subscribe(s2, "$share/g/t")
    for _ in range(4):
        b.publish(Message(topic="t"))
    assert len(s1.inbox) == 2
    assert len(s2.inbox) == 2


def test_queue_prefix_is_shared():
    b = Broker()
    s1, s2 = Q("c1"), Q("c2")
    b.subscribe(s1, "$queue/t")
    b.subscribe(s2, "$queue/t")
    total = sum(b.publish(Message(topic="t")) for _ in range(6))
    assert total == 6
    assert len(s1.inbox) + len(s2.inbox) == 6


def test_shared_and_plain_both_dispatch():
    b = Broker()
    plain, shared = Q("p"), Q("s")
    b.subscribe(plain, "t/#")
    b.subscribe(shared, "$share/g/t/1")
    n = b.publish(Message(topic="t/1"))
    assert n == 2
    assert len(plain.inbox) == 1 and len(shared.inbox) == 1


def test_no_local():
    b = Broker()
    s = Q("me")
    b.subscribe(s, "t", SubOpts(nl=1))
    assert b.publish(Message(topic="t", from_="me")) == 0
    assert b.publish(Message(topic="t", from_="other")) == 1
    assert b.metrics.val("delivery.dropped.no_local") == 1


def test_subscriber_down():
    b = Broker()
    s = Q()
    b.subscribe(s, "a/+")
    b.subscribe(s, "$share/g/b")
    b.subscriber_down(s)
    assert b.subscriptions(s) == {}
    assert b.publish(Message(topic="a/1")) == 0
    assert b.publish(Message(topic="b")) == 0
    assert not b.router.has_route("a/+")
    assert not b.router.has_route("b")


def test_forwarder_seam():
    b = Broker(node="n1")
    sent = []
    b.forwarder = lambda node, flt, msg: sent.append((node, flt))
    b.router.add_route("t/#", dest="n2")
    b.router.add_route("t/x", dest="n2")
    b.publish(Message(topic="t/x"))
    # one forward per matched (node, filter) route — aggre dedup
    assert sorted(sent) == [("n2", "t/#"), ("n2", "t/x")]


def test_shared_resubscribe_no_crash():
    b = Broker()
    s = Q()
    b.subscribe(s, "$share/g/t")
    b.subscribe(s, "$share/g/t")  # re-subscribe must not KeyError
    assert b.publish(Message(topic="t")) == 1
    assert len(s.inbox) == 1


def test_shared_and_plain_same_filter_coexist():
    b = Broker()
    s = Q()
    b.subscribe(s, "t")
    b.subscribe(s, "$share/g/t")
    assert b.publish(Message(topic="t")) == 2
    assert b.unsubscribe(s, "t")
    assert b.publish(Message(topic="t")) == 1  # shared leg remains
    assert b.unsubscribe(s, "$share/g/t")
    assert b.publish(Message(topic="t")) == 0
    assert b.subscriptions(s) == {}


def test_publish_topic_containing_plus_matches_once():
    b = Broker()
    s = Q()
    b.subscribe(s, "a/+")
    # '+' in a publish name is invalid MQTT, but must not double-match
    assert b.publish(Message(topic="a/+")) == 1


def test_publish_batch():
    b = Broker()
    s = Q()
    b.subscribe(s, "a/+")
    counts = b.publish_batch([
        Message(topic="a/1"), Message(topic="b/1"), Message(topic="a/2")])
    assert counts == [1, 0, 1]
    assert len(s.inbox) == 2


def test_package_facade():
    """Module-level subscribe/publish/hook — emqx.erl:26-64 parity on
    a process-default broker."""
    import emqx_tpu

    class S:
        def __init__(self):
            self.got = []

        def deliver(self, t, m):
            self.got.append(m.payload)

    # the default broker is process-global: use unique topics
    s = S()
    emqx_tpu.subscribe(s, "facade/+")
    n = emqx_tpu.publish(Message(topic="facade/x", payload=b"hi"))
    assert n == 1 and s.got == [b"hi"]
    assert emqx_tpu.unsubscribe(s, "facade/+")
    assert emqx_tpu.publish(Message(topic="facade/x")) == 0
    seen = []

    def on_pub(msg, acc=None):
        seen.append(msg.topic)
        return acc

    emqx_tpu.hook("message.publish", on_pub)
    emqx_tpu.publish(Message(topic="facade/hooked"))
    assert "facade/hooked" in seen
    emqx_tpu.unhook("message.publish", on_pub)
