"""Model-based stateful test of the outbound QoS window:
random interleavings of deliver / puback / pubrec / pubcomp / retry /
bad-acks against a reference model of the MQTT server->client flow
(the reference pins these semantics across emqx_session_SUITE +
emqx_inflight_SUITE; this explores the interleavings those example
tests cannot).

Invariants checked after every step:
  - inflight occupancy == model, never exceeds the window;
  - a packet id is never reused while in flight;
  - queued messages refill the window strictly FIFO;
  - acks for unknown ids / wrong phase raise SessionError;
  - retry re-emits exactly the in-flight set, DUP where applicable.
"""

import time

import pytest

# optional dependency: skip the model-based tier cleanly where
# hypothesis isn't installed (tier-1 hygiene)
pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from emqx_tpu.session import PUBREL_MARKER, Session, SessionError
from emqx_tpu.types import Message, SubOpts

WINDOW = 4

op = st.sampled_from(
    ["deliver1", "deliver2", "puback", "pubrec", "pubcomp", "retry",
     "bad_puback", "bad_pubcomp"])


@settings(max_examples=150, deadline=None)
@given(ops=st.lists(op, min_size=1, max_size=60),
       picks=st.lists(st.integers(0, 10**9), min_size=60, max_size=60))
def test_session_qos_window_model(ops, picks):
    s = Session("model", max_inflight=WINDOW, max_mqueue_len=100,
                retry_interval=30.0)
    s.subscriptions["t/#"] = SubOpts(qos=2)
    model = {}          # pid -> (phase, serial)
    fifo = []           # serials queued behind a full window
    serial = 0
    clock = time.time()  # logical time: each retry advances past the
    # interval so every in-flight entry is due again

    def drain(expect_serials=None):
        got = []
        for pid, msg in s.drain_outbox():
            if pid == PUBREL_MARKER or pid is None:
                continue
            sr = int(msg.payload)
            assert pid not in model, f"pid {pid} reused while in flight"
            model[pid] = ("pub1" if msg.qos == 1 else "pub2", sr)
            got.append(sr)
        if expect_serials is not None:
            assert got == expect_serials  # FIFO refill order
        return got

    def pick(seq, i):
        seq = sorted(seq)
        return seq[picks[i % len(picks)] % len(seq)] if seq else None

    for i, o in enumerate(ops):
        if o in ("deliver1", "deliver2"):
            serial += 1
            qos = 1 if o == "deliver1" else 2
            s.deliver("t/#", Message(topic="t/x",
                                     payload=str(serial).encode(),
                                     qos=qos))
            if len(model) < WINDOW:
                drain(expect_serials=[serial])
            else:
                drain(expect_serials=[])
                fifo.append(serial)
        elif o == "puback":
            pid = pick([p for p, (ph, _) in model.items()
                        if ph == "pub1"], i)
            if pid is None:
                continue
            s.puback(pid)
            del model[pid]
            refill = fifo[: WINDOW - len(model)]
            del fifo[: len(refill)]
            drain(expect_serials=refill)
        elif o == "pubrec":
            pid = pick([p for p, (ph, _) in model.items()
                        if ph == "pub2"], i)
            if pid is None:
                continue
            s.pubrec(pid)
            model[pid] = ("rel", model[pid][1])
        elif o == "pubcomp":
            pid = pick([p for p, (ph, _) in model.items()
                        if ph == "rel"], i)
            if pid is None:
                continue
            s.pubcomp(pid)
            del model[pid]
            refill = fifo[: WINDOW - len(model)]
            del fifo[: len(refill)]
            drain(expect_serials=refill)
        elif o == "retry":
            clock += 60
            s.retry(now=clock)
            # re-emissions only: every pub-phase message comes back
            # with DUP, RELs as markers; nothing NEW may appear
            redone = []
            rels = []
            for pid, msg in s.drain_outbox():
                if pid == PUBREL_MARKER:
                    rels.append(msg)  # payload slot carries the pid
                    continue
                assert msg.flags.get("dup"), "retry must set DUP"
                assert model[pid][0] in ("pub1", "pub2")
                redone.append(pid)
            assert sorted(redone) == sorted(
                p for p, (ph, _) in model.items() if ph != "rel")
            assert sorted(rels) == sorted(
                p for p, (ph, _) in model.items() if ph == "rel")
        elif o == "bad_puback":
            free = next(p for p in range(1, 70000)
                        if p not in model)
            with pytest.raises(SessionError):
                s.puback(free)
            rel = [p for p, (ph, _) in model.items() if ph == "rel"]
            if rel:
                with pytest.raises(SessionError):
                    s.puback(rel[0])  # wrong phase
        elif o == "bad_pubcomp":
            pub = [p for p, (ph, _) in model.items()
                   if ph in ("pub1", "pub2")]
            if pub:
                with pytest.raises(SessionError):
                    s.pubcomp(pub[0])  # not in REL phase

        # global invariants
        assert len(s.inflight) == len(model) <= WINDOW
        assert len(s.mqueue) == len(fifo)
        assert sorted(s.inflight.keys()) == sorted(model)



