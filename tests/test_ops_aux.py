"""Host monitors, PSK lookup, GC policies, logger metadata
(emqx_os_mon / emqx_vm_mon / emqx_sys_mon / emqx_psk / emqx_gc /
emqx_logger parity)."""

import logging

from emqx_tpu import logger as elog
from emqx_tpu.alarm import AlarmManager
from emqx_tpu.gc import GcPolicy, GlobalGc
from emqx_tpu.hooks import Hooks
from emqx_tpu.monitors import (OsMon, SysMon, VmMon, read_cpu_times,
                               read_mem_usage)
from emqx_tpu.psk import PskAuth


# -- os_mon -----------------------------------------------------------------

def test_os_mon_cpu_watermarks():
    alarms = AlarmManager()
    mon = OsMon(alarms, cpu_high=0.8, cpu_low=0.6)
    mon.check(0.9, None)
    assert any(a.name == "high_cpu_usage"
               for a in alarms.get_alarms("activated"))
    mon.check(0.7, None)  # between: hysteresis, stays active
    assert any(a.name == "high_cpu_usage"
               for a in alarms.get_alarms("activated"))
    mon.check(0.5, None)
    assert not alarms.get_alarms("activated")


def test_os_mon_mem_watermarks():
    alarms = AlarmManager()
    mon = OsMon(alarms, mem_high=0.8, mem_low=0.6)
    mon.check(None, 0.95)
    assert any(a.name == "high_memory_usage"
               for a in alarms.get_alarms("activated"))
    mon.check(None, 0.3)
    assert not alarms.get_alarms("activated")


def test_os_mon_proc_readers():
    # live /proc readings on Linux: sane ranges
    cpu = read_cpu_times()
    assert cpu is None or (cpu[1] >= cpu[0] >= 0)
    mem = read_mem_usage()
    assert mem is None or 0.0 <= mem <= 1.0
    # a second CPU sample yields a usage fraction
    mon = OsMon(AlarmManager())
    mon.sample_cpu()
    u = mon.sample_cpu()
    assert u is None or 0.0 <= u <= 1.0


# -- vm_mon -----------------------------------------------------------------

def test_vm_mon_count_watermark():
    alarms = AlarmManager()
    mon = VmMon(alarms, count_fn=lambda: 0, max_count=100,
                high=0.8, low=0.6)
    mon.check(90)
    assert any(a.name == "too_many_processes"
               for a in alarms.get_alarms("activated"))
    mon.check(50)
    assert not alarms.get_alarms("activated")


# -- sys_mon ----------------------------------------------------------------

def test_sys_mon_long_schedule_and_gc():
    hooks = Hooks()
    events = []
    hooks.add("sysmon.long_schedule", lambda ms: events.append(ms))
    mon = SysMon(hooks=hooks, long_schedule_ms=100.0)
    mon.check_lag(1.0, 1.05)   # 50ms lag: fine
    assert mon.long_schedule_count == 0
    mon.check_lag(1.0, 1.5)    # 500ms lag
    assert mon.long_schedule_count == 1 and events == [500.0]
    mon.on_long_gc(150.0)
    assert mon.long_gc_count == 1


def test_sys_mon_gc_hook_install_remove():
    import gc
    mon = SysMon()
    mon.install_gc_hook()
    assert mon._on_gc in gc.callbacks
    gc.collect()  # must not raise through the callback
    mon.remove_gc_hook()
    assert mon._on_gc not in gc.callbacks


# -- psk --------------------------------------------------------------------

def test_psk_lookup_and_chain():
    hooks = Hooks()
    auth = PskAuth(hooks, {"dev1": b"secret1"})
    assert auth.lookup("dev1") == b"secret1"
    assert auth.lookup("ghost") is None
    auth.add("dev2", b"k2")
    assert auth.lookup("dev2") == b"k2"
    auth.remove("dev2")
    assert auth.lookup("dev2") is None
    # a second resolver fills misses; the first keeps priority
    PskAuth(hooks, {"dev1": b"shadowed", "dev3": b"k3"})
    assert auth.lookup("dev1") == b"secret1"
    assert auth.lookup("dev3") == b"k3"


# -- gc ---------------------------------------------------------------------

def test_gc_policy_triggers():
    p = GcPolicy(count=10, bytes_=1000)
    for _ in range(9):
        assert not p.inc(1, 10)
    assert p.inc(1, 10)          # count trigger
    assert p.collections == 1
    assert p.inc(1, 2000)        # bytes trigger
    assert p.collections == 2


def test_global_gc_runs():
    g = GlobalGc(interval=None)
    freed = g.run_gc()
    assert g.runs == 1 and freed >= 0


# -- logger -----------------------------------------------------------------

def test_logger_metadata_and_formatter():
    elog.clear_metadata()
    elog.set_metadata_clientid("c1")
    elog.set_metadata_peername(("10.0.0.1", 4321))
    assert elog.get_metadata() == {"clientid": "c1",
                                   "peername": "10.0.0.1:4321"}
    rec = logging.LogRecord("emqx_tpu.x", logging.INFO, "f", 1,
                            "hello %s", ("world",), None)
    assert elog.MetadataFilter().filter(rec)
    line = elog.BrokerFormatter().format(rec)
    assert "c1@10.0.0.1:4321 hello world" in line
    elog.clear_metadata()
    rec2 = logging.LogRecord("emqx_tpu.x", logging.INFO, "f", 1,
                             "plain", (), None)
    elog.MetadataFilter().filter(rec2)
    line2 = elog.BrokerFormatter().format(rec2)
    assert line2.endswith("plain") and "@" not in line2


def test_logger_setup_attaches_handler():
    sink = []

    class ListHandler(logging.Handler):
        def emit(self, record):
            sink.append(self.format(record))

    h = elog.setup(level=logging.DEBUG, handler=ListHandler())
    try:
        elog.set_metadata_clientid("cX")
        logging.getLogger("emqx_tpu.test").info("msg")
        assert any("cX" in line and "msg" in line for line in sink)
    finally:
        logging.getLogger("emqx_tpu").removeHandler(h)
        elog.clear_metadata()


def test_vm_introspection():
    from emqx_tpu import vm
    info = vm.get_system_info()
    assert info["cpu_count"] >= 1
    assert info["memory"]["rss"] > 0
    assert info["process"]["threads"] >= 1
    assert len(info["load"]) == 3
    assert isinstance(info["devices"], list)


def test_ctl_vm_command():
    from emqx_tpu.node import Node
    n = Node(boot_listeners=False)
    out = n.ctl.run(["vm"])
    assert '"cpu_count"' in out and '"rss"' in out


# -- profiling (SURVEY §5 tracing/profiling: jax-profiler + kernel timing) --

def test_kernel_timer_spans_and_stats():
    import jax.numpy as jnp

    from emqx_tpu.profiling import KernelTimer

    t = KernelTimer()
    for _ in range(5):
        with t.span("mul") as done:
            done(jnp.ones((64, 64)) * 2.0)
    t.record("host_phase", 1.5)
    st = t.stats()
    assert st["mul"]["count"] == 5
    assert st["mul"]["p99_ms"] >= st["mul"]["p50_ms"] >= 0
    assert st["host_phase"]["total_ms"] == 1.5
    t.reset()
    assert t.stats() == {}


def test_profiler_trace_writes_artifacts(tmp_path):
    import jax
    import jax.numpy as jnp

    from emqx_tpu.profiling import trace

    logdir = str(tmp_path / "trace")
    with trace(logdir):
        jax.block_until_ready(jnp.ones((32, 32)) @ jnp.ones((32, 32)))
    import os
    found = [os.path.join(dp, f) for dp, _, fs in os.walk(logdir)
             for f in fs]
    assert found, "profiler wrote no trace artifacts"


def test_rebuild_recorded_in_kernel_timer():
    from emqx_tpu.profiling import timer
    from emqx_tpu.router import MatcherConfig, Router

    timer.reset()
    r = Router(MatcherConfig(device_min_filters=0))
    r.add_route("prof/+")
    r.match_filters(["prof/x"])
    st = timer.stats()
    assert st.get("automaton.rebuild", {}).get("count", 0) >= 1
