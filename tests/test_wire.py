"""The data-only cluster wire codec (emqx_tpu.wire): round-trips,
session transfer, and the security property the round-4 pickle wire
lacked — a hostile frame cannot execute code, it can only fail to
decode (reference analogue: Erlang term transfer is data, not code).
"""

import math
import pickle

import pytest

from emqx_tpu import wire
from emqx_tpu.session import PUBREL_MARKER, Session
from emqx_tpu.types import Message, SubOpts


def rt(x):
    return wire.loads(wire.dumps(x))


def test_scalar_roundtrip():
    for v in (None, True, False, 0, -1, 7, 1 << 40, -(1 << 62),
              (1 << 80), 0.5, -2.25, float("inf"), "", "topic/a",
              "ünïcode/…", b"", b"\x00\xff payload", "pubrel"):
        got = rt(v)
        assert got == v and type(got) is type(v), v
    assert math.isnan(rt(float("nan")))


def test_container_roundtrip():
    v = {"a": [1, (2, 3), {"x": b"y"}],
         7: ("mixed", None, [set([1, 2]), frozenset(["z"])]),
         None: {1.5: "prio", float("inf"): "top"}}
    got = rt(v)
    assert got == v
    # tuple/list distinction survives (handle_rpc unpacks positionally)
    assert isinstance(got["a"][1], tuple)
    assert isinstance(got[7][2][0], set)
    assert isinstance(got[7][2][1], frozenset)


def test_message_roundtrip():
    m = Message(topic="a/b", payload=b"\x01\x02", qos=1, from_="c1",
                flags={"retain": True},
                headers={"properties": {"Message-Expiry-Interval": 9},
                         "peerhost": "1.2.3.4"})
    got = rt(m)
    assert isinstance(got, Message)
    assert (got.topic, got.payload, got.qos, got.from_) == \
        ("a/b", b"\x01\x02", 1, "c1")
    assert got.flags == m.flags and got.headers == m.headers
    assert got.id == m.id and got.timestamp == m.timestamp


def test_subopts_roundtrip():
    o = SubOpts(qos=2, nl=1, rap=1, rh=2, share="g1", subid="s9")
    got = rt(o)
    assert isinstance(got, SubOpts) and got == o


def test_session_roundtrip():
    s = Session("c-wire", clean_start=False, max_inflight=8,
                max_mqueue_len=50, mqueue_store_qos0=True,
                mqueue_priorities={"hot/t": 5}, expiry_interval=120.0)
    s.subscriptions = {"a/+": SubOpts(qos=1),
                       "b/#": SubOpts(qos=2, share="g")}
    s.inflight.insert(3, (Message(topic="a/x", qos=1), 123.0))
    s.inflight.insert(5, (PUBREL_MARKER, 124.0))
    s.awaiting_rel = {9: 125.0}
    s.next_pkt_id = 77
    s.mqueue.push(Message(topic="hot/t", qos=1, payload=b"p1"))
    s.mqueue.push(Message(topic="cold/t", qos=1, payload=b"p2"))
    s.outbox.append((None, Message(topic="o/t", qos=0)))
    s.outbox.append((PUBREL_MARKER, 5))

    got = rt(s)
    assert isinstance(got, Session)
    assert got.client_id == "c-wire" and not got.connected
    assert got.broker is None and got.notify is None
    assert set(got.subscriptions) == {"a/+", "b/#"}
    assert got.subscriptions["b/#"].share == "g"
    assert got.next_pkt_id == 77
    assert got.inflight.lookup(5) == (PUBREL_MARKER, 124.0)
    m3 = got.inflight.lookup(3)
    assert isinstance(m3[0], Message) and m3[0].topic == "a/x"
    assert got.awaiting_rel == {9: 125.0}
    assert len(got.mqueue) == 2
    first = got.mqueue.pop()
    assert first.topic == "hot/t"  # priority order preserved
    assert got.outbox[1] == (PUBREL_MARKER, 5)


def test_unencodable_raises_at_sender():
    class Evil:
        pass

    with pytest.raises(wire.WireError):
        wire.dumps(Evil())
    with pytest.raises(wire.WireError):
        wire.dumps(lambda: 1)  # callables never cross the wire


def test_malicious_frame_cannot_execute_code(tmp_path):
    """A pickle bomb (the round-4 wire's RCE vector) fed to the new
    decoder must raise, not execute. The payload, if unpickled, would
    create a file — assert it does not exist after decode fails."""
    marker = tmp_path / "pwned"

    class Bomb:
        def __reduce__(self):
            import os
            return (os.system, (f"touch {marker}",))

    payload = pickle.dumps(Bomb())
    with pytest.raises(wire.WireError):
        wire.loads(payload)
    assert not marker.exists()
    # malformed-but-valid-JSON shapes fail cleanly too
    for bad in (b"{\"a\": 1}", b"[\"Z\", 1]", b"[\"M\", []]",
                b"[\"t\"]", b"\xff\xfe", b"[[1,2],3]"):
        with pytest.raises(wire.WireError):
            wire.loads(bad)


def test_frame_decoder_never_builds_objects_from_names():
    """Defense-in-depth probe: frames naming importable paths decode
    to plain strings (or fail), never to live objects."""
    got = wire.loads(wire.dumps(("os.system", "builtins.eval")))
    assert got == ("os.system", "builtins.eval")
    assert all(isinstance(x, str) for x in got)

def test_dumps_lone_surrogate_raises_wireerror():
    # json.dumps accepts the string; the utf-8 encode step raises
    # UnicodeEncodeError — dumps() must keep its WireError contract
    with pytest.raises(wire.WireError):
        wire.dumps("bad \ud800 payload")
    with pytest.raises(wire.WireError):
        wire.dumps({"k": ["nested \udfff"]})


def test_dumps_deep_structure_raises_wireerror():
    import sys

    x = "leaf"
    for _ in range(sys.getrecursionlimit() * 2):
        x = [x]
    with pytest.raises(wire.WireError):
        wire.dumps(x)
