"""CLI + observability suites: ctl command registry/builtins
(emqx_ctl_SUITE), metrics catalog (emqx_metrics_SUITE), logger
metadata/formatter (emqx_logger_SUITE), host/runtime introspection
(emqx_vm_SUITE)."""

import logging

from emqx_tpu import logger as L
from emqx_tpu import vm
from emqx_tpu.metrics import Metrics
from emqx_tpu.node import Node
from emqx_tpu.types import Message


# -- emqx_ctl ---------------------------------------------------------------

async def test_ctl_registry_and_builtins():
    n = Node(boot_listeners=False)
    await n.start()
    try:
        ctl = n.ctl
        # custom command registration (emqx_ctl:register_command)
        ctl.register_command("hello", lambda args: f"hi {args}")
        assert "hi ['x']" == ctl.run(["hello", "x"])
        ctl.unregister_command("hello")
        out = ctl.run(["hello"])
        assert "unknown" in out.lower() or "usage" in out.lower()
        # builtins respond with real state
        assert "node:" in ctl.run(["status"])
        assert "MQTT broker" in ctl.run(["broker"])
        s = Sub()
        n.broker.subscribe(s, "ctl/t")
        assert "ctl/t" in ctl.run(["topics"])
        assert "ctl/t" in ctl.run(["routes"])
        n.metrics.inc("messages.received")
        metrics_out = ctl.run(["metrics"])
        assert "messages.received" in metrics_out
        assert ctl.run(["vm"])  # introspection renders
        assert "commands:" in ctl.usage()
    finally:
        await n.stop()


class Sub:
    client_id = "ctl-sub"

    def deliver(self, f, m):
        pass


async def test_ctl_log_level_runtime():
    n = Node(boot_listeners=False)
    await n.start()
    try:
        out = n.ctl.run(["log", "set-level", "debug"])
        assert "debug" in out.lower()
        assert logging.getLogger("emqx_tpu").level == logging.DEBUG
        n.ctl.run(["log", "set-level", "warning"])
        assert logging.getLogger("emqx_tpu").level == logging.WARNING
    finally:
        n.ctl.run(["log", "set-level", "info"])
        await n.stop()


# -- emqx_metrics -----------------------------------------------------------

def test_metrics_catalog_and_qos_counters():
    m = Metrics()
    # the standard catalog is pre-registered (emqx_metrics.erl:82-183)
    names = m.names()
    for expected in ("messages.received", "messages.sent",
                     "messages.dropped", "delivery.dropped.queue_full",
                     "packets.connect.received"):
        assert expected in names, expected
    m.inc_msg(Message(topic="t", qos=1))
    m.inc_msg(Message(topic="t", qos=2))
    m.inc_sent(Message(topic="t", qos=0))
    assert m.val("messages.received") == 2
    assert m.val("messages.qos1.received") == 1
    assert m.val("messages.qos2.received") == 1
    assert m.val("messages.sent") == 1
    assert m.val("messages.qos0.sent") == 1
    m.inc("messages.dropped", 5)
    m.dec("messages.dropped", 2)
    assert m.val("messages.dropped") == 3
    assert m.all()["messages.dropped"] == 3


def test_metrics_device_fold():
    m = Metrics()
    m.fold_device_stats({"matches": 10, "deliveries": 30,
                         "overflows": 1})
    m.fold_device_stats({"matches": 5, "deliveries": 5, "overflows": 0})
    assert m.val("device.matches") == 15
    assert m.val("device.deliveries") == 35
    assert m.val("device.overflows") == 1


def test_metrics_dynamic_registration():
    m = Metrics()
    m.new("custom.counter")
    m.inc("custom.counter")
    assert m.val("custom.counter") == 1


# -- emqx_logger ------------------------------------------------------------

def test_logger_metadata_injection():
    L.clear_metadata()
    L.set_metadata_clientid("c-42")
    L.set_metadata_peername(("10.0.0.9", 1883))
    md = L.get_metadata()
    assert md["clientid"] == "c-42"
    assert "10.0.0.9" in str(md["peername"])
    rec = logging.LogRecord("emqx_tpu.test", logging.INFO, "f", 1,
                            "connected", (), None)
    f = L.MetadataFilter()
    f.filter(rec)
    out = L.BrokerFormatter().format(rec)
    assert "c-42" in out and "connected" in out
    L.clear_metadata()
    rec2 = logging.LogRecord("emqx_tpu.test", logging.INFO, "f", 1,
                             "anon", (), None)
    f.filter(rec2)
    assert "c-42" not in L.BrokerFormatter().format(rec2)


def test_logger_setup_idempotent():
    lg = logging.getLogger("emqx_tpu")
    before = list(lg.handlers)
    try:
        L.setup()
        n1 = len(lg.handlers)
        L.setup()
        assert len(lg.handlers) == n1  # no duplicate handlers
        # explicit-handler path dedupes too (ADVICE round-1 item)
        h = logging.StreamHandler()
        h.setFormatter(L.BrokerFormatter())
        L.setup(handler=h)
        n2 = len(lg.handlers)
        L.setup(handler=h)
        assert len(lg.handlers) == n2
    finally:
        lg.handlers = before


# -- emqx_vm ----------------------------------------------------------------

def test_vm_introspection_shapes():
    mem = vm.get_memory()
    assert mem.get("rss", 0) > 0 and mem.get("vms", 0) > 0
    pi = vm.get_process_info()
    assert pi.get("threads", 0) >= 1
    assert vm.cpu_count() >= 1
    assert len(vm.loads()) == 3
    gc = vm.get_gc_info()
    assert "collections" in gc or gc
    sysinfo = vm.get_system_info()
    assert sysinfo.get("python") and sysinfo.get("cpu_count")
    devs = vm.get_device_info()
    assert isinstance(devs, list)  # device list renders (may be CPU)


# -- publish-path telemetry ctl (ISSUE 2) -----------------------------------


async def test_ctl_telemetry_stages_slow_reset():
    from emqx_tpu.types import Message as _Msg

    n = Node(boot_listeners=False, batch_ingress=False)
    await n.start()
    try:
        s = Sub()
        n.broker.subscribe(s, "tel/t")
        n.publish(_Msg(topic="tel/t"))
        out = n.ctl.run(["telemetry"])
        assert "stage" in out and "end_to_end" in out
        assert "p99_ms" in out
        assert n.ctl.run(["telemetry", "slow"]) == "(none)"
        # force a slow batch into the ring, then read + reset it
        n.telemetry.config.slow_threshold_ms = 0.0
        n.publish(_Msg(topic="tel/t"))
        assert "end_to_end_ms" in n.ctl.run(["telemetry", "slow"])
        assert n.ctl.run(["telemetry", "reset"]) == "ok"
        assert n.ctl.run(["telemetry", "slow"]) == "(none)"
        assert "error" in n.ctl.run(["telemetry", "nope"])
    finally:
        await n.stop()
