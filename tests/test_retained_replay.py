"""Device-resident retained replay (PR 19, docs/DISPATCH.md
"Retained replay"): batched subscribe-time matching parity against
the ``T.match`` host oracle (lax AND forced-Pallas variants),
planner-egress replay wire/metric parity (planner on/off, loops=1
vs 2), the ≤1-wakeup / onloop==0 delivery contract, device-path will
batching, and devloss riding of the retain index."""

import asyncio
import json
import random

import pytest

from emqx_tpu import topic as T
from emqx_tpu.broker import DispatchConfig
from emqx_tpu.modules.retainer import RetainerModule, RetainIndex
from emqx_tpu.mqtt import constants as C
from emqx_tpu.node import Node
from emqx_tpu.types import Message

from mqtt_client import TestClient


# -- batched kernel vs host oracle: differential fuzz ------------------------

_WORDS = ["a", "b", "c", "sensor", "west", "x", "$SYS", "$priv", ""]


def _rand_topic(rng, max_depth=20):
    return "/".join(rng.choice(_WORDS[:-1])
                    for _ in range(rng.randint(1, max_depth)))


def _rand_filter(rng):
    depth = rng.randint(1, 19)
    ws = [rng.choice(_WORDS + ["+"]) for _ in range(depth)]
    if rng.random() < 0.4:
        ws.append("#")
    return "/".join(ws)


def _oracle(live, flt):
    return sorted(t for t in live if T.match(t, flt))


def _fuzz_index(rng, n=350):
    idx = RetainIndex()
    live = set()
    for _ in range(n):
        t = _rand_topic(rng)
        idx.add(t)
        live.add(t)
    for t in rng.sample(sorted(live), n // 3):
        idx.remove(t)
        live.discard(t)
    for _ in range(n // 8):  # slot reuse
        t = _rand_topic(rng)
        idx.add(t)
        live.add(t)
    return idx, live


def _burst(rng, live):
    """A mixed burst: random filters + exact live names + edge
    shapes ($-roots, root wildcards, deeper-than-L, duplicates)."""
    flts = [_rand_filter(rng) for _ in range(rng.randint(1, 9))]
    flts += rng.sample(sorted(live), min(2, len(live)))
    flts += ["#", "+/+", "$SYS/#", "/".join(["+"] * 18) + "/#"]
    flts.append(flts[0])  # duplicate in-burst
    rng.shuffle(flts)
    return flts


@pytest.mark.parametrize("variant", ["lax", "pallas"])
def test_match_many_fuzz_parity(monkeypatch, variant):
    """Exact oracle parity of the BATCHED device match across mixed
    bursts, for both kernel variants (the forced-Pallas run goes
    through interpret mode on CPU — slow, byte-exact)."""
    monkeypatch.setenv("EMQX_TPU_WALK", variant)
    rng = random.Random(77 if variant == "lax" else 78)
    rounds = 6 if variant == "lax" else 2  # interpret mode is slow
    for _ in range(rounds):
        idx, live = _fuzz_index(rng)
        flts = _burst(rng, live)
        got = idx.match_many(flts, device_threshold=0)
        assert len(got) == len(flts)
        for flt, hits in zip(flts, got):
            assert sorted(hits) == _oracle(live, flt), (variant, flt)
        assert idx._last_batch == len(flts)


def test_match_many_lax_pallas_byte_parity(monkeypatch):
    """Same index, same burst, both kernels: identical hit lists
    (the Pallas tiles are a pure reimplementation, pinned here)."""
    rng = random.Random(5)
    idx, live = _fuzz_index(rng, n=300)
    flts = _burst(rng, live)
    monkeypatch.setenv("EMQX_TPU_WALK", "lax")
    lax = idx.match_many(flts, device_threshold=0)
    monkeypatch.setenv("EMQX_TPU_WALK", "pallas")
    pal = idx.match_many(flts, device_threshold=0)
    assert [sorted(h) for h in lax] == [sorted(h) for h in pal]


def test_match_many_interleaved_mutations():
    """add/remove between bursts exercises the dirty-row patch path
    under the batched kernel."""
    rng = random.Random(11)
    idx = RetainIndex()
    live = set()
    for i in range(300):
        t = f"i/{rng.randint(0, 40)}/r{i}"
        idx.add(t)
        live.add(t)
    idx.match_many(["i/#"], device_threshold=0)  # build device cache
    for step in range(12):
        for _ in range(4):
            if live and rng.random() < 0.5:
                t = rng.choice(sorted(live))
                idx.remove(t)
                live.discard(t)
            else:
                t = f"i/{rng.randint(0, 40)}/n{step}_{rng.randint(0, 99)}"
                idx.add(t)
                live.add(t)
        flts = ["i/#", "i/3/+", "#", f"i/{step}/+"]
        got = idx.match_many(flts, device_threshold=0)
        for flt, hits in zip(flts, got):
            assert sorted(hits) == _oracle(live, flt), (step, flt)


# -- devloss riding ----------------------------------------------------------

class _FakeRouter:
    def __init__(self):
        self.suspended = False

    def device_suspended(self):
        return self.suspended


def test_retain_index_devloss_suspension_and_breaker_reset():
    """Suspended device plane → host scan + cached matrix dropped;
    suspension lifting (rebuild_complete ran) → the failure breaker
    resets and the device path resumes."""
    idx = RetainIndex()
    router = _FakeRouter()
    idx.attach_router(router)
    live = {f"d/{i}" for i in range(50)}
    for t in live:
        idx.add(t)
    assert sorted(idx.match("d/+", device_threshold=0)) == sorted(live)
    assert idx._dev is not None  # device cache built
    idx._device_broken = 2  # two strikes before the devloss
    router.suspended = True
    assert sorted(idx.match("d/+", device_threshold=0)) == sorted(live)
    assert idx._dev is None  # dropped: its HBM refs may be dead
    assert idx._suspended_seen
    assert idx._device_broken == 2  # no strikes burned while down
    router.suspended = False
    assert sorted(idx.match("d/+", device_threshold=0)) == sorted(live)
    assert idx._device_broken == 0  # fresh backend, clean slate
    assert idx._dev is not None  # device path resumed
    assert idx.device_info()["suspended"] is False


async def test_retainer_module_attaches_router():
    n = Node(boot_listeners=False)
    n.modules.load(RetainerModule)
    await n.start()
    try:
        ret = n.modules._loaded["retainer"]
        assert ret._index._router is n.router
    finally:
        await n.stop()


# -- replay plan: unit-level delivery contract -------------------------------

class _PlanSession:
    """Fake with the batched protocol: records deliver_many batches."""

    def __init__(self):
        self.batches = []
        self.singles = []
        self.subscriptions = {}

    def deliver_many(self, items):
        self.batches.append(list(items))

    def deliver(self, f, m):
        self.singles.append((f, m))


async def test_replay_flush_one_deliver_many_per_session():
    """The planner path: however many (filter × topic) pairs a burst
    resolves for a session, the session takes ONE deliver_many — the
    ≤1-wakeup-per-connection contract at the session seam — and the
    legacy path (dispatch.planner=false) walks per delivery."""
    n = Node(boot_listeners=False)
    mod = n.modules.load(RetainerModule)
    await n.start()
    try:
        for t in ("p/a", "p/b", "q/c"):
            n.publish(Message(topic=t, payload=b"v",
                              flags={"retain": True}))
        s1, s2 = _PlanSession(), _PlanSession()
        items = [(s1, "p/+", {"qos": 0}), (s1, "q/c", {"qos": 0}),
                 (s2, "p/a", {"qos": 0})]
        mod._replay_flush(list(items))
        assert len(s1.batches) == 1 and not s1.singles
        assert sorted((f, m.topic) for f, m, _o, _fast in s1.batches[0]) \
            == [("p/+", "p/a"), ("p/+", "p/b"), ("q/c", "q/c")]
        assert [(f, m.topic) for f, m, _o, _fast in s2.batches[0]] \
            == [("p/a", "p/a")]
        # every replayed copy carries retain + the retained header
        for f, m, _o, _fast in s1.batches[0] + s2.batches[0]:
            assert m.flags.get("retain") and m.headers.get("retained")
        # ONE shared out-copy per stored topic per burst
        pa = [m for _f, m, _o, _x in s1.batches[0] + s2.batches[0]
              if m.topic == "p/a"]
        assert len(pa) == 2 and pa[0] is pa[1]
        assert n.metrics.val("retained.replay.batches") == 1
        assert n.metrics.val("retained.replay.messages") == 4
        assert mod.replay_info()["replay_last_batch"] == 4
        # legacy path: byte-for-byte the old per-delivery walk
        n.broker.dispatch_config.planner = False
        s3 = _PlanSession()
        mod._replay_flush([(s3, "p/+", {"qos": 0})])
        assert not s3.batches and len(s3.singles) == 2
    finally:
        await n.stop()


async def test_replay_flush_expiry_evicted_in_plan_stage():
    """An entry past Message-Expiry at replay time is filtered in the
    plan stage AND lazily evicted (store + counters)."""
    import time as _t

    n = Node(boot_listeners=False)
    mod = n.modules.load(RetainerModule)
    await n.start()
    try:
        dead = Message(topic="e/t", payload=b"x",
                       flags={"retain": True},
                       timestamp=_t.time() - 100,
                       headers={"properties":
                                {"Message-Expiry-Interval": 1}})
        n.publish(dead)
        n.publish(Message(topic="e/u", payload=b"y",
                          flags={"retain": True}))
        assert len(mod._store) == 2
        s = _PlanSession()
        mod._replay_flush([(s, "e/+", {"qos": 0})])
        assert [(f, m.topic) for f, m, _o, _x in s.batches[0]] \
            == [("e/+", "e/u")]
        assert "e/t" not in mod._store
        assert n.metrics.val("retained.expired") == 1
        assert n.metrics.val("retained.count") == 1
    finally:
        await n.stop()


# -- replay over the wire: burst coalescing, metrics, parity -----------------

async def _retained_node(**kw):
    n = Node(boot_listeners=False, **kw)
    n.modules.load(RetainerModule)
    lst = n.add_listener(port=0)
    await n.start()
    return n, lst.port


async def _seed_store(port, topics):
    pub = TestClient("seed", version=C.MQTT_V5)
    await pub.connect(port=port)
    for t, payload in topics:
        await pub.publish(t, payload, qos=1, retain=True)
    await pub.close()


_SEED = [("w/a", b"pa"), ("w/b", b"pb"), ("w/c/d", b"pcd"),
         ("v/1", b"p1"), ("v/2", b"p2")]


async def _replay_burst(node, port, client_id="burst",
                        version=C.MQTT_V5):
    """One multi-filter SUBSCRIBE → one replay burst; returns the
    delivered (filter-agnostic) packet tuples + metric deltas."""
    m = node.metrics
    before = {k: m.val(k) for k in
              ("delivery.wakeups", "delivery.serialize.onloop",
               "retained.replay.batches", "retained.replay.messages")}
    sub = TestClient(client_id, version=version)
    await sub.connect(port=port)
    await sub.subscribe(("w/+", {"qos": 1, "nl": 0, "rap": 0, "rh": 0}),
                        ("w/c/#", {"qos": 0, "nl": 0, "rap": 1, "rh": 0}),
                        ("v/1", {"qos": 1, "nl": 0, "rap": 0, "rh": 0}))
    got = []
    for _ in range(4):  # w/a, w/b, w/c/d, v/1
        p = await sub.recv(5)
        got.append((p.topic, bytes(p.payload), p.qos, p.retain))
    with pytest.raises(asyncio.TimeoutError):
        await sub.recv(0.3)
    await sub.close()
    delta = {k: m.val(k) - before[k] for k in before}
    return sorted(got), delta


_EXPECT = sorted([("w/a", b"pa", 1, True), ("w/b", b"pb", 1, True),
                  ("w/c/d", b"pcd", 0, True), ("v/1", b"p1", 1, True)])


async def test_replay_burst_planner_metrics_and_wire():
    """The full pinned contract on the default (planner+preserialize)
    path with the device index forced on: exact delivered set with
    retain kept (MQTT-3.3.1-8), ONE replay batch per SUBSCRIBE burst,
    serialization fully off-loop, and exactly one delivery wakeup for
    the subscribing connection (SUBACK is written inline by the read
    loop — it never passes through the wakeup path)."""
    n, port = await _retained_node()
    try:
        n.modules._loaded["retainer"].index_device_threshold = 0
        await _seed_store(port, _SEED)
        got, delta = await _replay_burst(n, port)
        assert got == _EXPECT
        assert delta["retained.replay.batches"] == 1
        assert delta["retained.replay.messages"] == 4
        assert delta["delivery.serialize.onloop"] == 0
        assert delta["delivery.wakeups"] == 1
    finally:
        await n.stop()


async def test_replay_wire_parity_planner_off():
    """dispatch.planner=false restores the legacy per-delivery replay
    — the delivered set must be identical (wire parity)."""
    n, port = await _retained_node(
        dispatch_config=DispatchConfig(planner=False))
    try:
        n.modules._loaded["retainer"].index_device_threshold = 0
        await _seed_store(port, _SEED)
        got, delta = await _replay_burst(n, port)
        assert got == _EXPECT
        assert delta["retained.replay.batches"] == 1
    finally:
        await n.stop()


async def test_replay_wire_parity_two_loops():
    """loops=2: the hook fires on the subscribing channel's owner
    loop and replay flushes per loop — delivered sets stay identical
    to the single-loop node for subscribers on BOTH loops."""
    n, port = await _retained_node(loops=2)
    try:
        n.modules._loaded["retainer"].index_device_threshold = 0
        await _seed_store(port, _SEED)
        # sequential connects round-robin across the ring: these two
        # land on different loops
        got1, d1 = await _replay_burst(n, port, "ring1")
        got2, d2 = await _replay_burst(n, port, "ring2")
        assert got1 == _EXPECT and got2 == _EXPECT
        assert d2["delivery.serialize.onloop"] == 0
        assert d2["retained.replay.batches"] == 1
    finally:
        await n.stop()


async def test_replay_rh_share_matrix_batched():
    """RH 2 / RH 1-on-resub / shared-group gating holds on the
    batched path: gated subscriptions contribute nothing to the
    burst (no batch fires when everything is gated)."""
    n, port = await _retained_node()
    try:
        ret = n.modules._loaded["retainer"]
        ret.index_device_threshold = 0
        await _seed_store(port, _SEED)
        m = n.metrics
        before = m.val("retained.replay.batches")
        sub = TestClient("gated", version=C.MQTT_V5)
        await sub.connect(port=port)
        await sub.subscribe(
            ("w/a", {"qos": 1, "nl": 0, "rap": 0, "rh": 2}),
            ("$share/g/w/+", {"qos": 1, "nl": 0, "rap": 0, "rh": 0}))
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(0.3)
        assert m.val("retained.replay.batches") == before  # no batch
        # rh=1 resub: gated at submit time too
        await sub.subscribe(("w/a", {"qos": 1, "nl": 0, "rap": 0,
                                     "rh": 1}))
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(0.3)
        assert m.val("retained.replay.batches") == before
        # rh=1 on a NEW subscription replays through one batch
        await sub.subscribe(("w/b", {"qos": 1, "nl": 0, "rap": 0,
                                     "rh": 1}))
        p = await sub.recv(5)
        assert (p.topic, bytes(p.payload), p.retain) == ("w/b", b"pb",
                                                         True)
        assert m.val("retained.replay.batches") == before + 1
        await sub.close()
    finally:
        await n.stop()


# -- device-path wills -------------------------------------------------------

async def test_will_storm_one_ingress_batch():
    """A mass-disconnect will storm funnels through the ingress
    accumulator: N wills submitted in one tick → ONE ingress flush,
    every will counted batched, exact fan-out to the observer."""
    n, port = await _retained_node()
    try:
        obs = TestClient("wobs", version=C.MQTT_V5)
        await obs.connect(port=port)
        await obs.subscribe("ws/#", qos=0)
        N = 12
        flushes0 = n.ingress.flushes
        for i in range(N):
            n.broker.publish_will(Message(topic=f"ws/{i}",
                                          payload=b"died"))
        got = set()
        for _ in range(N):
            p = await obs.recv(5)
            got.add(p.topic)
        assert got == {f"ws/{i}" for i in range(N)}
        assert n.metrics.val("wills.batched") == N
        assert n.metrics.val("wills.direct") == 0
        assert n.ingress.flushes == flushes0 + 1  # ONE batch
        await obs.close()
    finally:
        await n.stop()


async def test_abrupt_disconnect_will_rides_ingress():
    """End-to-end: an abnormal disconnect's will reaches subscribers
    through the batched device path (wills.batched counts it)."""
    n, port = await _retained_node()
    try:
        obs = TestClient("wobs2")
        await obs.connect(port=port)
        await obs.subscribe("wd/#", qos=1)
        w = TestClient("wful", will_flag=True, will_qos=1,
                       will_topic="wd/t", will_payload=b"gone")
        await w.connect(port=port)
        await w.close()  # abrupt: will must fire
        p = await obs.recv(5)
        assert (p.topic, bytes(p.payload)) == ("wd/t", b"gone")
        assert n.metrics.val("wills.batched") == 1
        await obs.close()
    finally:
        await n.stop()


def test_publish_will_direct_fallback_without_loop():
    """Loop-less callers (sync adapters, tests) can't ride the
    accumulator: publish_will falls back to the direct path."""
    n = Node(boot_listeners=False)
    n.modules.load(RetainerModule)
    n.broker.publish_will(Message(topic="wf/t", payload=b"x"))
    assert n.metrics.val("wills.direct") == 1
    assert n.metrics.val("wills.batched") == 0


# -- expired-retained GC on the stats tick -----------------------------------

async def test_stats_tick_gc_sweeps_expired():
    import time as _t

    n = Node(boot_listeners=False)
    mod = n.modules.load(RetainerModule)
    await n.start()
    try:
        n.publish(Message(topic="gc/t", payload=b"x",
                          flags={"retain": True},
                          timestamp=_t.time() - 100,
                          headers={"properties":
                                   {"Message-Expiry-Interval": 1}}))
        n.publish(Message(topic="gc/live", payload=b"y",
                          flags={"retain": True}))
        assert len(mod._store) == 2
        for _ in range(RetainerModule._GC_EVERY):
            n.stats.tick()
        assert "gc/t" not in mod._store and "gc/live" in mod._store
        assert n.metrics.val("retained.expired") == 1
        assert n.metrics.val("retained.count") == 1
    finally:
        await n.stop()


# -- ctl surface -------------------------------------------------------------

async def test_ctl_retained_snapshot():
    n, port = await _retained_node()
    try:
        n.modules._loaded["retainer"].index_device_threshold = 0
        await _seed_store(port, _SEED)
        got, _delta = await _replay_burst(n, port, "ctlsub")
        assert got == _EXPECT
        out = json.loads(n.ctl.run(["retained"]))
        assert out["store"] == len(_SEED)
        assert out["replay_batches"] == 1
        assert out["replay_last_batch"] == 4
        idx = out["index"]
        assert idx["rows"] == len(_SEED)
        assert idx["last_batch"] == 2  # two wildcard filters batched
        assert idx["device_broken"] == 0 and not idx["suspended"]
        assert idx["walk"] in ("lax", "pallas")
    finally:
        await n.stop()


async def test_ctl_retained_without_module():
    async def _bare():
        n = Node(boot_listeners=False)
        await n.start()
        return n

    n = await _bare()
    try:
        assert "not loaded" in n.ctl.run(["retained"])
    finally:
        await n.stop()
